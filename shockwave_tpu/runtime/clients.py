"""gRPC clients for all three control-plane directions
(reference: runtime/rpc/{scheduler_client,worker_client,iterator_client}.py).

Every call carries a deadline and rides the resilience layer
(`resilience.py`): bounded exponential-backoff retry on transport
failures, and — for the scheduler->worker direction — a circuit breaker
per worker channel so one dead worker fails fast instead of costing
every round a full retry budget. No call in this module can block
indefinitely.
"""
from __future__ import annotations

import logging
from typing import List, Optional, Sequence, Tuple

import grpc

from .proto import control_pb2 as pb
from .resilience import (CircuitBreaker, RetryPolicy, call_with_retry,
                         policy_from_env)
from .rpc import Stub

logger = logging.getLogger("shockwave_tpu.runtime")

#: Scheduler -> worker: short deadlines — the scheduler holds its round
#: lock across dispatch, so a dead worker must surface fast.
WORKER_RPC_POLICY = RetryPolicy(deadline_s=10.0, total_budget_s=25.0,
                                max_attempts=3)
#: Worker/iterator -> scheduler: more patient (the scheduler may be
#: solving a MILP), but still bounded.
SCHED_RPC_POLICY = RetryPolicy(deadline_s=30.0, total_budget_s=90.0,
                               max_attempts=4)


class SchedulerToWorkerClient:
    """Scheduler -> one worker daemon."""

    def __init__(self, addr: str, port: int,
                 policy: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None):
        self.addr = addr
        self.port = port
        self._policy = policy or WORKER_RPC_POLICY
        self.breaker = breaker or CircuitBreaker()
        self._channel = grpc.insecure_channel(f"{addr}:{port}")
        self._stub = Stub(self._channel, "shockwave_tpu.SchedulerToWorker")

    def _call(self, method: str, request, policy: Optional[RetryPolicy] = None):
        return call_with_retry(
            getattr(self._stub, method), request,
            method=f"worker {self.addr}:{self.port}/{method}",
            policy=policy or self._policy, breaker=self.breaker)

    def run_job(self, job_descriptions: Sequence[dict], worker_id: int,
                round_id: int) -> None:
        request = pb.RunJobRequest(
            jobs=[pb.JobDescription(**d) for d in job_descriptions],
            worker_id=worker_id, round_id=round_id)
        self._call("RunJob", request)

    def kill_job(self, job_id: int, deadline_s: Optional[float] = None) -> None:
        """With `deadline_s`, a single bounded attempt — for best-effort
        kills issued under the scheduler lock, where the full retry
        budget would stall the round pipeline."""
        policy = None
        if deadline_s is not None:
            from dataclasses import replace
            policy = replace(self._policy.one_shot(), deadline_s=deadline_s,
                             total_budget_s=deadline_s)
        self._call("KillJob", pb.KillJobRequest(job_id=job_id), policy=policy)

    def reset(self) -> None:
        self._call("Reset", pb.Empty())

    def ping(self, deadline_s: Optional[float] = None) -> None:
        """Single-attempt liveness probe; raises RpcUnavailableError (or
        CircuitOpenError) on failure. The heartbeat monitor owns the
        retry cadence, so no client-side retries here."""
        policy = self._policy.one_shot()
        if deadline_s is not None:
            from dataclasses import replace
            policy = replace(policy, deadline_s=deadline_s,
                             total_budget_s=deadline_s)
        self._call("Ping", pb.Empty(), policy=policy)

    def shutdown(self) -> None:
        try:
            self._stub.Shutdown(pb.Empty(), timeout=5)
        except grpc.RpcError:
            pass  # worker may exit before replying

    def close(self) -> None:
        self._channel.close()


class WorkerToSchedulerClient:
    """Worker daemon -> scheduler."""

    def __init__(self, sched_addr: str, sched_port: int,
                 policy: Optional[RetryPolicy] = None):
        self._policy = policy or policy_from_env(SCHED_RPC_POLICY)
        self._done_policy = self._policy
        self._channel = grpc.insecure_channel(f"{sched_addr}:{sched_port}")
        self._stub = Stub(self._channel, "shockwave_tpu.WorkerToScheduler")

    def stretch_done_deadline(self, min_deadline_s: float) -> None:
        """Raise Done's deadline floor. The scheduler's Done handler
        legitimately blocks an early finisher until the round boundary,
        so the deadline must cover a full round — the daemon calls this
        once the round duration is known (at registration)."""
        from dataclasses import replace
        if min_deadline_s > self._done_policy.deadline_s:
            self._done_policy = replace(
                self._done_policy, deadline_s=min_deadline_s,
                total_budget_s=max(self._done_policy.total_budget_s,
                                   min_deadline_s * 1.5))

    def register_worker(self, worker_type: str, ip_addr: str, port: int,
                        num_chips: int) -> Tuple[List[int], float]:
        # Single attempt with a deadline: the daemon's bring-up loop owns
        # registration retries (with its own, much longer window).
        response = self._stub.RegisterWorker(pb.RegisterWorkerRequest(
            worker_type=worker_type, ip_addr=ip_addr, port=port,
            num_chips=num_chips), timeout=self._policy.deadline_s)
        if not response.success:
            raise RuntimeError(response.error_message)
        return list(response.worker_ids), response.round_duration

    def notify_done(self, job_ids: Sequence[int], worker_id: int,
                    num_steps: Sequence[int], execution_times: Sequence[float],
                    iterator_logs: Optional[Sequence[str]] = None) -> None:
        # Done is not idempotent (the scheduler aggregates each report
        # into step accounting), so only connection-level failures are
        # retried: a deadline expiry may mean the server is still
        # processing attempt 1, and replaying would double-count.
        call_with_retry(
            self._stub.Done,
            pb.DoneRequest(
                job_ids=list(job_ids), worker_id=worker_id,
                num_steps=[int(s) for s in num_steps],
                execution_times=list(execution_times),
                iterator_logs=list(iterator_logs or [])),
            method="scheduler/Done", policy=self._done_policy,
            retryable=frozenset({grpc.StatusCode.UNAVAILABLE}))


class IteratorToSchedulerClient:
    """Training process (lease iterator) -> scheduler. A fresh channel per
    call keeps the client robust to scheduler restarts, as in the reference;
    deadlines + bounded retry keep a dead scheduler from hanging the
    training process inside a lease renewal."""

    def __init__(self, job_id: int, worker_id: int, sched_addr: str,
                 sched_port: int, policy: Optional[RetryPolicy] = None):
        self._job_id = job_id
        self._worker_id = worker_id
        self._target = f"{sched_addr}:{sched_port}"
        self._policy = policy or policy_from_env(SCHED_RPC_POLICY)

    def _stub(self, channel):
        return Stub(channel, "shockwave_tpu.IteratorToScheduler")

    def _call(self, method: str, request):
        with grpc.insecure_channel(self._target) as channel:
            return call_with_retry(
                getattr(self._stub(channel), method), request,
                method=f"scheduler/{method}", policy=self._policy)

    def init(self) -> Tuple[int, float, float]:
        r = self._call("InitJob", pb.InitJobRequest(
            job_id=self._job_id, worker_id=self._worker_id))
        return r.max_steps, r.max_duration, r.extra_time

    def update_lease(self, steps: int, duration: float, max_steps: int,
                     max_duration: float) -> Tuple[int, float, float, float]:
        r = self._call("UpdateLease", pb.UpdateLeaseRequest(
            job_id=self._job_id, worker_id=self._worker_id,
            steps=int(steps), duration=duration, max_steps=int(max_steps),
            max_duration=max_duration))
        return r.max_steps, r.max_duration, r.run_time_so_far, r.deadline

    def update_resource_requirement(self, big_bs: bool, small_bs: bool) -> None:
        self._call("UpdateResourceRequirement",
                   pb.UpdateResourceRequirementRequest(
                       job_id=self._job_id, worker_id=self._worker_id,
                       big_bs=big_bs, small_bs=small_bs))
