"""One-place logging configuration for every driver and daemon.

Before this module, each driver called ``logging.basicConfig`` with its
own ad-hoc format (or not at all — the ``shockwave_tpu.sched`` logger
was effectively unconfigured under pytest and library embedding).
``setup_logging`` is the single entry point: drivers expose
``--log_level`` and pass it here.
"""
from __future__ import annotations

import logging

#: Level names accepted by --log_level flags.
LEVELS = ("debug", "info", "warning", "error", "critical")

DEFAULT_FORMAT = "%(asctime)s %(name)s %(levelname)s %(message)s"


def setup_logging(level: str = "warning", fmt: str = DEFAULT_FORMAT) -> int:
    """Configure the root logger (handlers replaced, so repeated calls
    and prior ad-hoc basicConfig setups don't stack). Returns the
    numeric level. Raises ValueError on an unknown level name."""
    name = str(level).strip().lower()
    if name not in LEVELS:
        raise ValueError(
            f"unknown log level {level!r} (choose from {', '.join(LEVELS)})")
    numeric = getattr(logging, name.upper())
    logging.basicConfig(level=numeric, format=fmt, force=True)
    return numeric
