"""Fuse per-process span shards into one Perfetto/Chrome fleet trace.

    python -m shockwave_tpu.obs.merge <trace_dir> [-o merged.json]

Reads every ``spans-<role>-<pid>.json`` shard in the directory
(scheduler, worker daemons, trainers — see obs/shard.py), aligns
per-host clock offsets, and writes a single Chrome-trace JSON whose
span args carry the propagated (trace_id, span_id, parent_id)
identities — so one round's solve -> dispatch -> launch -> trainer ->
done chain renders as one connected timeline and tests can walk parent
links across process boundaries.

Clock alignment: every scheduler->worker RPC carries the sender's send
timestamp (names.TRACE_SENDTS_METADATA_KEY); the receiver's `runjob`
span records it beside its own receive stamp. For each non-scheduler
host the offset estimate is the MINIMUM of (recv - send) over all
pairs — the pair least inflated by network latency; one-directional,
so the residual error is bounded by the fastest observed RPC, which on
an intra-cluster fabric is well under a round. The scheduler's host is
the reference (offset 0), and trainer shards inherit their host's
offset (trainers run on the worker host).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

from . import names
from .shard import discover_shards, load_shard


def _host_offsets(shards: List[dict]) -> Dict[str, float]:
    """host -> seconds to SUBTRACT from that host's timestamps."""
    sched_hosts = {s.get("host") for s in shards
                   if s.get("role") == "scheduler"}
    estimates: Dict[str, List[float]] = {}
    for shard in shards:
        host = shard.get("host", "?")
        if host in sched_hosts:
            continue
        for span in shard.get("spans", []):
            send_ts = (span.get("args") or {}).get("send_ts")
            if send_ts is None:
                continue
            try:
                estimates.setdefault(host, []).append(
                    float(span["ts"]) - float(send_ts))
            except (TypeError, ValueError):
                continue
    offsets = {host: 0.0 for host in sched_hosts if host is not None}
    for host, deltas in estimates.items():
        offsets[host] = min(deltas)
    return offsets


def merge_directory(directory: str, out_path: Optional[str] = None,
                    obs=None) -> dict:
    """Merge every shard in `directory` into one Chrome trace at
    `out_path` (default ``<directory>/merged_trace.json``). Returns a
    summary dict: shard/span counts, per-host offsets, output path."""
    if obs is None:
        from . import get_observability
        obs = get_observability()
    paths = discover_shards(directory)
    shards = []
    skipped = []
    for path in paths:
        shard = load_shard(path)
        if shard is None:
            skipped.append(os.path.basename(path))
            continue
        shards.append(shard)
    offsets = _host_offsets(shards)
    events = []
    process_meta = []
    total_spans = 0
    for idx, shard in enumerate(shards):
        role = shard.get("role", "?")
        host = shard.get("host", "?")
        offset = offsets.get(host, 0.0)
        pid = idx + 1
        process_meta.append({
            "name": "process_name", "ph": "M", "pid": pid,
            "args": {"name": f"{role} {host}:{shard.get('pid')}"}})
        obs.inc(names.TRACE_MERGE_SHARDS_TOTAL, role=role)
        from .tracing import Tracer
        for span in shard.get("spans", []):
            # Shard spans share the tracer-event shape, so the one
            # identity-folding implementation serves both exports.
            args = Tracer.event_args(span)
            args["role"] = role
            events.append({
                "name": span.get("name", "?"), "ph": "X",
                "cat": "swtpu",
                "ts": (float(span.get("ts", 0.0)) - offset) * 1e6,
                "dur": float(span.get("dur", 0.0)) * 1e6,
                "pid": pid, "tid": span.get("tid", 0) or 0,
                "args": args})
            total_spans += 1
    obs.inc(names.TRACE_MERGE_SPANS_TOTAL, amount=total_spans)
    for host, offset in offsets.items():
        obs.set_gauge(names.TRACE_MERGE_CLOCK_OFFSET_SECONDS, offset,
                      host=host)
    if out_path is None:
        out_path = os.path.join(directory, names.MERGED_TRACE_NAME)
    trace = {"displayTimeUnit": "ms",
             "traceEvents": process_meta + events}
    from ..core.durable_io import write_text_atomic
    write_text_atomic(out_path, json.dumps(trace))
    return {"out": out_path, "shards": len(shards),
            "skipped": skipped, "spans": total_spans,
            "offsets": {h: round(o, 6) for h, o in offsets.items()}}


# -- parent-link helpers (merge consumers: explain, tests) --------------

def spans_by_id(trace_events: List[dict]) -> Dict[str, dict]:
    """span_id -> event for every identity-carrying span event."""
    out = {}
    for e in trace_events:
        if e.get("ph", "X") != "X":
            continue
        span_id = (e.get("args") or {}).get("span_id")
        if span_id:
            out[span_id] = e
    return out


def parent_chain(index: Dict[str, dict], event: dict,
                 limit: int = 64) -> List[dict]:
    """The chain [event, parent, grandparent, ...] following parent_id
    links through `index` (stops at a missing parent or `limit`)."""
    chain = [event]
    seen = set()
    current = event
    while len(chain) < limit:
        parent_id = (current.get("args") or {}).get("parent_id")
        if not parent_id or parent_id in seen:
            break
        seen.add(parent_id)
        parent = index.get(parent_id)
        if parent is None:
            break
        chain.append(parent)
        current = parent
    return chain


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m shockwave_tpu.obs.merge",
        description=__doc__.splitlines()[0])
    p.add_argument("trace_dir", help="directory of spans-*.json shards "
                                     "(the drive's --trace_dir)")
    p.add_argument("-o", "--out", default=None,
                   help="merged Chrome-trace path (default "
                        "<trace_dir>/merged_trace.json)")
    args = p.parse_args(argv)
    summary = merge_directory(args.trace_dir, args.out)
    if summary["shards"] == 0:
        print(f"{args.trace_dir}: no span shards found", file=sys.stderr)
        return 1
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
