"""Observability subsystem tests: registry semantics + concurrency
(under the lock sanitizer), golden Chrome-trace export, the report CLI,
the /metrics + /healthz endpoint (unit and scraped mid-run through a
real loopback scheduler), and obs-on/off simulator determinism."""
import json
import os
import re
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from shockwave_tpu.core.job import Job, JobIdPair
from shockwave_tpu.obs import Observability, names
from shockwave_tpu.obs.exporter import ObsHttpServer
from shockwave_tpu.obs.names import MetricSpec
from shockwave_tpu.obs.registry import MetricsRegistry
from shockwave_tpu.obs.report import load_spans, phase_table, render
from shockwave_tpu.obs.tracing import Tracer

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
DATA = os.path.join(REPO, "data")


class SteppingClock:
    """Deterministic clock: every read advances by `step`."""

    def __init__(self, start=100.0, step=1.0):
        self.now = start
        self.step = step

    def __call__(self):
        t = self.now
        self.now += self.step
        return t


def free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def parse_prometheus(text):
    """Exposition text -> {(name, frozenset(label pairs)): value}.
    Doubles as the 'is this parseable' check: any malformed sample
    line raises."""
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        body, value = line.rsplit(" ", 1)
        if "{" in body:
            name, labels_body = body.split("{", 1)
            assert labels_body.endswith("}")
            labels = _LABEL_RE.findall(labels_body[:-1])
            key = (name, frozenset(labels))
        else:
            key = (body, frozenset())
        samples[key] = float(value)
    return samples


COUNTER = MetricSpec("test_events_total", "counter", "events", ("kind",))
GAUGE = MetricSpec("test_depth", "gauge", "depth")
HIST = MetricSpec("test_latency_seconds", "histogram", "latency",
                  ("op",), (0.1, 1.0, 10.0))


class TestRegistry:
    def test_counter_accumulates_per_label(self):
        reg = MetricsRegistry()
        reg.inc(COUNTER, kind="a")
        reg.inc(COUNTER, amount=2.5, kind="a")
        reg.inc(COUNTER, kind="b")
        assert reg.value(COUNTER, kind="a") == 3.5
        assert reg.value(COUNTER, kind="b") == 1.0
        assert reg.value(COUNTER, kind="never") == 0.0

    def test_gauge_overwrites(self):
        reg = MetricsRegistry()
        reg.set_gauge(GAUGE, 4)
        reg.set_gauge(GAUGE, 2)
        assert reg.value(GAUGE) == 2.0

    def test_histogram_buckets_and_stats(self):
        reg = MetricsRegistry()
        for v in (0.05, 0.5, 5.0, 50.0):
            reg.observe(HIST, v, op="x")
        count, total = reg.histogram_stats(HIST, op="x")
        assert count == 4
        assert total == pytest.approx(55.55)
        samples = parse_prometheus(reg.render_prometheus())
        le = lambda b: samples[("test_latency_seconds_bucket",
                                frozenset({("op", "x"), ("le", b)}))]
        assert le("0.1") == 1        # cumulative
        assert le("1") == 2
        assert le("10") == 3
        assert le("+Inf") == 4

    def test_kind_and_label_misuse_raise(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError):
            reg.inc(GAUGE)                       # wrong kind
        with pytest.raises(ValueError):
            reg.observe(COUNTER, 1.0, kind="a")  # wrong kind
        with pytest.raises(ValueError):
            reg.inc(COUNTER)                     # missing label
        with pytest.raises(ValueError):
            reg.inc(COUNTER, kind="a", extra="b")
        with pytest.raises(ValueError):
            reg.inc(COUNTER, amount=-1, kind="a")

    def test_timed_uses_injected_clock(self):
        clock = SteppingClock(step=2.0)
        reg = MetricsRegistry(clock=clock)
        with reg.timed(HIST, op="solve"):
            pass
        count, total = reg.histogram_stats(HIST, op="solve")
        assert (count, total) == (1, 2.0)  # exactly one clock step

    def test_disabled_registry_records_nothing(self):
        reg = MetricsRegistry(enabled=False)
        reg.inc(COUNTER, kind="a")
        reg.set_gauge(GAUGE, 9)
        reg.observe(HIST, 1.0, op="x")
        assert reg.render_prometheus().strip() == ""

    def test_rendering_is_parseable_and_typed(self):
        reg = MetricsRegistry()
        reg.inc(COUNTER, kind='we"ird\nlabel')
        reg.set_gauge(GAUGE, 1.5)
        text = reg.render_prometheus()
        assert "# TYPE test_events_total counter" in text
        assert "# HELP test_depth depth" in text
        samples = parse_prometheus(text)
        assert samples[("test_depth", frozenset())] == 1.5


@pytest.mark.runtime
class TestRegistryConcurrency:
    """Exact counts under thread contention, with the registry lock
    instrumented by the sanitizer (the conftest `runtime`-marker
    fixture sets SWTPU_SANITIZE=1 and asserts a clean report)."""

    def test_parallel_increments_are_exact(self):
        reg = MetricsRegistry()
        n_threads, n_ops = 8, 2000
        barrier = threading.Barrier(n_threads)

        def worker(k):
            barrier.wait()
            for _ in range(n_ops):
                reg.inc(COUNTER, kind="shared")
                reg.observe(HIST, 0.5, op=f"t{k % 2}")

        threads = [threading.Thread(target=worker, args=(k,))
                   for k in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.value(COUNTER, kind="shared") == n_threads * n_ops
        c0, _ = reg.histogram_stats(HIST, op="t0")
        c1, _ = reg.histogram_stats(HIST, op="t1")
        assert c0 + c1 == n_threads * n_ops


class TestTracer:
    def test_golden_chrome_trace_export(self, tmp_path):
        clock = SteppingClock(start=10.0, step=1.0)
        tracer = Tracer(clock=clock)
        with tracer.span(names.SPAN_SOLVE, round=0):       # t=10..13
            with tracer.span(names.SPAN_DISPATCH, round=0):  # t=11..12
                pass
        path = str(tmp_path / "trace.json")
        tracer.export_chrome_trace(path)
        with open(path) as f:
            trace = json.load(f)
        golden = [
            {"name": "dispatch", "ph": "X", "cat": "swtpu",
             "ts": 11_000_000.0, "dur": 1_000_000.0,
             "args": {"round": 0}},
            {"name": "solve", "ph": "X", "cat": "swtpu",
             "ts": 10_000_000.0, "dur": 3_000_000.0,
             "args": {"round": 0}},
        ]
        got = [{k: e[k] for k in ("name", "ph", "cat", "ts", "dur",
                                  "args")}
               for e in trace["traceEvents"]]
        assert got == golden
        assert trace["displayTimeUnit"] == "ms"
        # pid/tid present on every event (Perfetto requires them).
        assert all("pid" in e and "tid" in e for e in trace["traceEvents"])

    def test_ring_buffer_bounds_memory(self):
        tracer = Tracer(clock=SteppingClock(), max_events=3)
        for i in range(10):
            with tracer.span(names.SPAN_WAIT, i=i):
                pass
        events = tracer.events()
        assert len(events) == 3
        assert [e["args"]["i"] for e in events] == [7, 8, 9]

    def test_disabled_tracer_records_nothing(self):
        tracer = Tracer(enabled=False)
        with tracer.span(names.SPAN_WAIT):
            pass
        assert tracer.events() == []


class TestReport:
    def _write_trace(self, tmp_path):
        clock = SteppingClock(start=0.0, step=0.5)
        tracer = Tracer(clock=clock)
        for rnd in range(2):
            with tracer.span(names.SPAN_SOLVE, round=rnd):
                pass
            with tracer.span(names.SPAN_DISPATCH, round=rnd):
                pass
            # Round-less span (journal fsync fires from RPC threads):
            # attributed to the round whose window contains it.
            with tracer.span(names.SPAN_JOURNAL_FSYNC, etype="x"):
                pass
            with tracer.span(names.SPAN_WAIT, round=rnd):
                pass
            with tracer.span(names.SPAN_END_ROUND, round=rnd):
                pass
        path = str(tmp_path / "trace.json")
        tracer.export_chrome_trace(path)
        return path

    def test_phase_table_assigns_roundless_spans(self, tmp_path):
        spans = load_spans(self._write_trace(tmp_path))
        rounds, per_round, totals = phase_table(spans)
        assert rounds == [0, 1]
        for rnd in (0, 1):
            assert per_round[rnd][names.SPAN_JOURNAL_FSYNC] > 0
        assert totals[names.SPAN_SOLVE][0] == 2

    def test_render_has_all_phase_columns(self, tmp_path):
        spans = load_spans(self._write_trace(tmp_path))
        table = render(spans)
        for phase in names.REPORT_PHASES:
            assert phase in table
        assert "total_s" in table and "mean_s" in table

    def test_cli_prints_table(self, tmp_path):
        path = self._write_trace(tmp_path)
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.obs.report", path],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "solve" in out.stdout
        assert "journal-fsync" in out.stdout

    def test_cli_fails_on_empty_trace(self, tmp_path):
        path = tmp_path / "empty.json"
        path.write_text('{"traceEvents": []}')
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.obs.report", str(path)],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 1


class TestCatalog:
    def test_catalog_covers_every_spec(self):
        from shockwave_tpu.obs.catalog import catalog_markdown
        table = catalog_markdown()
        for spec in names.all_metric_specs():
            assert spec.name in table

    def test_readme_contains_every_metric(self):
        """README's generated catalog must not drift from names.py."""
        with open(os.path.join(REPO, "README.md")) as f:
            readme = f.read()
        for spec in names.all_metric_specs():
            assert spec.name in readme, (
                f"{spec.name} missing from README.md — regenerate the "
                "catalog with `python -m shockwave_tpu.obs.catalog`")


class TestExporter:
    def test_metrics_and_healthz_roundtrip(self):
        reg = MetricsRegistry()
        reg.inc(COUNTER, kind="a")
        server = ObsHttpServer(
            reg, health_fn=lambda: {"round": 7, "live_workers": 2},
            addr="127.0.0.1", port=0).start()
        try:
            base = f"http://127.0.0.1:{server.port}"
            with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
                assert r.status == 200
                assert "text/plain" in r.headers["Content-Type"]
                samples = parse_prometheus(r.read().decode())
            assert samples[("test_events_total",
                            frozenset({("kind", "a")}))] == 1.0
            with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
                health = json.loads(r.read())
            assert health == {"round": 7, "live_workers": 2,
                              "status": "ok"}
            try:
                urllib.request.urlopen(base + "/nope", timeout=5)
                assert False, "expected 404"
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            server.stop()

    def test_failing_health_callback_returns_500(self):
        def broken():
            raise RuntimeError("wedged")

        server = ObsHttpServer(MetricsRegistry(), health_fn=broken,
                               addr="127.0.0.1", port=0).start()
        try:
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/healthz", timeout=5)
                assert False, "expected 500"
            except urllib.error.HTTPError as e:
                assert e.code == 500
                body = json.loads(e.read())
                assert body["status"] == "error"
                assert "wedged" in body["error"]
        finally:
            server.stop()


class _StubWorker:
    """Minimal in-process worker daemon (mirrors test_runtime's stub):
    simulates execution at a fixed throughput, no subprocesses."""

    def __init__(self, sched_port, worker_port, num_chips=2,
                 throughput=100.0, execution_time=0.4):
        from shockwave_tpu.runtime.clients import (
            IteratorToSchedulerClient, WorkerToSchedulerClient)
        from shockwave_tpu.runtime.servers import serve_worker
        self.throughput = throughput
        self.execution_time = execution_time
        self.sched_port = sched_port
        self._iter_client = IteratorToSchedulerClient
        self._client = WorkerToSchedulerClient("localhost", sched_port)
        self.server = serve_worker(worker_port, {
            "RunJob": self._run_job, "KillJob": lambda j: None,
            "Reset": lambda: None, "Shutdown": lambda: None,
        })
        self.worker_ids, self.round_duration = self._client.register_worker(
            "v5e", "127.0.0.1", worker_port, num_chips)

    def _run_job(self, jobs, worker_id, round_id):
        def execute():
            for j in jobs:
                it = self._iter_client(j["job_id"], worker_id,
                                       "localhost", self.sched_port)
                max_steps, _, _ = it.init()
            time.sleep(self.execution_time)
            steps = [min(int(self.throughput * self.round_duration),
                         j["num_steps"], int(max_steps)) for j in jobs]
            self._client.notify_done(
                [j["job_id"] for j in jobs], worker_id, steps,
                [self.execution_time] * len(jobs))
        threading.Thread(target=execute, daemon=True).start()

    def stop(self):
        self.server.stop(grace=0)


@pytest.mark.runtime
@pytest.mark.timeout(120)
class TestPhysicalObsLoopback:
    """Scrape /metrics and /healthz from a REAL loopback scheduler
    mid-run, then report on its exported trace — the acceptance drive
    for the endpoint and the round-phase spans."""

    def test_scrape_mid_run_and_report_after(self, tmp_path):
        from shockwave_tpu.sched.physical import PhysicalScheduler
        from shockwave_tpu.sched.scheduler import SchedulerConfig
        from shockwave_tpu.solver import get_policy
        sched_port, worker_port = free_port(), free_port()
        trace_path = str(tmp_path / "round_trace.json")
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(
                time_per_iteration=2.0, max_rounds=4,
                state_dir=str(tmp_path / "state"),
                snapshot_interval_rounds=2,
                obs_port=0, obs_trace_path=trace_path),
            expected_num_workers=2, port=sched_port)
        worker = _StubWorker(sched_port, worker_port, num_chips=2)
        base = f"http://127.0.0.1:{sched.obs_port}"
        try:
            for _ in range(2):
                sched.add_job(Job(
                    None, "ResNet-18 (batch size 32)",
                    "python3 main.py --batch_size 32",
                    "image_classification/cifar10", "--num_steps",
                    total_steps=600, duration=10000))
            runner = threading.Thread(target=sched.run, daemon=True)
            runner.start()

            # Mid-run scrape: poll until the first dispatch lands.
            deadline = time.time() + 30
            samples = {}
            while time.time() < deadline:
                with urllib.request.urlopen(base + "/metrics",
                                            timeout=5) as r:
                    samples = parse_prometheus(r.read().decode())
                if samples.get(("swtpu_dispatches_total",
                                frozenset({("outcome", "ok")})), 0) >= 1:
                    break
                time.sleep(0.2)
            assert samples.get(("swtpu_dispatches_total",
                                frozenset({("outcome", "ok")})), 0) >= 1
            # Journal fsync histogram is live (state_dir set).
            assert samples.get(("swtpu_journal_append_seconds_count",
                                frozenset({("sync", "true")})), 0) >= 1

            with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
                health = json.loads(r.read())
            assert health["status"] == "ok"
            assert health["live_workers"] == 2
            assert isinstance(health["round"], int)
            assert health["journal"]["last_seq"] >= 1
            assert isinstance(health["breakers"], dict)

            deadline = time.time() + 40
            while time.time() < deadline and len(sched._completed_jobs) < 2:
                time.sleep(0.2)
            assert len(sched._completed_jobs) == 2

            # Final scrape: solve-time histogram and phase histogram.
            with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
                samples = parse_prometheus(r.read().decode())
            assert samples.get(
                ("swtpu_allocation_solve_seconds_count",
                 frozenset({("policy", "MaxMinFairness")})), 0) >= 1
            assert samples.get(
                ("swtpu_round_phase_seconds_count",
                 frozenset({("phase", "solve")})), 0) >= 1
            assert samples[("swtpu_jobs_completed_total",
                            frozenset())] == 2.0
        finally:
            sched._done_event.set()
            worker.stop()
            sched.shutdown()
            sched._server.stop(grace=0)

        # Trace exported at shutdown; the report CLI digests it.
        assert os.path.exists(trace_path)
        span_names = {e["name"] for e in load_spans(trace_path)}
        for phase in (names.SPAN_SOLVE, names.SPAN_DISPATCH,
                      names.SPAN_WAIT, names.SPAN_END_ROUND,
                      names.SPAN_JOURNAL_FSYNC):
            assert phase in span_names, span_names
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.obs.report",
             trace_path], capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "journal-fsync" in out.stdout


class TestSimObsDeterminism:
    """Scheduling decisions are bit-identical with obs recording on and
    off: instrumentation observes, never steers."""

    def _run(self, monkeypatch, obs_value):
        from shockwave_tpu.sched.scheduler import (Scheduler,
                                                   SchedulerConfig)
        from shockwave_tpu.solver import get_policy
        monkeypatch.setenv("SWTPU_OBS", obs_value)
        jobs = [Job(None, "ResNet-18 (batch size 32)",
                    "python3 main.py --batch_size 32",
                    "image_classification/cifar10", "--num_steps",
                    total_steps=(i + 1) * 20000, duration=4000)
                for i in range(5)]
        arrivals = [i * 150.0 for i in range(5)]
        sched = Scheduler(
            get_policy("max_min_fairness", seed=0), simulate=True,
            throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=120.0))
        makespan = sched.simulate({"v100": 2}, arrivals, jobs)
        assert sched.obs.enabled == (obs_value == "1")
        return (makespan, sched.get_average_jct()[3],
                sched.rounds.per_round_schedule)

    def test_enabled_vs_disabled_bit_identical(self, monkeypatch):
        on = self._run(monkeypatch, "1")
        off = self._run(monkeypatch, "0")
        assert on == off


@pytest.mark.slow
class TestCanonicalObsDeterminism:
    """The canonical 120-job replay stays bit-identical (33207.58
    max_min makespan, exact JSON match with the recorded reproduce
    pickle) with obs instrumentation enabled vs. disabled."""

    def _simulate(self, obs_value):
        env = dict(os.environ, SWTPU_OBS=obs_value, JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts/drivers/simulate.py"),
             "--trace", os.path.join(DATA, "canonical_120job.trace"),
             "--policy", "max_min_fairness",
             "--throughputs", os.path.join(DATA, "tacc_throughputs.json"),
             "--cluster_spec", "v100:32", "--round_duration", "120"],
            capture_output=True, text=True, timeout=1800, env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        return json.loads(out.stdout.strip().splitlines()[-1])

    def test_canonical_replay_bit_identical(self):
        def strip_wall(summary):
            # sim_wall_s / sim_core_wall_s / milp_wall_s are wall-clock
            # telemetry (nondeterministic run to run by construction);
            # everything else in the summary must replay exactly.
            return {k: v for k, v in summary.items()
                    if not k.endswith("_wall_s")}
        enabled = strip_wall(self._simulate("1"))
        disabled = strip_wall(self._simulate("0"))
        assert enabled == disabled
        with open(os.path.join(REPO, "reproduce", "pickles",
                               "max_min_fairness.json")) as f:
            recorded = strip_wall(json.load(f))
        assert enabled == recorded
        assert enabled["makespan"] == 33207.58
