"""Shared plumbing for the swtpu-check passes: parsed-file index,
findings, and inline suppressions.

A finding is ``path:line: [pass-id] message`` — stable, greppable, and
what the tier-1 gate (tests/test_analysis.py) asserts against.

Inline suppression: a line (or the ``def`` line of a function, which
covers the whole function) may carry

    # swtpu-check: ignore[<pass-id>]            (one id)
    # swtpu-check: ignore[<pass-a>,<pass-b>]    (several)

Every suppression is an auditable exception to an invariant; the
comment should say why (e.g. "telemetry, not durable state"), and the
suppression-audit pass flags any that stop matching a real finding.
"""
from __future__ import annotations

import ast
import fnmatch
import os
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set

SUPPRESS_RE = re.compile(r"#\s*swtpu-check:\s*ignore\[([a-zA-Z0-9_,\- ]+)\]")


@dataclass(frozen=True)
class Finding:
    path: str       # repo-relative, forward slashes
    line: int
    pass_id: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"


class SourceFile:
    """One parsed module: AST plus per-line suppression sets."""

    def __init__(self, abs_path: str, rel_path: str, text: str):
        self.abs_path = abs_path
        self.rel = rel_path.replace(os.sep, "/")
        self.text = text
        self.tree = ast.parse(text, filename=rel_path)
        self.suppressions: Dict[int, Set[str]] = {}
        #: (line, pass_id) pairs a pass actually consulted AND matched:
        #: the suppression-audit pass flags declared suppressions that
        #: never land here (nothing would have fired on that line).
        self.suppression_hits: Set[tuple] = set()
        for lineno, line in enumerate(text.splitlines(), start=1):
            m = SUPPRESS_RE.search(line)
            if m:
                ids = {p.strip() for p in m.group(1).split(",") if p.strip()}
                self.suppressions[lineno] = ids

    def suppressed(self, line: int, pass_id: str) -> bool:
        ids = self.suppressions.get(line)
        hit = ids is not None and pass_id in ids
        if hit:
            self.suppression_hits.add((line, pass_id))
        return hit

    def matches(self, globs: Iterable[str]) -> bool:
        return any(fnmatch.fnmatch(self.rel, g) for g in globs)


class RepoIndex:
    """The set of files one analyzer run looks at.

    Every pass shares ONE index (files parsed once); the concurrency
    passes additionally share one call graph (`call_graph` memoizes).
    """

    def __init__(self, files: List[SourceFile], root: str):
        self.files = files
        self.root = root
        self._call_graph = None
        #: (serve-funcs, callback-kwargs) -> (roots, findings); see
        #: threads.discover_thread_roots.
        self._thread_roots_memo: Dict[tuple, tuple] = {}

    def call_graph(self):
        """The shared static call graph (analysis/threads.py), built on
        first use and reused by every concurrency pass in this run."""
        if self._call_graph is None:
            from .threads import CallGraph
            self._call_graph = CallGraph(self)
        return self._call_graph

    def reset_suppression_hits(self) -> None:
        """Forget which suppressions fired (a cached index is reused
        across analyzer runs; the audit must see only this run). Also
        drops the thread-roots discovery memo — its findings consult
        suppressions, so a new run must re-record the hits."""
        for src in self.files:
            src.suppression_hits.clear()
        self._thread_roots_memo = {}

    @classmethod
    def from_root(cls, root: str,
                  include_dirs: Optional[Iterable[str]] = None,
                  exclude_globs: Iterable[str] = ()) -> "RepoIndex":
        """Index every .py file under `root` (restricted to
        `include_dirs`, repo-relative, when given). A file that does
        not parse becomes a hard error — the analyzer must never
        silently skip code."""
        root = os.path.abspath(root)
        files: List[SourceFile] = []
        for rel, abs_path in iter_py_files(root, include_dirs,
                                           exclude_globs):
            with open(abs_path, encoding="utf-8") as f:
                text = f.read()
            files.append(SourceFile(abs_path, rel, text))
        return cls(files, root)


def iter_py_files(root: str, include_dirs: Optional[Iterable[str]],
                  exclude_globs: Iterable[str]):
    """The ONE directory walk behind both the index build and the
    cache-validation signature: (rel, abs) pairs of every .py file
    under `root` (restricted to `include_dirs` when given), pruning
    __pycache__/.git and applying `exclude_globs`. Keeping a single
    walk guarantees the signature covers exactly the files the index
    parses."""
    bases = ([os.path.join(root, d) for d in include_dirs]
             if include_dirs else [root])
    for base in bases:
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                abs_path = os.path.join(dirpath, name)
                rel = os.path.relpath(abs_path, root).replace(os.sep, "/")
                if any(fnmatch.fnmatch(rel, g) for g in exclude_globs):
                    continue
                yield rel, abs_path


#: Process-wide index cache: (root, include, exclude) -> (signature,
#: RepoIndex). The signature is every file's (path, mtime_ns, size);
#: any change rebuilds. Saves re-parsing ~180 modules when the CLI and
#: the tier-1 gate run the analyzer repeatedly in one process.
_INDEX_CACHE: Dict[tuple, tuple] = {}


def _tree_signature(root: str, include_dirs, exclude_globs) -> tuple:
    sig = []
    for rel, abs_path in iter_py_files(root, include_dirs, exclude_globs):
        st = os.stat(abs_path)
        sig.append((rel, st.st_mtime_ns, st.st_size))
    return tuple(sig)


def cached_index(root: str,
                 include_dirs: Optional[Iterable[str]] = None,
                 exclude_globs: Iterable[str] = ()) -> RepoIndex:
    """`RepoIndex.from_root` behind an mtime/size-validated cache: the
    parsed AST table (and the call graph hanging off it) is shared
    across analyzer runs in one process, rebuilt the moment any indexed
    file changes on disk."""
    root = os.path.abspath(root)
    include = tuple(include_dirs) if include_dirs else None
    exclude = tuple(exclude_globs)
    key = (root, include, exclude)
    sig = _tree_signature(root, include, exclude)
    cached = _INDEX_CACHE.get(key)
    if cached is not None and cached[0] == sig:
        return cached[1]
    index = RepoIndex.from_root(root, include_dirs=include,
                                exclude_globs=exclude)
    _INDEX_CACHE[key] = (sig, index)
    return index


def finding(src: SourceFile, node_or_line, pass_id: str,
            message: str) -> Optional[Finding]:
    """Build a Finding unless the line (or the enclosing suppression
    line passed by the caller) suppresses this pass."""
    line = (node_or_line if isinstance(node_or_line, int)
            else node_or_line.lineno)
    if src.suppressed(line, pass_id):
        return None
    return Finding(src.rel, line, pass_id, message)


# ----------------------------------------------------------------------
# Small AST helpers shared by the passes
# ----------------------------------------------------------------------

def is_self_attr(node: ast.AST, attr: Optional[str] = None) -> bool:
    """`self.<attr>` (any attribute when attr is None)."""
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and (attr is None or node.attr == attr))


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target ("os.replace", "open", "self._emit");
    empty string for anything fancier (subscripts, calls of calls)."""
    parts: List[str] = []
    cur = node.func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif not parts:
        return ""
    else:
        parts.append("?")
    return ".".join(reversed(parts))


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def literal_str_set(node: ast.AST) -> Optional[Set[str]]:
    """Evaluate `frozenset({...})` / set / tuple / list of string
    literals; None when the node is anything else."""
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id in ("frozenset", "set") and node.args):
        return literal_str_set(node.args[0])
    if isinstance(node, (ast.Set, ast.Tuple, ast.List)):
        out: Set[str] = set()
        for elt in node.elts:
            value = const_str(elt)
            if value is None:
                return None
            out.add(value)
        return out
    return None


def decorated_requires_lock(fn: ast.AST) -> bool:
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    for dec in fn.decorator_list:
        if isinstance(dec, ast.Name) and dec.id == "requires_lock":
            return True
        if isinstance(dec, ast.Attribute) and dec.attr == "requires_lock":
            return True
    return False
