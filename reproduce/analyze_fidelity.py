#!/usr/bin/env python3
"""Simulation-fidelity analysis: physical run vs simulation, same trace.

Compares the metric pickles of a physical run (run_physical.py) and a
simulation (simulate.py) of the same trace + policy and reports the
relative deltas of makespan, average JCT, and unfair-job fraction — the
paper's Table 3 methodology (reference: reproduce/analyze_fidelity.py:20-56).

Usage:
    python reproduce/analyze_fidelity.py physical.pkl simulated.pkl \
        [--tolerance 0.1]
Exit code 1 if any delta exceeds --tolerance.
"""
import argparse
import json
import os
import pickle
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from shockwave_tpu.core.metrics import unfair_fraction as _unfair_list


def unfair_fraction(metrics: dict) -> float:
    return _unfair_list(metrics.get("finish_time_fairness_list") or [])


def rel_delta(physical: float, simulated: float) -> float:
    if physical == 0:
        return 0.0 if simulated == 0 else float("inf")
    return abs(physical - simulated) / abs(physical)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("physical_pickle")
    p.add_argument("simulated_pickle")
    p.add_argument("--tolerance", type=float, default=0.10,
                   help="max relative delta before flagging (paper reports "
                        "single-digit-percent fidelity)")
    args = p.parse_args()

    with open(args.physical_pickle, "rb") as f:
        phys = pickle.load(f)
    with open(args.simulated_pickle, "rb") as f:
        sim = pickle.load(f)

    deltas = {
        "makespan": rel_delta(phys["makespan"], sim["makespan"]),
        "avg_jct": rel_delta(phys.get("avg_jct") or 0.0,
                             sim.get("avg_jct") or 0.0),
        "unfair_fraction": abs(unfair_fraction(phys) - unfair_fraction(sim)),
    }
    report = {
        "physical": {"makespan": phys["makespan"],
                     "avg_jct": phys.get("avg_jct"),
                     "unfair_fraction": unfair_fraction(phys)},
        "simulated": {"makespan": sim["makespan"],
                      "avg_jct": sim.get("avg_jct"),
                      "unfair_fraction": unfair_fraction(sim)},
        "relative_deltas": {k: round(v, 4) for k, v in deltas.items()},
        "tolerance": args.tolerance,
    }
    print(json.dumps(report, indent=1))
    if max(deltas.values()) > args.tolerance:
        print("FIDELITY CHECK FAILED", file=sys.stderr)
        sys.exit(1)
    print("fidelity within tolerance")


if __name__ == "__main__":
    main()
