"""gRPC servers for both ends of the control plane.

- `serve_scheduler`: hosts WorkerToScheduler + IteratorToScheduler on the
  scheduler (reference: runtime/rpc/scheduler_server.py).
- `serve_worker`: hosts SchedulerToWorker on each worker daemon
  (reference: runtime/rpc/worker_server.py).

Callback dicts carry plain-Python payloads; proto (de)serialization stays
inside this module.
"""
from __future__ import annotations

import logging
import socket
from concurrent import futures
from typing import Callable, Dict

import grpc

from ..core.job import JobIdPair
from ..obs import get_observability
from ..obs import names as obs_names
from .proto import control_pb2 as pb
from .resilience import EPOCH_ADVANCED, EPOCH_METADATA_KEY, EPOCH_STALE
from .rpc import generic_handler

logger = logging.getLogger("shockwave_tpu.runtime")


def _metadata_epoch(context) -> int | None:
    """The sender's leader epoch from invocation metadata, or None when
    absent (HA disabled — every RPC passes unfenced)."""
    for key, value in (context.invocation_metadata() or ()):
        if key == EPOCH_METADATA_KEY:
            try:
                return int(value)
            except ValueError:
                return None
    return None


def _fenced(fn, fence, on_epoch_advance=None):
    """Wrap a dispatch-effecting worker handler with the epoch fence:
    a stale leader epoch is REJECTED (FAILED_PRECONDITION — the deposed
    leader treats it as its own fencing signal), an advanced one is
    adopted (and the observer re-resolves its scheduler endpoint /
    resets breakers before the new leader's work runs)."""

    def handler(request, context):
        epoch = _metadata_epoch(context)
        if epoch is not None:
            verdict = fence.observe(epoch)
            if verdict == EPOCH_STALE:
                get_observability().inc(obs_names.HA_FENCED_RPCS_TOTAL,
                                        side="worker")
                logger.warning(
                    "rejecting RPC from stale leader epoch %d (current "
                    "epoch %d)", epoch, fence.epoch)
                context.abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    f"stale leader epoch {epoch} (worker has seen "
                    f"{fence.epoch}); you have been superseded")
            if verdict == EPOCH_ADVANCED and on_epoch_advance is not None:
                try:
                    on_epoch_advance(epoch)
                except Exception:  # noqa: BLE001 - the refresh is an
                    # optimization; the RPC itself must still run
                    logger.exception("epoch-advance callback failed")
        return fn(request, context)
    return handler


def get_host_ip() -> str:
    try:
        return socket.gethostbyname(socket.gethostname())
    except socket.gaierror:
        return "127.0.0.1"


def serve_scheduler(port: int, callbacks: Dict[str, Callable],
                    max_workers: int = 32,
                    fenced_check: Callable[[], bool] = None) -> grpc.Server:
    """Start the scheduler-side server (non-blocking); returns the server.

    `fenced_check` (control-plane HA): when it returns True, every
    handler aborts with FAILED_PRECONDITION before touching scheduler
    state — a fenced ex-leader must refuse reports rather than swallow
    them, so workers re-resolve the endpoint and deliver to the real
    leader instead."""

    def _guard(fn):
        if fenced_check is None:
            return fn

        def handler(request, context):
            if fenced_check():
                get_observability().inc(obs_names.HA_FENCED_RPCS_TOTAL,
                                        side="scheduler")
                context.abort(
                    grpc.StatusCode.FAILED_PRECONDITION,
                    "leader fenced: a higher epoch was claimed; "
                    "re-resolve the scheduler endpoint")
            return fn(request, context)
        return handler

    def register_worker(request, context):
        try:
            worker_ids, round_duration = callbacks["RegisterWorker"](
                worker_type=request.worker_type,
                num_chips=request.num_chips,
                ip_addr=request.ip_addr,
                port=request.port)
            return pb.RegisterWorkerResponse(
                success=True, worker_ids=worker_ids,
                round_duration=round_duration)
        except Exception as e:  # noqa: BLE001 - reported to the caller
            logger.exception("RegisterWorker failed")
            return pb.RegisterWorkerResponse(success=False, error_message=str(e))

    def done(request, context):
        job_id = JobIdPair(*(list(request.job_ids) + [None])[:2])
        callbacks["Done"](job_id, request.worker_id,
                          list(request.num_steps),
                          list(request.execution_times),
                          list(request.iterator_logs) or None)
        return pb.Empty()

    def init_job(request, context):
        max_steps, max_duration, extra_time = callbacks["InitJob"](
            JobIdPair(request.job_id))
        return pb.InitJobResponse(max_steps=int(max_steps),
                                  max_duration=max_duration,
                                  extra_time=extra_time)

    # Measured-serving telemetry rides the renewal heartbeat
    # (UpdateLeaseRequest.measured_reports); handlers that predate the
    # field (test stubs, chaos stubs) keep their 6-arg signature.
    import inspect
    try:
        _ul_params = inspect.signature(callbacks["UpdateLease"]).parameters
        update_lease_takes_reports = ("measured_reports" in _ul_params
                                      or any(
                                          p.kind is inspect.Parameter.VAR_KEYWORD
                                          for p in _ul_params.values()))
    except (KeyError, TypeError, ValueError):
        update_lease_takes_reports = False

    def update_lease(request, context):
        kwargs = {}
        if update_lease_takes_reports and request.measured_reports:
            kwargs["measured_reports"] = list(request.measured_reports)
        max_steps, max_duration, run_time_so_far, deadline = callbacks["UpdateLease"](
            JobIdPair(request.job_id), request.worker_id, request.steps,
            request.duration, request.max_steps, request.max_duration,
            **kwargs)
        return pb.UpdateLeaseResponse(
            max_steps=int(max_steps), max_duration=float(max_duration),
            run_time_so_far=float(run_time_so_far), deadline=float(deadline))

    def update_resource_requirement(request, context):
        callbacks["UpdateResourceRequirement"](
            JobIdPair(request.job_id), request.worker_id,
            request.big_bs, request.small_bs)
        return pb.Empty()

    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((
        generic_handler("shockwave_tpu.WorkerToScheduler", {
            "RegisterWorker": _guard(register_worker),
            "Done": _guard(done),
        }),
        generic_handler("shockwave_tpu.IteratorToScheduler", {
            "InitJob": _guard(init_job),
            "UpdateLease": _guard(update_lease),
            "UpdateResourceRequirement": _guard(update_resource_requirement),
        }),
    ))
    server.add_insecure_port(f"[::]:{port}")
    server.start()
    logger.info("scheduler control server listening on %d", port)
    return server


def serve_worker(port: int, callbacks: Dict[str, Callable],
                 max_workers: int = 16, fence=None,
                 on_epoch_advance: Callable[[int], None] = None
                 ) -> grpc.Server:
    """Start the worker-side server (non-blocking); returns the server.

    With a `fence` (resilience.EpochFence), every dispatch-effecting
    handler (RunJob / KillJob / Reset / Shutdown) rejects RPCs carrying
    a leader epoch lower than the highest this worker has seen —
    fencing a deposed leader out of double-dispatching. Ping stays
    unfenced: liveness probes must answer whoever asks (a fenced old
    leader probing the fleet is harmless; a standby probing before its
    first dispatch is essential)."""

    # Fleet tracing: hand the propagated span context (traceparent +
    # sender send-timestamp metadata) to callbacks that accept it; the
    # legacy 3-arg signature (test stubs, chaos stubs) stays untouched.
    import inspect
    try:
        params = inspect.signature(callbacks["RunJob"]).parameters
        run_job_takes_trace = ("trace" in params or any(
            p.kind is inspect.Parameter.VAR_KEYWORD
            for p in params.values()))
    except (TypeError, ValueError):
        run_job_takes_trace = False

    def run_job(request, context):
        jobs = [
            dict(job_id=j.job_id, command=j.command,
                 working_directory=j.working_directory,
                 needs_data_dir=j.needs_data_dir,
                 num_steps_arg=j.num_steps_arg, num_steps=j.num_steps,
                 mode=j.mode)
            for j in request.jobs
        ]
        if run_job_takes_trace:
            from ..obs.propagation import from_rpc_metadata
            trace = from_rpc_metadata(context.invocation_metadata())
            callbacks["RunJob"](jobs, request.worker_id,
                                request.round_id, trace=trace)
        else:
            callbacks["RunJob"](jobs, request.worker_id, request.round_id)
        return pb.Empty()

    def kill_job(request, context):
        callbacks["KillJob"](request.job_id)
        return pb.Empty()

    def reset(request, context):
        callbacks["Reset"]()
        return pb.Empty()

    def shutdown(request, context):
        callbacks["Shutdown"]()
        return pb.Empty()

    def ping(request, context):
        # Liveness probe: answering at all is the signal. An optional
        # callback lets the daemon surface health state in the future.
        cb = callbacks.get("Ping")
        if cb is not None:
            cb()
        return pb.Empty()

    guard = ((lambda fn: _fenced(fn, fence, on_epoch_advance))
             if fence is not None else (lambda fn: fn))
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers((
        generic_handler("shockwave_tpu.SchedulerToWorker", {
            "RunJob": guard(run_job),
            "KillJob": guard(kill_job),
            "Reset": guard(reset),
            "Shutdown": guard(shutdown),
            "Ping": ping,
        }),
    ))
    server.add_insecure_port(f"[::]:{port}")
    server.start()
    logger.info("worker control server listening on %d", port)
    return server
