"""Policy factory: name -> policy instance
(reference: scheduler/utils.py:603-685)."""
from __future__ import annotations

from typing import Optional

from .allox import AlloXPolicy
from .fifo import FIFOPolicy, FIFOPolicyWithPacking, FIFOPolicyWithPerf
from .finish_time_fairness import (FinishTimeFairnessPolicy,
                                   FinishTimeFairnessPolicyWithPacking,
                                   FinishTimeFairnessPolicyWithPerf)
from .gandiva import GandivaPolicy
from .max_min_fairness import (MaxMinFairnessPolicy,
                               MaxMinFairnessPolicyWithPacking,
                               MaxMinFairnessPolicyWithPerf,
                               MaxMinFairnessStrategyProofPolicy)
from .max_sum_throughput import (ThroughputNormalizedByCostSumWithPerf,
                                 ThroughputNormalizedByCostSumWithPerfSLOs,
                                 ThroughputSumWithPerf)
from .min_total_duration import (MinTotalDurationPolicy,
                                 MinTotalDurationPolicyWithPacking,
                                 MinTotalDurationPolicyWithPerf)
from .simple import (GandivaFairPolicy, IsolatedPlusPolicy, IsolatedPolicy,
                     ProportionalPolicy)
from .water_filling import (MaxMinFairnessWaterFillingPolicy,
                            MaxMinFairnessWaterFillingPolicyWithPacking,
                            MaxMinFairnessWaterFillingPolicyWithPerf)


class ShockwavePolicy:
    """Marker policy: scheduling decisions come from the Shockwave planner,
    not a time-fraction LP (reference: policies/shockwave.py)."""

    name = "shockwave"

    def get_allocation(self, *args, **kwargs):
        return None


def get_policy(policy_name: str, solver: Optional[str] = None,
               seed: Optional[int] = None,
               priority_reweighting_policies=None):
    if policy_name.startswith("allox"):
        alpha = 0.2 if policy_name == "allox" else float(
            policy_name.split("allox_alpha=")[1])
        return AlloXPolicy(alpha=alpha)
    factories = {
        "fifo": lambda: FIFOPolicy(seed=seed),
        "fifo_perf": FIFOPolicyWithPerf,
        "fifo_packed": FIFOPolicyWithPacking,
        "finish_time_fairness": FinishTimeFairnessPolicy,
        "finish_time_fairness_perf": FinishTimeFairnessPolicyWithPerf,
        "finish_time_fairness_packed": FinishTimeFairnessPolicyWithPacking,
        "gandiva": lambda: GandivaPolicy(seed=seed),
        "gandiva_fair": GandivaFairPolicy,
        "isolated": IsolatedPolicy,
        "isolated_plus": IsolatedPlusPolicy,
        "max_min_fairness": MaxMinFairnessPolicy,
        "max_min_fairness_perf": MaxMinFairnessPolicyWithPerf,
        "max_min_fairness_packed": MaxMinFairnessPolicyWithPacking,
        "max_min_fairness_strategy_proof": MaxMinFairnessStrategyProofPolicy,
        "max_min_fairness_water_filling": lambda: MaxMinFairnessWaterFillingPolicy(
            priority_reweighting_policies),
        "max_min_fairness_water_filling_perf": lambda: MaxMinFairnessWaterFillingPolicyWithPerf(
            priority_reweighting_policies),
        "max_min_fairness_water_filling_packed": lambda: MaxMinFairnessWaterFillingPolicyWithPacking(
            priority_reweighting_policies),
        "max_sum_throughput_perf": ThroughputSumWithPerf,
        "max_sum_throughput_normalized_by_cost_perf": ThroughputNormalizedByCostSumWithPerf,
        "max_sum_throughput_normalized_by_cost_perf_SLOs": ThroughputNormalizedByCostSumWithPerfSLOs,
        "min_total_duration": MinTotalDurationPolicy,
        "min_total_duration_perf": MinTotalDurationPolicyWithPerf,
        "min_total_duration_packed": MinTotalDurationPolicyWithPacking,
        "proportional": ProportionalPolicy,
        "shockwave": ShockwavePolicy,
    }
    try:
        return factories[policy_name]()
    except KeyError:
        raise ValueError(f"unknown policy {policy_name!r}") from None
