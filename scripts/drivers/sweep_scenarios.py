#!/usr/bin/env python3
"""Parallel Monte Carlo scenario sweep over the discrete-event simulator.

Fans seeded what-if perturbations of a base trace across a process
pool, streams per-scenario results incrementally into ONE resumable
JSON artifact, and aggregates distributional statistics — the
capacity-planning harness the serving tier, the 10k-job planner arc and
the learned throughput oracle all consume (ROADMAP item 4).

Scenario perturbations (each drawn from the scenario's own seeded RNG,
so the same seed always produces the same scenario):

- ``--subsample lo:hi``       keep a uniform random fraction of the
                              trace's jobs (arrival order preserved)
- ``--load_scale lo:hi``      compress/stretch arrival times by a
                              uniform factor (>1 = more load)
- ``--arrival_jitter_s S``    add N(0, S) seconds to each arrival
                              (clamped at 0, then re-sorted)
- ``--fault_rate R``          Poisson(R) chip-failure events per
                              scenario, injected through the
                              simulator's fault hook (the sim-side
                              analog of runtime/faults.py): each kills
                              1..--fault_max_chips chips of one worker
                              type at a uniform time in
                              [0, --fault_window_s) and revives them
                              --fault_down_s later
- ``--degrade_rate R``        Poisson(R) GRAY-failure events per
                              scenario: each degrades
                              1..--fault_max_chips chips of one worker
                              type to a uniform factor in
                              --degrade_factor of oracle speed (the
                              simulator's `degrade` fault event — the
                              chips stay in capacity, just slow) and
                              restores them --degrade_down_s later
- ``--serving_spike_seeds``   redraw each serving service's spike seed
                              (load-curve variation for mixed traces)

Crash safety / resume: the artifact is atomically rewritten after every
completed scenario (core/durable_io.write_text_atomic), scenarios are
keyed by seed, and a rerun skips seeds already present (meta mismatch
is an error unless --restart). Identical seeds and knobs produce a
byte-equal artifact: all wall-clock telemetry stays OUT of the artifact
(stdout/--timing_out only), and aggregation is computed from the
seed-sorted scenario set.

Example (the CI smoke):
    python scripts/drivers/sweep_scenarios.py \
        --trace data/canonical_120job.trace --policy max_min_fairness \
        --throughputs data/tacc_throughputs.json --cluster_spec v100:32 \
        --round_duration 120 --num_scenarios 8 --subsample 0.2:0.4 \
        --load_scale 0.8:1.3 --arrival_jitter_s 600 --fault_rate 1 \
        --out /tmp/sweep.json
"""
import argparse
import json
import multiprocessing
import os
import re
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import driver_common  # noqa: E402
from shockwave_tpu.core.durable_io import write_text_atomic  # noqa: E402
from shockwave_tpu.core.metrics import parse_cluster_spec  # noqa: E402
from shockwave_tpu.core.oracle import read_throughputs  # noqa: E402
from shockwave_tpu.core.profiles import build_profiles  # noqa: E402
from shockwave_tpu.core.trace import parse_trace  # noqa: E402
from shockwave_tpu.obs import get_observability  # noqa: E402
from shockwave_tpu.obs import names as obs_names  # noqa: E402
from shockwave_tpu.obs.logconfig import setup_logging  # noqa: E402

ARTIFACT_SCHEMA = 1
#: Summary keys whose quantiles the aggregate reports (serving
#: attainment joins when any scenario carries it).
AGGREGATE_KEYS = ("makespan", "avg_jct", "unfair_fraction",
                  "cluster_util", "rounds")


def parse_range(spec, name):
    """'lo:hi' -> (lo, hi) floats, or None for an unset knob."""
    if spec is None:
        return None
    try:
        lo, hi = (float(x) for x in spec.split(":"))
    except ValueError:
        raise SystemExit(f"--{name} wants lo:hi, got {spec!r}") from None
    if hi < lo:
        raise SystemExit(f"--{name}: hi < lo in {spec!r}")
    return (lo, hi)


chip_layout = driver_common.chip_layout


def draw_scenario(rng, jobs, arrivals, knobs, cluster_spec):
    """Apply the seeded perturbations. Returns (jobs, arrivals,
    fault_events, params) — params records what was drawn so the
    artifact is self-describing. Draw order is part of the scenario
    contract (changing it changes every seeded scenario)."""
    params = {}

    subsample = knobs.get("subsample")
    if subsample is not None:
        frac = float(rng.uniform(subsample[0], subsample[1]))
        keep = max(1, int(round(frac * len(jobs))))
        idx = sorted(int(i) for i in rng.choice(len(jobs), size=keep,
                                                replace=False))
        jobs = [jobs[i] for i in idx]
        arrivals = [arrivals[i] for i in idx]
        params["subsample_fraction"] = round(frac, 6)
        params["num_jobs"] = keep

    load_scale = knobs.get("load_scale")
    if load_scale is not None:
        factor = float(rng.uniform(load_scale[0], load_scale[1]))
        arrivals = [a / factor for a in arrivals]
        params["load_scale"] = round(factor, 6)

    jitter = knobs.get("arrival_jitter_s", 0.0)
    if jitter > 0:
        noise = rng.normal(0.0, jitter, size=len(arrivals))
        arrivals = [max(0.0, a + float(n)) for a, n in zip(arrivals, noise)]
        params["arrival_jitter_s"] = jitter

    # Admission is gated on the head arrival (ids follow file order), so
    # perturbed traces are re-sorted; python sort is stable, preserving
    # file order among equal arrivals.
    order = sorted(range(len(jobs)), key=lambda i: arrivals[i])
    jobs = [jobs[i] for i in order]
    arrivals = [arrivals[i] for i in order]

    if knobs.get("serving_spike_seeds"):
        respiked = 0
        for job in jobs:
            if job.mode == "serving" and "--spike_seed" in job.command:
                new_seed = int(rng.randint(0, 2**31 - 1))
                job.command = re.sub(r"--spike_seed \d+",
                                     f"--spike_seed {new_seed}", job.command)
                respiked += 1
        params["serving_respiked"] = respiked

    fault_events = []
    fault_rate = knobs.get("fault_rate", 0.0)
    if fault_rate > 0:
        layout = chip_layout(cluster_spec)
        types = sorted(layout)
        for _ in range(int(rng.poisson(fault_rate))):
            wt = types[int(rng.randint(len(types)))]
            k = min(int(rng.randint(1, knobs["fault_max_chips"] + 1)),
                    len(layout[wt]))
            ids = sorted(int(i) for i in rng.choice(layout[wt], size=k,
                                                    replace=False))
            at = float(rng.uniform(0.0, knobs["fault_window_s"]))
            fault_events.append({"at": round(at, 3), "kill": ids})
            fault_events.append({"at": round(at + knobs["fault_down_s"], 3),
                                 "revive": ids, "worker_type": wt})
        params["fault_events"] = sum(1 for e in fault_events if "kill" in e)

    # Gray failures: degrade events ride the same queue. Drawn AFTER
    # the kill events (draw order is the scenario contract), so
    # degrade_rate=0 — every pre-existing sweep config — reproduces the
    # exact historical scenarios.
    degrade_rate = knobs.get("degrade_rate", 0.0)
    if degrade_rate > 0:
        layout = chip_layout(cluster_spec)
        types = sorted(layout)
        lo, hi = knobs.get("degrade_factor") or (0.05, 0.5)
        for _ in range(int(rng.poisson(degrade_rate))):
            wt = types[int(rng.randint(len(types)))]
            k = min(int(rng.randint(1, knobs["fault_max_chips"] + 1)),
                    len(layout[wt]))
            ids = sorted(int(i) for i in rng.choice(layout[wt], size=k,
                                                    replace=False))
            factor = round(float(rng.uniform(lo, hi)), 6)
            at = float(rng.uniform(0.0, knobs["fault_window_s"]))
            fault_events.append({"at": round(at, 3), "degrade": ids,
                                 "factor": factor})
            fault_events.append(
                {"at": round(at + knobs["degrade_down_s"], 3),
                 "restore": ids})
        params["degrade_events"] = sum(1 for e in fault_events
                                       if "degrade" in e)

    fault_events.sort(key=lambda e: e["at"])
    return jobs, arrivals, fault_events, params


def draw_state_faults(rng, twin, knobs, now):
    """Seeded fault/degrade events for a mid-run twin, targeting the
    chips the restored cluster actually holds (dead ones excluded) and
    offset from the twin's frozen clock. Draw order is the scenario
    contract, mirroring draw_scenario's kill-then-degrade order."""
    layout = {wt: [w for server in servers for w in server]
              for wt, servers in twin.workers.type_to_server_ids.items()}
    layout = {wt: ids for wt, ids in layout.items() if ids}
    params = {}
    fault_events = []
    types = sorted(layout)
    if not types:
        return fault_events, params
    fault_rate = knobs.get("fault_rate", 0.0)
    if fault_rate > 0:
        for _ in range(int(rng.poisson(fault_rate))):
            wt = types[int(rng.randint(len(types)))]
            k = min(int(rng.randint(1, knobs["fault_max_chips"] + 1)),
                    len(layout[wt]))
            ids = sorted(int(i) for i in rng.choice(layout[wt], size=k,
                                                    replace=False))
            at = now + float(rng.uniform(0.0, knobs["fault_window_s"]))
            fault_events.append({"at": round(at, 3), "kill": ids})
            fault_events.append(
                {"at": round(at + knobs["fault_down_s"], 3),
                 "revive": ids, "worker_type": wt})
        params["fault_events"] = sum(1 for e in fault_events
                                     if "kill" in e)
    degrade_rate = knobs.get("degrade_rate", 0.0)
    if degrade_rate > 0:
        lo, hi = knobs.get("degrade_factor") or (0.05, 0.5)
        for _ in range(int(rng.poisson(degrade_rate))):
            wt = types[int(rng.randint(len(types)))]
            k = min(int(rng.randint(1, knobs["fault_max_chips"] + 1)),
                    len(layout[wt]))
            ids = sorted(int(i) for i in rng.choice(layout[wt], size=k,
                                                    replace=False))
            factor = round(float(rng.uniform(lo, hi)), 6)
            at = now + float(rng.uniform(0.0, knobs["fault_window_s"]))
            fault_events.append({"at": round(at, 3), "degrade": ids,
                                 "factor": factor})
            fault_events.append(
                {"at": round(at + knobs["degrade_down_s"], 3),
                 "restore": ids})
        params["degrade_events"] = sum(1 for e in fault_events
                                       if "degrade" in e)
    fault_events.sort(key=lambda e: e["at"])
    return fault_events, params


def run_state_scenario(seed_index, cfg):
    """One --from_state scenario: restore the journaled mid-run
    snapshot through the what-if fork loader, perturb with seeded
    fault/degrade events, roll the admitted workload to drain."""
    import random as _random

    from shockwave_tpu.sched import SchedulerConfig
    from shockwave_tpu.solver import get_policy
    from shockwave_tpu.whatif import fork as whatif_fork

    seed = cfg["seed_base"] + seed_index
    rng = np.random.RandomState(seed)
    jobs, _ = parse_trace(cfg["trace"])
    cluster_spec = parse_cluster_spec(cfg["cluster_spec"])
    throughputs = read_throughputs(cfg["throughputs"])
    profiles = build_profiles(jobs, throughputs)
    shockwave_config, serving_config, _, _ = (
        driver_common.load_configs(cfg["config"], cfg["policy"],
                                   cluster_spec, cfg["round_duration"]))
    config = SchedulerConfig(
        time_per_iteration=cfg["round_duration"], seed=seed,
        shockwave=shockwave_config, serving=serving_config,
        vectorized_sim=not cfg["scalar_sim"])
    twin, queued, running, remaining = whatif_fork.load_twin(
        cfg["from_state"], get_policy(cfg["policy"], seed=seed),
        profiles, config, throughputs_file=cfg["throughputs"])
    if cfg["max_rounds"] is not None:
        twin._config.max_rounds = cfg["max_rounds"]
    now = twin.get_current_timestamp()
    fault_events, params = draw_state_faults(rng, twin, cfg["knobs"], now)
    # Scenario axis beyond faults: reseeded scheduling tie-breaks.
    twin._rng = np.random.RandomState(seed)
    twin._worker_type_shuffler = _random.Random(seed + 5)
    params["from_round"] = twin.rounds.num_completed_rounds
    params["active_jobs"] = len(twin.acct.jobs)
    makespan = whatif_fork.rollforward(
        twin, queued=queued, running=running, remaining_jobs=remaining,
        fault_events=fault_events)
    return twin, makespan, params


def run_scenario(payload):
    """Process-pool worker: one seeded scenario end to end. Returns
    (seed_index, record) where record is fully deterministic (no wall
    telemetry)."""
    seed_index, cfg = payload
    import time as _time
    # Worker-side wall telemetry (returned beside the record, never in
    # it — the artifact stays byte-deterministic).
    _t0 = _time.monotonic()  # swtpu-check: ignore[determinism]
    try:
        if cfg.get("from_state"):
            sched, makespan, params = run_state_scenario(seed_index, cfg)
        else:
            rng = np.random.RandomState(cfg["seed_base"] + seed_index)
            jobs, arrivals = parse_trace(cfg["trace"])
            cluster_spec = parse_cluster_spec(cfg["cluster_spec"])
            jobs, arrivals, fault_events, params = draw_scenario(
                rng, jobs, arrivals, cfg["knobs"], cluster_spec)

            throughputs = read_throughputs(cfg["throughputs"])
            profiles = build_profiles(jobs, throughputs)
            shockwave_config, serving_config, whatif_config, _ = (
                driver_common.load_configs(cfg["config"], cfg["policy"],
                                           cluster_spec,
                                           cfg["round_duration"]))
            sched = driver_common.build_scheduler(
                cfg["policy"], cfg["throughputs"], profiles,
                round_duration=cfg["round_duration"],
                seed=cfg["seed_base"] + seed_index,
                max_rounds=cfg["max_rounds"],
                shockwave_config=shockwave_config,
                serving_config=serving_config,
                whatif_config=whatif_config,
                vectorized=not cfg["scalar_sim"])
            makespan = sched.simulate(cluster_spec, arrivals, jobs,
                                      fault_events=fault_events)
        metrics = driver_common.collect_metrics(sched, makespan,
                                                cfg["round_duration"],
                                                cfg["policy"])
        summary = driver_common.summary_core(metrics, sched)
        milp = driver_common.milp_summary(metrics["milp_solve_stats"])
        milp.pop("milp_wall_s", None)  # wall telemetry stays out
        summary.update(milp)
        summary["completed_jobs"] = sched.get_num_completed_jobs()
        wall = _time.monotonic() - _t0  # swtpu-check: ignore[determinism]
        return seed_index, {"seed": cfg["seed_base"] + seed_index,
                            "params": params, "summary": summary}, wall
    except Exception as e:  # noqa: BLE001 - one bad scenario must not
        # sink a multi-hour sweep; the error lands in the artifact.
        wall = _time.monotonic() - _t0  # swtpu-check: ignore[determinism]
        return seed_index, {"seed": cfg["seed_base"] + seed_index,
                            "error": f"{type(e).__name__}: {e}"}, wall


def quantile_stats(values):
    arr = np.asarray(sorted(values), dtype=np.float64)
    return {
        "mean": round(float(arr.mean()), 4),
        "min": round(float(arr[0]), 4),
        "p10": round(float(np.percentile(arr, 10)), 4),
        "p50": round(float(np.percentile(arr, 50)), 4),
        "p90": round(float(np.percentile(arr, 90)), 4),
        "p99": round(float(np.percentile(arr, 99)), 4),
        "max": round(float(arr[-1]), 4),
        "n": int(arr.size),
    }


def aggregate(scenarios):
    """Distributional stats over the seed-sorted completed scenarios."""
    ok = [s["summary"] for _, s in sorted(scenarios.items(),
                                          key=lambda kv: int(kv[0]))
          if "summary" in s]
    agg = {"num_ok": len(ok),
           "num_failed": len(scenarios) - len(ok)}
    keys = list(AGGREGATE_KEYS)
    if any("serving_slo_attainment" in s for s in ok):
        keys.append("serving_slo_attainment")
    for key in keys:
        values = [s[key] for s in ok
                  if s.get(key) is not None]
        if values:
            agg[key] = quantile_stats(values)
    return agg


def write_artifact(path, meta, scenarios):
    doc = {"schema": ARTIFACT_SCHEMA, "meta": meta,
           "scenarios": {str(k): scenarios[k] for k in sorted(scenarios)},
           "aggregate": aggregate(scenarios)}
    write_text_atomic(path, json.dumps(doc, indent=1, sort_keys=True) + "\n")


def main():
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--sweep_config", default=None,
                   help="JSON file of defaults for any option below "
                        "(explicit CLI flags win); see "
                        "configs/sweep_canonical.json")
    p.add_argument("--trace", default=None)
    p.add_argument("--policy", default="max_min_fairness")
    p.add_argument("--throughputs", default=None)
    p.add_argument("--cluster_spec", default="v100:32")
    p.add_argument("--round_duration", type=float, default=120.0)
    p.add_argument("--config", default=None,
                   help="scheduler config JSON (shockwave/serving blocks)")
    p.add_argument("--from_state", default=None, metavar="STATE",
                   help="seed every scenario from a journaled mid-run "
                        "snapshot instead of trace time-zero: a "
                        "scheduler state DIR (snapshot + journal, as "
                        "written by --state_dir runs) or a simulation "
                        "checkpoint file, loaded through the what-if "
                        "fork loader (whatif/fork.load_twin). Only the "
                        "fault/degrade knobs apply (the admitted "
                        "workload is already fixed); --trace still "
                        "names the original run's trace (profiles)")
    p.add_argument("--num_scenarios", type=int, default=200)
    p.add_argument("--seed_base", type=int, default=0)
    p.add_argument("--processes", type=int, default=None,
                   help="pool size (default: cpu count)")
    p.add_argument("--out", required=True, help="results JSON artifact")
    p.add_argument("--restart", action="store_true",
                   help="ignore an existing artifact instead of resuming")
    p.add_argument("--max_rounds", type=int, default=None)
    p.add_argument("--scalar_sim", action="store_true")
    # -- scenario knobs --
    p.add_argument("--subsample", default=None, metavar="LO:HI")
    p.add_argument("--load_scale", default=None, metavar="LO:HI")
    p.add_argument("--arrival_jitter_s", type=float, default=0.0)
    p.add_argument("--fault_rate", type=float, default=0.0)
    p.add_argument("--fault_max_chips", type=int, default=2)
    p.add_argument("--fault_down_s", type=float, default=3600.0)
    p.add_argument("--fault_window_s", type=float, default=20000.0)
    p.add_argument("--degrade_rate", type=float, default=0.0,
                   help="Poisson rate of gray-failure (degrade) events "
                        "per scenario")
    p.add_argument("--degrade_factor", default="0.05:0.5", metavar="LO:HI",
                   help="uniform range of the multiplicative slowdown "
                        "factor for degrade events")
    p.add_argument("--degrade_down_s", type=float, default=3600.0,
                   help="seconds a degrade event lasts before its chips "
                        "are restored to full speed")
    p.add_argument("--serving_spike_seeds", action="store_true")
    # -- telemetry (never enters the artifact) --
    p.add_argument("--timing_out", default=None,
                   help="sidecar JSON with wall-clock timings")
    p.add_argument("--metrics_out", default=None,
                   help="Prometheus text dump of the sweep metrics")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()

    if args.sweep_config:
        with open(args.sweep_config) as f:
            defaults = json.load(f)
        defaults = {k: v for k, v in defaults.items()
                    if not k.startswith("_")}  # _comment etc.
        unknown = [k for k in defaults if not hasattr(args, k)]
        if unknown:
            raise SystemExit(f"--sweep_config: unknown keys {unknown}")
        p.set_defaults(**defaults)
        args = p.parse_args()
    if not args.trace or not args.throughputs:
        raise SystemExit("--trace and --throughputs are required "
                         "(directly or via --sweep_config)")
    setup_logging("info" if args.verbose else "warning")

    if args.from_state:
        trace_zero_only = [k for k, v in (
            ("subsample", args.subsample), ("load_scale", args.load_scale),
            ("arrival_jitter_s", args.arrival_jitter_s or None),
            ("serving_spike_seeds", args.serving_spike_seeds or None),
        ) if v]
        if trace_zero_only:
            # These knobs rewrite the trace BEFORE admission; a mid-run
            # snapshot's workload is already admitted, so silently
            # accepting them would produce misleading no-op scenarios.
            raise SystemExit(f"--from_state is incompatible with "
                             f"{trace_zero_only} (the snapshot's "
                             "workload is already admitted; use the "
                             "fault/degrade knobs)")
    knobs = {
        "subsample": parse_range(args.subsample, "subsample"),
        "load_scale": parse_range(args.load_scale, "load_scale"),
        "arrival_jitter_s": args.arrival_jitter_s,
        "fault_rate": args.fault_rate,
        "fault_max_chips": args.fault_max_chips,
        "fault_down_s": args.fault_down_s,
        "fault_window_s": args.fault_window_s,
        "degrade_rate": args.degrade_rate,
        "degrade_factor": parse_range(args.degrade_factor,
                                      "degrade_factor"),
        "degrade_down_s": args.degrade_down_s,
        "serving_spike_seeds": bool(args.serving_spike_seeds),
    }
    meta = {
        "trace": args.trace,
        "policy": args.policy,
        "throughputs": args.throughputs,
        "cluster_spec": args.cluster_spec,
        "round_duration": args.round_duration,
        "config": args.config,
        "seed_base": args.seed_base,
        "max_rounds": args.max_rounds,
        "from_state": args.from_state,
        "knobs": {k: (list(v) if isinstance(v, tuple) else v)
                  for k, v in knobs.items()},
    }

    obs = get_observability()
    scenarios = {}
    existing = driver_common.load_resumable_artifact(args.out, meta,
                                                     args.restart)
    if existing is not None:
        scenarios = {int(k): v for k, v in existing["scenarios"].items()}
        for _ in scenarios:
            obs.inc(obs_names.SWEEP_SCENARIOS_TOTAL,
                    outcome="skipped_existing")

    pending = [i for i in range(args.num_scenarios) if i not in scenarios]
    cfg = {
        "trace": args.trace, "policy": args.policy,
        "throughputs": args.throughputs,
        "cluster_spec": args.cluster_spec,
        "round_duration": args.round_duration, "config": args.config,
        "seed_base": args.seed_base, "max_rounds": args.max_rounds,
        "scalar_sim": bool(args.scalar_sim), "knobs": knobs,
        "from_state": args.from_state,
    }

    import time as _time
    # Wall-clock is sweep-throughput telemetry only; scenario content is
    # purely seed-driven and the artifact stays byte-deterministic.
    t0 = _time.monotonic()  # swtpu-check: ignore[determinism]
    n_failed = 0
    if pending:
        processes = args.processes or os.cpu_count() or 4
        ctx = multiprocessing.get_context("fork")
        with ctx.Pool(min(processes, len(pending))) as pool:
            payloads = [(i, cfg) for i in pending]
            for seed_index, record, wall in pool.imap_unordered(
                    run_scenario, payloads):
                now = _time.monotonic()  # swtpu-check: ignore[determinism]
                scenarios[seed_index] = record
                failed = "error" in record
                n_failed += failed
                obs.inc(obs_names.SWEEP_SCENARIOS_TOTAL,
                        outcome="failed" if failed else "ok")
                # Worker-measured per-scenario wall (the parent's
                # inter-completion gap would undercount by the pool
                # concurrency factor).
                obs.observe(obs_names.SWEEP_SCENARIO_WALL_SECONDS, wall)
                write_artifact(args.out, meta, scenarios)
                done = len(scenarios)
                print(f"[{done}/{args.num_scenarios}] scenario "
                      f"{seed_index} {'FAILED' if failed else 'ok'} "
                      f"({wall:.1f}s sim, {now - t0:.1f}s elapsed)",
                      file=sys.stderr, flush=True)
    else:
        write_artifact(args.out, meta, scenarios)
    wall_s = _time.monotonic() - t0  # swtpu-check: ignore[determinism]

    if not pending:
        print("all scenarios already present; artifact refreshed",
              file=sys.stderr)
    # Stats over the REQUESTED seed range only: a resumed artifact may
    # carry more scenarios than this invocation asked for (e.g. a rerun
    # with a smaller --num_scenarios), and those must not produce
    # negative failure counts in the result line / bench row.
    in_range = {i: r for i, r in scenarios.items()
                if i < args.num_scenarios}
    completed = sum(1 for r in in_range.values() if "summary" in r)
    result = {
        "artifact": args.out,
        "scenarios": args.num_scenarios,
        "completed": completed,
        "failed": len(in_range) - completed,
        "skipped_existing": len(in_range) - len(pending),
        "wall_s": round(wall_s, 2),
        "scenarios_per_min": (round(len(pending) / wall_s * 60.0, 2)
                              if pending and wall_s > 0 else None),
    }
    print(json.dumps(result))
    if args.timing_out:
        # Telemetry sidecar, not durable state.
        with open(args.timing_out, "w") as f:
            json.dump(result, f, indent=2)
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            f.write(obs.registry.render_prometheus())


if __name__ == "__main__":
    main()
