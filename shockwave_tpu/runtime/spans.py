"""Runtime-side span emission: the one place worker daemons, the
dispatcher and the job-side LeaseIterator touch the fleet-trace
machinery.

This module owns the per-process `ShardSpanWriter` (obs/shard.py) and
the remote-parent plumbing (obs/propagation.py); the runtime modules
call its helpers and never read a wall clock for span purposes — every
span timestamp is stamped inside the shard writer by its injected
clock. Enforced statically: the obs-discipline pass's clock rule covers
this module alongside ``shockwave_tpu/obs/`` (a ``time.time()`` here is
a finding), so span timing cannot silently fork from the obs clock
discipline.

Tracing is opt-in per process: without a trace directory (the
`names.SHARD_DIR_ENV` environment variable, or an explicit
``--trace_dir``) every helper degrades to a no-op and the runtime
behaves byte-identically to the pre-tracing tree.
"""
from __future__ import annotations

import atexit
import logging
import os
import threading
from typing import Optional

from ..obs import names
from ..obs.propagation import (SpanContext, from_environ, from_rpc_metadata,
                               to_environ)
from ..obs.shard import OpenSpan, ShardSpanWriter

logger = logging.getLogger("shockwave_tpu.runtime")

_LOCK = threading.Lock()
_SHARD: Optional[ShardSpanWriter] = None

__all__ = ["SpanContext", "OpenSpan", "from_environ", "from_rpc_metadata",
           "to_environ", "init_process_shard", "shard_from_env",
           "get_shard", "trace_dir_from_env", "export_trace_env",
           "flush"]


def trace_dir_from_env() -> Optional[str]:
    return os.environ.get(names.SHARD_DIR_ENV) or None


def init_process_shard(directory: Optional[str],
                       role: str) -> Optional[ShardSpanWriter]:
    """Create (once) this process's span shard under `directory`; None
    disables tracing for the process. Flushed at exit so a clean
    process never loses its tail spans."""
    global _SHARD
    if directory is None:
        return None
    with _LOCK:
        if _SHARD is None:
            try:
                _SHARD = ShardSpanWriter(directory, role=role)
            except OSError as e:
                logger.warning("span shard disabled: cannot create %s "
                               "(%s)", directory, e)
                return None
            atexit.register(flush)
        elif os.path.abspath(_SHARD.directory) != os.path.abspath(
                directory):
            # Singleton-per-process by design (the atexit flush and the
            # env contract both assume one shard); a second caller with
            # a DIFFERENT directory keeps writing into the first one —
            # say so instead of silently dropping its drive's spans.
            logger.warning(
                "process span shard already bound to %s; ignoring "
                "request for %s (one shard per process)",
                _SHARD.directory, directory)
        return _SHARD


def shard_from_env(role: str) -> Optional[ShardSpanWriter]:
    """Process shard from the dispatcher-exported environment (trainer
    subprocesses), or None when tracing is off."""
    return init_process_shard(trace_dir_from_env(), role)


def get_shard() -> Optional[ShardSpanWriter]:
    return _SHARD


def export_trace_env(env: dict, ctx: Optional[SpanContext],
                     trace_dir: Optional[str]) -> dict:
    """Export the launch span's context + the shard directory into a
    trainer subprocess environment (in place; no-ops when tracing is
    off)."""
    to_environ(ctx, env)
    if trace_dir is not None:
        env[names.SHARD_DIR_ENV] = trace_dir
    return env


def flush() -> None:
    """Flush the process shard (atexit hook; safe to call any time)."""
    shard = _SHARD
    if shard is None:
        return
    try:
        shard.flush()
    except OSError as e:
        logger.warning("span shard flush failed: %s", e)
