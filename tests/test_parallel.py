"""Parallel layer tests on the virtual 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shockwave_tpu.parallel.mesh import (data_parallel_sharding, make_mesh,
                                         replicate, shard_batch)
from shockwave_tpu.parallel.ring_attention import (reference_attention,
                                                   ring_attention)


@pytest.fixture(scope="module")
def devices():
    ds = jax.devices()
    if len(ds) < 8:
        pytest.skip("needs 8 virtual devices")
    return ds


class TestMesh:
    def test_make_mesh_shapes(self, devices):
        mesh = make_mesh()
        assert mesh.devices.size == len(devices)
        mesh = make_mesh(dp=2, tp=2, sp=2)
        assert dict(mesh.shape) == {"dp": 2, "pp": 1, "tp": 2, "sp": 2,
                                    "ep": 1}

    def test_mismatched_mesh_raises(self, devices):
        with pytest.raises(AssertionError):
            make_mesh(dp=3, tp=3, sp=1)

    def test_batch_size_caps_dp(self, devices):
        """Small-batch jobs must get a dp that divides the batch (largest
        such divisor), leaving leftover devices out of the mesh."""
        assert dict(make_mesh(batch_size=1).shape)["dp"] == 1
        assert dict(make_mesh(batch_size=20).shape)["dp"] == 5
        assert dict(make_mesh(batch_size=32).shape)["dp"] == len(devices)
        # Explicit dp wins; batch_size only applies to the default.
        assert dict(make_mesh(dp=4, tp=2, batch_size=1).shape)["dp"] == 4

    def test_shard_and_replicate(self, devices):
        mesh = make_mesh()
        batch = jnp.arange(16.0).reshape(16, 1)
        sharded = shard_batch(mesh, batch)
        assert sharded.sharding.spec == jax.sharding.PartitionSpec("dp")
        params = {"w": jnp.ones((4, 4))}
        rep = replicate(mesh, params)
        assert rep["w"].sharding.is_fully_replicated

    def test_dp_gradient_allreduce(self, devices):
        """A jit'd loss over a dp-sharded batch must equal the unsharded one
        (XLA inserts the cross-chip reduction)."""
        mesh = make_mesh()
        batch_sh, repl_sh = data_parallel_sharding(mesh)
        w = jax.device_put(jnp.ones((4,)), repl_sh)
        x = jnp.arange(32.0).reshape(8, 4)

        def loss(w, x):
            return jnp.mean((x @ w) ** 2)

        g_sharded = jax.jit(jax.grad(loss))(w, jax.device_put(x, batch_sh))
        g_local = jax.grad(loss)(jnp.ones((4,)), x)
        np.testing.assert_allclose(np.asarray(g_sharded), np.asarray(g_local),
                                   rtol=1e-6)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, devices, causal):
        mesh = make_mesh(dp=1, tp=1, sp=8)
        rng = jax.random.PRNGKey(0)
        b, s, h, d = 2, 64, 4, 16
        q, k, v = (jax.random.normal(key, (b, s, h, d), jnp.float32)
                   for key in jax.random.split(rng, 3))
        expected = reference_attention(q, k, v, causal=causal)
        got = ring_attention(q, k, v, mesh, axis_name="sp", causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-3, atol=2e-3)

    def test_long_sequence_sharded_memory(self, devices):
        # Just exercises a longer sequence through the ring path.
        mesh = make_mesh(dp=1, tp=1, sp=8)
        rng = jax.random.PRNGKey(1)
        q = k = v = jax.random.normal(rng, (1, 512, 2, 8), jnp.float32)
        out = ring_attention(q, k, v, mesh, causal=True)
        assert out.shape == q.shape
        assert bool(jnp.all(jnp.isfinite(out)))


class TestPipeline:
    def test_matches_sequential(self, devices):
        """GPipe schedule over 4 stages == applying the 4 blocks in
        order on one device."""
        from shockwave_tpu.parallel.pipeline import pipeline_apply

        mesh = make_mesh(dp=2, pp=4)
        rng = jax.random.PRNGKey(0)
        pp, dim, mlp = 4, 16, 32
        k1, k2, k3 = jax.random.split(rng, 3)
        stage_params = {
            "w1": jax.random.normal(k1, (pp, dim, mlp)) * 0.1,
            "w2": jax.random.normal(k2, (pp, mlp, dim)) * 0.1,
        }
        x = jax.random.normal(k3, (8, 6, dim))

        def block(p, x):
            return x + jax.nn.gelu(x @ p["w1"]) @ p["w2"]

        got = pipeline_apply(stage_params, x, mesh, num_microbatches=4,
                             stage_fn=block)
        expected = x
        for s in range(pp):
            expected = block(
                jax.tree.map(lambda a, s=s: a[s], stage_params), expected)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)

    def test_differentiable(self, devices):
        from shockwave_tpu.parallel.pipeline import pipeline_apply

        mesh = make_mesh(pp=2)  # dp absorbs the remaining devices
        stage_params = {"w": jnp.ones((2, 4, 4)) * 0.1}
        x = jnp.ones((8, 4))  # microbatch size 4 divides the dp=4 axis

        def block(p, x):
            return jnp.tanh(x @ p["w"])

        def loss(sp, x):
            return jnp.sum(pipeline_apply(sp, x, mesh, 2, block) ** 2)

        g = jax.jit(jax.grad(loss))(stage_params, x)
        assert bool(jnp.all(jnp.isfinite(g["w"])))
        assert float(jnp.abs(g["w"]).sum()) > 0


class TestMoE:
    def test_routes_and_shapes(self, devices):
        from shockwave_tpu.parallel.moe import moe_mlp

        mesh = make_mesh(dp=2, ep=4)
        rng = jax.random.PRNGKey(0)
        b, s, d, e, f = 2, 16, 8, 4, 16
        ks = jax.random.split(rng, 4)
        x = jax.random.normal(ks[0], (b, s, d))
        router = jax.random.normal(ks[1], (d, e))
        w1 = jax.random.normal(ks[2], (e, d, f)) * 0.1
        w2 = jax.random.normal(ks[3], (e, f, d)) * 0.1
        out, aux = jax.jit(
            lambda x: moe_mlp(x, router, w1, w2, mesh))(x)
        assert out.shape == x.shape
        assert bool(jnp.all(jnp.isfinite(out)))
        # Balanced-routing aux loss is ~1 at uniform routing, >= 1 always.
        assert float(aux) >= 0.99

    def test_matches_dense_single_expert(self, devices):
        """With one expert and ample capacity, MoE == its dense FFN
        scaled by the (softmax) gate of 1.0."""
        from shockwave_tpu.parallel.moe import moe_mlp

        mesh = make_mesh()  # ep=1: single expert, dp absorbs devices
        rng = jax.random.PRNGKey(1)
        b, s, d, f = 2, 8, 6, 12
        ks = jax.random.split(rng, 3)
        x = jax.random.normal(ks[0], (b, s, d))
        router = jnp.zeros((d, 1))
        w1 = jax.random.normal(ks[1], (1, d, f)) * 0.2
        w2 = jax.random.normal(ks[2], (1, f, d)) * 0.2
        out, _ = moe_mlp(x, router, w1, w2, mesh, capacity_factor=2.0)
        expected = jax.nn.gelu(x @ w1[0]) @ w2[0]
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   rtol=1e-4, atol=1e-5)

    def test_differentiable(self, devices):
        from shockwave_tpu.parallel.moe import moe_mlp

        mesh = make_mesh(ep=2)
        rng = jax.random.PRNGKey(2)
        ks = jax.random.split(rng, 4)
        x = jax.random.normal(ks[0], (2, 8, 6))
        params = {
            "router": jax.random.normal(ks[1], (6, 2)),
            "w1": jax.random.normal(ks[2], (2, 6, 12)) * 0.1,
            "w2": jax.random.normal(ks[3], (2, 12, 6)) * 0.1,
        }

        def loss(p, x):
            out, aux = moe_mlp(x, p["router"], p["w1"], p["w2"], mesh)
            return jnp.sum(out ** 2) + 1e-2 * aux

        g = jax.jit(jax.grad(loss))(params, x)
        for leaf in jax.tree.leaves(g):
            assert bool(jnp.all(jnp.isfinite(leaf)))


class TestFiveAxisTrainStep:
    def test_pp_ep_mesh_step_runs_and_learns(self, devices):
        from shockwave_tpu.parallel.train_step import (
            build_multi_parallel_train_step)

        mesh = make_mesh(dp=2, pp=2, ep=2)
        step, params, (tokens, targets) = build_multi_parallel_train_step(
            mesh, seq_len=16, batch=8, vocab=64, dim=32, heads=2,
            mlp_dim=64)
        losses = []
        for _ in range(4):
            params, loss = step(params, tokens, targets)
            losses.append(float(loss))
        assert losses[-1] < losses[0]
