"""Gandiva policy: random exploratory packing with equal time split.

When demand fits the cluster, behaves like isolated; under contention it
randomly pairs jobs (same scale factor), drops pairs whose measured
normalized throughput falls below 1.0, and splits time equally among the
resulting combinations (reference: scheduler/policies/gandiva.py).
"""
from __future__ import annotations

import random
from typing import Dict, Optional, Tuple

import numpy as np

from ..core.job import JobIdPair
from .policy import PolicyWithPacking


class GandivaPolicy(PolicyWithPacking):
    name = "Gandiva_Packing"

    def __init__(self, seed: Optional[int] = None):
        super().__init__()
        self._combinations: Dict[JobIdPair, Tuple[JobIdPair, Optional[JobIdPair]]] = {}
        self._rng = random.Random(seed)

    def _normalized_throughput(self, combo, throughputs, worker_types) -> float:
        if not combo.is_pair():
            return 0.0
        total = 0.0
        for wt in worker_types:
            packed = throughputs.get(combo, {}).get(wt)
            if packed is None:
                # No measured pair throughput: treat as not paying off so
                # the combination is retired (and re-explored later).
                return 0.0
            for i, member in enumerate(combo.singletons()):
                if packed[i] <= 0.0:
                    return 0.0
                total += packed[i] / throughputs[member][wt]
        return total

    def _equal_split(self, combos_to_schedule, index, scale_factors, cluster_spec):
        job_ids, _, worker_types, _ = index
        m = len(combos_to_schedule)
        sf = self.scale_factors_array(scale_factors, job_ids,
                                      len(job_ids), len(worker_types))
        x = np.zeros((len(job_ids), len(worker_types)))
        for combo in combos_to_schedule:
            share = np.array([cluster_spec[wt] / m for wt in worker_types])
            if combo in job_ids:
                i = job_ids.index(combo)
                x[i] = share / sf[i]
            else:
                # No measured pair throughput for this combination yet, so
                # it has no flattened row; space-sharing gives each member
                # the combo's full time fraction.
                for member in combo.singletons():
                    i = job_ids.index(member)
                    x[i] = share / sf[i]
        row_sums = np.maximum(x.sum(axis=1), 1.0)
        return x / row_sums[:, None]

    def get_allocation(self, unflattened_throughputs, scale_factors, cluster_spec):
        tensor, index = self.flatten(unflattened_throughputs, cluster_spec)
        if tensor is None or len(tensor) == 0:
            return None
        job_ids, single_job_ids, worker_types, _ = index

        # Retire combinations whose members finished or that stopped paying off.
        stale = []
        for job_id, (combo, other) in list(self._combinations.items()):
            if job_id not in job_ids or (other is not None and other not in job_ids):
                stale.extend([job_id, other])
            elif self._normalized_throughput(combo, unflattened_throughputs,
                                             worker_types) < 1.0:
                stale.extend([job_id, other])
        for job_id in stale:
            if job_id is not None:
                self._combinations.pop(job_id, None)

        demand = sum(scale_factors[s] for s in single_job_ids)
        capacity = sum(cluster_spec[wt] for wt in worker_types)

        if demand <= capacity:
            x = self._equal_split(single_job_ids, index, scale_factors, cluster_spec)
        else:
            unassigned = [s for s in single_job_ids if s not in self._combinations]
            attempts = len(unassigned)
            while len(unassigned) > 1 and attempts > 0:
                attempts -= 1
                a, b = self._rng.sample(unassigned, 2)
                if scale_factors[a] != scale_factors[b]:
                    continue
                unassigned.remove(a)
                unassigned.remove(b)
                combo = JobIdPair(a[0], b[0])
                self._combinations[a] = (combo, b)
                self._combinations[b] = (combo, a)
            for s in unassigned:
                self._combinations[s] = (s, None)
            combos = list({self._combinations[s][0] for s in self._combinations})
            x = self._equal_split(combos, index, scale_factors, cluster_spec)

        return self.unflatten(x, index)
