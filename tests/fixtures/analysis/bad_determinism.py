"""determinism negative fixture: wall clock + unseeded RNGs (lines
marked SEEDED); seeded RNG construction must NOT be reported."""
import random
import time

import numpy as np


def decide(jobs, seed):
    rng = random.Random(seed)  # seeded: not a finding
    now = time.time()  # SEEDED: wall clock
    jitter = random.random()  # SEEDED: unseeded module-level RNG
    noise = np.random.rand()  # SEEDED: unseeded numpy RNG
    ok = np.random.RandomState(seed)  # seeded: not a finding
    kw_ok = np.random.RandomState(seed=seed)  # keyword-seeded: not a finding
    entropy = random.Random(None)  # SEEDED: None seeds from OS entropy
    return (rng.random() + now + jitter + noise + ok.rand()
            + kw_ok.rand() + entropy.random())
