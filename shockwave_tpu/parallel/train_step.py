"""A fully-parallel transformer training step: dp x tp x sp on one mesh.

Demonstrates (and dry-runs) the framework's multi-chip execution model in
one jitted step:
- batch sharded over `dp` (XLA all-reduces grads on ICI),
- MLP hidden dimension sharded over `tp` (XLA inserts the reduce-scatter/
  all-gather pair around the two matmuls),
- sequence sharded over `sp` with ring attention (explicit ppermute ring).

Used by `__graft_entry__.dryrun_multichip` and as the template for scaling
workloads past data parallelism.
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .ring_attention import ring_attention


def build_multi_parallel_train_step(mesh: Mesh, vocab: int = 1024,
                                    dim: int = 128, heads: int = 8,
                                    mlp_dim: int = 512, seq_len: int = 64,
                                    batch: int = 8):
    """Returns (step_fn, state, example_batch), all mesh-sharded."""
    assert dim % heads == 0
    head_dim = dim // heads
    rng = np.random.RandomState(0)

    def init(shape, scale=0.02):
        return jnp.asarray(rng.normal(0, scale, shape), jnp.float32)

    params = {
        "embed": init((vocab, dim)),
        "wq": init((dim, heads, head_dim)),
        "wk": init((dim, heads, head_dim)),
        "wv": init((dim, heads, head_dim)),
        "wo": init((heads, head_dim, dim)),
        "w1": init((dim, mlp_dim)),   # hidden dim sharded over tp
        "w2": init((mlp_dim, dim)),
        "out": init((dim, vocab)),
    }
    param_specs = {
        "embed": P(), "wq": P(), "wk": P(), "wv": P(), "wo": P(),
        "w1": P(None, "tp"), "w2": P("tp", None), "out": P(),
    }
    param_shardings = {k: NamedSharding(mesh, s) for k, s in param_specs.items()}
    params = {k: jax.device_put(v, param_shardings[k]) for k, v in params.items()}

    batch_sharding = NamedSharding(mesh, P("dp", "sp"))
    tokens = jnp.asarray(rng.randint(1, vocab, (batch, seq_len)), jnp.int32)
    targets = jnp.asarray(rng.randint(1, vocab, (batch, seq_len)), jnp.int32)
    example = (jax.device_put(tokens, batch_sharding),
               jax.device_put(targets, batch_sharding))

    def forward(params, tokens):
        x = params["embed"][tokens]  # (b, s, d)
        q = jnp.einsum("bsd,dhk->bshk", x, params["wq"])
        k = jnp.einsum("bsd,dhk->bshk", x, params["wk"])
        v = jnp.einsum("bsd,dhk->bshk", x, params["wv"])
        attn = ring_attention(q, k, v, mesh, axis_name="sp", causal=True)
        x = x + jnp.einsum("bshk,hkd->bsd", attn, params["wo"])
        # Tensor-parallel MLP: w1 column-sharded, w2 row-sharded over tp.
        h = jax.nn.gelu(jnp.einsum("bsd,df->bsf", x, params["w1"]))
        x = x + jnp.einsum("bsf,fd->bsd", h, params["w2"])
        return jnp.einsum("bsd,dv->bsv", x, params["out"])

    def loss_fn(params, tokens, targets):
        logits = forward(params, tokens)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(jnp.take_along_axis(logp, targets[..., None],
                                             axis=-1))

    lr = 1e-2

    def step_fn(params, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, loss

    step = jax.jit(
        step_fn,
        in_shardings=(param_shardings, batch_sharding, batch_sharding),
        out_shardings=(param_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,))
    return step, params, example
