#!/bin/bash
# Multi-seed spread for the headline claims (EXPERIMENTS.md): the
# canonical tuned-config shockwave replay and the continuous-arrival
# load sweep, re-run at 5 / 3 seeds. Seed 0 stays the pinned
# bit-deterministic result; this records the spread around it.
#
# The seed feeds the scheduler RNG (worker shuffling, round-scheduler
# tie-breaks) and — for the sweep — the generated Poisson trace, so the
# sweep's spread covers workload draw as well as scheduler stochasticity.
#
# Writes one JSON line per run to $OUT/canonical_seeds.jsonl and the
# sweep tool's aggregate to $OUT/load_sweep_seeds.json.
set -u
cd "$(dirname "$0")/.."
OUT=${1:-reproduce/pickles/multiseed}
SEEDS=${SEEDS:-0 1 2 3 4}
SWEEP_SEEDS=${SWEEP_SEEDS:-0 1 2}
mkdir -p "$OUT"

: > "$OUT/canonical_seeds.jsonl"
for SEED in $SEEDS; do
    echo "=== canonical shockwave seed $SEED ==="
    python3 scripts/drivers/simulate.py \
        --trace data/canonical_120job.trace \
        --policy shockwave \
        --throughputs data/tacc_throughputs.json \
        --cluster_spec v100:32 --round_duration 120 \
        --seed "$SEED" \
        --config configs/tacc_32gpus.json \
        | tail -1 | sed "s/^{/{\"seed\": $SEED, /" \
        >> "$OUT/canonical_seeds.jsonl"
done

echo "=== load sweep (seeds: $SWEEP_SEEDS) ==="
python3 scripts/sweeps/run_sweep_continuous.py \
    --policies shockwave max_min_fairness finish_time_fairness \
    --num_jobs 120 --lams 3600 300 150 \
    --seeds $SWEEP_SEEDS \
    --output "$OUT/load_sweep_seeds.json"

python3 - "$OUT" <<'EOF'
import json, statistics, sys
out = sys.argv[1]
rows = [json.loads(l) for l in open(f"{out}/canonical_seeds.jsonl")]
mk = [r["makespan"] for r in rows]
jct = [r["avg_jct"] for r in rows]
print(f"canonical makespan: mean {statistics.mean(mk):.1f} "
      f"+- {statistics.stdev(mk) if len(mk) > 1 else 0:.1f} "
      f"(min {min(mk):.1f}, max {max(mk):.1f}, n={len(mk)})")
print(f"canonical avg JCT:  mean {statistics.mean(jct):.1f} "
      f"+- {statistics.stdev(jct) if len(jct) > 1 else 0:.1f}")
EOF
