"""Thin LP layer over scipy's HiGHS solver.

The Gavel policy LPs are small (jobs x worker-types), so we build dense
constraint matrices. This replaces the reference's cvxpy/ECOS/Gurobi stack
(reference: scheduler/policies/*.py) with a dependency-free formulation.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np
from scipy.optimize import linprog


@dataclass
class LinearProgram:
    """Incrementally built LP: minimize c @ x subject to A_ub x <= b_ub, A_eq x = b_eq.

    Variables are indexed by the caller; all variables default to bounds
    [0, +inf) unless overridden via `bounds`.
    """

    num_vars: int
    c: np.ndarray = field(init=False)
    _A_ub: List[np.ndarray] = field(default_factory=list)
    _b_ub: List[float] = field(default_factory=list)
    _A_eq: List[np.ndarray] = field(default_factory=list)
    _b_eq: List[float] = field(default_factory=list)
    bounds: Optional[List] = None

    def __post_init__(self):
        self.c = np.zeros(self.num_vars)
        self.bounds = [(0, None)] * self.num_vars

    def row(self) -> np.ndarray:
        return np.zeros(self.num_vars)

    def add_le(self, coeffs: np.ndarray, rhs: float) -> None:
        self._A_ub.append(coeffs)
        self._b_ub.append(rhs)

    def add_eq(self, coeffs: np.ndarray, rhs: float) -> None:
        self._A_eq.append(coeffs)
        self._b_eq.append(rhs)

    def minimize(self, c: np.ndarray):
        self.c = np.asarray(c, dtype=float)
        return self

    def solve(self):
        res = linprog(
            self.c,
            A_ub=np.vstack(self._A_ub) if self._A_ub else None,
            b_ub=np.array(self._b_ub) if self._b_ub else None,
            A_eq=np.vstack(self._A_eq) if self._A_eq else None,
            b_eq=np.array(self._b_eq) if self._b_eq else None,
            bounds=self.bounds,
            method="highs",
        )
        return res


def solve_feasibility(lp: LinearProgram) -> Optional[np.ndarray]:
    """Solve with a zero objective; return x if feasible else None."""
    res = lp.minimize(np.zeros(lp.num_vars)).solve()
    return res.x if res.success else None
