#!/usr/bin/env python3
"""Per-workload dispatch-overhead microbenchmark.

Measures, for each workload family, the wall-clock cost of one dispatch
cycle — process start + imports + jit compile + one step + checkpoint —
cold and then warm (XLA persistent compile cache hit). This is the
preemption/restore overhead the round mechanism pays whenever a job is
rescheduled, and what the simulator models as a fixed per-preemption
penalty (reference: scheduler/scripts/microbenchmarks/
sweep_models_for_overhead.py; the simulator's 20 s constant is
scheduler.py:1936-1968).

Example:
    python scripts/microbenchmarks/sweep_models_for_overhead.py \
        --families cifar10 lm --output /tmp/overhead.json
"""
import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
WORKLOADS = os.path.join(REPO, "shockwave_tpu", "workloads")

ENTRIES = {
    "cifar10": ("image_classification/cifar10/main.py",
                ["--batch_size", "32", "--num_steps", "1"]),
    "imagenet": ("image_classification/imagenet/main.py",
                 ["-b", "16", "x", "--num_minibatches", "1"]),
    "translation": ("translation/train.py",
                    ["-data", "x", "-batch_size", "16", "-step", "1"]),
    "lm": ("language_modeling/main.py",
           ["--batch_size", "10", "--steps", "1"]),
    "recommendation": ("recommendation/train.py",
                       ["--data_dir", "x", "--batch_size", "512", "-n", "1"]),
    "rl": ("rl/main.py", ["--workers", "2", "--unroll", "4",
                          "--max-steps", "1"]),
    "cyclegan": ("cyclegan/cyclegan.py",
                 ["--batch_size", "1", "--img_size", "64", "--n_steps", "1"]),
}


def one_dispatch(script, extra_args, ckpt_dir, cache_dir):
    env = dict(os.environ, SWTPU_COMPILE_CACHE=cache_dir)
    start = time.time()
    out = subprocess.run(
        [sys.executable, os.path.join(WORKLOADS, script), *extra_args,
         "--checkpoint_dir", ckpt_dir],
        capture_output=True, text=True, timeout=1800, env=env)
    elapsed = time.time() - start
    if out.returncode != 0:
        raise RuntimeError(out.stderr[-1000:])
    return elapsed


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--families", nargs="*", default=list(ENTRIES))
    p.add_argument("--output", default=None)
    args = p.parse_args()

    results = []
    for family in args.families:
        script, extra = ENTRIES[family]
        workdir = tempfile.mkdtemp(prefix=f"swtpu_overhead_{family}_")
        cache = os.path.join(workdir, "cache")
        try:
            # Fresh checkpoint dir per run (a shared one would satisfy the
            # cumulative step budget and skip training entirely); only the
            # compile cache is shared, so warm isolates the cache hit.
            cold = one_dispatch(script, extra, os.path.join(workdir, "c1"),
                                cache)
            warm = one_dispatch(script, extra, os.path.join(workdir, "c2"),
                                cache)
            row = {"family": family, "cold_dispatch_s": round(cold, 2),
                   "warm_dispatch_s": round(warm, 2),
                   "compile_cache_saving_s": round(cold - warm, 2)}
        except Exception as e:  # noqa: BLE001 - report and continue sweep
            row = {"family": family, "error": str(e)[:300]}
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
        results.append(row)
        print(json.dumps(row), flush=True)

    if args.output:
        with open(args.output, "w") as f:
            json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
