"""Live-scheduler knobs the what-if plane may auto-tune.

One tiny interface — ``applicable`` / ``get`` / ``set`` over a
scheduler (live or twin) — so the tuning sweep in plane.py is generic:
it forks a twin per candidate value, sets the knob ON THE TWIN, rolls
the horizon, and commits the winner to the live scheduler through the
same ``set``. The committed value is journaled (`whatif_knob`) so a
resumed scheduler re-applies it.

Shipped knobs:

- ``autoscaler_headroom`` — the serving autoscaler's peak-rate
  multiplier (serving/autoscaler.py). The flagship: serving dynamics
  are fully modeled in the twin, so the sweep sees real SLO/capacity
  trade-offs.
- ``solver_budget_rounds`` — the Shockwave MILP budget cap
  (shockwave/milp.MilpOptions.budget_cap_rounds). Behind the same
  interface; note the solve budget is a WALL-clock bound, which the
  virtual-clock twin cannot price — sweeps over it measure schedule
  quality only.
- ``quarantine_backoff_s`` — the gray-failure quarantine release
  backoff (runtime/resilience.HealthConfig). Physical-only state; on a
  simulation twin ``set`` is a recorded no-op (the sim has no health
  layer), so twin sweeps cannot differentiate it yet — the knob exists
  so the physical plane can journal operator-visible changes through
  one mechanism.
"""
from __future__ import annotations

from dataclasses import replace
from typing import Dict, Sequence


class Knob:
    """One tunable: pure accessors, no state."""

    name: str = ""
    #: Default candidate grid (config may override).
    candidates: Sequence[float] = ()

    def applicable(self, sched) -> bool:
        raise NotImplementedError

    def get(self, sched) -> float:
        raise NotImplementedError

    def set(self, sched, value: float) -> None:
        raise NotImplementedError


class AutoscalerHeadroomKnob(Knob):
    name = "autoscaler_headroom"
    candidates = (1.0, 1.15, 1.3, 1.6, 2.0)

    def applicable(self, sched) -> bool:
        return sched._serving_tier is not None

    def get(self, sched) -> float:
        return float(sched._serving_tier.autoscaler_config.headroom)

    def set(self, sched, value: float) -> None:
        sched._serving_tier.set_headroom(float(value))


class SolverBudgetKnob(Knob):
    name = "solver_budget_rounds"
    candidates = (0.5, 1.0, 2.0)

    def applicable(self, sched) -> bool:
        return sched._shockwave_planner is not None

    def get(self, sched) -> float:
        return float(sched._shockwave_planner.opts.budget_cap_rounds)

    def set(self, sched, value: float) -> None:
        planner = sched._shockwave_planner
        planner.opts = replace(planner.opts,
                               budget_cap_rounds=float(value))


class QuarantineBackoffKnob(Knob):
    name = "quarantine_backoff_s"
    candidates = (60.0, 120.0, 300.0)

    def applicable(self, sched) -> bool:
        # Live physical schedulers carry the health config; a sim twin
        # does not (set() below is then a no-op by construction).
        return getattr(sched, "_health_enabled", False)

    def get(self, sched) -> float:
        return float(sched._health_cfg.quarantine_backoff_s)

    def set(self, sched, value: float) -> None:
        if not hasattr(sched, "_health_cfg"):
            return  # simulation twin: no health layer to retune
        sched._health_cfg = sched._health_cfg.with_quarantine_backoff(
            float(value))
        # Existing classifiers keep scoring against the updated config.
        for health in sched._host_health.values():
            health.config = sched._health_cfg


KNOBS: Dict[str, Knob] = {k.name: k for k in (
    AutoscalerHeadroomKnob(), SolverBudgetKnob(), QuarantineBackoffKnob())}


def get_knob(name: str) -> Knob:
    try:
        return KNOBS[name]
    except KeyError:
        raise ValueError(
            f"unknown what-if knob {name!r}; known: {sorted(KNOBS)}"
        ) from None
