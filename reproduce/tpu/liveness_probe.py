#!/usr/bin/env python3
"""Bounded-retry, subprocess-isolated accelerator liveness probe.

A wedged accelerator tunnel blocks `jax.devices()` **forever** inside
whatever process touches the backend — so the probe always runs in a
child process with a hard timeout, and the parent can only ever lose
`attempts x (timeout + backoff)` seconds, never hang. BENCH_r05
recorded exactly this failure (`tpu_error: backend liveness probe timed
out (wedged accelerator tunnel?)`); every evidence-capture entry point
now goes through this one probe so a wedged tunnel degrades to the
last-good committed evidence files instead of poisoning the bench row
or hanging `capture_tpu_evidence.sh` at step 1.

Used as a library by bench.py (`probe_backend()`) and as a CLI by
reproduce/tpu/capture_tpu_evidence.sh:

    python reproduce/tpu/liveness_probe.py && <capture steps>

Exit codes: 0 = backend live, 3 = unreachable/wedged (reason on stdout).
"""
from __future__ import annotations

import subprocess
import sys
import time
from typing import Optional

#: Child command: touching jax.devices() forces full backend init.
PROBE_SNIPPET = "import jax; jax.devices()"
DEFAULT_ATTEMPTS = 2
DEFAULT_TIMEOUT_S = 120.0
DEFAULT_BACKOFF_S = 45.0


def probe_backend(attempts: int = DEFAULT_ATTEMPTS,
                  timeout_s: float = DEFAULT_TIMEOUT_S,
                  backoff_s: float = DEFAULT_BACKOFF_S,
                  cwd: Optional[str] = None,
                  python: Optional[str] = None,
                  snippet: str = PROBE_SNIPPET,
                  sleep=time.sleep) -> Optional[str]:
    """Probe backend liveness in an isolated child with bounded retry.

    Returns None when the backend answered, else a one-line reason
    (timeout = wedged tunnel, nonzero exit = init failure). Transient
    relay hiccups often clear within a minute, hence the backoff'd
    retries; the budget is hard-bounded either way."""
    err: Optional[str] = None
    for attempt in range(max(attempts, 1)):
        if attempt:
            sleep(backoff_s)
        try:
            probe = subprocess.run(
                [python or sys.executable, "-c", snippet],
                capture_output=True, text=True, timeout=timeout_s, cwd=cwd)
        except subprocess.TimeoutExpired:
            err = ("backend liveness probe timed out "
                   "(wedged accelerator tunnel?)")
            continue
        if probe.returncode != 0:
            err = "backend init failed: " + probe.stderr[-300:]
            continue
        return None
    return err


def main(argv=None) -> int:
    import argparse
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--attempts", type=int, default=DEFAULT_ATTEMPTS)
    p.add_argument("--timeout", type=float, default=DEFAULT_TIMEOUT_S)
    p.add_argument("--backoff", type=float, default=DEFAULT_BACKOFF_S)
    args = p.parse_args(argv)
    err = probe_backend(attempts=args.attempts, timeout_s=args.timeout,
                        backoff_s=args.backoff)
    if err is None:
        print("backend live")
        return 0
    print(err)
    return 3


if __name__ == "__main__":
    sys.exit(main())
