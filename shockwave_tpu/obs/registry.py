"""Thread-safe metrics registry: labeled counters, gauges and
fixed-bucket histograms.

Design points:

- **Specs, not strings.** Every instrument is declared once in
  `obs/names.py` as a `MetricSpec`; call sites pass the spec object.
  The registry materializes storage lazily on first use and rejects a
  second spec with the same name but a different shape.
- **Injected clock.** `timed()` measures with the registry's clock, so
  the same instrumentation runs under the simulator's virtual clock
  (durations collapse to zero, counts stay meaningful) and under wall
  clocks in the physical control plane. No wall-clock reads happen in
  this module (obs-discipline pass).
- **Leaf lock.** One registry lock guards all storage and is never held
  across a call into other subsystems, so instrumenting code that runs
  under the scheduler or journal locks cannot create an ordering cycle.
  Under ``SWTPU_SANITIZE=1`` the lock rides the concurrency sanitizer
  like the scheduler's own locks do.
- **Fail loud on misuse, never on recording.** Wrong kind / wrong label
  set raises (these are programming errors the tests catch); recording
  itself never raises.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from ..core.locking import requires_lock
from .clock import Clock, wall_clock
from .names import MetricSpec


class _Histogram:
    """Fixed-bucket histogram data for one label combination."""

    __slots__ = ("bucket_counts", "sum", "count")

    def __init__(self, nbuckets: int):
        self.bucket_counts = [0] * (nbuckets + 1)   # + the +Inf bucket
        self.sum = 0.0
        self.count = 0


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _fmt(value: float) -> str:
    """Prometheus sample value: integral values render without the
    trailing .0 noise, everything else as repr (full precision)."""
    f = float(value)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


class MetricsRegistry:
    def __init__(self, clock: Optional[Clock] = None, enabled: bool = True):
        self._clock: Clock = clock or wall_clock
        self._enabled = enabled
        self._specs: Dict[str, MetricSpec] = {}
        # Scalar storage (counters + gauges): name -> {label_values: v}.
        self._scalars: Dict[str, Dict[Tuple[str, ...], float]] = {}
        self._hists: Dict[str, Dict[Tuple[str, ...], _Histogram]] = {}
        from ..analysis.sanitizer import maybe_wrap
        self._lock = maybe_wrap(threading.Lock(), "MetricsRegistry._lock")

    # The registry rides inside scheduler objects that get pickled by
    # the simulation-checkpoint path; the lock must not go with it.
    def __getstate__(self):
        state = dict(self.__dict__)
        del state["_lock"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        from ..analysis.sanitizer import maybe_wrap
        self._lock = maybe_wrap(threading.Lock(), "MetricsRegistry._lock")

    @property
    def clock(self) -> Clock:
        return self._clock

    @property
    def enabled(self) -> bool:
        return self._enabled

    # -- spec/label plumbing -------------------------------------------

    @requires_lock
    def _resolve(self, spec: MetricSpec, kind: str,
                 labels: dict) -> Tuple[str, Tuple[str, ...]]:
        """Validate kind/labels and return (name, label-value key).
        Hot path: recording runs inside the scheduler's round loop, so
        the common case (known spec, correct labels) is identity checks
        and one tuple build — no set construction, no dataclass eq."""
        if spec.kind != kind:
            raise ValueError(
                f"{spec.name} is a {spec.kind}, not a {kind}")
        known = self._specs.get(spec.name)
        if known is None:
            self._specs[spec.name] = spec
        elif known is not spec and known != spec:
            raise ValueError(
                f"metric {spec.name!r} redeclared with a different shape")
        if len(labels) != len(spec.labels):
            raise ValueError(
                f"{spec.name}: labels {sorted(labels)} != declared "
                f"{sorted(spec.labels)}")
        try:
            return spec.name, tuple(str(labels[k]) for k in spec.labels)
        except KeyError:
            raise ValueError(
                f"{spec.name}: labels {sorted(labels)} != declared "
                f"{sorted(spec.labels)}") from None

    # -- recording ------------------------------------------------------

    def inc(self, spec: MetricSpec, amount: float = 1.0, **labels) -> None:
        if not self._enabled:
            return
        if amount < 0:
            raise ValueError(f"{spec.name}: counters only go up")
        with self._lock:
            name, key = self._resolve(spec, "counter", labels)
            series = self._scalars.setdefault(name, {})
            series[key] = series.get(key, 0.0) + amount

    def set_gauge(self, spec: MetricSpec, value: float, **labels) -> None:
        if not self._enabled:
            return
        with self._lock:
            name, key = self._resolve(spec, "gauge", labels)
            self._scalars.setdefault(name, {})[key] = float(value)

    def observe(self, spec: MetricSpec, value: float, **labels) -> None:
        if not self._enabled:
            return
        with self._lock:
            name, key = self._resolve(spec, "histogram", labels)
            series = self._hists.setdefault(name, {})
            hist = series.get(key)
            if hist is None:
                hist = series[key] = _Histogram(len(spec.buckets))
            v = float(value)
            for i, bound in enumerate(spec.buckets):
                if v <= bound:
                    hist.bucket_counts[i] += 1
                    break
            else:
                hist.bucket_counts[-1] += 1
            hist.sum += v
            hist.count += 1

    @contextmanager
    def timed(self, spec: MetricSpec, **labels):
        """Observe the clock delta across the block into a histogram."""
        if not self._enabled:
            yield
            return
        t0 = self._clock()
        try:
            yield
        finally:
            self.observe(spec, max(self._clock() - t0, 0.0), **labels)

    def remove_series(self, spec: MetricSpec, **labels) -> None:
        """Drop one label combination's series (no-op if absent). For
        retired entities — e.g. a dead worker host's heartbeat-age
        gauge, which would otherwise export its last pre-retirement
        value forever, masking exactly the event it exists to show."""
        if not self._enabled:
            return
        with self._lock:
            name, key = self._resolve(spec, spec.kind, labels)
            store = (self._hists if spec.kind == "histogram"
                     else self._scalars)
            store.get(name, {}).pop(key, None)

    # -- reading (tests, reports, exporter) -----------------------------

    def value(self, spec: MetricSpec, **labels) -> float:
        """Current counter/gauge value (0.0 when never recorded)."""
        with self._lock:
            _, key = self._resolve(spec, spec.kind, labels)
            return self._scalars.get(spec.name, {}).get(key, 0.0)

    def histogram_stats(self, spec: MetricSpec,
                        **labels) -> Tuple[int, float]:
        """(count, sum) of a histogram series ((0, 0.0) if unrecorded)."""
        with self._lock:
            _, key = self._resolve(spec, "histogram", labels)
            hist = self._hists.get(spec.name, {}).get(key)
            return (hist.count, hist.sum) if hist else (0, 0.0)

    def snapshot(self) -> dict:
        """All recorded series as plain data (dump/debug helper)."""
        with self._lock:
            out: dict = {}
            for name, series in self._scalars.items():
                spec = self._specs[name]
                out[name] = {
                    "kind": spec.kind,
                    "series": {key: v for key, v in series.items()}}
            for name, series in self._hists.items():
                spec = self._specs[name]
                out[name] = {
                    "kind": "histogram",
                    "series": {key: {"count": h.count, "sum": h.sum,
                                     "buckets": list(h.bucket_counts)}
                               for key, h in series.items()}}
            return out

    # -- Prometheus text exposition ------------------------------------

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4 of every recorded
        series (specs touched but never recorded render header-only)."""
        with self._lock:
            lines: List[str] = []
            for name in sorted(self._specs):
                spec = self._specs[name]
                lines.append(f"# HELP {name} {spec.help}")
                lines.append(f"# TYPE {name} {spec.kind}")
                if spec.kind == "histogram":
                    for key, hist in sorted(
                            self._hists.get(name, {}).items()):
                        base = dict(zip(spec.labels, key))
                        cum = 0
                        for bound, n in zip(spec.buckets,
                                            hist.bucket_counts):
                            cum += n
                            lines.append(self._sample(
                                f"{name}_bucket",
                                dict(base, le=_fmt(bound)), cum))
                        lines.append(self._sample(
                            f"{name}_bucket", dict(base, le="+Inf"),
                            hist.count))
                        lines.append(self._sample(f"{name}_sum", base,
                                                  hist.sum))
                        lines.append(self._sample(f"{name}_count", base,
                                                  hist.count))
                else:
                    for key, v in sorted(
                            self._scalars.get(name, {}).items()):
                        lines.append(self._sample(
                            name, dict(zip(spec.labels, key)), v))
            return "\n".join(lines) + "\n"

    @staticmethod
    def _sample(name: str, labels: dict, value: float) -> str:
        if labels:
            body = ",".join(f'{k}="{_escape_label(v)}"'
                            for k, v in labels.items())
            return f"{name}{{{body}}} {_fmt(value)}"
        return f"{name} {_fmt(value)}"
