"""Telemetry history: a crash-safe ring of per-round metric snapshots
plus per-microtask observed-throughput points.

Instantaneous gauges answer "what is happening"; the learned throughput
oracle (ROADMAP item 2) and any regression analysis need "what has been
happening" — and nothing retained that beyond the journal's accounting
events. This module keeps two bounded rings:

- **rounds**: one flattened snapshot of every registered metric per
  scheduling round (counters, gauges, histogram count/sum), stamped
  with the injected clock;
- **observations**: one ``(job_type, batch_size, scale_factor,
  worker_type) -> observed steps/s`` point per completed micro-task —
  exactly the training set a learned performance model consumes
  (PAPERS.md 2008.01040);
- **serving**: one measured-serving row per (service, round) with
  samples — measured p50/p99, tokens/s, the analytic p99 and the
  online mu estimate (serving/tier.take_measured_rows) — the
  ``mu``-estimation / latency-calibration training set.

Both rings are flushed to ONE file (``history.json`` in the state dir)
through `core/durable_io.write_text_atomic` every few rounds, so a
crash or an HA failover loses at most one flush interval and the
promoted leader reloads the ring and keeps appending — the history is
served by whichever process holds the journal. The exporter serves the
whole payload as ``/history.json``.

Simple burn-rate / regression checks run at every round sample and
surface as the ``swtpu_alert`` gauge (one series per check), which the
PR 8 health scorer and the PR 9 what-if forecasts can read off the
shared registry.

Off by default in simulation: the scheduler only constructs a history
when configured (physical drivers enable it), so canonical replays
never execute this code.
"""
from __future__ import annotations

import json
import threading
from collections import deque
from typing import Dict, List, Optional

from ..core.locking import requires_lock
from . import names
from .clock import Clock
from .registry import MetricsRegistry

#: Ring bounds: ~512 rounds of snapshots (days at 360 s rounds) and a
#: few thousand throughput / measured-serving points.
DEFAULT_MAX_ROUNDS = 512
DEFAULT_MAX_OBSERVATIONS = 8192
DEFAULT_MAX_SERVING = 4096
DEFAULT_FLUSH_INTERVAL_ROUNDS = 8

HISTORY_SCHEMA = 1

#: Schema of the per-microtask observation rows — the learned oracle's
#: training set (shockwave_tpu/oracle/train.py). Versioned separately
#: from the payload envelope so the trainer can skip-and-warn on rows
#: written by a different build instead of KeyError-ing mid-fit.
#: Version 1: ``[round:int, job_type:str, batch_size:int|float,
#: scale_factor:int, worker_type:str, steps_per_s:float]``.
OBSERVATIONS_SCHEMA = 1


def valid_observation(entry) -> bool:
    """Whether one observation ring row matches OBSERVATIONS_SCHEMA 1.
    Shared by the ring loader (crash recovery) and oracle.train (both
    must agree on what a training row is)."""
    return (isinstance(entry, list) and len(entry) == 6
            and isinstance(entry[0], int)
            and isinstance(entry[1], str)
            and isinstance(entry[2], (int, float))
            and not isinstance(entry[2], bool)
            and isinstance(entry[3], int)
            and isinstance(entry[4], str)
            and isinstance(entry[5], (int, float))
            and not isinstance(entry[5], bool))

#: Check names of the swtpu_alert gauge.
CHECK_ROUND_OVERRUN = "round_overrun"
CHECK_DISPATCH_BURN = "dispatch_failure_burn"
CHECK_THROUGHPUT_REGRESSION = "throughput_regression"

#: Thresholds (module constants so tests can reason about them).
ROUND_OVERRUN_FACTOR = 1.5
DISPATCH_BURN_WINDOW_ROUNDS = 8
DISPATCH_BURN_RATIO = 0.2
REGRESSION_MIN_SAMPLES = 6
REGRESSION_RATIO = 0.7


def _median(values: List[float]) -> float:
    ordered = sorted(values)
    n = len(ordered)
    mid = n // 2
    if n % 2:
        return ordered[mid]
    return 0.5 * (ordered[mid - 1] + ordered[mid])


class TelemetryHistory:
    """Bounded, durable telemetry rings over one MetricsRegistry."""

    #: Ring state shared between the scheduler round loop (sample/
    #: record under the scheduler lock), the exporter's request thread
    #: (/history.json serializes `payload`) and the done-callback gRPC
    #: threads (`record_observation`) — guarded by the history's own
    #: leaf lock; enforced by the lock-discipline pass and checked
    #: cross-thread by the race detector.
    _LOCK_PROTECTED = frozenset({
        "_rounds", "_observations", "_serving", "_alerts",
        "_samples_since_flush",
    })

    def __init__(self, registry: MetricsRegistry, clock: Clock,
                 path: str,
                 time_per_iteration: Optional[float] = None,
                 max_rounds: int = DEFAULT_MAX_ROUNDS,
                 max_observations: int = DEFAULT_MAX_OBSERVATIONS,
                 max_serving: int = DEFAULT_MAX_SERVING,
                 flush_interval_rounds: int = DEFAULT_FLUSH_INTERVAL_ROUNDS):
        self._registry = registry
        self._clock = clock
        self.path = path
        self._time_per_iteration = time_per_iteration
        self._flush_interval = max(int(flush_interval_rounds), 1)
        self._rounds: "deque[dict]" = deque(maxlen=max_rounds)
        self._observations: "deque[list]" = deque(maxlen=max_observations)
        self._serving: "deque[dict]" = deque(maxlen=max_serving)
        self._alerts: Dict[str, int] = {}
        self._samples_since_flush = 0
        # Leaf lock: the round loop appends under the scheduler lock
        # while the exporter's request thread reads /history.json; like
        # the registry lock it is never held across another subsystem.
        from ..analysis.sanitizer import maybe_wrap
        self._lock = maybe_wrap(threading.Lock(),
                                "TelemetryHistory._lock")
        with self._lock:
            self._load()

    @classmethod
    def from_config(cls, cfg: Optional[dict], registry, clock, path,
                    time_per_iteration=None) -> "TelemetryHistory":
        cfg = dict(cfg or {})
        return cls(registry, clock,
                   path=cfg.get("path", path),
                   time_per_iteration=time_per_iteration,
                   max_rounds=int(cfg.get("max_rounds",
                                          DEFAULT_MAX_ROUNDS)),
                   max_observations=int(cfg.get(
                       "max_observations", DEFAULT_MAX_OBSERVATIONS)),
                   max_serving=int(cfg.get("max_serving",
                                           DEFAULT_MAX_SERVING)),
                   flush_interval_rounds=int(cfg.get(
                       "flush_interval_rounds",
                       DEFAULT_FLUSH_INTERVAL_ROUNDS)))

    # -- durability -----------------------------------------------------

    @requires_lock
    def _load(self) -> None:
        """Seed the rings from a previous incarnation's flush (crash
        recovery / HA takeover); a missing, foreign, future-schema or
        partially-malformed file contributes nothing rather than
        planting entries the alert checks (which run inside the round
        loop) would KeyError on."""
        try:
            with open(self.path, encoding="utf-8") as f:
                payload = json.load(f)
        except (OSError, ValueError):
            return
        if not isinstance(payload, dict):
            return
        schema = payload.get("schema")
        if schema != HISTORY_SCHEMA:
            import logging
            logging.getLogger("shockwave_tpu.obs").warning(
                "telemetry history %s has schema %r (this build writes "
                "%d); starting a fresh ring", self.path, schema,
                HISTORY_SCHEMA)
            return
        for entry in payload.get("rounds", []):
            if (isinstance(entry, dict) and "round" in entry
                    and isinstance(entry.get("t"), (int, float))
                    and isinstance(entry.get("metrics"), dict)):
                self._rounds.append(entry)
        obs_schema = payload.get("observations_schema")
        if obs_schema in (None, OBSERVATIONS_SCHEMA):
            # None is a pre-versioning flush: its rows still validate
            # individually. A different version contributes nothing.
            for entry in payload.get("observations", []):
                if valid_observation(entry):
                    self._observations.append(entry)
        else:
            import logging
            logging.getLogger("shockwave_tpu.obs").warning(
                "telemetry history %s has observations_schema %r (this "
                "build writes %d); dropping its observation rows",
                self.path, obs_schema, OBSERVATIONS_SCHEMA)
        for entry in payload.get("serving", []):
            if (isinstance(entry, dict) and "service" in entry
                    and "round" in entry):
                self._serving.append(entry)

    def flush(self) -> str:
        from ..core.durable_io import write_text_atomic
        text = json.dumps(self.payload())
        write_text_atomic(self.path, text)
        with self._lock:
            self._samples_since_flush = 0
        self._registry.inc(names.HISTORY_FLUSHES_TOTAL)
        return self.path

    # -- sampling -------------------------------------------------------

    @staticmethod
    def _flatten_snapshot(snapshot: dict) -> Dict[str, float]:
        """Registry snapshot -> flat {series_key: value}; histogram
        series flatten to _count and _sum."""
        flat: Dict[str, float] = {}
        for name, data in snapshot.items():
            for key, value in data.get("series", {}).items():
                label = ",".join(str(k) for k in key)
                suffix = f"{{{label}}}" if label else ""
                if data.get("kind") == "histogram":
                    flat[f"{name}_count{suffix}"] = float(value["count"])
                    flat[f"{name}_sum{suffix}"] = float(value["sum"])
                else:
                    flat[f"{name}{suffix}"] = float(value)
        return flat

    def sample_round(self, round_id: int) -> None:
        """Append one full metric snapshot for a completed round, run
        the alert checks, and flush if the interval is due."""
        entry = {"round": int(round_id), "t": float(self._clock()),
                 "metrics": self._flatten_snapshot(
                     self._registry.snapshot())}
        with self._lock:
            self._rounds.append(entry)
            verdicts = self._compute_checks_locked()
            self._alerts = verdicts
            self._samples_since_flush += 1
            need_flush = self._samples_since_flush >= self._flush_interval
        self._registry.inc(names.HISTORY_SAMPLES_TOTAL, kind="round")
        for check, firing in verdicts.items():
            self._registry.set_gauge(names.ALERT, float(firing),
                                     check=check)
        if need_flush:
            self.flush()

    def record_observation(self, job_type: str, batch_size,
                           scale_factor: int, worker_type: str,
                           steps_per_s: float, round_id: int) -> None:
        """One per-microtask observed rate point — the learned-oracle
        training row."""
        with self._lock:
            self._observations.append(
                [int(round_id), str(job_type), batch_size,
                 int(scale_factor), str(worker_type),
                 float(steps_per_s)])
        self._registry.inc(names.HISTORY_SAMPLES_TOTAL,
                           kind="observation")

    def record_serving(self, row: dict, round_id: int) -> None:
        """One measured-serving round row (serving/tier
        `take_measured_rows` output): the latency-calibration and
        mu-estimation training point."""
        with self._lock:
            self._serving.append(dict(row, round=int(round_id)))
        self._registry.inc(names.HISTORY_SAMPLES_TOTAL, kind="serving")

    # -- checks ---------------------------------------------------------

    @requires_lock
    def _metric_delta(self, series_key: str, window: int) -> float:
        """Counter increase of `series_key` over the last `window`
        round samples (0.0 with insufficient history)."""
        if len(self._rounds) < 2:
            return 0.0
        recent = list(self._rounds)[-(window + 1):]
        first = recent[0]["metrics"].get(series_key, 0.0)
        last = recent[-1]["metrics"].get(series_key, 0.0)
        return max(last - first, 0.0)

    @requires_lock
    def _compute_checks_locked(self) -> Dict[str, int]:
        """All check verdicts; caller holds self._lock (the checks read
        the rings) and publishes the gauges outside it."""
        return {
            CHECK_ROUND_OVERRUN: self._check_round_overrun(),
            CHECK_DISPATCH_BURN: self._check_dispatch_burn(),
            CHECK_THROUGHPUT_REGRESSION: self._check_regression(),
        }

    @requires_lock
    def _check_round_overrun(self) -> int:
        if self._time_per_iteration is None or len(self._rounds) < 2:
            return 0
        wall = self._rounds[-1]["t"] - self._rounds[-2]["t"]
        return int(wall > ROUND_OVERRUN_FACTOR * self._time_per_iteration)

    @requires_lock
    def _check_dispatch_burn(self) -> int:
        window = DISPATCH_BURN_WINDOW_ROUNDS
        bad = (self._metric_delta(
                   "swtpu_dispatches_total{unavailable}", window)
               + self._metric_delta(
                   "swtpu_dispatches_total{rejected}", window))
        ok = self._metric_delta("swtpu_dispatches_total{ok}", window)
        total = ok + bad
        return int(total > 0 and bad / total > DISPATCH_BURN_RATIO)

    @requires_lock
    def _check_regression(self) -> int:
        by_key: Dict[tuple, List[float]] = {}
        for rnd, job_type, bs, sf, wt, rate in self._observations:
            by_key.setdefault((job_type, bs, sf, wt), []).append(rate)
        for rates in by_key.values():
            if len(rates) < REGRESSION_MIN_SAMPLES:
                continue
            head, tail = rates[:-3], rates[-3:]
            if not head:
                continue
            if _median(tail) < REGRESSION_RATIO * _median(head):
                return 1
        return 0

    # -- reading (exporter /history.json, tests) ------------------------

    @property
    def alerts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._alerts)

    def payload(self) -> dict:
        with self._lock:
            return {
                "schema": HISTORY_SCHEMA,
                "observations_schema": OBSERVATIONS_SCHEMA,
                "rounds": list(self._rounds),
                "observations": [list(o) for o in self._observations],
                "serving": [dict(s) for s in self._serving],
                "alerts": dict(self._alerts),
            }
