"""Min-total-duration ("OSSP") policy: minimize makespan of current jobs.

Binary search on horizon T; for each T a feasibility LP checks whether
every job can finish its remaining steps within T (reference:
scheduler/policies/min_total_duration.py:55-135).
"""
from __future__ import annotations

import numpy as np

from .lp import LinearProgram, solve_feasibility
from .policy import Policy, PolicyWithPacking


class MinTotalDurationPolicyWithPerf(Policy):
    name = "MinTotalDuration_Perf"

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       num_steps_remaining, cluster_spec):
        throughputs, index = self.flatten(unflattened_throughputs, cluster_spec)
        if throughputs is None:
            return None
        m, n = throughputs.shape
        job_ids, _ = index
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)
        remaining = np.array([num_steps_remaining[j] for j in job_ids], dtype=float)

        def feasible(T: float):
            lp = LinearProgram(m * n)
            for i in range(m):
                row = lp.row()
                row[i * n:(i + 1) * n] = -throughputs[i]
                lp.add_le(row, -remaining[i] / T)
            for row, rhs in zip(*self.cluster_capacity_rows(m, n, sf, self._num_workers)):
                lp.add_le(row, rhs)
            for row, rhs in zip(*self.job_time_rows(m, n)):
                lp.add_le(row, rhs)
            return solve_feasibility(lp)

        lo, hi = 100.0, 1e6
        while (best := feasible(hi)) is None:
            lo, hi = hi, hi * 10.0
            if hi > 1e12:
                return None
        while hi > lo * 1.05:
            mid = (lo + hi) / 2.0
            x = feasible(mid)
            if x is not None:
                best, hi = x, mid
            else:
                lo = mid
        return self.unflatten(best.reshape((m, n)).clip(0.0, 1.0), index)


class MinTotalDurationPolicyWithPacking(PolicyWithPacking):
    """Packed variant: each single job's effective throughput sums over all
    combinations containing it (reference: min_total_duration.py:138-234)."""

    name = "MinTotalDuration_Packing"

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       num_steps_remaining, cluster_spec):
        tensor, index = self.flatten(unflattened_throughputs, cluster_spec)
        if tensor is None or len(tensor) == 0:
            return None
        job_ids, single_job_ids, worker_types, relevant = index
        m, n = tensor[0].shape
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)
        remaining = np.array([num_steps_remaining[s] for s in single_job_ids],
                             dtype=float)

        def feasible(T: float):
            lp = LinearProgram(m * n)
            for si, s in enumerate(single_job_ids):
                row = lp.row()
                for ci in relevant[s]:
                    row[ci * n:(ci + 1) * n] = -tensor[si, ci]
                lp.add_le(row, -remaining[si] / T)
            for row, rhs in zip(*self.cluster_capacity_rows(
                    m, n, sf, self._num_workers)):
                lp.add_le(row, rhs)
            for row, rhs in zip(*self.per_job_time_rows(
                    job_ids, single_job_ids, relevant, n)):
                lp.add_le(row, rhs)
            for i in range(m):
                for j in range(n):
                    if sf[i, j] == 0:
                        lp.bounds[i * n + j] = (0, 0)
            return solve_feasibility(lp)

        lo, hi = 100.0, 1e6
        while (best := feasible(hi)) is None:
            lo, hi = hi, hi * 10.0
            if hi > 1e12:
                return None
        while hi > lo * 1.05:
            mid = (lo + hi) / 2.0
            x = feasible(mid)
            if x is not None:
                best, hi = x, mid
            else:
                lo = mid
        return self.unflatten(best.reshape((m, n)).clip(0.0, 1.0), index)


class MinTotalDurationPolicy(Policy):
    """Collapses worker types to the reference type before delegating."""

    name = "MinTotalDuration"

    def __init__(self, solver=None, reference_worker_type="v100"):
        super().__init__(solver)
        self._perf = MinTotalDurationPolicyWithPerf(solver)
        self._reference_worker_type = reference_worker_type

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       num_steps_remaining, cluster_spec):
        uniform = {
            job_id: {wt: per_wt[self._reference_worker_type] for wt in per_wt}
            for job_id, per_wt in unflattened_throughputs.items()
        }
        if not uniform:
            return None
        return self._perf.get_allocation(uniform, scale_factors,
                                         num_steps_remaining, cluster_spec)
