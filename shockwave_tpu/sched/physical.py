"""Physical-cluster execution: the round mechanism over real workers.

`PhysicalScheduler` extends the simulator-capable core with:
- wall-clock time and thread-safe callback entry points,
- the begin/mid/end round pipeline: recompute the schedule at 50% of the
  round, extend leases when placements repeat, dispatch the next round
  early, and enforce round completion with watchdog events,
- the lease protocol callbacks (init / renew / consensus for multi-chip
  gangs) and failure handling (kill unresponsive jobs)
(reference: scheduler/scheduler.py:2382-2777, 3880-4339).
"""
from __future__ import annotations

import collections
import copy
import logging
import math
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..core.job import JobIdPair
from .scheduler import DEADLINE_SLACK, INFINITY, Scheduler, SchedulerConfig

logger = logging.getLogger("shockwave_tpu.sched")

SCHEDULE_RECOMPUTE_FRACTION = 0.5
JOB_COMPLETION_BUFFER_TIME = 60.0
EARLY_INIT_THRESHOLD = 3.0
# Minimum initial lease grant. TPU jobs can spend most of a round in
# imports + jit compilation before InitJob arrives; granting only the
# round's sliver of remaining time would expire the lease before a
# single step, and the job would livelock re-paying startup every round.
# Must stay below JOB_COMPLETION_BUFFER_TIME so the round-end kill
# watchdog still leaves room for the expiry checkpoint.
INIT_LEASE_FLOOR_S = 45.0
# A job whose latest heartbeat is younger than this is never killed as
# unresponsive — the kill timer re-arms once instead (it may be running
# its lease-expiry checkpoint right now).
KILL_HEARTBEAT_FRESHNESS_S = 30.0
BASE_JOB_PORT = 60570
MAX_PORT = 65535


class PhysicalScheduler(Scheduler):
    def __init__(self, policy, throughputs_file=None, profiles=None,
                 config: Optional[SchedulerConfig] = None,
                 expected_num_workers: Optional[int] = None,
                 port: int = 50070):
        super().__init__(policy, simulate=False,
                         throughputs_file=throughputs_file, profiles=profiles,
                         config=config)
        self._start_time = time.time()
        self._lock = threading.RLock()
        self._cv = threading.Condition(self._lock)
        self._expected_num_workers = expected_num_workers

        self._worker_connections: Dict[int, object] = {}
        self._available_workers: "queue.Queue[int]" = queue.Queue()
        self._lease_update_requests: Dict[JobIdPair, list] = {}
        self._last_heartbeat: Dict[JobIdPair, float] = {}
        # Jobs that have reached at least one RPC since their LATEST
        # dispatch — only these may be unresponsive-killed before the
        # first-init grace expires (see SchedulerConfig.first_init_grace_s).
        self._ever_signaled: set = set()
        self._max_steps_consensus: Dict[JobIdPair, Optional[int]] = {}
        self._completion_events: Dict[JobIdPair, threading.Timer] = {}
        self._redispatch_assignments: "collections.OrderedDict" = collections.OrderedDict()
        self._current_round_start_time = 0.0
        self._port_offset = 0
        self._done_event = threading.Event()

        from ..runtime.servers import serve_scheduler
        self._server = serve_scheduler(port, {
            "RegisterWorker": self._register_worker_rpc,
            "Done": self.done_callback,
            "InitJob": self._init_job_callback,
            "UpdateLease": self._update_lease_callback,
            "UpdateResourceRequirement": self._update_resource_requirement_callback,
        })

        if self._config.watchdog_interval:
            import faulthandler
            faulthandler.dump_traceback_later(
                self._config.watchdog_interval, repeat=True)

        if policy.name != "shockwave":
            threading.Thread(target=self._allocation_thread, daemon=True).start()

    # ------------------------------------------------------------------
    # Time / threading
    # ------------------------------------------------------------------

    def get_current_timestamp(self) -> float:
        return time.time()

    def add_job(self, job, timestamp=None):
        with self._cv:
            job_id = super().add_job(job, timestamp)
            self._lease_update_requests[job_id] = []
            self._max_steps_consensus[job_id] = None
            self._cv.notify_all()
            return job_id

    def _remove_job(self, job_id: JobIdPair) -> None:
        super()._remove_job(job_id)
        # Drop per-job protocol state so a long-running scheduler does not
        # grow without bound (and a straggler RPC cannot resurrect it).
        for m in job_id.singletons():
            self._last_heartbeat.pop(m, None)
            self._ever_signaled.discard(m)
            self._lease_update_requests.pop(m, None)
            self._max_steps_consensus.pop(m, None)

    # ------------------------------------------------------------------
    # RPC callbacks
    # ------------------------------------------------------------------

    def _register_worker_rpc(self, worker_type, num_chips, ip_addr, port):
        from ..runtime.clients import SchedulerToWorkerClient
        client = SchedulerToWorkerClient(ip_addr, port)
        with self._cv:
            worker_ids, round_duration = self.register_worker(
                worker_type, num_chips)
            for worker_id in worker_ids:
                self._worker_connections[worker_id] = client
            self._cv.notify_all()
        return worker_ids, round_duration

    def _init_job_callback(self, job_id: JobIdPair):
        """Grant the initial lease (reference: scheduler.py:3880-4048)."""
        with self._cv:
            if job_id not in self.acct.jobs:
                return (0, 0.0, 0.0)
            # If the job was dispatched early for the *next* round, wait for
            # its current-round run (or a colocated partner) to finish.
            while True:
                next_combo = None
                if self.rounds.next_assignments is not None:
                    for combo in self.rounds.next_assignments:
                        if job_id.overlaps_with(combo):
                            next_combo = combo
                            break
                blocked = False
                if next_combo is not None:
                    for combo in self.rounds.current_assignments:
                        for m in next_combo.singletons():
                            if (m.overlaps_with(combo) and combo not in
                                    self.rounds.completed_in_round):
                                blocked = True
                if blocked:
                    self._cv.wait()
                else:
                    break

            self.acct.latest_timestamps[job_id] = self.get_current_timestamp()
            for m in job_id.singletons():
                self._running_jobs.add(m)
                self._last_heartbeat[m] = self.get_current_timestamp()
                self._ever_signaled.add(m)

            job = self.acct.jobs[job_id]
            remaining = int(math.ceil(
                self._get_remaining_steps(job_id) / job.scale_factor))
            now = self.get_current_timestamp()
            round_end = self._current_round_start_time + self._time_per_iteration
            time_left = max(round_end - now, 0.0)

            if self.rounds.next_assignments is not None and next_combo is not None:
                # Early dispatch for the next round: full round + leftover.
                return (remaining, self._time_per_iteration, time_left)
            if time_left > 0:
                # Floor clamped to the round duration: with short rounds
                # (< INIT_LEASE_FLOOR_S) an unclamped floor would overrun
                # every round and delay the next dispatch on this chip.
                floor = min(INIT_LEASE_FLOOR_S, self._time_per_iteration)
                return (remaining, max(time_left, floor), 0.0)
            # Init in the gap between rounds.
            return (remaining, self._time_per_iteration - EARLY_INIT_THRESHOLD,
                    time_left)

    def _update_lease_callback(self, job_id: JobIdPair, worker_id: int,
                               steps: int, duration: float, max_steps: int,
                               max_duration: float):
        """Renew a lease (reference: scheduler.py:4050-4180)."""
        with self._lock:
            if job_id not in self.acct.jobs:
                return (0, 0.0, 0.0, 0.0)
            job = self.acct.jobs[job_id]
            run_time_so_far = int(
                sum(self.acct.run_time_per_worker[job_id].values())
                / job.scale_factor)
            deadline = int(job.duration * DEADLINE_SLACK)
            self._lease_update_requests.setdefault(job_id, [])
            update_id = len(self._lease_update_requests[job_id])
            self._lease_update_requests[job_id].append(
                (steps, duration, max_steps, max_duration))
            self._last_heartbeat[job_id] = self.get_current_timestamp()
            self._ever_signaled.add(job_id)

            scale_factor = job.scale_factor
            remaining = int(math.ceil(
                self._get_remaining_steps(job_id) / scale_factor))
            now = self.get_current_timestamp()
            round_end = self._current_round_start_time + self._time_per_iteration
            time_left = max(0.0, round_end - now)

            # Track in-lease progress so the planner sees fresh epochs even
            # under extended leases.
            self._steps_run_in_current_lease[job_id] = steps * scale_factor

        if steps == 0 or duration == 0:
            return (remaining, time_left, run_time_so_far, deadline)

        with self._lock:
            for combo in self.rounds.extended_leases:
                if job_id.overlaps_with(combo):
                    extended = duration + time_left + self._time_per_iteration
                    return (max_steps, extended, run_time_so_far, deadline)

        if scale_factor == 1:
            return (max_steps, duration + time_left, run_time_so_far, deadline)

        # Multi-chip gang: the first renewer computes the shared step budget;
        # the rest adopt it (first-requester-computes consensus).
        if update_id == 0:
            with self._lock:
                throughput = steps / duration
                self._max_steps_consensus[job_id] = min(
                    remaining, steps + int(time_left * throughput))
                return (self._max_steps_consensus[job_id], INFINITY,
                        run_time_so_far, deadline)
        while True:
            with self._lock:
                consensus = self._max_steps_consensus.get(job_id)
            if consensus is not None:
                return (consensus, INFINITY, run_time_so_far, deadline)
            time.sleep(1)

    def _update_resource_requirement_callback(self, job_id: JobIdPair,
                                              worker_id: int, big_bs: bool,
                                              small_bs: bool):
        with self._cv:
            if job_id not in self._bs_flags:
                return
            if big_bs:
                self._bs_flags[job_id]["big_bs"] = True
            else:
                self._bs_flags[job_id]["small_bs"] = True
            self._cv.notify_all()

    def done_callback(self, job_id, worker_id, all_num_steps,
                      all_execution_times, iterator_logs=None):
        with self._cv:
            # If the job was dispatched for round r+1 and finished before
            # round r closed, wait for the round boundary.
            while (job_id not in self.rounds.current_assignments
                   or job_id in self.rounds.completed_in_round):
                if (job_id not in self.rounds.current_assignments
                        and self.rounds.next_assignments is not None
                        and job_id not in self.rounds.next_assignments):
                    self.log.warning("discarding completion for unscheduled job %s",
                                   job_id)
                    return
                self._cv.wait()

            for m in job_id.singletons():
                if m in self.acct.jobs:
                    self.acct.latest_timestamps[m] = self.get_current_timestamp()
                    self._last_heartbeat[m] = self.get_current_timestamp()
                    self._ever_signaled.add(m)
            self._available_workers.put(worker_id)

            timer = self._completion_events.pop(job_id, None)
            if timer is not None:
                timer.cancel()

            super().done_callback(job_id, worker_id, all_num_steps,
                                  all_execution_times,
                                  iterator_logs=iterator_logs)

            for m in job_id.singletons():
                self._lease_update_requests[m] = []
                self._max_steps_consensus[m] = None

            # Early finisher holding an extended lease must be re-dispatched
            # for the round it was already granted.
            is_active = any(m in self.acct.jobs for m in job_id.singletons())
            if is_active and job_id in self.rounds.extended_leases:
                self._redispatch_assignments[job_id] = (
                    self.rounds.next_assignments[job_id])
            self._cv.notify_all()

    def _inflight_elapsed_times(self, current_time: float):
        """Unaccounted time of currently-running microtasks, charged into
        the priority fractions (reference: scheduler.py:3640-3666). Done
        callbacks only arrive when a process exits, so without this a
        lease-extended job looks like it has received no time at all and
        sticky placement would re-extend it until completion, starving
        the queue (observed as sequential JCTs in the CPU loopback
        fidelity run)."""
        inflight_job: dict = {}
        inflight_worker: dict = {}
        for job_id, worker_ids in self.rounds.current_assignments.items():
            # Only microtasks whose process is still alive: an exited
            # job stays in current_assignments until the round boundary,
            # but its real time was already charged by its done
            # callback — counting idle tail time would double-charge.
            # For colocated pairs, any still-running member keeps the
            # combo in flight (its peer's exit does not free the chip),
            # and the combo is charged once, from the latest dispatch
            # stamp among the running members.
            running = [m for m in job_id.singletons()
                       if m in self._running_jobs
                       and self.acct.latest_timestamps.get(m) is not None]
            if not running or not worker_ids:
                continue
            dispatch = max(self.acct.latest_timestamps[m] for m in running)
            elapsed = current_time - max(dispatch, self._last_reset_time)
            if elapsed <= 0:
                continue
            wt = self.workers.id_to_type[worker_ids[0]]
            per_wt = inflight_job.setdefault(job_id, {})
            per_wt[wt] = per_wt.get(wt, 0.0) + elapsed
            inflight_worker[wt] = inflight_worker.get(wt, 0.0) + elapsed
        return inflight_job, inflight_worker

    # ------------------------------------------------------------------
    # Allocation thread
    # ------------------------------------------------------------------

    def _allocation_thread(self):
        while not self._done_event.is_set():
            with self._cv:
                while not self._need_to_update_allocation:
                    self._cv.wait(timeout=1.0)
                    if self._done_event.is_set():
                        return
                state = self._allocation_state()
            allocation = self._compute_allocation(state)
            with self._cv:
                self._allocation = allocation
                self._need_to_update_allocation = False
                self._cv.notify_all()

    # ------------------------------------------------------------------
    # Round pipeline
    # ------------------------------------------------------------------

    def _try_dispatch_job(self, job_id: JobIdPair, worker_ids: Tuple[int, ...],
                          next_round: bool = False):
        if not next_round or job_id not in self.rounds.current_assignments:
            self._in_progress_updates[job_id] = []
            self._iterator_log_buffers.pop(job_id, None)
            for m in job_id.singletons():
                self._lease_update_requests[m] = []
                self._max_steps_consensus[m] = None

        scale_factor = len(worker_ids)
        round_id = self.rounds.num_completed_rounds + (1 if next_round else 0)
        coordinator = None
        if scale_factor > 1:
            head = self._worker_connections[worker_ids[0]]
            port = BASE_JOB_PORT + self._port_offset
            self._port_offset = (self._port_offset + 1) % (MAX_PORT - BASE_JOB_PORT)
            coordinator = f"{head.addr}:{port}"

        for m in job_id.singletons():
            # The liveness clock starts at dispatch: process launch +
            # imports + jit compile all happen before the first RPC.
            self._last_heartbeat[m] = self.get_current_timestamp()
            self._ever_signaled.discard(m)  # cold spawn: init grace re-arms
        for rank, worker_id in enumerate(worker_ids):
            descriptions = []
            for m in job_id.singletons():
                job = self.acct.jobs[m]
                command = job.command
                if scale_factor > 1:
                    # Multi-chip gang: coordinator rendezvous for
                    # jax.distributed.initialize.
                    command += (f" --coordinator {coordinator}"
                                f" --num_processes {scale_factor}"
                                f" --process_id {rank}")
                descriptions.append(dict(
                    job_id=m.integer_job_id(), command=command,
                    working_directory=job.working_directory,
                    needs_data_dir=job.needs_data_dir,
                    num_steps_arg=job.num_steps_arg,
                    num_steps=job.total_steps, mode=job.mode))
            self._worker_connections[worker_id].run_job(
                descriptions, worker_id, round_id)
            if not next_round:
                self._remove_available_worker(worker_id)

    def _remove_available_worker(self, worker_id):
        try:
            # Drain this specific id (queue holds unique ids).
            items = []
            while True:
                item = self._available_workers.get_nowait()
                if item == worker_id:
                    break
                items.append(item)
            for item in items:
                self._available_workers.put(item)
        except queue.Empty:
            for item in items:
                self._available_workers.put(item)

    def _begin_round(self):
        self._current_round_start_time = self.get_current_timestamp()
        for job_id in self.rounds.current_assignments:
            for m in job_id.singletons():
                self._lease_update_requests[m] = []
                self._max_steps_consensus[m] = None
        for job_id, worker_ids in self._redispatch_assignments.items():
            if any(m in self.acct.jobs for m in job_id.singletons()):
                self.log.info("re-dispatching early-finished job %s", job_id)
                self._try_dispatch_job(job_id, worker_ids)
        self._redispatch_assignments = collections.OrderedDict()
        self.log.info("*** START ROUND %d ***", self.rounds.num_completed_rounds)

    def _is_final_round(self):
        return (self._config.max_rounds is not None
                and self.rounds.num_completed_rounds + 1 == self._config.max_rounds)

    def _mid_round(self):
        """Recompute next round's schedule, extend leases, dispatch early."""
        if self._is_final_round():
            self.rounds.extended_leases = set()
            return
        round_end = self._current_round_start_time + self._time_per_iteration

        self.rounds.next_assignments = self._schedule_jobs_on_workers()

        for job_id in self.rounds.current_assignments:
            if any(m in self.acct.jobs for m in job_id.singletons()):
                self.rounds.num_lease_opportunities += 1

        for job_id in self.rounds.current_assignments:
            current = set(self.rounds.current_assignments[job_id])
            if (job_id in self.rounds.next_assignments
                    and job_id not in self.rounds.completed_in_round):
                if current == set(self.rounds.next_assignments[job_id]):
                    self.rounds.extended_leases.add(job_id)
                    self.rounds.num_lease_extensions += 1
                else:
                    self.rounds.extended_leases.discard(job_id)
            else:
                self.rounds.extended_leases.discard(job_id)

        for job_id, worker_ids in self.rounds.next_assignments.items():
            if not any(m in self.acct.jobs for m in job_id.singletons()):
                continue
            if (job_id not in self.rounds.extended_leases
                    or job_id in self.rounds.completed_in_round):
                self._try_dispatch_job(job_id, worker_ids, next_round=True)

        self._schedule_completion_events(round_end)

    def _schedule_completion_events(self, round_end):
        """Watchdogs: kill jobs that miss the round deadline; synthesize
        completion for jobs with extended leases."""
        now = self.get_current_timestamp()
        for job_id in self.rounds.current_assignments:
            if not any(m in self.acct.jobs for m in job_id.singletons()):
                continue
            if job_id in self.rounds.completed_in_round:
                continue
            delay = round_end - now
            if job_id not in self.rounds.extended_leases:
                delay += (self._config.job_completion_buffer_s
                          if self._config.job_completion_buffer_s is not None
                          else JOB_COMPLETION_BUFFER_TIME)
                action = self._kill_job
            else:
                action = self._done_callback_extended_lease
            timer = threading.Timer(max(delay, 0.0), action, args=(job_id,))
            timer.daemon = True
            timer.start()
            self._completion_events[job_id] = timer

    def _end_round(self):
        """Wait for all scheduled jobs to complete, then roll the round."""
        jobs_to_complete = {
            job_id for job_id in self.rounds.current_assignments
            if any(m in self.acct.jobs for m in job_id.singletons())}
        while not jobs_to_complete.issubset(self.rounds.completed_in_round):
            self._cv.wait()

        for job_id in list(self.rounds.extended_leases):
            if job_id in self.acct.jobs:
                for worker_id in self.rounds.current_assignments[job_id]:
                    self._available_workers.put(worker_id)
            self.rounds.extended_leases.discard(job_id)

        if not self._is_final_round():
            assert self.rounds.next_assignments is not None
            for job_id, worker_ids in self.rounds.next_assignments.items():
                if any(m in self.acct.jobs for m in job_id.singletons()):
                    if job_id in self._redispatch_assignments:
                        continue
                    for worker_id in worker_ids:
                        self._remove_available_worker(worker_id)
            now = self.get_current_timestamp()
            remaining = (self._current_round_start_time
                         + self._time_per_iteration - now)
            if remaining > 0:
                self._cv.release()
                try:
                    time.sleep(remaining)
                finally:
                    self._cv.acquire()

        self.rounds.num_completed_rounds += 1
        self.rounds.completed_in_round = set()
        self.rounds.current_assignments = self.rounds.next_assignments or (
            collections.OrderedDict())
        self.rounds.next_assignments = None
        self._cv.notify_all()
        self.log.info("*** END ROUND %d ***", self.rounds.num_completed_rounds - 1)

    def _kill_job(self, job_id: JobIdPair):
        with self._cv:
            if job_id not in self.rounds.current_assignments:
                return
            if job_id not in self._completion_events:
                if (job_id in self.rounds.completed_in_round
                        and job_id not in self.rounds.extended_leases):
                    return
            grace = self._config.first_init_grace_s
            if grace and not any(m in self._ever_signaled
                                 for m in job_id.singletons()):
                dispatched = min((self._last_heartbeat.get(m, 0.0)
                                  for m in job_id.singletons()), default=0.0)
                waited = self.get_current_timestamp() - dispatched
                if waited < grace:
                    # Cold dispatch through a relayed TPU can spend minutes
                    # in backend init waiting for the chip grant; killing
                    # the waiter (SIGKILL) wedges the relay so the NEXT
                    # dispatch hangs too — a kill->wedge->kill livelock
                    # observed live on the v5e tunnel. Re-arm instead.
                    self.log.warning(
                        "job %s silent %.0fs after dispatch; granting "
                        "first-init grace (%.0fs)", job_id, waited, grace)
                    timer = threading.Timer(max(grace - waited, 1.0),
                                            self._kill_job, args=(job_id,))
                    timer.daemon = True
                    timer.start()
                    self._completion_events[job_id] = timer
                    return
            # A job that signaled moments ago (e.g. its first InitJob landed
            # just before the re-armed grace timer fired) is alive and mid-
            # checkpoint, not unresponsive: give it one short re-arm window
            # instead of killing it seconds after its first RPC.
            now = self.get_current_timestamp()
            youngest = max((self._last_heartbeat.get(m, 0.0)
                            for m in job_id.singletons()), default=0.0)
            if now - youngest < KILL_HEARTBEAT_FRESHNESS_S:
                timer = threading.Timer(KILL_HEARTBEAT_FRESHNESS_S,
                                        self._kill_job, args=(job_id,))
                timer.daemon = True
                timer.start()
                self._completion_events[job_id] = timer
                return
            self.log.warning("killing unresponsive job %s", job_id)
            worker_ids = self.rounds.current_assignments[job_id]
            servers = set()
            for worker_id in worker_ids:
                client = self._worker_connections[worker_id]
                if (client.addr, client.port) not in servers:
                    for m in job_id.singletons():
                        client.kill_job(m.integer_job_id())
                    servers.add((client.addr, client.port))
            self._completion_events.pop(job_id, None)
            prev_round = self.rounds.num_completed_rounds
            self._cv.wait(timeout=30)
            killed = (self.rounds.num_completed_rounds != prev_round
                      or job_id in self.rounds.completed_in_round)
            if killed:
                return
            all_ids = set(self.rounds.current_assignments[job_id])
            reported = {u[0] for u in self._in_progress_updates.get(job_id, [])}
            missing = all_ids - reported
        zeros = [0 for _ in job_id.singletons()]
        for worker_id in missing:
            self.done_callback(job_id, worker_id, zeros, zeros)

    def _done_callback_extended_lease(self, job_id: JobIdPair):
        """Round-boundary completion for jobs running across rounds on an
        extended lease (they never exit, so no worker Done arrives)."""
        kill = False
        with self._cv:
            if not any(m in self.acct.jobs for m in job_id.singletons()):
                return
            # Liveness by heartbeat age, not by per-round renewal count:
            # InitJob / UpdateLease / Done all stamp a heartbeat. On TPU
            # the first dispatch can spend most of a round inside jit
            # compilation before the first step, and a renewed lease's 75%
            # checkpoint can legitimately skip a round boundary, so the
            # reference's "no renewal this round => dead" rule
            # (scheduler.py:4313-4339) produces spurious kills here.
            now = self.get_current_timestamp()
            # Only live members count, and a missing stamp defaults to
            # `now`, not 0: when one job of a packed pair has already
            # completed (its heartbeat entry removed), a 0.0 default
            # would read as an ~epoch-old heartbeat and instantly kill
            # the surviving job.
            oldest = min((self._last_heartbeat.get(m, now)
                          for m in job_id.singletons()
                          if m in self.acct.jobs), default=now)
            if now - oldest > (self._time_per_iteration
                               + (self._config.job_completion_buffer_s
                                  if self._config.job_completion_buffer_s
                                  is not None
                                  else JOB_COMPLETION_BUFFER_TIME)):
                # No signal for over a round: job is unresponsive.
                kill = True
            elif job_id in self._completion_events:
                self.rounds.completed_in_round.add(job_id)
                del self._completion_events[job_id]
                for m in job_id.singletons():
                    self._lease_update_requests[m] = []
                    self._max_steps_consensus[m] = None
            if not kill:
                self._cv.notify_all()
        if kill:
            self._kill_job(job_id)

    # ------------------------------------------------------------------
    # Main loop
    # ------------------------------------------------------------------

    def run(self):
        """Drive the round mechanism until max_rounds (or forever)."""
        with self._cv:
            while not self.acct.jobs or (
                    self._expected_num_workers is not None
                    and len(self.workers.worker_ids) < self._expected_num_workers):
                self._cv.wait()
            if self._policy.name != "shockwave":
                while self._need_to_update_allocation:
                    self._cv.wait()
            self.rounds.current_assignments = self._schedule_jobs_on_workers()
            if self._shockwave_planner is not None:
                self._shockwave_planner.increment_round()
            for job_id, worker_ids in self.rounds.current_assignments.items():
                self._try_dispatch_job(job_id, worker_ids)

        while True:
            final = self._is_final_round()
            with self._cv:
                self._begin_round()
            time.sleep(self._time_per_iteration * SCHEDULE_RECOMPUTE_FRACTION)
            with self._cv:
                self._mid_round()
                if self._shockwave_planner is not None:
                    extended = copy.deepcopy(self.rounds.extended_leases)
                self._end_round()
                if self._shockwave_planner is not None:
                    self._update_shockwave_planner_physical(extended)
            if final or not self.acct.jobs and self._config.max_rounds is None:
                if final or self._all_done():
                    break
        self._done_event.set()

    def _all_done(self):
        with self._lock:
            return not self.acct.jobs

    def _update_shockwave_planner_physical(self, extended_leases):
        """Physical variant: account in-lease steps for extended leases
        (reference: scheduler.py:2294-2331)."""
        planner = self._shockwave_planner
        scheduled = self._scheduled_jobs_in_prev_round or []
        from ..core import constants
        for int_id in scheduled:
            job_id = JobIdPair(int_id)
            if job_id in self._completed_jobs:
                if int_id in planner.metadata:
                    planner.mark_progress(int_id, planner.metadata[int_id].epochs)
                continue
            if job_id not in self.acct.jobs:
                continue
            steps = sum(self.acct.steps_run.get(job_id, {}).values())
            if job_id in extended_leases:
                steps += self._steps_run_in_current_lease.get(job_id, 0)
            job = self.acct.jobs[job_id]
            epoch = math.floor(
                steps / constants.steps_per_epoch(job.model, job.batch_size))
            planner.mark_progress(int_id, epoch)
        active = {j.integer_job_id() for j in self.acct.jobs}
        for int_id in active - set(scheduled):
            planner.add_waiting_delay(int_id, self._time_per_iteration)
        planner.increment_round()
        self._rounds_since_reopt += 1
        from .scheduler import REOPT_ROUNDS
        if self._shockwave_job_completed or self._rounds_since_reopt >= REOPT_ROUNDS:
            self._shockwave_job_completed = False
            self._rounds_since_reopt = 0
            planner.request_resolve()

    def shutdown(self):
        self._done_event.set()
        for client in set(self._worker_connections.values()):
            client.shutdown()
        self._server.stop(grace=1)
