"""Tier-1 gate for swtpu-check (the static analysis suite) and unit
tests for the runtime concurrency sanitizer.

- The shipped tree must be analyzer-clean (exit 0): this is the CI
  gate that stops the invariants from rotting.
- Every pass has a fixture-based negative test proving it reports its
  seeded violation at the right file:line — and nothing else. The
  fixtures mark each seeded line with the string "SEEDED", so the
  expected line numbers are read from the fixture itself rather than
  hard-coded.
- The sanitizer tests prove the lock-order-cycle and unowned-access
  detectors fire on synthetic inversions and stay quiet on clean
  nesting (the loopback/recovery tests then run under it for real via
  the conftest fixture).
"""
import os
import shutil
import subprocess
import sys
import threading

from shockwave_tpu.analysis import __main__ as cli
from shockwave_tpu.analysis import passes, sanitizer
from shockwave_tpu.analysis.core import RepoIndex, SourceFile
from shockwave_tpu.core.locking import requires_lock

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "analysis")


def fixture_index(*names):
    files = []
    for name in names:
        path = os.path.join(FIXTURES, name)
        with open(path) as f:
            files.append(SourceFile(path, name, f.read()))
    return RepoIndex(files, FIXTURES)


def seeded_lines(name):
    with open(os.path.join(FIXTURES, name)) as f:
        return {i for i, line in enumerate(f.read().splitlines(), start=1)
                if "# SEEDED" in line}


def assert_exactly_seeded(findings, name, pass_id):
    """Each seeded line reported once, nothing else reported."""
    assert {f.pass_id for f in findings} <= {pass_id}
    assert {f.path for f in findings} <= {name}
    got = sorted(f.line for f in findings)
    assert got == sorted(seeded_lines(name)), (
        f"expected findings exactly at {sorted(seeded_lines(name))}, "
        f"got {[str(f) for f in findings]}")


class TestRepoIsClean:
    """The shipped tree passes its own analyzer — the tier-1 invariant
    gate."""

    def test_all_passes_clean(self):
        findings = cli.run(root=REPO)
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_cli_exits_zero(self):
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.analysis",
             "--root", REPO],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        assert "0 finding(s)" in out.stdout

    def test_cli_lists_all_passes_with_walls(self):
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.analysis", "--list"],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0
        for pass_id in ("lock-discipline", "journal-coverage",
                        "durability", "determinism", "exception-hygiene",
                        "obs-discipline", "thread-roots", "race-detector",
                        "deadlock", "hold-discipline",
                        "suppression-audit"):
            assert pass_id in out.stdout
        # Per-pass wall reporting (the analyzer-performance satellite).
        assert "[wall " in out.stdout
        assert "total analyzer wall:" in out.stdout

    def test_cli_json_report(self):
        import json
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.analysis",
             "--root", REPO, "--json"],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        report = json.loads(out.stdout)
        assert report["count"] == 0
        assert report["findings"] == []
        pass_ids = {p["id"] for p in report["passes"]}
        assert {"race-detector", "thread-roots",
                "suppression-audit"} <= pass_ids
        assert all("wall_s" in p and "findings" in p
                   for p in report["passes"])

    def test_cli_sarif_report(self):
        """--sarif: a valid SARIF 2.1.0 log with one rule per pass
        (all 11) and zero results on the clean tree."""
        import json
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.analysis",
             "--root", REPO, "--sarif"],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        sarif = json.loads(out.stdout)
        assert sarif["version"] == "2.1.0"
        run = sarif["runs"][0]
        assert run["tool"]["driver"]["name"] == "swtpu-check"
        rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
        assert {"deadlock", "hold-discipline", "race-detector",
                "suppression-audit"} <= rule_ids
        assert len(rule_ids) == 11
        assert run["results"] == []

    def test_sarif_results_carry_location_and_rule(self, tmp_path):
        """A broken tree's SARIF results anchor ruleId + file:line."""
        pkg = tmp_path / "shockwave_tpu"
        pkg.mkdir()
        shutil.copy(os.path.join(FIXTURES, "bad_exceptions.py"),
                    pkg / "bad_exceptions.py")
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.analysis",
             "--root", str(tmp_path), "--sarif"],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 1
        import json
        results = json.loads(out.stdout)["runs"][0]["results"]
        assert results, "expected findings from the seeded fixture"
        got = {(r["ruleId"],
                r["locations"][0]["physicalLocation"]
                ["artifactLocation"]["uri"],
                r["locations"][0]["physicalLocation"]
                ["region"]["startLine"])
               for r in results}
        for line in seeded_lines("bad_exceptions.py"):
            assert ("exception-hygiene",
                    "shockwave_tpu/bad_exceptions.py", line) in got

    def test_cli_lock_graph_matches_library(self):
        """--lock-graph prints the static order graph, non-vacuously:
        the scheduler's lock orders over its service singletons must
        be present (a vacuously empty graph would make the containment
        gate pass trivially)."""
        import json
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.analysis",
             "--root", REPO, "--lock-graph"],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr
        graph = json.loads(out.stdout)
        assert "PhysicalScheduler._lock->Tracer._lock" in graph["edges"]
        assert ("PhysicalScheduler._lock->DurabilityLayer._lock"
                in graph["edges"])
        assert "PhysicalScheduler._lock" in graph["nodes"]

    def test_findings_output_is_deterministic(self):
        """The CI analysis-smoke gate: two runs over the same tree are
        byte-identical (the analyzer itself must be deterministic)."""
        runs = []
        for _ in range(2):
            out = subprocess.run(
                [sys.executable, "-m", "shockwave_tpu.analysis",
                 "--root", REPO],
                capture_output=True, text=True, cwd=REPO)
            runs.append(out.stdout)
        assert runs[0] == runs[1]


class TestNegativeFixtures:
    """Each pass catches its seeded violation at the right file:line."""

    def test_lock_discipline(self):
        findings = passes.check_lock_discipline(
            fixture_index("bad_lock.py"))
        assert_exactly_seeded(findings, "bad_lock.py", "lock-discipline")

    def test_journal_coverage(self):
        findings = passes.check_journal_coverage(
            fixture_index("bad_journal.py"))
        assert_exactly_seeded(findings, "bad_journal.py",
                              "journal-coverage")

    def test_durability(self):
        findings = passes.check_durability(
            fixture_index("bad_durability.py"),
            state_globs=("bad_durability.py",), allow_globs=())
        assert_exactly_seeded(findings, "bad_durability.py", "durability")

    def test_determinism(self):
        findings = passes.check_determinism(
            fixture_index("bad_determinism.py"),
            scope_globs=("bad_determinism.py",), allow_globs=())
        assert_exactly_seeded(findings, "bad_determinism.py",
                              "determinism")

    def test_exception_hygiene(self):
        findings = passes.check_exception_hygiene(
            fixture_index("bad_exceptions.py"))
        assert_exactly_seeded(findings, "bad_exceptions.py",
                              "exception-hygiene")

    def test_obs_discipline(self):
        findings = passes.check_obs_discipline(
            fixture_index("bad_obs.py"),
            names_globs=(), obs_globs=("bad_obs.py",),
            clock_allow_globs=(), clock_extra_globs=())
        assert_exactly_seeded(findings, "bad_obs.py", "obs-discipline")

    def test_obs_propagation_contract(self):
        """The fleet-tracing half: a reserved span-context/shard
        literal copied outside the name catalog, and a wall-clock read
        in a span-emitting runtime module, are both findings."""
        findings = passes.check_obs_discipline(
            fixture_index("good_names.py", "bad_propagation.py"),
            names_globs=("good_names.py",), obs_globs=(),
            clock_allow_globs=(),
            clock_extra_globs=("bad_propagation.py",))
        assert_exactly_seeded(findings, "bad_propagation.py",
                              "obs-discipline")

    def test_reserved_literals_harvested_from_real_catalog(self):
        """The real names.py declares the propagation contract in the
        shape the harvester expects — an empty harvest would silently
        disable the contract rule tree-wide."""
        index = RepoIndex.from_root(
            REPO, include_dirs=("shockwave_tpu",))
        reserved = passes._reserved_literals(
            index, passes.OBS_NAMES_GLOBS)
        from shockwave_tpu.obs import names as obs_names
        assert obs_names.TRACEPARENT_METADATA_KEY in reserved
        assert obs_names.TRACEPARENT_ENV in reserved
        assert obs_names.SHARD_DIR_ENV in reserved
        assert obs_names.SHARD_FILE_PREFIX in reserved

    def test_thread_roots(self):
        from shockwave_tpu.analysis.threads import check_thread_roots
        findings = check_thread_roots(fixture_index("bad_threads.py"))
        assert_exactly_seeded(findings, "bad_threads.py", "thread-roots")

    def test_race_detector(self):
        from shockwave_tpu.analysis.races import check_race_detector
        findings = check_race_detector(fixture_index("bad_races.py"))
        assert_exactly_seeded(findings, "bad_races.py", "race-detector")

    def test_race_detector_clean_on_locked_and_documented(self):
        """The negative control: consistent locksets, thread-safe field
        types, init-frozen config and registry verdicts all stay
        quiet."""
        from shockwave_tpu.analysis.races import check_race_detector
        assert check_race_detector(fixture_index("good_races.py")) == []

    def test_suppression_audit(self):
        from shockwave_tpu.analysis.passes import check_suppression_audit
        index = fixture_index("bad_suppression.py")
        live = passes.check_determinism(
            index, scope_globs=("bad_suppression.py",), allow_globs=())
        # The load-bearing suppression ate the real finding...
        assert live == []
        # ...and the audit flags exactly the stale one + the typo'd id.
        findings = check_suppression_audit(
            index, ran_pass_ids=["determinism"])
        assert_exactly_seeded(findings, "bad_suppression.py",
                              "suppression-audit")

    def test_suppression_audit_skips_unran_passes(self):
        """A --select subset must not misreport other passes'
        suppressions as stale."""
        from shockwave_tpu.analysis.passes import check_suppression_audit
        index = fixture_index("bad_suppression.py")
        findings = check_suppression_audit(
            index, ran_pass_ids=["durability"])
        # Only the unknown-id finding survives (flagged regardless).
        assert [f.pass_id for f in findings] == ["suppression-audit"]
        assert "unknown pass id" in findings[0].message

    def test_deadlock(self):
        """A lock-order cycle across two spawned-thread roots is
        reported once, anchored at the inverting acquire."""
        from shockwave_tpu.analysis.lockflow import check_deadlock
        findings = check_deadlock(fixture_index("bad_deadlock.py"))
        assert_exactly_seeded(findings, "bad_deadlock.py", "deadlock")
        assert "Clash._lock_a->Clash._lock_b" in findings[0].message
        assert "2 thread root(s)" in findings[0].message

    def test_hold_discipline(self):
        """An RPC and a sleep inside a critical section: one finding
        per (function, kind), each at its blocking line."""
        from shockwave_tpu.analysis.lockflow import check_hold_discipline
        findings = check_hold_discipline(fixture_index("bad_blocking.py"))
        assert_exactly_seeded(findings, "bad_blocking.py",
                              "hold-discipline")
        kinds = {f.message.split("(")[0].strip() for f in findings}
        assert "a gRPC call" in kinds
        assert any("time.sleep" in f.message for f in findings)

    def test_lockflow_clean_on_ordered_contracted_and_justified(self):
        """Negative controls: consistent nesting order, the
        @requires_lock entry contract + own-cv wait, and both
        documented-verdict registries (whose live entries must not be
        reported stale) all stay quiet."""
        from shockwave_tpu.analysis.lockflow import (check_deadlock,
                                                     check_hold_discipline)
        index = fixture_index("good_lockflow.py")
        assert [str(f) for f in check_deadlock(index)] == []
        assert [str(f) for f in check_hold_discipline(index)] == []

    def test_lockflow_suppression_and_select_coverage(self, tmp_path):
        """The new pass ids ride the shared machinery: an inline
        ignore[deadlock] suppresses the cycle finding (and the audit
        knows the id — no unknown-pass-id finding), and --select
        accepts both ids."""
        from shockwave_tpu.analysis.core import RepoIndex, SourceFile
        from shockwave_tpu.analysis.lockflow import check_deadlock
        src = open(os.path.join(FIXTURES, "bad_deadlock.py")).read()
        line = sorted(seeded_lines("bad_deadlock.py"))[0]
        lines = src.splitlines()
        lines[line - 1] += "  # swtpu-check: ignore[deadlock]"
        patched = "\n".join(lines) + "\n"
        idx = RepoIndex(
            [SourceFile(str(tmp_path / "m.py"), "m.py", patched)],
            str(tmp_path))
        assert check_deadlock(idx) == []
        audit = passes.check_suppression_audit(
            idx, ran_pass_ids=["deadlock"])
        assert audit == [], [str(f) for f in audit]
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.analysis",
             "--root", REPO, "--select", "deadlock,hold-discipline"],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 0, out.stdout + out.stderr

    def test_deadlock_stale_registry_entry_is_a_finding(self, tmp_path):
        """A _LOCK_ORDER_JUSTIFIED entry naming an edge the static
        graph no longer has must be flagged at its declaration."""
        from shockwave_tpu.analysis.core import RepoIndex, SourceFile
        from shockwave_tpu.analysis.lockflow import check_deadlock
        src = ("import threading\n"
               "class Lone:\n"
               "    _LOCK_ORDER_JUSTIFIED = frozenset({'A->B'})\n"
               "    def __init__(self):\n"
               "        self._lock = threading.Lock()\n")
        idx = RepoIndex([SourceFile(str(tmp_path / "m.py"), "m.py", src)],
                        str(tmp_path))
        findings = check_deadlock(idx)
        assert [f.line for f in findings] == [3]
        assert "stale" in findings[0].message

    def test_cli_exits_one_on_violations(self, tmp_path):
        """End-to-end exit-1 proof: a copy of a broken fixture placed
        where the default scan looks is reported with file:line and
        fails the run."""
        pkg = tmp_path / "shockwave_tpu"
        pkg.mkdir()
        shutil.copy(os.path.join(FIXTURES, "bad_exceptions.py"),
                    pkg / "bad_exceptions.py")
        out = subprocess.run(
            [sys.executable, "-m", "shockwave_tpu.analysis",
             "--root", str(tmp_path)],
            capture_output=True, text=True, cwd=REPO)
        assert out.returncode == 1, out.stdout + out.stderr
        for line in seeded_lines("bad_exceptions.py"):
            assert f"shockwave_tpu/bad_exceptions.py:{line}:" in out.stdout

    def test_inline_suppression_is_honored(self, tmp_path):
        """A swtpu-check: ignore[pass-id] comment on the offending line
        suppresses exactly that pass."""
        src = ("def f(t):\n"
               "    try:\n"
               "        t()\n"
               "    except Exception:  # swtpu-check: ignore[exception-hygiene]\n"
               "        pass\n")
        path = tmp_path / "mod.py"
        path.write_text(src)
        idx = RepoIndex([SourceFile(str(path), "mod.py", src)],
                        str(tmp_path))
        assert passes.check_exception_hygiene(idx) == []


class TestLiveTreeThreadRoots:
    """Discovery over the real tree names every background-thread
    entry the concurrency story depends on — if a rename or a new
    spawn pattern makes one vanish, this fails before the race
    detector silently loses coverage of it."""

    def test_named_roots_discovered(self):
        from shockwave_tpu.analysis import __main__ as main_mod
        from shockwave_tpu.analysis.core import cached_index
        from shockwave_tpu.analysis.threads import discover_thread_roots
        index = cached_index(REPO,
                             include_dirs=main_mod.DEFAULT_INCLUDE_DIRS,
                             exclude_globs=main_mod.DEFAULT_EXCLUDE_GLOBS)
        roots, findings = discover_thread_roots(index)
        assert findings == [], [str(f) for f in findings]
        entries = {str(r.key) for r in roots}
        for expected in (
                # the six thread-root families named in the PR
                "PhysicalScheduler._planner_solve_loop",   # pipelined solve
                "PhysicalScheduler._allocation_thread",
                "PhysicalScheduler._liveness_loop",
                "PhysicalScheduler._whatif_loop",          # what-if rollouts
                "HAController._renew_loop",                # HA deadman
                "HotStandby.health",                       # standby /healthz
                "_Handler.do_GET",                         # exporter HTTP
                "TelemetryHistory.payload",                # /history.json
                "PhysicalScheduler.obs_health",            # /healthz callback
                "PhysicalScheduler._on_ha_fenced",         # renewal callback
                "WorkerDaemon._run_job",                   # gRPC servicer
                "PhysicalScheduler.done_callback",         # gRPC servicer
                "Dispatcher._dispatch_jobs_helper",        # per-dispatch
                "PhysicalScheduler._kill_job",             # watchdog timer
        ):
            assert expected in entries, (
                f"{expected} not discovered; roots: {sorted(entries)}")

    def test_rpc_and_http_roots_are_self_concurrent(self):
        from shockwave_tpu.analysis.threads import SELF_CONCURRENT_KINDS
        assert {"rpc-handler", "http-handler"} <= SELF_CONCURRENT_KINDS


class TestSanitizer:
    """Synthetic proofs that the runtime detectors fire (and stay quiet
    on clean patterns)."""

    def setup_method(self):
        sanitizer.monitor().reset()

    def teardown_method(self):
        sanitizer.monitor().reset()

    def _locks(self):
        return (sanitizer.SanitizedLock(threading.RLock(), "sanitytest.A"),
                sanitizer.SanitizedLock(threading.RLock(), "sanitytest.B"))

    def test_lock_order_inversion_fires(self):
        a, b = self._locks()
        with a:
            with b:
                pass

        def inverted():
            with b:
                with a:
                    pass

        t = threading.Thread(target=inverted)
        t.start()
        t.join()
        violations = sanitizer.monitor().report()["violations"]
        assert any(v.kind == "lock-order-cycle" for v in violations), (
            violations)

    def test_consistent_order_is_clean(self):
        a, b = self._locks()
        for _ in range(3):
            with a:
                with b:
                    pass
        report = sanitizer.monitor().report()
        assert report["violations"] == []
        assert report["order_edges"].get("sanitytest.A") == ["sanitytest.B"]

    def test_reentrant_hold_counts_once_and_reports_hold_time(self):
        a, _ = self._locks()
        with a:
            with a:  # re-entrant: no self-edge, one hold
                pass
        report = sanitizer.monitor().report()
        assert report["violations"] == []
        assert report["max_hold_s"].get("sanitytest.A", -1) >= 0.0
        assert not a._is_owned()

    def test_condition_wait_keeps_bookkeeping_balanced(self):
        lock = sanitizer.SanitizedLock(threading.RLock(), "sanitytest.CV")
        cv = threading.Condition(lock)
        with cv:
            cv.wait(timeout=0.01)  # full release + reacquire inside
            assert lock._is_owned()
        assert not lock._is_owned()
        assert sanitizer.monitor().report()["violations"] == []

    def test_unowned_access_fires_and_owned_access_does_not(self,
                                                            monkeypatch):
        monkeypatch.setenv("SWTPU_SANITIZE", "1")

        class Thing:
            def __init__(self):
                self._lock = sanitizer.SanitizedLock(
                    threading.RLock(), "sanitytest.Thing")

            @requires_lock
            def poke(self):
                return 1

        thing = Thing()
        with thing._lock:
            thing.poke()  # owned: clean
        assert sanitizer.monitor().report()["violations"] == []
        thing.poke()  # unowned: fires
        violations = sanitizer.monitor().report()["violations"]
        assert [v.kind for v in violations] == ["unowned-access"]
        assert "Thing.poke" in violations[0].message

    def test_requires_lock_is_free_when_disabled(self, monkeypatch):
        monkeypatch.delenv("SWTPU_SANITIZE", raising=False)

        class Thing:
            _lock = None

            @requires_lock
            def poke(self):
                return 41

        assert Thing().poke() == 41
        assert sanitizer.monitor().report()["violations"] == []

    def _reset_hold_env(self, monkeypatch):
        """Force hold_warn_ms() to re-read the env on next call, and
        restore the cache after the test."""
        monkeypatch.setattr(sanitizer, "_hold_env_checked", False)
        monkeypatch.setattr(sanitizer, "_hold_warn_ms_cached", None)

    def test_hold_warning_fires_at_threshold(self, monkeypatch):
        import time
        monkeypatch.setenv(sanitizer.HOLD_MS_ENV_VAR, "1")
        self._reset_hold_env(monkeypatch)
        a, _ = self._locks()
        with a:
            time.sleep(0.01)  # >= 1 ms threshold
        report = sanitizer.monitor().report()
        assert report["hold_warn_ms"] == 1.0
        assert report["hold_warning_count"] >= 1
        assert any(w["lock"] == "sanitytest.A"
                   and w["held_ms"] >= 1.0
                   for w in report["hold_warnings"])
        # reset() clears the warnings (per-seed explorer hygiene).
        sanitizer.monitor().reset()
        report = sanitizer.monitor().report()
        assert report["hold_warnings"] == []
        assert report["hold_warning_count"] == 0

    def test_hold_warning_default_off(self, monkeypatch):
        import time
        monkeypatch.delenv(sanitizer.HOLD_MS_ENV_VAR, raising=False)
        self._reset_hold_env(monkeypatch)
        a, _ = self._locks()
        with a:
            time.sleep(0.005)
        report = sanitizer.monitor().report()
        assert report["hold_warn_ms"] is None
        assert report["hold_warnings"] == []
        assert report["hold_warning_count"] == 0

    def test_hold_warning_garbage_env_logs_and_stays_off(
            self, monkeypatch, caplog):
        import logging
        for garbage in ("not-a-number", "-5", "0"):
            monkeypatch.setenv(sanitizer.HOLD_MS_ENV_VAR, garbage)
            self._reset_hold_env(monkeypatch)
            with caplog.at_level(logging.WARNING,
                                 logger="shockwave_tpu.analysis"):
                assert sanitizer.hold_warn_ms() is None
            assert sanitizer.HOLD_MS_ENV_VAR in caplog.text
            caplog.clear()

    def test_cumulative_graph_survives_reset_and_exports(self, tmp_path):
        """The graph the containment gate consumes must union every
        run in the process: the 20-seed smoke resets per seed."""
        import json
        a, b = self._locks()
        with a:
            with b:
                pass
        sanitizer.monitor().reset()  # per-seed reset in the smoke
        graph = sanitizer.monitor().cumulative_graph()
        assert "sanitytest.A->sanitytest.B" in graph["edges"]
        assert {"sanitytest.A", "sanitytest.B"} <= set(graph["nodes"])
        out = tmp_path / "graph.json"
        sanitizer.monitor().export_graph(str(out))
        assert json.loads(out.read_text()) == graph

    def test_physical_scheduler_lock_is_instrumented_when_enabled(
            self, monkeypatch, tmp_path):
        """The scheduler's own lock rides the wrapper under the env
        knob — the wiring the conftest fixture relies on."""
        monkeypatch.setenv("SWTPU_SANITIZE", "1")
        import socket

        from shockwave_tpu.sched.physical import PhysicalScheduler
        from shockwave_tpu.sched.scheduler import SchedulerConfig
        from shockwave_tpu.solver.registry import get_policy
        with socket.socket() as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        sched = PhysicalScheduler(
            get_policy("max_min_fairness"),
            throughputs_file=os.path.join(REPO, "data",
                                          "tacc_throughputs.json"),
            config=SchedulerConfig(time_per_iteration=2.0,
                                   heartbeat_interval_s=0.0),
            port=port)
        try:
            assert isinstance(sched._lock, sanitizer.SanitizedLock)
            with sched._cv:
                assert sched._lock._is_owned()
        finally:
            sched.shutdown()
        report = sanitizer.monitor().report()
        assert report["violations"] == [], report["violations"]
        assert "PhysicalScheduler._lock" in report["max_hold_s"]
