#!/usr/bin/env python3
"""Serving decode-throughput microbenchmark: tokens/s per chip.

Times the exact request-batch decode the serving replica runs
(`workloads/serving/serve.py`: KV-cached greedy decode through
`models/decoder.py`) on whatever backend is available, and reports the
ROADMAP-named ``tokens/s-per-chip`` row bench.py embeds — the measured
number the serving tier's declared ``decode_tokens_per_s`` (and so the
analytic ``mu``) must be calibrated against.

Prints ONE JSON line. ``--smoke`` exits nonzero when tokens/s falls
under ``--min_tokens_per_s`` — the CI floor gate (the CPU-backend floor
is deliberately modest; real-chip floors live with the TPU evidence
capture).
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from shockwave_tpu.models.decoder import DecoderLM  # noqa: E402


def build_decode(args):
    max_len = args.prompt_len + args.tokens_per_request + 1
    model = DecoderLM(dim=args.model_dim, num_layers=args.model_layers,
                      num_heads=args.model_heads,
                      mlp_dim=2 * args.model_dim, max_len=max_len)
    rng = jax.random.PRNGKey(0)
    prompt = jax.random.randint(
        rng, (args.batch_size, args.prompt_len), 0, model.vocab_size,
        dtype=jnp.int32)
    params = model.init(rng, prompt)

    @jax.jit
    def serve_request_batch(params, prompt):
        caches = model.init_cache(args.batch_size)

        def step(carry, token_in):
            caches, pos = carry
            logits, caches = model.apply(params, token_in, caches, pos,
                                         method=DecoderLM.decode_step)
            next_tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return (caches, pos + 1), next_tok[:, None]

        carry = (caches, jnp.int32(0))
        token = prompt[:, :1]
        for i in range(args.prompt_len):
            carry, token = step(carry, prompt[:, i:i + 1])

        def body(i, state):
            carry, token = state
            carry, token = step(carry, token)
            return (carry, token)

        carry, token = jax.lax.fori_loop(
            0, args.tokens_per_request, body, (carry, token))
        return token

    return serve_request_batch, params, prompt


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch_size", type=int, default=8)
    p.add_argument("--tokens_per_request", type=int, default=32)
    p.add_argument("--prompt_len", type=int, default=8)
    p.add_argument("--model_dim", type=int, default=128)
    p.add_argument("--model_layers", type=int, default=2)
    p.add_argument("--model_heads", type=int, default=4)
    p.add_argument("--warmup", type=int, default=2)
    p.add_argument("--steps", type=int, default=8,
                   help="timed request batches")
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--min_tokens_per_s", type=float, default=200.0,
                   help="--smoke: fail below this decode throughput")
    p.add_argument("--output", default=None, help="also write the JSON")
    args = p.parse_args()

    serve_request_batch, params, prompt = build_decode(args)
    for _ in range(max(args.warmup, 1)):     # includes the jit compile
        jax.block_until_ready(serve_request_batch(params, prompt))
    t0 = time.perf_counter()
    last = None
    for _ in range(args.steps):
        last = serve_request_batch(params, prompt)
    jax.block_until_ready(last)
    wall = time.perf_counter() - t0

    device = jax.devices()[0]
    tokens = args.steps * args.batch_size * args.tokens_per_request
    tokens_per_s = tokens / wall
    row = {
        "bench": "serving_decode",
        "backend": device.platform,
        "device_kind": getattr(device, "device_kind", device.platform),
        "batch_size": args.batch_size,
        "tokens_per_request": args.tokens_per_request,
        "model_dim": args.model_dim,
        "model_layers": args.model_layers,
        "steps": args.steps,
        "wall_s": round(wall, 4),
        "tokens_per_s": round(tokens_per_s, 1),
        # One replica owns one chip (JAX_VISIBLE_DEVICES pinning in the
        # dispatcher), so per-chip == per-replica here.
        "tokens_per_s_per_chip": round(tokens_per_s, 1),
        "requests_per_s": round(tokens_per_s / args.tokens_per_request, 2),
    }
    print(json.dumps(row))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(row, f)
    if args.smoke and row["tokens_per_s"] < args.min_tokens_per_s:
        print(f"SMOKE FAIL: {row['tokens_per_s']} tokens/s < "
              f"{args.min_tokens_per_s}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
