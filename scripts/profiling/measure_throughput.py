#!/usr/bin/env python3
"""Throughput profiler: measure isolated steps/sec per (job_type, sf).

Times the actual jitted train step of every workload family in-process
(two-point marginal timing, core/timing.py — async dispatch and relay
round-trip latency cannot inflate the numbers) and writes the result in
the throughput-oracle JSON format the scheduler consumes
(reference: scheduler/scripts/profiling/measure_throughput.py — there a
standalone gRPC profiler on real GPUs; on TPU the honest-timing concern
is device sync, not process isolation, so in-process timing is both
simpler and more accurate).

scale_factor > 1 rows are measured by sharding the batch over a dp mesh
of `sf` local devices; combinations with fewer attached devices are
skipped (run with XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu to profile the multi-chip shapes virtually).

Example:
    python scripts/profiling/measure_throughput.py \
        --worker_type v5e --output data/v5e_throughputs.json \
        --families ResNet-18 LM --steps 30
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import jax.numpy as jnp
import optax

from shockwave_tpu.core.constants import DEFAULT_BS, oracle_job_type
from shockwave_tpu.core.timing import marginal_step_time
from shockwave_tpu.models import data
from shockwave_tpu.obs import Observability
from shockwave_tpu.obs import names as obs_names
from shockwave_tpu.obs.clock import perf_clock
from shockwave_tpu.parallel.mesh import data_parallel_sharding, make_mesh

# (family -> profiled batch sizes) mirrors the job template table
# (reference: scheduler/job_table.py:110-130).
FAMILY_BATCH_SIZES = {
    "ResNet-18": [16, 32, 64, 128, 256],
    "ResNet-50": [16, 32, 64, 128],
    "Transformer": [16, 32, 64, 128],
    "LM": [5, 10, 20, 40, 80],
    "Recommendation": [512, 1024, 2048, 4096, 8192],
    "A3C": [4],
    "CycleGAN": [1],
}


def build_family(model_name: str, bs: int):
    """Returns (state, step_fn, batch) with step_fn jit-compiled."""
    rng = jax.random.PRNGKey(0)

    if model_name == "A3C":
        from shockwave_tpu.models.a3c import (ActorCritic, build_a3c_update,
                                              env_observe, env_reset)
        model = ActorCritic()
        env_state = env_reset(rng, bs)
        params = model.init(rng, env_observe(env_state))["params"]
        tx = optax.adam(1e-4)
        ts = {"params": params, "opt_state": tx.init(params), "rng": rng,
              "step": jnp.zeros((), jnp.int32)}
        update = build_a3c_update(model, tx)

        def step(state, batch):
            ts, env_state = state
            ts, env_state, metrics = update(ts, env_state)
            return (ts, env_state), metrics["loss"]
        return (ts, env_state), step, ()

    if model_name == "CycleGAN":
        from shockwave_tpu.models.cyclegan import Discriminator, Generator
        from shockwave_tpu.workloads.cyclegan.cyclegan import build_step
        g_ab, g_ba = Generator(), Generator()
        d_a, d_b = Discriminator(), Discriminator()
        sample = jnp.zeros((1, 128, 128, 3), jnp.float32)
        g_params = {"g_ab": g_ab.init(rng, sample)["params"],
                    "g_ba": g_ba.init(rng, sample)["params"]}
        d_params = {"d_a": d_a.init(rng, sample)["params"],
                    "d_b": d_b.init(rng, sample)["params"]}
        g_tx, d_tx = optax.adam(2e-4, b1=0.5), optax.adam(2e-4, b1=0.5)
        state = {"g_params": g_params, "d_params": d_params,
                 "g_opt": g_tx.init(g_params), "d_opt": d_tx.init(d_params),
                 "step": jnp.zeros((), jnp.int32)}
        fused = build_step((g_ab, g_ba, d_a, d_b), g_tx, d_tx)
        batch = next(iter(data.monet2photo(bs)))

        def step(state, batch):
            state, metrics = fused(state, *batch)
            return state, metrics["g_loss"]
        return state, step, batch

    if model_name == "ResNet-18":
        from shockwave_tpu.models.resnet import ResNet18
        model = ResNet18()
        sample = jnp.zeros((1, 32, 32, 3), jnp.float32)
        variables = model.init(rng, sample, train=True)
        state = {"params": variables["params"],
                 "batch_stats": variables["batch_stats"]}

        def loss_fn(params, state, images, labels):
            logits, mutated = model.apply(
                {"params": params, "batch_stats": state["batch_stats"]},
                images, train=True, mutable=["batch_stats"])
            return (optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean(), {"batch_stats": mutated["batch_stats"]})
        batch = next(iter(data.cifar10(bs)))
    elif model_name == "ResNet-50":
        from shockwave_tpu.models.resnet import ResNet50
        model = ResNet50()
        sample = jnp.zeros((1, 224, 224, 3), jnp.float32)
        variables = model.init(rng, sample, train=True)
        state = {"params": variables["params"],
                 "batch_stats": variables["batch_stats"]}

        def loss_fn(params, state, images, labels):
            logits, mutated = model.apply(
                {"params": params, "batch_stats": state["batch_stats"]},
                images, train=True, mutable=["batch_stats"])
            return (optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean(), {"batch_stats": mutated["batch_stats"]})
        batch = next(iter(data.imagenet(bs)))
    elif model_name == "Transformer":
        from shockwave_tpu.models.transformer import Seq2SeqTransformer
        model = Seq2SeqTransformer()
        src = jnp.zeros((1, 32), jnp.int32)
        state = {"params": model.init(rng, src, src)["params"]}

        def loss_fn(params, state, src_tokens, tgt_tokens):
            logits = model.apply({"params": params}, src_tokens,
                                 tgt_tokens[:, :-1])
            targets = tgt_tokens[:, 1:]
            mask = (targets != 0).astype(jnp.float32)
            losses = optax.softmax_cross_entropy_with_integer_labels(
                logits, targets)
            return (losses * mask).sum() / jnp.maximum(mask.sum(), 1.0), {}
        batch = next(iter(data.multi30k(bs, tgt_len=33)))
    elif model_name == "LM":
        from shockwave_tpu.models.lm import LSTMLanguageModel
        model = LSTMLanguageModel()
        sample = jnp.zeros((1, 35), jnp.int32)
        state = {"params": model.init(rng, sample)["params"]}

        def loss_fn(params, state, tokens, targets):
            logits = model.apply({"params": params}, tokens)
            return (optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean(), {})
        batch = next(iter(data.wikitext2(bs)))
    elif model_name == "Recommendation":
        from shockwave_tpu.models.recommendation import (AutoEncoder,
                                                         multinomial_nll)
        model = AutoEncoder()
        sample = jnp.zeros((1, model.num_items), jnp.float32)
        state = {"params": model.init(rng, sample)["params"]}

        def loss_fn(params, state, interactions):
            logits = model.apply({"params": params}, interactions)
            return multinomial_nll(logits, interactions), {}
        batch = next(iter(data.ml20m(bs)))
    else:
        raise ValueError(model_name)

    tx = optax.sgd(0.1, momentum=0.9)
    state = dict(state, opt_state=tx.init(state["params"]))

    def step(state, batch):
        def scalar_loss(params):
            return loss_fn(params, state, *batch)
        (loss, aux), grads = jax.value_and_grad(
            scalar_loss, has_aux=True)(state["params"])
        updates, new_opt = tx.update(grads, state["opt_state"],
                                     state["params"])
        new_params = optax.apply_updates(state["params"], updates)
        new_state = dict(state, params=new_params, opt_state=new_opt)
        if "batch_stats" in aux:
            new_state["batch_stats"] = aux["batch_stats"]
        return new_state, loss

    return state, jax.jit(step), batch


def measure(model_name: str, bs: int, sf: int, steps: int, warmup: int):
    """steps/sec for one (family, batch size, scale factor) combination.

    Uses two-point marginal timing (core/timing.py) so the fixed
    host<->device round-trip cost cancels — block_until_ready timing is
    not trustworthy through a relayed chip and reported dispatch rates,
    not execution rates."""
    devices = jax.devices()[:sf]
    if len(devices) < sf:
        return None
    mesh = make_mesh(dp=sf, devices=devices)
    batch_sharding, repl_sharding = data_parallel_sharding(mesh)

    # batch_size is per-chip (the reference's DDP semantics: --batch_size
    # is each process's local batch); the global batch is bs * sf.
    state, step_fn, batch = build_family(model_name, bs * sf)
    if model_name != "A3C":  # A3C state carries per-env RNG, not shardable
        state = jax.device_put(state, repl_sharding)
        batch = jax.device_put(batch, batch_sharding)

    dt = marginal_step_time(step_fn, state, batch,
                            n1=max(steps // 4, 2), n2=steps, warmup=warmup)
    return 1.0 / dt


def measure_pair(fam_a, bs_a, fam_b, bs_b, steps, warmup, dt_cache=None):
    """Packed-pair steps/s: both jobs co-resident on one chip.

    The reference's --packed grid (scheduler/scripts/profiling/
    measure_throughput.py) co-schedules two processes on one GPU via MPS.
    TPUs have no MPS: co-located jobs time-share the chip, so the honest
    pair rate is round-robin time-slicing with a step ratio k_a:k_b chosen
    from the isolated step times so each job gets ~equal device time (what
    a fair time-slicing executor would grant). Returns
    (rate_a, rate_b, dt_a, dt_b) — pair rates plus the isolated marginal
    step times measured along the way."""
    state_a, step_a, batch_a = build_family(fam_a, bs_a)
    state_b, step_b, batch_b = build_family(fam_b, bs_b)
    n1 = max(steps // 4, 2)
    # Isolated marginal step times are per-row quantities; cache them so a
    # --packed grid of n rows measures n of them, not n^2.
    if dt_cache is None:
        dt_cache = {}
    if (fam_a, bs_a) not in dt_cache:
        dt_cache[(fam_a, bs_a)] = marginal_step_time(
            step_a, state_a, batch_a, n1=n1, n2=steps, warmup=warmup)
    if (fam_b, bs_b) not in dt_cache:
        dt_cache[(fam_b, bs_b)] = marginal_step_time(
            step_b, state_b, batch_b, n1=n1, n2=steps, warmup=warmup)
    dt_a, dt_b = dt_cache[(fam_a, bs_a)], dt_cache[(fam_b, bs_b)]
    if dt_a <= dt_b:
        k_a, k_b = max(1, round(dt_b / dt_a)), 1
    else:
        k_a, k_b = 1, max(1, round(dt_a / dt_b))

    def quantum(state, _):
        sa, sb = state
        la = lb = None
        for _ in range(k_a):
            sa, la = step_a(sa, batch_a)
        for _ in range(k_b):
            sb, lb = step_b(sb, batch_b)
        # Sum the two losses so the closing fetch waits for BOTH chains.
        loss = (jnp.asarray(la).astype(jnp.float32).ravel()[0]
                + jnp.asarray(lb).astype(jnp.float32).ravel()[0])
        return (sa, sb), loss

    dt_q = marginal_step_time(quantum, (state_a, state_b), None,
                              n1=2, n2=8, warmup=max(1, warmup // 2))
    return k_a / dt_q, k_b / dt_q, dt_a, dt_b


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--worker_type", default="v5e")
    p.add_argument("--output", required=True)
    p.add_argument("--families", nargs="*", default=list(FAMILY_BATCH_SIZES))
    p.add_argument("--only", nargs="*", default=None, metavar="FAMILY:BS",
                   help="profile exactly these family:batch_size rows "
                        "(e.g. ResNet-18:32 LM:20), overriding --families; "
                        "the reference profiler takes explicit job types "
                        "the same way")
    p.add_argument("--scale_factors", nargs="*", type=int, default=[1, 2, 4, 8])
    p.add_argument("--packed", action="store_true",
                   help="also measure every unordered pair (including "
                        "self-pairs) of the resolved rows co-resident on "
                        "one chip (sf=1 only — the reference likewise "
                        "does not profile distributed+packed)")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--warmup", type=int, default=5)
    p.add_argument("--merge", action="store_true",
                   help="merge into an existing oracle file")
    p.add_argument("--trace_out", default=None, metavar="TRACE_JSON",
                   help="export one span per profiled row (with the "
                        "measured rate in its args) as Chrome-trace "
                        "JSON — the profiling session's timeline")
    args = p.parse_args()

    # Per-row wall time rides the obs pipeline (spans + the
    # swtpu_profile_measure_seconds histogram); the device timing
    # itself stays core/timing.marginal_step_time — the only honest
    # primitive under async dispatch and relayed chips.
    obs = Observability(clock=perf_clock, enabled=True)

    oracle = {}
    if args.merge and os.path.exists(args.output):
        with open(args.output) as f:
            oracle = json.load(f)
    table = oracle.setdefault(args.worker_type, {})

    if args.only:
        rows = []
        for spec in args.only:
            family, sep, bs = spec.rpartition(":")
            if not sep or family not in FAMILY_BATCH_SIZES \
                    or not bs.isdigit():
                p.error(f"--only expects FAMILY:BS with FAMILY one of "
                        f"{sorted(FAMILY_BATCH_SIZES)}; got {spec!r}")
            rows.append((family, int(bs)))
    else:
        rows = [(family, bs) for family in args.families
                for bs in FAMILY_BATCH_SIZES[family]]

    n_devices = len(jax.devices())
    for family, bs in rows:
        for sf in args.scale_factors:
            if sf > n_devices:
                print(f"skip {family} bs={bs} sf={sf}: "
                      f"only {n_devices} devices", file=sys.stderr)
                continue
            if family in DEFAULT_BS and sf > 1:
                continue  # A3C / CycleGAN are single-chip families
            with obs.span(obs_names.SPAN_PROFILE_MEASURE, family=family,
                          bs=bs, sf=sf), \
                    obs.timed(obs_names.PROFILE_MEASURE_SECONDS,
                              family=family):
                tput = measure(family, bs, sf, args.steps, args.warmup)
            if tput is None:
                continue
            job_type = oracle_job_type(family, bs)
            key = str((job_type, sf))
            table.setdefault(key, {})["null"] = round(tput, 4)
            print(f"{args.worker_type} {key}: {tput:.3f} steps/s",
                  flush=True)

    if args.packed:
        import itertools
        dt_cache = {}
        for (fam_a, bs_a), (fam_b, bs_b) in \
                itertools.combinations_with_replacement(rows, 2):
            with obs.span(obs_names.SPAN_PROFILE_MEASURE,
                          family=f"{fam_a}+{fam_b}", bs=[bs_a, bs_b],
                          sf=1), \
                    obs.timed(obs_names.PROFILE_MEASURE_SECONDS,
                              family=f"{fam_a}+{fam_b}"):
                rate_a, rate_b, _, _ = measure_pair(
                    fam_a, bs_a, fam_b, bs_b, args.steps, args.warmup,
                    dt_cache=dt_cache)
            key_a = str((oracle_job_type(fam_a, bs_a), 1))
            key_b = str((oracle_job_type(fam_b, bs_b), 1))
            table.setdefault(key_a, {})[key_b] = [round(rate_a, 4),
                                                  round(rate_b, 4)]
            table.setdefault(key_b, {})[key_a] = [round(rate_b, 4),
                                                  round(rate_a, 4)]
            print(f"{args.worker_type} {key_a} + {key_b}: "
                  f"{rate_a:.3f} / {rate_b:.3f} steps/s", flush=True)

    os.makedirs(os.path.dirname(os.path.abspath(args.output)), exist_ok=True)
    with open(args.output, "w") as f:
        json.dump(oracle, f, indent=1, sort_keys=True)
    print(f"wrote {args.output}")
    if args.trace_out:
        obs.tracer.export_chrome_trace(args.trace_out)
        print(f"wrote {args.trace_out}")


if __name__ == "__main__":
    main()
