#!/usr/bin/env python3
"""Offline validator for a scheduler durability state dir.

Checks, without touching the live scheduler:
- snapshot integrity (CRC footer + unpickle), including the .prev
  fallback,
- every journal segment's framing and CRCs, reporting a torn tail
  (recoverable: recovery discards it) separately from deeper corruption,
- sequence-number sanity: strictly increasing, and the post-snapshot
  event stream starts at snapshot.last_seq + 1 or earlier (gaps below
  the snapshot horizon are expected — compaction deletes covered
  segments),
- leader-epoch chain sanity (control-plane HA): along the surviving
  sequence chain, epochs are non-decreasing and each epoch owns one
  contiguous span — EXACTLY ONE WRITER PER EPOCH. Stale-writer records
  a deposed leader appended after its fencing are reported (they are
  expected fallout of a leader-freeze failover; recovery discards them
  deterministically) but do NOT fail the check.

``--follow`` streams instead of scanning: the journal is validated
WHILE the leader is writing it, using the same tail-tolerant
`JournalFollower` the hot standby replicates through — a torn tail is
WAIT (the writer is mid-append), never corruption. Each poll prints the
applied sequence and the replication lag (now minus the newest
record's wall stamp), giving operators a live lag check with zero
scheduler involvement. Follow mode exits 0 when --max_wait_s elapses
with a clean tail (or runs until interrupted without it).

Exit codes: 0 = clean, 1 = recoverable damage (torn tail / snapshot
fell back to .prev), 2 = state unusable or not found.

Usage:
    python scripts/utils/fsck_journal.py <state_dir> [--verbose]
    python scripts/utils/fsck_journal.py <state_dir> --follow \
        [--max_wait_s 30] [--poll_interval_s 0.5]
"""
import argparse
import collections
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.sched.journal import (FOLLOW_BEHIND, SNAPSHOT_NAME,  # noqa: E402
                                         TAIL_CLEAN, JournalError,
                                         JournalFollower,
                                         _read_snapshot_file,
                                         filter_epoch_chain, list_segments,
                                         read_journal)


def check_epoch_chain(records, out=print):
    """Validate the exactly-one-writer-per-epoch invariant over
    seq-sorted records. Returns (ok, num_stale_orphans): `ok` is False
    only on a REAL violation (an epoch re-appearing after a higher one
    inside the SURVIVING chain — two live writers interleaved); stale
    orphans that the supersede rule cleanly discards are counted but
    expected."""
    kept, orphans = filter_epoch_chain(sorted(
        records, key=lambda r: int(r.get("seq", 0))))
    seen_epochs = []
    for rec in kept:
        epoch = rec.get("epoch")
        if epoch is None:
            continue
        epoch = int(epoch)
        if not seen_epochs or seen_epochs[-1] != epoch:
            seen_epochs.append(epoch)
    ok = True
    if seen_epochs != sorted(set(seen_epochs)):
        out(f"EPOCH CHAIN VIOLATION: epochs interleave along the "
            f"surviving chain ({seen_epochs}) — two writers shared an "
            "epoch or a fenced writer's records survived")
        ok = False
    untagged = [r for r in orphans if r.get("epoch") is None]
    if untagged:
        # A superseded record WITHOUT an epoch cannot be a fenced
        # ex-leader's (those are always tagged): an untagged writer
        # duplicated sequence numbers — real structural damage.
        out(f"SEQ DUPLICATION: {len(untagged)} untagged record(s) "
            f"duplicate sequences (seqs "
            f"{sorted({int(r.get('seq', 0)) for r in untagged})[:10]}) "
            "— two writers without epoch fencing?")
        ok = False
    if orphans:
        by_epoch = collections.Counter(
            r.get("epoch") for r in orphans)
        out(f"stale-writer orphans discarded by the epoch supersede "
            f"rule: {dict(by_epoch)} (expected after a leader-freeze "
            "failover; recovery ignores them)")
    if seen_epochs:
        out(f"epoch chain: {seen_epochs} (one writer per epoch)")
    return ok, len(orphans)


def follow(args):
    """--follow: validate the live journal + report replication lag."""
    follower = JournalFollower(args.state_dir)
    deadline = (time.time() + args.max_wait_s
                if args.max_wait_s is not None else None)
    total = 0
    clean_at_eof = False
    try:
        while True:
            events, status = follower.poll()
            total += len(events)
            now = time.time()
            lag = (now - follower.last_record_walltime
                   if follower.last_record_walltime is not None else None)
            state = {TAIL_CLEAN: "clean",
                     FOLLOW_BEHIND: "BEHIND COMPACTION"}.get(status,
                                                             "WAIT (torn "
                                                             "tail)")
            print(f"applied_seq={follower.last_seq} new={len(events)} "
                  f"tail={state} lag_s="
                  f"{'n/a' if lag is None else f'{lag:.3f}'} "
                  f"stale_dropped={follower.stale_dropped}", flush=True)
            if status == FOLLOW_BEHIND:
                # Not corruption: the writer compacted past us. A fresh
                # follower (or recovery) starts from the snapshot.
                follower = JournalFollower(
                    args.state_dir,
                    start_after_seq=follower.snapshot_horizon())
            clean_at_eof = status == TAIL_CLEAN
            if deadline is not None and time.time() >= deadline:
                break
            time.sleep(args.poll_interval_s)
    except KeyboardInterrupt:
        pass
    tail = ("clean" if clean_at_eof
            else "pending (torn tail is WAIT, not corruption)")
    print(f"followed {total} records; tail {tail}")
    return 0 if clean_at_eof else 1


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("state_dir")
    p.add_argument("--verbose", action="store_true",
                   help="print every record type histogram per segment")
    p.add_argument("--follow", action="store_true",
                   help="stream-validate a journal WHILE it is written "
                        "(tail-tolerant; prints live replication lag)")
    p.add_argument("--max_wait_s", type=float, default=None,
                   help="--follow: stop after this many seconds "
                        "(default: run until interrupted)")
    p.add_argument("--poll_interval_s", type=float, default=0.5,
                   help="--follow: poll cadence")
    args = p.parse_args()

    rc = 0
    if not os.path.isdir(args.state_dir):
        print(f"ERROR: {args.state_dir} is not a directory")
        return 2
    if args.follow:
        return follow(args)

    # -- snapshot ------------------------------------------------------
    snap_path = os.path.join(args.state_dir, SNAPSHOT_NAME)
    last_seq = 0
    snapshot = None
    if os.path.exists(snap_path) or os.path.exists(snap_path + ".prev"):
        snapshot = _read_snapshot_file(snap_path)
        if snapshot is not None:
            last_seq = int(snapshot.get("last_seq", 0))
            print(f"snapshot: OK (covers seq <= {last_seq})")
        else:
            snapshot = _read_snapshot_file(snap_path + ".prev")
            if snapshot is not None:
                last_seq = int(snapshot.get("last_seq", 0))
                print(f"snapshot: current CORRUPT, .prev OK "
                      f"(covers seq <= {last_seq})")
                rc = max(rc, 1)
            else:
                print("snapshot: CORRUPT (current and .prev both "
                      "unreadable)")
                rc = 2
    else:
        print("snapshot: none (journal-only state)")

    # -- segments ------------------------------------------------------
    segments = list_segments(args.state_dir)
    if not segments and snapshot is None:
        print("no journal segments found")
        return 2 if rc == 0 else rc

    total = 0
    replayable = 0
    prev_replayable_seq = None
    types: collections.Counter = collections.Counter()
    all_records = []
    parsed = []
    for path in segments:
        try:
            parsed.append((path,) + read_journal(path))
        except JournalError as e:
            print(f"{os.path.basename(path)}: UNREADABLE ({e})")
            rc = 2
    global_max_epoch = max(
        (int(r["epoch"]) for _, records, _ in parsed for r in records
         if r.get("epoch") is not None), default=None)
    for path, records, tail in parsed:
        seg_types = collections.Counter(r.get("type", "?") for r in records)
        types.update(seg_types)
        total += len(records)
        all_records.extend(records)
        prev_seq = None
        for r in records:
            # WITHIN a segment, seqs must strictly increase (one writer
            # per file). Across segments they may overlap: a deposed
            # leader's stale tail duplicates seqs the successor re-
            # claimed in its own segment — judged by the epoch chain
            # check below, not flagged as structural damage here.
            seq = int(r.get("seq", 0))
            if prev_seq is not None and seq <= prev_seq:
                print(f"{os.path.basename(path)}: seq {seq} not "
                      f"increasing (prev {prev_seq})")
                rc = 2
            prev_seq = seq
        status = "OK"
        if tail != TAIL_CLEAN:
            # A torn tail on a SUPERSEDED writer's segment is expected
            # debris of a fenced failover: the dead/deposed leader's
            # file is never reopened (each HA incarnation rotates to a
            # fresh segment), so nothing ever truncates it — and even
            # if the torn record parsed, the epoch supersede rule would
            # discard it. Only the CURRENT writer chain's torn tail is
            # recoverable damage (exit 1).
            seg_epoch = max((int(r["epoch"]) for r in records
                             if r.get("epoch") is not None), default=None)
            superseded = (seg_epoch is not None
                          and global_max_epoch is not None
                          and seg_epoch < global_max_epoch)
            if superseded:
                status = ("TORN TAIL (superseded epoch "
                          f"{seg_epoch} writer; ignorable)")
            else:
                status = "TORN TAIL (recoverable)"
                rc = max(rc, 1)
        print(f"{os.path.basename(path)}: {len(records)} records, {status}")
        if args.verbose and seg_types:
            for etype, count in sorted(seg_types.items()):
                print(f"    {etype}: {count}")

    # The replayable stream — what recovery actually applies — is the
    # SURVIVING chain after the epoch supersede rule; it must be
    # gapless past the snapshot horizon (sequences are allocated one at
    # a time, so a jump means a lost segment or manual deletion).
    epochs_ok, _ = check_epoch_chain(all_records)
    if not epochs_ok:
        rc = 2
    kept, _ = filter_epoch_chain(sorted(
        all_records, key=lambda r: int(r.get("seq", 0))))
    for r in kept:
        seq = int(r.get("seq", 0))
        if seq > last_seq:
            expected = (last_seq if prev_replayable_seq is None
                        else prev_replayable_seq) + 1
            if seq != expected:
                print(f"GAP in replayable stream — expected seq "
                      f"{expected}, found {seq} (events lost?)")
                rc = 2
            prev_replayable_seq = seq
            replayable += 1

    print(f"total: {total} journal records, {replayable} replayable past "
          f"the snapshot horizon")
    if types and not args.verbose:
        top = ", ".join(f"{t}={c}" for t, c in types.most_common(6))
        print(f"event mix: {top}")
    print({0: "CLEAN", 1: "RECOVERABLE DAMAGE", 2: "UNUSABLE"}[rc])
    return rc


if __name__ == "__main__":
    sys.exit(main())
