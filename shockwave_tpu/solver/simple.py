"""Closed-form policies: isolated, proportional, gandiva-fair.

These split the cluster evenly and need no solver
(reference: scheduler/policies/{isolated,proportional,gandiva_fair_proportional}.py).
"""
from __future__ import annotations

import numpy as np

from .policy import Policy


class IsolatedPolicy(Policy):
    """Equal 1/m split, normalized by per-job scale factor."""

    name = "Isolated"

    def _allocation_matrix(self, m, n, worker_types, scale_factors_array, cluster_spec):
        x = np.tile([cluster_spec[wt] / m for wt in worker_types], (m, 1))
        x = x / scale_factors_array
        row_sums = np.maximum(x.sum(axis=1), 1.0)
        return x / row_sums[:, None]

    def get_throughputs(self, throughputs, index, scale_factors, cluster_spec):
        if throughputs is None:
            return None
        job_ids, worker_types = index
        m, n = throughputs.shape
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)
        x = self._allocation_matrix(m, n, worker_types, sf, cluster_spec)
        return (throughputs * x).sum(axis=1).reshape((m, 1))

    def get_allocation(self, unflattened_throughputs, scale_factors, cluster_spec):
        throughputs, index = self.flatten(unflattened_throughputs, cluster_spec)
        if throughputs is None:
            return None
        job_ids, worker_types = index
        m, n = throughputs.shape
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)
        x = self._allocation_matrix(m, n, worker_types, sf, cluster_spec)
        return self.unflatten(x, index)


class IsolatedPlusPolicy(IsolatedPolicy):
    """Isolated variant; round scheduler respects its priority order strictly."""

    name = "Isolated_plus"


class ProportionalPolicy(Policy):
    """Equal split without scale-factor normalization; also provides the
    normalizing throughputs used by the max-min policies."""

    name = "Proportional"

    def _allocation_matrix(self, m, worker_types, cluster_spec):
        x = np.tile([cluster_spec[wt] / m for wt in worker_types], (m, 1))
        return x / x.sum(axis=1).max()

    def get_throughputs(self, throughputs, index, cluster_spec):
        if throughputs is None:
            return None
        job_ids, worker_types = index
        m, _ = throughputs.shape
        x = self._allocation_matrix(m, worker_types, cluster_spec)
        return (throughputs * x).sum(axis=1).reshape((m, 1))

    def get_allocation(self, unflattened_throughputs, cluster_spec):
        throughputs, index = self.flatten(unflattened_throughputs, cluster_spec)
        if throughputs is None:
            return None
        _, worker_types = index
        m, _ = throughputs.shape
        x = self._allocation_matrix(m, worker_types, cluster_spec)
        return self.unflatten(x, index)


class GandivaFairPolicy(Policy):
    """Proportional share normalized so each row sums to at most 1
    (the 'Gandiva-Fair' baseline of the paper)."""

    name = "GandivaFairProportional"

    def get_allocation(self, unflattened_throughputs, scale_factors, cluster_spec):
        throughputs, index = self.flatten(unflattened_throughputs, cluster_spec)
        if throughputs is None:
            return None
        _, worker_types = index
        m, _ = throughputs.shape
        x = np.tile([cluster_spec[wt] / m for wt in worker_types], (m, 1))
        row_sums = np.maximum(x.sum(axis=1), 1.0)
        return self.unflatten(x / row_sums[:, None], index)
