"""Pipelined-planner loopback tests: the Shockwave MILP runs on a
background solve thread that overlaps round execution, and a slow solve
degrades to the deadline fallback (cached schedule / backfill) instead
of stalling the round pipeline. Runtime-marked classes run under the
concurrency sanitizer (tests/conftest.py)."""
import os
import threading
import time

import pytest

from shockwave_tpu.core.job import Job
from shockwave_tpu.core.oracle import read_throughputs
from shockwave_tpu.core.profiles import build_profiles
from shockwave_tpu.obs import names as obs_names
from shockwave_tpu.sched.physical import PhysicalScheduler
from shockwave_tpu.sched.scheduler import SchedulerConfig
from shockwave_tpu.solver import get_policy

from test_runtime import StubWorkerDaemon, free_port

DATA = os.path.join(os.path.dirname(__file__), "..", "data")


def _shockwave_jobs(total_steps_list):
    return [Job(None, "ResNet-18 (batch size 32)",
                "python3 main.py --batch_size 32",
                "image_classification/cifar10", "--num_steps",
                total_steps=steps, duration=10000)
            for steps in total_steps_list]


def _shockwave_scheduler(port, total_steps_list, max_rounds=8,
                         round_duration=2.0, num_chips=2):
    jobs = _shockwave_jobs(total_steps_list)
    throughputs = read_throughputs(
        os.path.join(DATA, "tacc_throughputs.json"))
    sched = PhysicalScheduler(
        get_policy("shockwave", seed=0),
        throughputs_file=os.path.join(DATA, "tacc_throughputs.json"),
        profiles=build_profiles(jobs, throughputs),
        config=SchedulerConfig(
            time_per_iteration=round_duration, max_rounds=max_rounds,
            shockwave={"num_gpus": num_chips}),
        expected_num_workers=num_chips, port=port)
    return sched, jobs


def _drive(sched, jobs, worker, deadline_s, done):
    for job in jobs:
        sched.add_job(job)
    runner = threading.Thread(target=sched.run, daemon=True)
    runner.start()
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if done():
            break
        time.sleep(0.2)


@pytest.mark.runtime
@pytest.mark.timeout(120)
class TestPipelinedPlanning:
    def test_background_solve_overlaps_round(self):
        """End-to-end shockwave loopback with pipelining on (default):
        jobs complete, re-solves run on the background thread
        (SolveStats.pipelined / hit counter), and no solve phase span
        ever approaches the round duration — the round loop never waits
        on the MILP."""
        sched_port, worker_port = free_port(), free_port()
        round_duration = 2.0
        sched, jobs = _shockwave_scheduler(
            sched_port, [150, 800], round_duration=round_duration)
        assert sched._shockwave_planner.pipelined
        worker = StubWorkerDaemon(sched_port, worker_port, num_chips=2,
                                  throughput=100.0)
        try:
            _drive(sched, jobs, worker, deadline_s=40,
                   done=lambda: len(sched._completed_jobs) == 2)
            assert len(sched._completed_jobs) == 2, "jobs did not complete"

            stats = sched._shockwave_planner.solve_stats
            assert stats, "no solve telemetry"
            # Startup solve is inline; the re-solve triggered by the
            # first completion must have run on the solve thread.
            assert stats[0].pipelined is False
            assert any(s.pipelined for s in stats), (
                f"no pipelined solve in {[s.path for s in stats]}")
            assert all(s.assembly_s <= s.wall_s for s in stats)

            reg = sched.obs.registry
            assert reg.value(obs_names.PIPELINED_SOLVES_TOTAL,
                             outcome="inline") >= 1
            assert reg.value(obs_names.PIPELINED_SOLVES_TOTAL,
                             outcome="hit") >= 1

            # Phase-span evidence: the mid-round solve phase (selection
            # + assignment; the MILP itself overlapped the round) never
            # ate a meaningful fraction of the round.
            solve_spans = [e for e in sched.obs.tracer.events()
                           if e["name"] == obs_names.SPAN_SOLVE]
            assert solve_spans
            assert all(e["dur"] < 0.5 * round_duration
                       for e in solve_spans), solve_spans
        finally:
            sched._done_event.set()
            worker.stop()
            sched._server.stop(grace=0)

    def test_slow_solve_hits_deadline_fallback(self, monkeypatch):
        """A background solve slower than the re-solve deadline must NOT
        stall the round: the planner serves the cached schedule /
        backfill (miss counter), rounds keep rolling on time, and the
        late result still commits at a later re-solve point."""
        from shockwave_tpu.shockwave import planner as planner_mod
        real_plan = planner_mod.plan_schedule
        round_duration = 2.0

        def slow_plan(*args, **kwargs):
            if kwargs.get("pipelined"):
                # Past this round's commit point AND the next round's
                # (kick is skipped while busy), then finish.
                time.sleep(2.2 * round_duration)
            return real_plan(*args, **kwargs)

        monkeypatch.setattr(planner_mod, "plan_schedule", slow_plan)

        sched_port, worker_port = free_port(), free_port()
        sched, jobs = _shockwave_scheduler(
            sched_port, [150, 2000], max_rounds=10,
            round_duration=round_duration)
        worker = StubWorkerDaemon(sched_port, worker_port, num_chips=2,
                                  throughput=100.0)
        try:
            _drive(sched, jobs, worker, deadline_s=60,
                   done=lambda: len(sched._completed_jobs) == 2)
            assert len(sched._completed_jobs) == 2, "jobs did not complete"

            reg = sched.obs.registry
            assert reg.value(obs_names.PIPELINED_SOLVES_TOTAL,
                             outcome="miss") >= 1, \
                "slow solve never exercised the deadline fallback"
            # The late result must eventually have been committed — and
            # counted `late`, never `hit` (its target round already ran
            # on the fallback).
            assert any(s.pipelined
                       for s in sched._shockwave_planner.solve_stats)
            assert reg.value(obs_names.PIPELINED_SOLVES_TOTAL,
                             outcome="late") >= 1
            # Liveness: rounds kept rolling while the solver slept.
            assert sched.rounds.num_completed_rounds >= 3
            solve_spans = [e for e in sched.obs.tracer.events()
                           if e["name"] == obs_names.SPAN_SOLVE]
            assert all(e["dur"] < 0.5 * round_duration
                       for e in solve_spans), solve_spans
        finally:
            sched._done_event.set()
            worker.stop()
            sched._server.stop(grace=0)


class TestPlannerSolvePhases:
    """Unit semantics of the prepare/solve/commit split (no loopback)."""

    def _planner(self, pipelined=False):
        from shockwave_tpu.shockwave.metadata import JobMetadata
        from shockwave_tpu.shockwave.planner import ShockwavePlanner
        planner = ShockwavePlanner(ngpus=2, future_nrounds=4,
                                   round_duration=60.0)
        planner.pipelined = pipelined
        profile = {
            "model": "ResNet-18", "dataset": "cifar10", "scale_factor": 1,
            "num_epochs": 4, "num_samples_per_epoch": 100,
            "util_every_epoch": [50] * 4, "mem_every_epoch": [1024] * 4,
            "duration_every_epoch": [60.0] * 4,
            "bs_every_epoch": [32] * 4,
        }
        for i in range(2):
            meta = JobMetadata(i, dict(profile))
            meta.register_submit(0.0)
            planner.add_job(i, meta)
        return planner

    def test_inline_three_phase_matches_round_schedule(self):
        a = self._planner()
        b = self._planner()
        sched_a = a.round_schedule()
        request = b.prepare_solve()
        b.commit_result(b.solve_prepared(request))
        assert sched_a == b.schedules[b.round_ptr]
        assert a.schedules == b.schedules
        assert not b.needs_resolve()

    def test_stale_generation_keeps_resolve_pending(self):
        planner = self._planner()
        request = planner.prepare_solve()
        result = planner.solve_prepared(request)
        # A new resolve request lands after the snapshot (job event).
        planner.request_resolve()
        planner.commit_result(result)
        # Schedules installed (fresher than nothing)...
        assert planner.schedules
        # ...but the newer request still forces the next re-solve.
        assert planner._resolve is True

    def test_fallback_serves_cache_then_backfill(self):
        planner = self._planner(pipelined=True)
        # No committed solve yet: backfill-only fallback, capacity-safe.
        selected = planner.round_schedule()
        assert selected, "backfill fallback scheduled nothing"
        used = sum(planner.metadata[j].nworkers for j in selected)
        assert used <= planner.ngpus
        # Commit a real solve; the cache then serves without solving.
        request = planner.prepare_solve()
        planner.commit_result(planner.solve_prepared(request))
        assert planner.round_schedule() == planner.schedules[planner.round_ptr]

    def test_pipelined_never_solves_inline(self, monkeypatch):
        from shockwave_tpu.shockwave import planner as planner_mod
        planner = self._planner(pipelined=True)

        def boom(*args, **kwargs):
            raise AssertionError("pipelined round_schedule solved inline")

        monkeypatch.setattr(planner_mod, "plan_schedule", boom)
        assert planner.round_schedule() is not None
