"""Throughput estimation for space-sharing (packing) decisions.

When a new job arrives, the scheduler has no packed-throughput profile for
it. The estimator profiles the job against a random subset of *reference*
job types, fills in the unmeasured entries by low-rank matrix completion,
and matches the job to the nearest reference job type by cosine distance
(reference: scheduler/throughput_estimator.py:17-204). The packed
throughputs of the matched reference type are then used as the new job's
estimates.

The matrix-completion step replaces the reference's external
`matrix_completion.pmf_solve` dependency with an in-repo regularized ALS
solver (`als_complete`) — fully vectorized numpy; the matrices involved
are tiny (num_reference_types x num_reference_types*num_worker_types), so
this runs in microseconds on the scheduler host.

This module also hosts `OracleThroughputChain`: the strict fallback
chain the scheduler consults for ISOLATED rates — profiled table ->
learned model (`shockwave_tpu/oracle`) -> conservative prior — with
every prediction tagged with provenance and a confidence that gates how
much the planner trusts it (README "Learned throughput oracle").
"""
from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Dict, Optional, Sequence

import numpy as np

MATRIX_COMPLETION_RANK = 10
MATRIX_COMPLETION_MU = 1e-2


def als_complete(A: np.ndarray, mask: np.ndarray, k: int = MATRIX_COMPLETION_RANK,
                 mu: float = MATRIX_COMPLETION_MU, max_iterations: int = 100,
                 epsilon: float = 1e-6, seed: int = 0) -> np.ndarray:
    """Low-rank completion of `A` where `mask==0`, via alternating least
    squares on the regularized PMF objective

        min_{U,V} ||mask * (A - U V^T)||_F^2 + mu (||U||^2 + ||V||^2).

    Returns the dense reconstruction U V^T.
    """
    n, m = A.shape
    k = min(k, n, m)
    rng = np.random.RandomState(seed)
    U = rng.randn(n, k) * 0.1
    V = rng.randn(m, k) * 0.1
    eye = mu * np.eye(k)
    prev = np.inf
    for _ in range(max_iterations):
        # Solve each row of U against the masked columns it observes.
        for i in range(n):
            w = mask[i] > 0
            if not w.any():
                continue
            Vw = V[w]
            U[i] = np.linalg.solve(Vw.T @ Vw + eye, Vw.T @ A[i, w])
        for j in range(m):
            w = mask[:, j] > 0
            if not w.any():
                continue
            Uw = U[w]
            V[j] = np.linalg.solve(Uw.T @ Uw + eye, Uw.T @ A[w, j])
        recon = U @ V.T
        err = float(np.linalg.norm(mask * (A - recon)))
        if abs(prev - err) < epsilon:
            break
        prev = err
    return U @ V.T


def cosine_distance(a: np.ndarray, b: np.ndarray) -> float:
    denom = np.linalg.norm(a) * np.linalg.norm(b)
    if denom == 0:
        return 2.0  # maximal distance for degenerate (all-zero) profiles
    return 1.0 - float(np.dot(a, b) / denom)


class ThroughputEstimator:
    """Match an unprofiled job to the nearest offline-profiled reference
    job type (reference: throughput_estimator.py:17-38).

    `oracle_throughputs` uses the parsed oracle format of
    `core.oracle.read_throughputs`: oracle[worker_type][job_type] is a dict
    with key "null" -> isolated steps/s and other job-type keys ->
    [tput_self, tput_other] packed throughputs.
    """

    def __init__(self, oracle_throughputs: Dict[str, dict],
                 worker_types: Sequence[str], job_types: Sequence,
                 num_reference_job_types: int,
                 profiling_percentage: float, seed: int = 0):
        self._rng = random.Random(seed)
        self._oracle = oracle_throughputs
        self._worker_types = list(worker_types)
        self._job_types = list(job_types)
        self._profiling_percentage = profiling_percentage
        self._normalized = self._build_normalized_matrix()
        self._select_reference_types(num_reference_job_types)

    def _build_normalized_matrix(self) -> np.ndarray:
        """Row i = job type i; columns = (worker_type, other job type) pairs;
        value = packed throughput of i when colocated with the other type,
        normalized by i's isolated throughput (in [0, 1])."""
        n, m = len(self._job_types), len(self._worker_types)
        out = np.zeros((n, m * n), dtype=np.float64)
        for j, worker_type in enumerate(self._worker_types):
            per_worker = self._oracle[worker_type]
            for i, job_type in enumerate(self._job_types):
                entry = per_worker[job_type]
                isolated = entry["null"]
                if isolated <= 0:
                    # Job type infeasible on this worker type (e.g. OOM
                    # profile entry): packed share is 0 everywhere.
                    continue
                for k, other in enumerate(self._job_types):
                    out[i, j * n + k] = entry[other][0] / isolated
        # NOTE: unlike Gavel's original oracle, measured packed throughputs
        # can exceed the isolated throughput (e.g. the TACC V100 profiles),
        # so normalized values may be > 1; cosine matching handles that fine.
        if out.size and out.min() < 0.0:
            raise ValueError("packed throughputs must be non-negative")
        return out

    def _select_reference_types(self, num_reference_job_types: int) -> None:
        n = len(self._job_types)
        idx = sorted(self._rng.sample(range(n), num_reference_job_types))
        self._reference_job_types = [self._job_types[i] for i in idx]
        cols = [w * n + i for w in range(len(self._worker_types)) for i in idx]
        self._reference_matrix = self._normalized[np.ix_(idx, cols)]

    def _profile_job(self, true_job_type) -> Dict[str, dict]:
        """Simulate partial profiling: each (worker type, reference type)
        packed measurement is observed with probability
        `profiling_percentage` (reference: throughput_estimator.py:88-100)."""
        i = self._job_types.index(true_job_type)
        n = len(self._job_types)
        measured: Dict[str, dict] = {}
        for w, worker_type in enumerate(self._worker_types):
            measured[worker_type] = {}
            for ref in self._reference_job_types:
                if self._rng.uniform(0, 1) <= self._profiling_percentage:
                    k = self._job_types.index(ref)
                    measured[worker_type][ref] = self._normalized[i, w * n + k]
        return measured

    def match_job_to_reference_job(self, true_job_type):
        """Profile a subset of entries, complete the rest, return the
        reference job type with smallest cosine distance."""
        measured = self._profile_job(true_job_type)
        nref = len(self._reference_job_types)
        row = np.zeros(self._reference_matrix.shape[1])
        row_mask = np.zeros_like(row)
        for w, worker_type in enumerate(self._worker_types):
            for j, ref in enumerate(self._reference_job_types):
                if ref in measured[worker_type]:
                    row[w * nref + j] = measured[worker_type][ref]
                    row_mask[w * nref + j] = 1.0

        matrix = np.vstack([self._reference_matrix, row])
        mask = np.vstack([np.ones_like(self._reference_matrix), row_mask])
        if mask.min() == 0:
            try:
                recon = als_complete(matrix, mask)
            except np.linalg.LinAlgError:
                return self._rng.choice(self._reference_job_types)
            hi = float(matrix[mask > 0].max(initial=1.0))
            matrix = np.where(mask > 0, matrix, np.clip(recon, 0.0, hi))

        target = matrix[-1]
        if np.linalg.norm(target) == 0:
            return self._rng.choice(self._reference_job_types)
        distances = [
            (cosine_distance(matrix[i], target), i)
            for i in range(nref)
        ]
        _, best = min(distances)
        return self._reference_job_types[best]

    def get_reference_throughputs(self) -> Dict[str, dict]:
        """Reference-type-only packed oracle in the standard nested format
        (normalized; [tput_self, tput_other] per pair)."""
        n = len(self._reference_job_types)
        out: Dict[str, dict] = {}
        for w, worker_type in enumerate(self._worker_types):
            out[worker_type] = {}
            for j, ref in enumerate(self._reference_job_types):
                out[worker_type][ref] = {}
                for k, other in enumerate(self._reference_job_types):
                    out[worker_type][ref][other] = [
                        self._reference_matrix[j, w * n + k],
                        self._reference_matrix[k, w * n + j],
                    ]
        return out


# ----------------------------------------------------------------------
# Learned-oracle fallback chain (shockwave_tpu/oracle)
# ----------------------------------------------------------------------

PROVENANCE_PROFILED = "profiled"
PROVENANCE_LEARNED = "learned"
PROVENANCE_PRIOR = "prior"

#: Matches sched.scheduler.DEFAULT_THROUGHPUT (not imported: core must
#: not depend on sched) — the rate the learn-online path starts from.
CONSERVATIVE_PRIOR_STEPS_PER_S = 1.0

#: Default trust gate: a learned prediction below this confidence is
#: demoted to the conservative prior.
DEFAULT_MIN_CONFIDENCE = 0.3


@dataclass(frozen=True)
class ThroughputPrediction:
    steps_per_s: float
    provenance: str      # profiled | learned | prior
    confidence: float


class OracleThroughputChain:
    """profiled table -> learned model -> conservative prior.

    Constructed only when `SchedulerConfig.oracle` is set; with it unset
    the scheduler never instantiates this class and every
    profiled-table code path is byte-identical to the pre-oracle build.
    `observe` feeds Done-report rates back into the learned model's
    online residual corrections, so a cold-start prediction converges
    toward the measured rate as micro-tasks complete.
    """

    def __init__(self, profiled: Optional[Dict[str, dict]] = None,
                 model=None,
                 min_confidence: float = DEFAULT_MIN_CONFIDENCE,
                 online_alpha: Optional[float] = None):
        #: Parsed oracle table ({worker_type: {(job_type, sf): {...}}},
        #: core.oracle.read_oracle output) — may be None (no file).
        self._profiled = profiled
        self._model = model
        self.min_confidence = float(min_confidence)
        self._online_alpha = online_alpha

    @classmethod
    def from_config(cls, cfg: dict,
                    profiled: Optional[Dict[str, dict]] = None
                    ) -> "OracleThroughputChain":
        """Build from a `SchedulerConfig.oracle` dict: ``model`` (path
        to an oracle.train artifact), ``min_confidence``,
        ``online_alpha``."""
        model = None
        model_path = (cfg or {}).get("model")
        if model_path:
            from ..oracle.model import ThroughputModel
            model = ThroughputModel.load(model_path)
        return cls(profiled=profiled, model=model,
                   min_confidence=float(
                       (cfg or {}).get("min_confidence",
                                       DEFAULT_MIN_CONFIDENCE)),
                   online_alpha=(cfg or {}).get("online_alpha"))

    @property
    def model(self):
        return self._model

    def _profiled_rate(self, job_type: str, scale_factor: int,
                       worker_type: str) -> Optional[float]:
        table = (self._profiled or {}).get(worker_type)
        if not table:
            return None
        entry = table.get((job_type, int(scale_factor)))
        if entry is None:
            return None
        rate = entry.get("null", 0.0)
        return float(rate) if rate and rate > 0.0 else None

    def predict(self, job_type: str, batch_size, scale_factor: int,
                worker_type: str) -> ThroughputPrediction:
        profiled = self._profiled_rate(job_type, scale_factor,
                                       worker_type)
        if profiled is not None:
            return ThroughputPrediction(profiled, PROVENANCE_PROFILED,
                                        1.0)
        if self._model is not None:
            rate, confidence = self._model.predict(
                job_type, batch_size, scale_factor, worker_type)
            if confidence >= self.min_confidence:
                return ThroughputPrediction(rate, PROVENANCE_LEARNED,
                                            confidence)
        return ThroughputPrediction(CONSERVATIVE_PRIOR_STEPS_PER_S,
                                    PROVENANCE_PRIOR, 0.0)

    def observe(self, job_type: str, batch_size, scale_factor: int,
                worker_type: str, steps_per_s: float) -> None:
        """Online refinement from a completed micro-task's observed
        rate (no-op without a model)."""
        if self._model is None:
            return
        kwargs = {}
        if self._online_alpha is not None:
            kwargs["alpha"] = float(self._online_alpha)
        self._model.observe(job_type, batch_size, scale_factor,
                            worker_type, steps_per_s, **kwargs)

    def serving_mu(self, job_type: str, batch_size,
                   worker_types: Sequence[str]) -> Optional[float]:
        """Learned decode-rate prior for a serving service (requests/s
        per replica, scale factor 1), or None — the caller must fall
        back to the exact configured rate, so a model with ZERO samples
        for this family leaves canonical serving replays bit-identical.
        Returns the best trusted prediction across the cluster's worker
        types (replicas land on whatever type has chips free)."""
        if (self._model is None
                or self._model.family_samples(job_type) == 0):
            return None
        best: Optional[float] = None
        for wt in worker_types:
            pred = self.predict(job_type, batch_size, 1, wt)
            if pred.provenance != PROVENANCE_LEARNED:
                continue
            if best is None or pred.steps_per_s > best:
                best = pred.steps_per_s
        return best


__all__ = ["ThroughputEstimator", "als_complete", "cosine_distance",
           "MATRIX_COMPLETION_RANK", "MATRIX_COMPLETION_MU",
           "OracleThroughputChain", "ThroughputPrediction",
           "PROVENANCE_PROFILED", "PROVENANCE_LEARNED",
           "PROVENANCE_PRIOR", "CONSERVATIVE_PRIOR_STEPS_PER_S",
           "DEFAULT_MIN_CONFIDENCE"]
