"""Expert-parallel mixture-of-experts MLP over the mesh's "ep" axis.

Switch-Transformer-style top-1 routing with fixed per-expert capacity:
tokens are dispatched into an (experts, capacity, dim) buffer, the
expert FFNs run with the expert dim sharded over "ep" (a sharding
constraint — XLA inserts the all-to-alls on ICI), and outputs are
combined back with the router gate. Everything is dense einsum
dispatch: static shapes, MXU-friendly, no host control flow.

The reference has no MoE/expert parallelism (single-model DDP jobs
only); this is part of the TPU-native scaling surface the framework
adds beyond reference parity.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def moe_mlp(x, router_w, w1, w2, mesh: Optional[Mesh] = None,
            capacity_factor: float = 1.25, axis_name: str = "ep"):
    """Top-1 MoE feed-forward.

    x: (batch, seq, dim); router_w: (dim, E);
    w1: (E, dim, hidden); w2: (E, hidden, dim) — shard E over "ep".
    Returns (out, aux_loss): out same shape as x; aux_loss is the
    Switch load-balancing loss (mean gate * mean assignment per expert,
    scaled by E) to be added to the task loss.
    """
    b, s, d = x.shape
    n_experts = router_w.shape[-1]
    tokens = x.reshape(b * s, d)
    n_tokens = tokens.shape[0]
    capacity = max(int(capacity_factor * n_tokens / n_experts), 1)

    logits = tokens @ router_w.astype(tokens.dtype)  # (T, E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)                 # (T,)
    gate = jnp.take_along_axis(probs, expert_idx[:, None], axis=-1)[:, 0]

    # Position of each token within its expert's capacity buffer;
    # overflowing tokens (pos >= capacity) are dropped (standard Switch).
    assign = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)
    pos = jnp.cumsum(assign, axis=0) * assign  # (T, E), 1-based
    pos_in_expert = jnp.max(pos, axis=-1) - 1               # (T,)
    keep = pos_in_expert < capacity

    # Dense dispatch tensor (T, E, C) -> buffer (E, C, d), ep-sharded.
    dispatch = (assign[:, :, None] * jax.nn.one_hot(
        jnp.where(keep, pos_in_expert, capacity - 1), capacity)[
        :, None, :]).astype(tokens.dtype)
    dispatch = dispatch * keep[:, None, None].astype(tokens.dtype)

    buf = jnp.einsum("tec,td->ecd", dispatch, tokens)  # (E, C, d)
    if mesh is not None and mesh.shape.get(axis_name, 1) > 1:
        buf = jax.lax.with_sharding_constraint(
            buf, NamedSharding(mesh, P(axis_name, None, None)))
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", buf, w1.astype(buf.dtype)))
    out_buf = jnp.einsum("ecf,efd->ecd", h, w2.astype(buf.dtype))
    if mesh is not None and mesh.shape.get(axis_name, 1) > 1:
        out_buf = jax.lax.with_sharding_constraint(
            out_buf, NamedSharding(mesh, P(axis_name, None, None)))

    combined = jnp.einsum("tec,ecd->td", dispatch, out_buf)
    combined = combined * (gate * keep).astype(combined.dtype)[:, None]

    # Switch load-balancing auxiliary loss.
    density = jnp.mean(assign.astype(jnp.float32), axis=0)      # (E,)
    density_proxy = jnp.mean(probs, axis=0)                     # (E,)
    aux_loss = n_experts * jnp.sum(density * density_proxy)

    return combined.reshape(b, s, d).astype(x.dtype), aux_loss
