"""Shared machinery for policy x load sweep scripts.

Each sweep point is one simulation run (a subprocess of the
simulate_generated.py driver so a solver crash in one point cannot take
down the sweep); results stream to stdout as JSON lines and accumulate
into an optional JSON file (reference: scheduler/scripts/sweeps/
run_sweep_{continuous,static}.py).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import List, Optional

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
DRIVER = os.path.join(REPO, "scripts", "drivers", "simulate_generated.py")


def run_point(policy: str, num_jobs: int, lam: float, throughputs: str,
              cluster_spec: str, round_duration: float, seed: int,
              config: Optional[str] = None, timeout: int = 3600) -> dict:
    cmd = [sys.executable, DRIVER,
           "--num_jobs", str(num_jobs), "--lam", str(lam),
           "--policy", policy, "--throughputs", throughputs,
           "--cluster_spec", cluster_spec,
           "--round_duration", str(round_duration), "--seed", str(seed)]
    if config:
        cmd += ["--config", config]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout)
    except subprocess.TimeoutExpired:
        return {"policy": policy, "num_jobs": num_jobs, "lam": lam,
                "seed": seed, "error": f"timeout after {timeout}s"}
    if out.returncode != 0:
        return {"policy": policy, "num_jobs": num_jobs, "lam": lam,
                "seed": seed, "error": out.stderr[-300:]}
    row = json.loads(out.stdout.strip().splitlines()[-1])
    row["seed"] = seed
    return row


def run_sweep(policies: List[str], num_jobs_list: List[int],
              lams: List[float], seeds: List[int], throughputs: str,
              cluster_spec: str, round_duration: float,
              config: Optional[str], output: Optional[str]) -> List[dict]:
    results = []
    for policy in policies:
        for num_jobs in num_jobs_list:
            for lam in lams:
                for seed in seeds:
                    row = run_point(policy, num_jobs, lam, throughputs,
                                    cluster_spec, round_duration, seed,
                                    config)
                    results.append(row)
                    print(json.dumps(row), flush=True)
                    if output:
                        with open(output, "w") as f:
                            json.dump(results, f, indent=1)
    return results


def add_common_args(p):
    p.add_argument("--policies", nargs="*",
                   default=["max_min_fairness", "finish_time_fairness",
                            "isolated", "fifo"])
    p.add_argument("--throughputs",
                   default=os.path.join(REPO, "data", "tacc_throughputs.json"))
    p.add_argument("--cluster_spec", default="v100:32")
    p.add_argument("--round_duration", type=float, default=360.0)
    p.add_argument("--seeds", nargs="*", type=int, default=[0, 1])
    p.add_argument("--config", default=None)
    p.add_argument("--output", default=None)
    return p
