"""Shared crash-safe file-write plumbing.

One implementation of the durable-write recipe (CRC32+magic footer,
file fsync, previous-generation retention, atomic rename, directory
fsync) used by both the scheduler's snapshot store
(`sched/journal.py`) and the trainers' checkpoint writer
(`models/train_common.py`). Crash-safety logic must not fork: a fix on
one side (e.g. a filesystem quirk around fsync) must reach the other.
"""
from __future__ import annotations

import os
import struct
import zlib
from typing import Optional, Tuple

FOOTER_OK = "ok"            # footer present, CRC verified
FOOTER_MISSING = "missing"  # no footer (legacy / foreign / torn file)
FOOTER_CORRUPT = "corrupt"  # footer present but CRC mismatch


def fsync_dir(path: str) -> None:
    """Make a rename/create in `path` durable (POSIX requires fsyncing
    the directory, not just the file)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds; best effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_durable(path: str, payload: bytes, magic: bytes,
                  keep_prev: bool = True) -> str:
    """Write `payload` + CRC footer to `path` crash-safely: tmp file,
    fsync, retain the existing generation as `<path>.prev`, atomic
    rename, directory fsync. A crash at any step leaves either the old
    file, the old file as .prev, or both generations intact."""
    tmp = path + ".tmp"
    footer = struct.pack("<I", zlib.crc32(payload)) + magic
    with open(tmp, "wb") as f:
        f.write(payload)
        f.write(footer)
        f.flush()
        os.fsync(f.fileno())
    if keep_prev and os.path.exists(path):
        os.replace(path, path + ".prev")
        # Make the .prev promotion durable BEFORE the new generation
        # lands at `path`: POSIX does not order two renames in one
        # directory across a crash, and a journal replay that persists
        # the second rename but loses the first would leave the new
        # generation current with a stale .prev fallback — recovery
        # after a subsequent corruption would then replay against the
        # wrong horizon. (Audited by the swtpu-check durability pass:
        # rename/delete of durable files must pair with a directory
        # fsync in the same function.)
        fsync_dir(os.path.dirname(path) or ".")
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")
    return path


def write_text_atomic(path: str, text: str) -> str:
    """Crash-safe plain-text artifact write: tmp file, fsync, atomic
    rename, directory fsync — the same replacement discipline as
    `write_durable` but without the CRC footer, for artifacts that must
    stay directly readable by external tools (e.g. the Monte Carlo
    sweep's incrementally-rewritten results JSON, which a crash must
    leave either whole-old or whole-new, never torn)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path) or ".")
    return path


def verify_footer(blob: bytes, magic: bytes) -> Tuple[str, Optional[bytes]]:
    """Check `blob`'s integrity footer. Returns (status, payload):
    (FOOTER_OK, payload) with the footer stripped, (FOOTER_MISSING,
    None) when no footer is present (callers decide whether legacy
    footer-less content is acceptable), or (FOOTER_CORRUPT, None)."""
    trailer = 4 + len(magic)
    if len(blob) < trailer or not blob.endswith(magic):
        return (FOOTER_MISSING, None)
    payload = blob[:-trailer]
    (crc,) = struct.unpack("<I", blob[-trailer:-len(magic)])
    if zlib.crc32(payload) != crc:
        return (FOOTER_CORRUPT, None)
    return (FOOTER_OK, payload)
