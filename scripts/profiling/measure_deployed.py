#!/usr/bin/env python3
"""Deployed-conditions oracle calibration via the real runtime stack.

The in-process profiler (measure_throughput.py) times jitted steps with
the job alone on the host. On a loopback deployment where the
scheduler, worker daemon, training process, and the next job's
early-dispatched startup all share the same cores, jobs run measurably
slower than that solo rate (e.g. -29% for the LM family on a 1-core
host), and each preemption cycle carries dead time outside the lease
(exit + progress scrape + done RPC + round rollover + unhidden
startup). Both effects are properties of the deployment, so — like the
reference, whose oracle was measured through its runtime harness on
the cluster it scheduled (scheduler/scripts/profiling) — they belong
in the oracle, not in fudge factors.

For each family this script runs a 2-job single-worker physical
loopback (two same-family jobs force an alternating preempt/redispatch
cycle, the regime contended traces live in) for a few rounds, then
reads the per-round iterator logs to measure:

  - deployed throughput: steps / in-lease seconds across all leases;
  - round drain: mean cycle excess over the round duration
    (init-to-init gap minus round), written to
    __meta__.round_drain_s[worker_type];
  - lease shortfall: round minus mean in-lease duration — the unhidden
    startup that shrinks the step window, written to
    __meta__.lease_shortfall_s_by_type (and the scalar mean under
    __meta__.lease_shortfall_s; the dispatch_overhead_s* keys belong to
    measure_startup.py's spawn->exit proxy, which has different
    semantics).

The simulator consumes all three (sched/scheduler.py calibrated model).
Calibration runs use dedicated 2-job traces, so validating a different
trace against the resulting oracle is not circular.

Example:
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \\
      python scripts/profiling/measure_deployed.py --worker_type cpu \\
      --oracle reproduce/fidelity/cpu_throughputs.json
"""
import argparse
import datetime
import glob
import json
import os
import re
import shutil
import statistics
import subprocess
import sys
import tempfile
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, REPO)

from shockwave_tpu.core.job_table import JOB_TABLE  # noqa: E402
from shockwave_tpu.core.trace import job_to_trace_line  # noqa: E402
from shockwave_tpu.core.job import Job  # noqa: E402

LOG_TS = "%Y-%m-%d %H:%M:%S"


def free_port():
    import socket
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def run_calibration(template, steps_per_job, duration, round_s, rounds,
                    data_dir, timeout, scale_factor=1, num_chips=None):
    """2-job loopback for `rounds` rounds; returns the checkpoint dir
    holding the per-round iterator logs.

    With scale_factor > 1 the two jobs are gangs (each needs all
    `num_chips` chips, so they alternate rounds exactly like the sf=1
    calibration). With num_chips > scale_factor capacity, THREE sf=1
    jobs rotate over the chips — the co-resident regime a multi-chip
    loopback cluster puts same-round jobs in, with the odd job out
    guaranteeing lease turnover every round (a 2-job variant extends
    leases indefinitely and only records on chance chip swaps)."""
    ckpt = tempfile.mkdtemp(prefix="swtpu_deployed_")
    concurrent = num_chips is not None and num_chips > scale_factor
    trace = os.path.join(ckpt, "cal.trace")
    with open(trace, "w") as f:
        for _ in range(3 if concurrent else 2):
            job = Job(None, template.model, template.command,
                      template.working_directory, template.num_steps_arg,
                      needs_data_dir=template.needs_data_dir,
                      total_steps=steps_per_job, duration=duration,
                      scale_factor=scale_factor)
            f.write(job_to_trace_line(job, 0.0) + "\n")
    port = free_port()
    sched = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts/drivers/run_physical.py"),
         "--trace", trace, "--policy", "max_min_fairness",
         "--throughputs", os.path.join(REPO, "data/tacc_throughputs.json"),
         "--expected_num_workers", "1", "--round_duration", str(round_s),
         "--port", str(port), "--timeout", str(timeout),
         "--max_rounds", str(rounds),
         "--output", os.path.join(ckpt, "out.pkl")],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    time.sleep(4)
    worker = subprocess.Popen(
        [sys.executable, "-m", "shockwave_tpu.runtime.worker",
         "--worker_type", "cal", "--sched_addr", "127.0.0.1",
         "--sched_port", str(port), "--worker_port", str(free_port()),
         "--num_chips", str(num_chips or scale_factor), "--data_dir", data_dir,
         "--checkpoint_dir", ckpt],
        cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    try:
        sched.wait(timeout=timeout + 120)
    finally:
        for p in (sched, worker):
            if p.poll() is None:
                p.kill()
    return ckpt


def parse_rounds(ckpt):
    """[(round, load_end, lease_expiry, save_end, steps, lease_dur)]

    Gang ranks are aggregated per (job, round) with total-steps
    semantics (steps sum across ranks; duration is the slowest rank;
    load is the earliest rank in, save is the last rank out), so a
    record's steps/dur IS the gang's aggregate rate."""
    per_rank = {}
    for path in glob.glob(os.path.join(
            ckpt, "job_id=*", ".swtpu", "round=*", "worker=*.log")):
        job = int(re.search(r"job_id=(\d+)", path).group(1))
        rnd = int(re.search(r"round=(\d+)", path).group(1))
        load = exp = save_end = None
        steps = dur = None
        for line in open(path):
            m = re.match(r"\[(.*?)\] \[(.*?)\] \[(.*?)\]\s*(.*)", line)
            if not m:
                continue
            t = datetime.datetime.strptime(m.group(1), LOG_TS)
            ev, st, msg = m.group(2), m.group(3), m.group(4)
            if ev == "LOAD CHECKPOINT" and st == "END":
                load = t
            elif ev == "LEASE" and st in ("EXPIRED", "COMPLETE"):
                exp = t
                sm = re.match(r"(\d+) / \S+ steps, ([\d.]+)", msg)
                if sm:
                    steps, dur = int(sm.group(1)), float(sm.group(2))
            elif ev == "SAVE CHECKPOINT" and st == "END":
                save_end = t
        if load is not None:
            per_rank.setdefault((rnd, job), []).append(
                (load, exp, save_end, steps, dur))
    out = []
    for (rnd, job), ranks in sorted(per_rank.items()):
        load = min(r[0] for r in ranks)
        exps = [r[1] for r in ranks if r[1] is not None]
        saves = [r[2] for r in ranks if r[2] is not None]
        step_vals = [r[3] for r in ranks if r[3] is not None]
        dur_vals = [r[4] for r in ranks if r[4] is not None]
        out.append((rnd, load, max(exps) if exps else None,
                    max(saves) if saves else None,
                    sum(step_vals) if step_vals else None,
                    max(dur_vals) if dur_vals else None))
    return out


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--worker_type", required=True)
    p.add_argument("--oracle", required=True)
    p.add_argument("--families", nargs="+",
                   default=["ResNet-18 (batch size 32)", "LM (batch size 20)",
                            "Recommendation (batch size 512)"])
    p.add_argument("--round_duration", type=float, default=120.0)
    p.add_argument("--rounds", type=int, default=5)
    p.add_argument("--data_dir", default="/tmp/swtpu_data")
    p.add_argument("--timeout", type=float, default=1500.0)
    p.add_argument("--scale_factor", type=int, default=1,
                   help="calibrate gang jobs: 2 sf=N jobs alternating "
                        "on an N-chip worker (jax.distributed gangs "
                        "through the real dispatch path); writes "
                        "('Family', N) oracle rows")
    p.add_argument("--concurrent", action="store_true",
                   help="calibrate the co-resident regime: 3 sf=1 jobs "
                        "rotating over a 2-chip worker, so the running "
                        "pair is co-resident and the odd job out forces "
                        "lease turnover every round (only rates are "
                        "written — drains keep their preemption-cycle "
                        "calibration). "
                        "OVERWRITES the ('family', 1) rate rows: point "
                        "--oracle at a dedicated copy (multi-chip-on-one-"
                        "host loopbacks), never at the main sf=1 oracle")
    args = p.parse_args()
    if args.concurrent and args.scale_factor != 1:
        p.error("--concurrent calibrates sf=1 co-residency")

    by_model = {t.model: t for t in JOB_TABLE}
    with open(args.oracle) as f:
        oracle = json.load(f)
    rows = oracle.setdefault(args.worker_type, {})
    meta = oracle.setdefault("__meta__", {})
    drains, shortfalls, detail = [], [], {}

    sf = args.scale_factor
    for family in args.families:
        template = by_model[family]
        # Enough steps that neither job finishes inside the calibration
        # window: rate is taken from solo profile when present, else a
        # conservative 0.2 steps/s. (A gang's aggregate rate on a
        # timeshared loopback host is ~the sf=1 rate; on real chips
        # it is higher and the jobs simply stop at max_rounds.)
        solo = rows.get(f"('{family}', 1)", {}).get("null") or 0.2
        steps_per_job = int(solo * args.round_duration * args.rounds)
        duration = int(args.rounds * args.round_duration * 4)
        ckpt = run_calibration(
            template, steps_per_job, duration, args.round_duration,
            args.rounds, args.data_dir, args.timeout,
            scale_factor=sf, num_chips=2 if args.concurrent else sf)
        try:
            recs = parse_rounds(ckpt)
        finally:
            shutil.rmtree(ckpt, ignore_errors=True)
        # Skip round 0 (cold compile cache perturbs it).
        leases = [(s, d) for rnd, _, _, _, s, d in recs
                  if rnd > 0 and s and d]
        if not leases:
            raise SystemExit(f"{family}: no usable leases measured")
        tput = sum(s for s, _ in leases) / sum(d for _, d in leases)
        lease_durs = [d for _, d in leases]
        # Gap and lease duration are paired PER ROUND RECORD: a round
        # with a missing/unparsed lease line (e.g. process killed
        # mid-round) is dropped whole, so one bad round can't shift
        # every subsequent gap onto the wrong round's lease duration.
        # Concurrent mode has no preemption cycle at all — consecutive
        # records are the two co-resident jobs of the SAME round, so a
        # gap chain would pair one job's load with the other's exit;
        # skip the computation entirely.
        cycles = []
        prev_exit = None
        if not args.concurrent:
            for rnd, load, exp, save_end, s, d in recs:
                end = save_end or exp
                if (prev_exit is not None and load is not None and rnd > 0
                        and s and d):
                    cycles.append(((load - prev_exit).total_seconds(), d))
                if end is not None:
                    prev_exit = end
        # Cycle excess over the round: everything outside the lease.
        cycle_excess = [
            g + (args.round_duration - min(d, args.round_duration))
            for g, d in cycles]
        drain = statistics.mean(cycle_excess) if cycle_excess else 0.0
        shortfall = max(
            args.round_duration - statistics.mean(lease_durs), 0.0)
        rows[f"('{family}', {sf})"] = {"null": round(tput, 4)}
        if not args.concurrent and sf == 1:
            # lease_shortfall_s* keys are OWNED by this script (in-lease
            # shortfall via the real runtime); the spawn->exit proxy keys
            # (dispatch_overhead_s*) are owned by measure_startup.py. The
            # simulator prefers the shortfall when both are present
            # (sched/scheduler.py:_cold_dispatch_overhead). Concurrent
            # mode has no preemption cycle, so drains/shortfalls keep
            # their preemption-cycle calibration; gang (sf>1) cycles
            # have their own (longer) excess, which must not clobber the
            # sf=1 calibration the committed artifacts are built on —
            # it stays visible in deployed_calibration["sf=N"] detail.
            meta.setdefault("lease_shortfall_s_by_type", {}).setdefault(
                args.worker_type, {})[family] = round(shortfall, 2)
            meta.setdefault("round_drain_s_by_type", {}).setdefault(
                args.worker_type, {})[family] = round(drain, 2)
            drains.append(drain)
            shortfalls.append(shortfall)
        elif not args.concurrent:
            # Gang (sf>1) preemption cycles cost measurably more than
            # sf=1 ones (2-process exit + rendezvous + redispatch); they
            # go under a per-sf key the simulator prefers for sf>1 jobs,
            # never clobbering the sf=1 calibration.
            drains.append(drain)
        detail[family] = {
            "deployed_steps_per_s": round(tput, 4),
            "solo_steps_per_s": solo,
            "scale_factor": sf,
            "concurrent": args.concurrent,
            "leases": len(leases),
            "mean_lease_s": round(statistics.mean(lease_durs), 1),
            "mean_cycle_excess_s": (None if args.concurrent
                                    else round(drain, 1)),
        }
        print(f"{family} sf={sf}: deployed {tput:.4f} steps/s "
              f"(solo {solo}), lease shortfall {shortfall:.1f}s, "
              f"cycle excess {drain:.1f}s")

    if shortfalls:
        meta.setdefault("lease_shortfall_s", {})[args.worker_type] = round(
            statistics.mean(shortfalls), 2)
        meta.setdefault("round_drain_s", {})[args.worker_type] = round(
            statistics.mean(drains), 2)
    elif drains and sf > 1:
        meta.setdefault("round_drain_s_by_sf", {}).setdefault(
            args.worker_type, {})[str(sf)] = round(
            statistics.mean(drains), 2)
    mode = ("3 jobs rotating over a 2-chip worker (co-resident pairs, "
            "odd job out forces lease turnover)"
            if args.concurrent else
            f"2-job alternating loopback (sf={sf})")
    meta.setdefault("deployed_calibration", {}).setdefault(
        args.worker_type, {})[f"sf={sf}{'+concurrent' if args.concurrent else ''}"] = {
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "method": f"{mode} via the real runtime; steps/in-lease-second; "
                  "cycle excess over round",
        "round_duration": args.round_duration,
        "per_family": detail,
    }
    with open(args.oracle, "w") as f:
        json.dump(oracle, f, indent=1)
        f.write("\n")
    print(f"round_drain_s[{args.worker_type}] = "
          f"{meta.get('round_drain_s', {}).get(args.worker_type)} "
          f"-> {args.oracle}")


if __name__ == "__main__":
    main()
