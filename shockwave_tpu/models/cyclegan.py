"""CycleGAN generators and discriminators in flax.linen.

Zhu et al. '17 architecture (ResNet-block generator, 70x70 PatchGAN
discriminator), NHWC layout with bfloat16 compute / fp32 params so the
convolutions tile onto the MXU. Capability parity with the reference's
monet2photo workload (workloads/pytorch/cyclegan/cyclegan.py); instance
norm replaces batch norm exactly as in the original paper.
"""
from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class InstanceNorm(nn.Module):
    """Per-sample, per-channel normalization (no running statistics)."""
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # Statistics in fp32: bf16's 8-bit mantissa is not enough to
        # reduce 128x128 spatial planes accurately.
        x32 = x.astype(jnp.float32)
        mean = jnp.mean(x32, axis=(1, 2), keepdims=True)
        var = jnp.var(x32, axis=(1, 2), keepdims=True)
        y = (x32 - mean) / jnp.sqrt(var + self.epsilon)
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (x.shape[-1],),
                          jnp.float32)
        return (y * scale + bias).astype(self.dtype)


class ResidualBlock(nn.Module):
    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        y = nn.Conv(self.features, (3, 3), padding="SAME", dtype=self.dtype)(x)
        y = InstanceNorm(dtype=self.dtype)(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), padding="SAME", dtype=self.dtype)(y)
        y = InstanceNorm(dtype=self.dtype)(y)
        return x + y


class Generator(nn.Module):
    """c7s1-64, d128, d256, R256 x num_blocks, u128, u64, c7s1-3."""
    base_features: int = 64
    num_blocks: int = 6
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        f = self.base_features
        x = x.astype(self.dtype)
        x = nn.Conv(f, (7, 7), padding="SAME", dtype=self.dtype)(x)
        x = nn.relu(InstanceNorm(dtype=self.dtype)(x))
        for mult in (2, 4):  # downsample
            x = nn.Conv(f * mult, (3, 3), strides=(2, 2), padding="SAME",
                        dtype=self.dtype)(x)
            x = nn.relu(InstanceNorm(dtype=self.dtype)(x))
        for _ in range(self.num_blocks):
            x = ResidualBlock(f * 4, dtype=self.dtype)(x)
        for mult in (2, 1):  # upsample
            x = nn.ConvTranspose(f * mult, (3, 3), strides=(2, 2),
                                 padding="SAME", dtype=self.dtype)(x)
            x = nn.relu(InstanceNorm(dtype=self.dtype)(x))
        x = nn.Conv(3, (7, 7), padding="SAME", dtype=self.dtype)(x)
        return jnp.tanh(x).astype(jnp.float32)


class Discriminator(nn.Module):
    """70x70 PatchGAN: C64-C128-C256-C512 -> 1-channel patch logits."""
    base_features: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        f = self.base_features
        for i, mult in enumerate((1, 2, 4, 8)):
            strides = (2, 2) if i < 3 else (1, 1)
            x = nn.Conv(f * mult, (4, 4), strides=strides, padding="SAME",
                        dtype=self.dtype)(x)
            if i > 0:
                x = InstanceNorm(dtype=self.dtype)(x)
            x = nn.leaky_relu(x, 0.2)
        x = nn.Conv(1, (4, 4), padding="SAME", dtype=self.dtype)(x)
        return x.astype(jnp.float32)
