"""Round-by-round replica-count policy for one serving service.

The autoscaler turns the deterministic load curve into a per-round
replica target:

- **Provision for the window's peak**, not its mean: the target is
  computed from ``peak_rate`` over the upcoming round (times a headroom
  factor), so a spike starting mid-round is already covered at the
  round's dispatch — the mechanism behind >99% SLO attainment under a
  10x burst without reactive lag.
- **Scale up immediately, scale down patiently**: an upward target is
  committed the round it appears; a downward one must persist for
  ``scale_down_patience`` consecutive rounds first, so a load dip
  between two spike shoulders does not flap replicas (each flap costs a
  cold dispatch on real hardware).
- **Scale to zero at troughs**: when the window's peak offered load
  rounds to fewer than ``min_requests_per_round`` requests, the target
  is 0 and the service releases all chips back to training.
- **Cluster-share cap**: ``max_cluster_fraction`` bounds what serving
  may reserve ahead of the training planner, the knob that keeps
  training FTF inside the Shockwave envelope even under pathological
  spike traces.
- **Measurement overrides the model**: when the physical replicas'
  merged request telemetry (serving/measured.py) reports a p99 over
  the SLO, the target escalates one replica above the committed level
  that produced the breach — even when the analytic M/M/c model says
  the pool is fine. Measured evidence of a breach beats a model that
  predicted none; the escalation commits immediately (it is upward)
  and decays through the ordinary patience window once measurement
  recovers. Without measured samples (simulation, cold start) the
  arithmetic is untouched.

Pure state machine over (spec, clock); no wall time, no RNG — replays
are bit-identical.
"""
from __future__ import annotations

from dataclasses import dataclass

from .latency_model import replicas_for_slo


@dataclass
class AutoscalerConfig:
    #: Multiplier on the window's peak rate before sizing the pool.
    headroom: float = 1.15
    #: Consecutive rounds a lower target must persist before committing.
    scale_down_patience: int = 2
    #: Below this many offered requests per round, scale to zero.
    min_requests_per_round: float = 0.5
    #: Fraction of total cluster chips serving may reserve (1.0 = all).
    max_cluster_fraction: float = 1.0
    #: Measured samples a round must contribute before its measured
    #: p99 / mu estimate may influence scaling (noise floor).
    measured_min_samples: int = 8
    #: Pseudo-sample weight of the analytic mu prior in the online
    #: blend (serving/measured.ServiceMeasuredState).
    mu_prior_weight: float = 64.0

    @classmethod
    def from_dict(cls, config: dict) -> "AutoscalerConfig":
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(config) - known
        if unknown:
            raise ValueError(
                f"unknown serving autoscaler option(s): {sorted(unknown)}")
        return cls(**config)


class Autoscaler:
    """Per-service scaling state (hysteresis counters live here; the
    load curve and latency model are pure functions)."""

    def __init__(self, config: AutoscalerConfig):
        self.config = config
        self._committed = 0
        self._pending_down: int = 0
        self._pending_target: int = 0

    def target_replicas(self, peak_rate: float, mu: float, slo_p99_s: float,
                        max_replicas: int, round_duration_s: float,
                        measured_p99_s: float = None) -> int:
        """Replica target for a round whose peak arrival rate is
        ``peak_rate`` req/s. Stateful: applies headroom, scale-to-zero,
        and the scale-down patience window. ``measured_p99_s`` is the
        last round's measured p99 when the replicas reported enough
        samples (None otherwise — simulation and cold start)."""
        cfg = self.config
        if (max_replicas <= 0
                or peak_rate * round_duration_s < cfg.min_requests_per_round):
            # A zero cap (operator- or budget-imposed) must yield zero —
            # never the max(1, ...) floor below.
            raw = 0
        else:
            raw = max(1, replicas_for_slo(peak_rate * cfg.headroom, mu,
                                          slo_p99_s, max_replicas))
            if (measured_p99_s is not None and measured_p99_s > slo_p99_s
                    and self._committed > 0):
                # Measured breach at the committed level: the pool that
                # produced those samples is demonstrably too small,
                # whatever the model says — escalate one above it.
                raw = min(max(raw, self._committed + 1), max_replicas)
        if raw >= self._committed:
            # Scale up (or hold): commit immediately, clear hysteresis.
            self._committed = raw
            self._pending_down = 0
            return self._committed
        # Downward pressure: require it to persist. Track the HIGHEST
        # pending target seen during the patience window — scaling below
        # a level the window still demanded would violate the SLO there.
        if self._pending_down == 0 or raw > self._pending_target:
            self._pending_target = raw
        self._pending_down += 1
        if self._pending_down >= cfg.scale_down_patience:
            self._committed = self._pending_target
            self._pending_down = 0
        return self._committed

    @property
    def committed(self) -> int:
        return self._committed


__all__ = ["Autoscaler", "AutoscalerConfig"]
