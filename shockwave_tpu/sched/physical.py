"""Physical-cluster execution: the round mechanism over real workers.

`PhysicalScheduler` extends the simulator-capable core with:
- wall-clock time and thread-safe callback entry points,
- the begin/mid/end round pipeline: recompute the schedule at 50% of the
  round, extend leases when placements repeat, dispatch the next round
  early, and enforce round completion with watchdog events,
- the lease protocol callbacks (init / renew / consensus for multi-chip
  gangs) and failure handling (kill unresponsive jobs),
- worker liveness: heartbeats piggybacked on Done/UpdateLease plus an
  active Ping probe; a dead worker's chips leave the schedulable pool,
  its in-round jobs are failed-in-round and requeued (so `_end_round`
  never blocks on a crashed daemon), and a rejoining daemon revives its
  old chip ids via an idempotent RegisterWorker,
- pipelined planning (shockwave policy): the EG MILP runs on a
  background solve thread kicked at round start, committed at the
  mid-round re-solve point, with a deadline fallback to the cached
  schedule + work-conserving backfill — the round loop never waits on
  the solver, so physical mode can grant the full solver budget
(reference: scheduler/scheduler.py:2382-2777, 3880-4339).
"""
from __future__ import annotations

import collections
import logging
import math
import os
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import grpc

from ..analysis.sanitizer import maybe_wrap
from ..core.job import JobIdPair
from ..core.locking import requires_lock
from ..obs import names as obs_names
from ..runtime.resilience import (HEALTH_DEGRADED, HEALTH_HEALTHY,
                                  HealthConfig, HostHealth,
                                  RpcUnavailableError)
from .journal import encode_job_key
from .scheduler import DEADLINE_SLACK, INFINITY, Scheduler, SchedulerConfig

logger = logging.getLogger("shockwave_tpu.sched")

#: Errors meaning "the worker daemon is unreachable" on a control RPC.
WORKER_RPC_ERRORS = (RpcUnavailableError, grpc.RpcError)

SCHEDULE_RECOMPUTE_FRACTION = 0.5
JOB_COMPLETION_BUFFER_TIME = 60.0
EARLY_INIT_THRESHOLD = 3.0
# Minimum initial lease grant. TPU jobs can spend most of a round in
# imports + jit compilation before InitJob arrives; granting only the
# round's sliver of remaining time would expire the lease before a
# single step, and the job would livelock re-paying startup every round.
# Must stay below JOB_COMPLETION_BUFFER_TIME so the round-end kill
# watchdog still leaves room for the expiry checkpoint.
INIT_LEASE_FLOOR_S = 45.0
# A job whose latest heartbeat is younger than this is never killed as
# unresponsive — the kill timer re-arms once instead (it may be running
# its lease-expiry checkpoint right now).
KILL_HEARTBEAT_FRESHNESS_S = 30.0
BASE_JOB_PORT = 60570
MAX_PORT = 65535


class PhysicalScheduler(Scheduler):
    #: Mutable state shared between the round loop, the allocation
    #: thread, the liveness monitor, watchdog timers and the gRPC
    #: handlers: reads and writes must hold self._lock (self._cv is the
    #: condition built on the same lock). Enforced statically by
    #: `python -m shockwave_tpu.analysis` (pass lock-discipline) and at
    #: runtime by analysis/sanitizer.py under SWTPU_SANITIZE=1; methods
    #: whose CALLERS must hold the lock are annotated @requires_lock.
    _LOCK_PROTECTED = frozenset({
        # scheduling-core aggregates (inherited from Scheduler)
        "workers", "acct", "rounds",
        "_allocation", "_need_to_update_allocation",
        "_running_jobs", "_in_progress_updates", "_iterator_log_buffers",
        "_steps_run_in_current_lease", "_job_timelines", "_bs_flags",
        "_completed_jobs",
        # physical-mode protocol state
        "_worker_hosts", "_worker_connections", "_lease_update_requests",
        "_last_heartbeat", "_kill_rearm_counts", "_dispatch_stamp",
        "_done_stamp", "_dispatch_seq", "_failure_compensated",
        "_ever_signaled", "_max_steps_consensus", "_completion_events",
        "_redispatch_assignments", "_current_round_start_time",
        "_port_offset",
        # pipelined-planning handoff (round loop <-> solve thread)
        "_planner_request", "_planner_result", "_planner_busy",
        # fleet-trace per-round root span context (round loop; read by
        # the dispatch path under the same lock)
        "_round_ctx", "_round_ctx_round", "_round_ctx_started",
        # gray-failure health scoring + quarantine (fed by done/dispatch
        # callbacks and the liveness monitor; read by the round pipeline
        # and the serving tier's suspect-skip)
        "_host_health", "_fleet_rate",
        # serving tier (mutated by plan_round inside the locked round
        # pipeline and by add_job; read by _serving_live)
        "_serving_tier", "_serving_job_ids",
        # HA fence flag: set under the lock by the renewal thread /
        # dispatch path, observed by the round loop under _cv (the two
        # advisory unlocked reads are inline-suppressed monotonic-bool
        # probes)
        "_ha_fenced",
    })
    # Scheduling-core maps mutated by add_job / register_worker / reset
    # paths (gRPC handlers) and the round loop live in
    # Scheduler._EXTERNALLY_SYNCHRONIZED, NOT here: their access sites
    # are base-class methods in sched/scheduler.py, which the
    # lock-discipline pass (scoped to the registry-declaring class's
    # own body) cannot see — listing them here would claim a lexical
    # check that never runs. The physical-side helpers touching them
    # are @requires_lock, which the sanitizer verifies at runtime.

    #: Sanctioned blocking-under-lock sites (hold-discipline pass,
    #: analysis/lockflow.py). Every entry is a deliberate design
    #: decision, documented at its call site:
    #:
    #: - ``_try_dispatch_job:rpc`` / ``_kill_job:rpc`` /
    #:   ``_fail_jobs_on_dead_workers:rpc`` /
    #:   ``_quarantine_worker_host:rpc`` — single bounded-deadline
    #:   best-effort RPCs (``deadline_s=worker_probe_deadline_s`` or the
    #:   dispatch deadline). The round protocol REQUIRES the dispatch /
    #:   kill decision and its assignment-map mutation to be atomic
    #:   under the scheduler lock (a release window would let a Done
    #:   callback observe a half-dispatched gang); the deadline bounds
    #:   the stall, and a dead host is reaped by the probe loop, not by
    #:   a retry budget here.
    #: - ``_maybe_snapshot:fsync`` — write-ahead durability: the
    #:   snapshot MUST capture scheduler state at a quiescent point
    #:   under the lock, or recovery replays against a torn state. The
    #:   round-cadence snapshot interval amortizes the fsync wall.
    #: - ``run:solve`` — the startup-only inline MILP solve: no round
    #:   is executing yet and no worker is waiting on the lock; the
    #:   first dispatch needs a committed schedule. Every later solve
    #:   runs on the _planner_solve_loop thread with the lock RELEASED.
    #: - ``_mid_round:solve`` — static-path-only: round_schedule()'s
    #:   inline-solve branch is the simulator path; PhysicalScheduler
    #:   always constructs the planner with pipelined=True, where
    #:   round_schedule serves the committed result or the deadline
    #:   fallback and never solves inline (shockwave/planner.py).
    _HOLD_DISCIPLINE_JUSTIFIED = frozenset({
        "_try_dispatch_job:rpc", "_kill_job:rpc",
        "_fail_jobs_on_dead_workers:rpc", "_quarantine_worker_host:rpc",
        "_maybe_snapshot:fsync", "run:solve", "_mid_round:solve",
    })

    def __init__(self, policy, throughputs_file=None, profiles=None,
                 config: Optional[SchedulerConfig] = None,
                 expected_num_workers: Optional[int] = None,
                 port: int = 50070):
        super().__init__(policy, simulate=False,
                         throughputs_file=throughputs_file, profiles=profiles,
                         config=config)
        self._start_time = time.time()
        # Instrumented under SWTPU_SANITIZE=1 (lock-order + hold-time
        # recording, analysis/sanitizer.py); the raw RLock otherwise.
        self._lock = maybe_wrap(threading.RLock(), "PhysicalScheduler._lock")
        self._cv = threading.Condition(self._lock)
        self._expected_num_workers = expected_num_workers

        self._worker_connections: Dict[int, object] = {}
        # Host endpoint (ip, port) -> {worker_type, num_chips, worker_ids,
        # client, probe_failures}: the unit of liveness (one daemon serves
        # all its chips) and the key for idempotent re-registration.
        self._worker_hosts: Dict[Tuple[str, int], dict] = {}
        self._available_workers: "queue.Queue[int]" = queue.Queue()
        self._lease_update_requests: Dict[JobIdPair, list] = {}
        self._last_heartbeat: Dict[JobIdPair, float] = {}
        # Consecutive heartbeat-freshness kill deferrals per job, cleared
        # on dispatch and on done — bounds the _kill_job re-arm loop.
        self._kill_rearm_counts: Dict[JobIdPair, int] = {}
        # Per-(job, worker) dispatch sequence numbers and the sequence a
        # Done was last accepted for. Each dispatch gets a fresh number
        # from a monotonic counter (NOT wall clock — an NTP step must
        # not flip the comparison and wedge completions); a report is
        # accepted only if its dispatch's number has not been consumed
        # yet. Rejects at-least-once retry duplicates (gRPC can return
        # UNAVAILABLE after the server processed the request, and a
        # replay would double-count steps) and late real reports landing
        # after a synthesized completion. Early dispatch to the SAME
        # worker only happens once the round's Done was processed
        # (extended-lease rule), so a legitimate report can never be
        # rejected by this ordering.
        self._dispatch_stamp: Dict[Tuple[JobIdPair, int], int] = {}
        self._done_stamp: Dict[Tuple[JobIdPair, int], int] = {}
        self._dispatch_seq = 0
        # Jobs whose failure counter was pre-decremented for a synthesized
        # failed-in-round completion this dispatch (see
        # _fail_jobs_on_dead_workers); cleared on the next dispatch.
        self._failure_compensated: set = set()
        # Jobs that have reached at least one RPC since their LATEST
        # dispatch — only these may be unresponsive-killed before the
        # first-init grace expires (see SchedulerConfig.first_init_grace_s).
        self._ever_signaled: set = set()
        self._max_steps_consensus: Dict[JobIdPair, Optional[int]] = {}
        self._completion_events: Dict[JobIdPair, threading.Timer] = {}
        self._redispatch_assignments: "collections.OrderedDict" = collections.OrderedDict()
        self._current_round_start_time = 0.0
        self._port_offset = 0
        self._done_event = threading.Event()
        # Pipelined planning: one in-flight MILP request/result pair
        # handed between the round loop and the background solve thread
        # (same pattern as _allocation_thread; all three under _lock).
        self._planner_request = None
        self._planner_result = None
        self._planner_busy = False

        # Gray-failure detection (see README "Gray failures & chaos
        # testing"): per-host EWMA health classifier + the
        # fleet-reference rates it scores observed steps/s against.
        self._health_enabled = bool(self._config.worker_health_enabled)
        self._health_cfg = HealthConfig.from_dict(self._config.worker_health)
        self._host_health: Dict[Tuple[str, int], HostHealth] = {}
        # (job_type, scale_factor, worker_type) -> fastest recent
        # observed steps/s (decayed max): the yardstick a host's own
        # observation is scored against, deliberately NOT the EMA
        # throughput table (which tracks the degraded host downward and
        # would launder a slow worker back to "expected").
        self._fleet_rate: Dict[Tuple[str, int, str], float] = {}

        # Control-plane HA (config.ha; see sched/ha.py): claim a fenced
        # leader epoch BEFORE recovery so every journal record this
        # incarnation writes carries it, and so a deposed predecessor's
        # post-fencing writes are already superseded when we replay.
        self._ha = None
        self._ha_fenced = False
        if self._config.ha is not None:
            if not self._config.state_dir:
                raise ValueError("config error: ha requires state_dir "
                                 "(the lease, epoch claims and shipped "
                                 "journal all live there)")
            from .ha import HAConfig, HAController
            os.makedirs(self._config.state_dir, exist_ok=True)
            self._ha = HAController(
                self._config.state_dir,
                HAConfig.from_dict(self._config.ha), port=port,
                obs=self._obs, on_fenced=self._on_ha_fenced)

        # Durability: recover BEFORE the gRPC server starts (RPCs land
        # the moment the port is bound, and they must see the rebuilt
        # state), then attach the journal so every subsequent mutation
        # is written ahead.
        self._durability = None
        self._recovered = False
        self._recovered_at = 0.0
        if self._config.resume and not self._config.state_dir:
            raise ValueError("config error: resume=True requires "
                             "state_dir (there is no journal to recover "
                             "from)")
        if self._config.state_dir:
            from .journal import DurabilityLayer, has_state, load_state
            # Recovery mutates protected state and runs @requires_lock
            # replay helpers; hold the (uncontended) lock so the
            # discipline holds even during construction.
            with self._lock:
                if self._config.resume:
                    recovered = load_state(self._config.state_dir)
                    self.restore_from_durable_state(recovered)
                    self._recovered = True
                    self._recovered_at = self.get_current_timestamp()
                elif has_state(self._config.state_dir):
                    raise ValueError(
                        f"state dir {self._config.state_dir!r} contains "
                        "existing scheduler state; pass resume=True "
                        "(--resume) to recover it, or point state_dir at "
                        "a fresh directory")
                self._durability = DurabilityLayer(
                    self._config.state_dir,
                    self._config.snapshot_interval_rounds,
                    obs=self._obs,
                    epoch=(self._ha.epoch if self._ha is not None
                           else None),
                    # HA incarnations never append to a segment a
                    # deposed zombie may still hold open.
                    rotate_on_open=self._ha is not None)
                self.attach_durability(self._durability)
                if self._recovered:
                    self._requeue_inflight_after_recovery()

        # Fleet-trace propagation (opt-in via obs_trace_dir): each round
        # gets a root span context; phase spans and per-dispatch RunJob
        # RPCs nest under it and the context rides the RPC metadata into
        # worker daemons and trainer subprocesses. None means no
        # contexts are ever constructed — historical tracer content is
        # untouched.
        self._trace_propagation = self._config.obs_trace_dir is not None
        self._round_ctx = None
        self._round_ctx_round = -1
        self._round_ctx_started = 0.0

        # Telemetry history (opt-in; see obs/history.py): per-round
        # metric snapshots + per-microtask observed steps/s, crash-safe
        # in the state dir, served as /history.json, surfacing
        # swtpu_alert burn-rate checks.
        self._history = None
        if self._config.history is not None:
            from ..obs import names as _names
            from ..obs.history import TelemetryHistory
            hist_cfg = dict(self._config.history)
            path = hist_cfg.get("path") or (
                os.path.join(self._config.state_dir,
                             _names.HISTORY_FILE_NAME)
                if self._config.state_dir else None)
            if path is None:
                raise ValueError(
                    "config error: history requires state_dir (the "
                    "ring file lives beside the journal) or an "
                    "explicit history.path")
            self._history = TelemetryHistory.from_config(
                hist_cfg, self._obs.registry,
                self.get_current_timestamp, path,
                time_per_iteration=self._time_per_iteration)

        # Health endpoint (opt-in): /metrics + /healthz (+ the history
        # ring as /history.json). Started before the gRPC server so a
        # hung bring-up is already observable.
        self._obs_server = None
        if self._config.obs_port is not None:
            from ..obs.exporter import ObsHttpServer
            self._obs_server = ObsHttpServer(
                self._obs.registry, health_fn=self.obs_health,
                history_fn=(self._history.payload
                            if self._history is not None else None),
                port=self._config.obs_port).start()

        from ..runtime.servers import serve_scheduler
        self._server = serve_scheduler(port, {
            "RegisterWorker": self._register_worker_rpc,
            "Done": self.done_callback,
            "InitJob": self._init_job_callback,
            "UpdateLease": self._update_lease_callback,
            "UpdateResourceRequirement": self._update_resource_requirement_callback,
        }, fenced_check=(  # monotonic-bool probe from gRPC threads; a
            # stale read is one extra refused RPC, never a wrong accept
            (lambda: self._ha_fenced)  # swtpu-check: ignore[lock-discipline]
            if self._ha is not None else None))
        if self._ha is not None:
            # First lease only once the port is bound: the lease IS the
            # endpoint registry workers re-resolve through.
            self._ha.start()

        if self._config.watchdog_interval:
            import faulthandler
            faulthandler.dump_traceback_later(
                self._config.watchdog_interval, repeat=True)

        if policy.name != "shockwave":
            threading.Thread(target=self._allocation_thread, daemon=True).start()
        elif self._config.pipelined_planning:
            # Background MILP solve thread: _begin_round kicks a
            # prepared request, _mid_round commits the result (or the
            # planner serves its deadline fallback). The solve itself
            # runs OFF the scheduler lock, so the round pipeline and
            # every RPC handler stay responsive through a full-budget
            # solve.
            self._shockwave_planner.pipelined = True
            threading.Thread(target=self._planner_solve_loop,
                             daemon=True).start()
        if self._config.heartbeat_interval_s:
            threading.Thread(target=self._liveness_loop, daemon=True).start()

        # What-if control plane (config.whatif): the round pipeline
        # captures state forks UNDER the lock (the instrumented
        # `whatif_fork` phase — a few ms of pickle), and this thread
        # rolls the detached twins OFF it, re-taking the lock only for
        # a committed knob value. Admission evaluation in physical mode
        # is ADVISORY: the verdict is logged/journaled, the job is
        # admitted regardless (deferral is a simulation-loop mechanism).
        self._whatif_work: "queue.Queue" = queue.Queue()
        if self._whatif is not None:
            threading.Thread(target=self._whatif_loop, daemon=True).start()

    # ------------------------------------------------------------------
    # Time / threading
    # ------------------------------------------------------------------

    def get_current_timestamp(self) -> float:
        return time.time()

    # ------------------------------------------------------------------
    # Observability
    # ------------------------------------------------------------------

    @property
    def obs_port(self) -> Optional[int]:
        """Bound port of the /metrics + /healthz endpoint (resolves an
        ephemeral obs_port=0), or None when the endpoint is disabled."""
        return self._obs_server.port if self._obs_server else None

    def obs_health(self) -> dict:
        """Live scheduler health for /healthz: round/job/worker state,
        per-host breaker states, journal lag. Runs on the exporter's
        request thread with a BOUNDED lock acquire: the scheduler lock
        is legitimately held for tens of seconds across a dead worker's
        dispatch retry budget, and a health probe that blocks behind it
        would time out exactly when the cluster is degraded — the
        moment it exists to report. On contention it answers "busy"
        instead of hanging."""
        if not self._lock.acquire(timeout=2.0):
            return {"status": "busy",
                    "detail": "scheduler lock contended >2s (round "
                              "pipeline may be stalled on a worker "
                              "RPC); metrics remain live on /metrics"}
        try:
            payload = self._obs_health_locked()
        finally:
            self._lock.release()
        if self._durability is not None:
            payload["journal"] = {
                "last_seq": self._durability.last_seq,
                "lag_events": self._durability.pending_events,
            }
        if self._ha is not None:
            from .ha import read_lease
            lease = read_lease(self._config.state_dir)
            payload["ha"] = {
                # Advisory probe of a monotonic bool (False -> True
                # exactly once); a stale read self-corrects next scrape.
                "role": ("fenced" if self._ha_fenced  # swtpu-check: ignore[lock-discipline]
                         else "leader"),
                "epoch": self._ha.epoch,
                "lease_age_s": (
                    round(time.time() - float(lease.get("stamp", 0.0)), 3)
                    if lease else None),
            }
        return payload

    @requires_lock
    def _obs_health_locked(self) -> dict:
        breakers = {}
        for (addr, port), host in self._worker_hosts.items():
            breaker = getattr(host.get("client"), "breaker", None)
            if breaker is not None:
                breakers[f"{addr}:{port}"] = breaker.state
        worker_health = {
            f"{addr}:{port}": {"state": h.state,
                               "score": round(h.score, 4)}
            for (addr, port), h in self._host_health.items()}
        payload = {
            "round": self.rounds.num_completed_rounds,
            "active_jobs": len(self.acct.jobs),
            "completed_jobs": len(self._completed_jobs),
            "live_workers": len(self.workers.worker_ids),
            "dead_workers": len(self.workers.dead),
            "quarantined_workers": len(self.workers.quarantined),
            "worker_hosts": len(self._worker_hosts),
            "breakers": breakers,
            "worker_health": worker_health,
            "recovered": self._recovered,
            "uptime_s": round(time.time() - self._start_time, 3),
        }
        if self._whatif is not None:
            # Forecast quantiles + fork/rollout counters + the latest
            # tuned-knob record, on the same probe the operator already
            # watches.
            payload["whatif"] = self._whatif.status()
        return payload

    def add_job(self, job, timestamp=None):
        with self._cv:
            advisory = None
            if (self._whatif is not None
                    and self._whatif.cfg.admission == "gate"
                    and self.workers.worker_ids
                    # Gate TRACE admissions only: autoscaler-spawned
                    # serving replicas arrive through this same method
                    # from inside the locked round pipeline (a fork +
                    # rollouts per scale-up would be pure overhead and
                    # the verdict meaningless), and journal replay must
                    # not pollute the decision log with replay-time
                    # verdicts.
                    and not self._replaying
                    and "--replica_of" not in job.command):
                # Advisory Monte-Carlo admission: fork the PRE-admission
                # state here (the only lock-held cost), evaluate
                # with-vs-without on the background thread. The job is
                # admitted either way — physical deferral would mean
                # holding a real submitter's RPC hostage to K rollouts.
                import pickle as _pickle
                from ..whatif import fork as _fork
                advisory = (_fork.capture(self),
                            _pickle.dumps(job),
                            self.get_current_timestamp())
            job_id = super().add_job(job, timestamp)
            self._lease_update_requests[job_id] = []
            self._max_steps_consensus[job_id] = None
            if advisory is not None:
                self._whatif_work.put(("advise",) + advisory)
            self._cv.notify_all()
            return job_id

    @requires_lock
    def _remove_job(self, job_id: JobIdPair) -> None:
        super()._remove_job(job_id)
        # Drop per-job protocol state so a long-running scheduler does not
        # grow without bound (and a straggler RPC cannot resurrect it).
        for m in job_id.singletons():
            self._last_heartbeat.pop(m, None)
            self._ever_signaled.discard(m)
            self._lease_update_requests.pop(m, None)
            self._max_steps_consensus.pop(m, None)
            self._kill_rearm_counts.pop(m, None)
        self._failure_compensated.discard(job_id)
        # job_id is always a singleton here (to_remove members); keep it
        # as the receiver — overlaps_with requires a single-id receiver
        # and k[0] may be a packed pair.
        for key in [k for k in (set(self._dispatch_stamp)
                                | set(self._done_stamp))
                    if job_id.overlaps_with(k[0])]:
            self._dispatch_stamp.pop(key, None)
            self._done_stamp.pop(key, None)

    # ------------------------------------------------------------------
    # Durability (physical extensions)
    # ------------------------------------------------------------------

    @requires_lock
    def snapshot_state(self) -> dict:
        state = super().snapshot_state()
        # Host endpoints (not clients — those are rebuilt on restore) so
        # a restarted scheduler can re-adopt its workers without waiting
        # for daemons to re-register.
        state["worker_hosts"] = {
            key: dict(worker_type=host["worker_type"],
                      num_chips=host["num_chips"],
                      worker_ids=list(host["worker_ids"]))
            for key, host in self._worker_hosts.items()}
        return state

    @requires_lock
    def restore_state(self, state: dict) -> None:
        super().restore_state(state)
        for key, host in state.get("worker_hosts", {}).items():
            self._adopt_worker_host(key[0], int(key[1]),
                                    host["worker_type"],
                                    host["num_chips"],
                                    [int(i) for i in host["worker_ids"]])
        # Quarantine survives --resume: the chip-level marker rides the
        # snapshot (workers.quarantined) and the journal events; rebuild
        # the host-level bookkeeping from it. The release clock restarts
        # conservatively at recovery time — a restarted scheduler
        # re-observes a full backoff before trusting the host again.
        now = self.get_current_timestamp()
        for key, host in self._worker_hosts.items():
            ids = set(host["worker_ids"])
            # ANY quarantined chip marks the host: a chip that died
            # BEFORE the quarantine is in workers.dead but not in the
            # marker, and requiring the full id set would leave the
            # host without a release clock — quarantined forever.
            if ids & self.workers.quarantined:
                host["quarantined_at"] = now
                host.setdefault("quarantine_backoff_s",
                                self._health_cfg.quarantine_backoff_s)
                health = self._host_health.setdefault(
                    key, HostHealth(self._health_cfg))
                health.state = HEALTH_DEGRADED
                health.samples = max(health.samples,
                                     self._health_cfg.min_samples)
        self._obs.set_gauge(obs_names.QUARANTINED_CHIPS,
                            len(self.workers.quarantined))

    @requires_lock
    def _adopt_worker_host(self, addr: str, port: int, worker_type: str,
                           num_chips: int, worker_ids) -> None:
        """Rebuild the connection plumbing for a journaled worker host.
        The daemon may be long dead — last_seen is stamped `now`, so the
        liveness monitor gives it one timeout window to answer a probe
        before its chips are retired (and a later heal revives them)."""
        key = (addr, port)
        old = self._worker_hosts.get(key)
        if old is not None:
            self._close_host_client(old)
        client = self._new_worker_client(addr, port)
        now = self.get_current_timestamp()
        for worker_id in worker_ids:
            self._worker_connections[worker_id] = client
            if worker_id not in self.workers.dead:
                self.workers.last_seen[worker_id] = now
        self._worker_hosts[key] = dict(
            worker_type=worker_type, num_chips=num_chips,
            worker_ids=list(worker_ids), client=client, probe_failures=0)
        self._host_health.setdefault(key, HostHealth(self._health_cfg))

    def _replay_worker_host(self, data: dict) -> None:
        self._adopt_worker_host(data["addr"], int(data["port"]),
                                data["worker_type"],
                                int(data.get("num_chips", 1)),
                                [int(i) for i in data["worker_ids"]])

    @requires_lock
    def _requeue_inflight_after_recovery(self) -> None:
        """Conservative re-adoption of whatever was in flight at the
        crash: every assignment is dropped and its job requeued by the
        next allocation — WITHOUT charging the job a failure (the crash
        was the scheduler's fault, not the job's). Orphan trainers still
        holding pre-crash leases drain via the post-recovery gates in
        done_callback / _update_lease_callback."""
        requeued = [job_id for job_id in self.rounds.current_assignments
                    if any(m in self.acct.jobs
                           for m in job_id.singletons())]
        now = self.get_current_timestamp()
        for job_id in requeued:
            for m in job_id.singletons():
                int_id = m.integer_job_id()
                if int_id in self._job_timelines:
                    self._job_timelines[int_id].append(
                        f"t={now:.1f} RECOVERY_REQUEUE scheduler "
                        "restarted mid-round; lease abandoned")
        if requeued:
            self._obs.inc(obs_names.JOBS_REQUEUED_TOTAL,
                          amount=len(requeued), reason="recovery")
        self.rounds.abandon_in_flight()
        self._redispatch_assignments = collections.OrderedDict()
        self._running_jobs.clear()
        self._in_progress_updates.clear()
        self._iterator_log_buffers.clear()
        self._dispatch_stamp.clear()
        self._done_stamp.clear()
        self._failure_compensated.clear()
        self._last_heartbeat.clear()
        self._ever_signaled.clear()
        self._kill_rearm_counts.clear()
        for job_id in list(self._steps_run_in_current_lease):
            self._steps_run_in_current_lease[job_id] = 0
        for job_id in self.acct.jobs:
            self._lease_update_requests[job_id] = []
            self._max_steps_consensus[job_id] = None
        self._need_to_update_allocation = True
        if requeued:
            self.log.warning(
                "[Recovery] %d in-flight jobs requeued conservatively "
                "(no failure charged): %s", len(requeued), requeued)

    @requires_lock
    def _maybe_snapshot(self) -> None:
        """End-of-round compacting snapshot every
        snapshot_interval_rounds rounds. Must hold the lock."""
        interval = self._config.snapshot_interval_rounds
        if (self._durability is None or not interval
                or self.rounds.num_completed_rounds % interval != 0):
            return
        try:
            self._durability.snapshot({"state": self.snapshot_state()})
            self.log.info("snapshot written at round %d (journal "
                          "compacted)", self.rounds.num_completed_rounds)
        except Exception:  # noqa: BLE001 - durability must not kill rounds
            self.log.exception("snapshot failed at round %d",
                               self.rounds.num_completed_rounds)

    # ------------------------------------------------------------------
    # Control-plane HA (leader side)
    # ------------------------------------------------------------------

    @property
    def ha_fenced(self) -> bool:
        """Whether this incarnation was deposed by a promoted standby
        (drivers exit with a distinct status so chaos harnesses can
        tell a clean fence from a crash). Lock-free read of a monotonic
        bool: drivers poll it after run() returns."""
        return self._ha_fenced  # swtpu-check: ignore[lock-discipline]

    def _on_ha_fenced(self, successor_epoch: int) -> None:
        """A higher epoch exists: this process is no longer the leader.
        Runs on the HA renewal thread (or the dispatch path via
        fence_now). Stop writing the journal (the successor owns it),
        refuse further RPCs (serve_scheduler's fenced_check), and kick
        every waiter so the round loop can observe the flag and exit.
        Nothing is requeued here — the successor's recovery already
        requeued everything conservatively on ITS side; this side's
        only job is to stop acting."""
        with self._cv:
            self._ha_fenced = True
            if self._durability is not None:
                # Closing the writer makes any straggling append raise
                # (swallowed + logged by _emit_event): the zombie's
                # write window ends HERE, not at process exit.
                self._durability.close()
            self._cv.notify_all()
        self.log.warning(
            "scheduler FENCED: epoch %d superseded by %d; ceasing "
            "dispatch and journal writes",
            self._ha.epoch if self._ha else -1, successor_epoch)

    def _worker_epoch_source(self):
        """epoch_source for SchedulerToWorkerClient: the claimed epoch
        under HA, None (no metadata at all) otherwise."""
        if self._ha is None:
            return None
        return self._ha.epoch_value

    def _new_worker_client(self, addr: str, port: int):
        """Build a scheduler->worker client carrying this leader's
        epoch metadata (single construction chokepoint: registration,
        revival, and journal re-adoption must all fence identically)."""
        from ..runtime.clients import SchedulerToWorkerClient
        return SchedulerToWorkerClient(
            addr, port, epoch_source=self._worker_epoch_source())

    @staticmethod
    def _is_stale_epoch_error(error) -> bool:
        """A worker refused our leader epoch: we are fenced (a standby
        promoted while we were wedged), regardless of what the renewal
        thread has noticed yet."""
        if not isinstance(error, grpc.RpcError):
            return False
        try:
            code = error.code()
            details = error.details() or ""
        except Exception:  # noqa: BLE001 - non-standard RpcError stub
            return False
        return (code == grpc.StatusCode.FAILED_PRECONDITION
                and "stale leader epoch" in details)

    # ------------------------------------------------------------------
    # RPC callbacks
    # ------------------------------------------------------------------

    def _register_worker_rpc(self, worker_type, num_chips, ip_addr, port):
        """Register a worker host — idempotently. A daemon re-registering
        from an endpoint we already know (crash/restart, or a retry whose
        first response was lost) gets its ORIGINAL chip ids back, revived
        into capacity with a fresh channel, instead of ghost-duplicating
        the host's chips."""
        with self._cv:
            key = (ip_addr, port)
            host = self._worker_hosts.get(key)
            if host is not None:
                if (host["worker_type"] == worker_type
                        and host["num_chips"] == num_chips):
                    ids = self._revive_worker_host(key)
                    self._emit("worker_host", addr=ip_addr, port=port,
                               worker_type=worker_type,
                               num_chips=num_chips, worker_ids=list(ids))
                    return (ids, self._time_per_iteration)
                # Same endpoint, different shape: retire the old
                # incarnation and register fresh below.
                self.log.warning(
                    "re-registration from %s:%d changed shape (%s x%d -> "
                    "%s x%d); retiring old worker ids %s", ip_addr, port,
                    host["worker_type"], host["num_chips"], worker_type,
                    num_chips, host["worker_ids"])
                self._retire_worker_host(key)
                self._close_host_client(host)
                del self._worker_hosts[key]
            client = self._new_worker_client(ip_addr, port)
            worker_ids, round_duration = self.register_worker(
                worker_type, num_chips)
            now = self.get_current_timestamp()
            for worker_id in worker_ids:
                self._worker_connections[worker_id] = client
                self.workers.last_seen[worker_id] = now
            self._worker_hosts[key] = dict(
                worker_type=worker_type, num_chips=num_chips,
                worker_ids=list(worker_ids), client=client,
                probe_failures=0)
            self._host_health[key] = HostHealth(self._health_cfg)
            self._emit("worker_host", addr=ip_addr, port=port,
                       worker_type=worker_type, num_chips=num_chips,
                       worker_ids=list(worker_ids))
            self._cv.notify_all()
        return worker_ids, round_duration

    @requires_lock
    def _revive_worker_host(self, key) -> List[int]:
        """Re-admit a known host (rejoin after death, daemon restart, or a
        duplicate register retry). Must hold the lock."""
        host = self._worker_hosts[key]
        ids = host["worker_ids"]
        if any(i in self.workers.quarantined for i in ids):
            # Re-registration of a quarantined host: a restarted daemon
            # is operator intervention — clear the quarantine (journaled
            # so replay agrees) and let the probation scoring below
            # re-earn trust.
            self._clear_quarantine_marker(key, reason="reregistered")
        if any(i not in self.workers.dead for i in ids):
            # Re-register from a host we still considered live: the
            # daemon restarted (losing its dispatch state), so anything
            # in flight there is gone — fail it in-round first.
            self._retire_worker_host(key)
        self._close_host_client(host)
        client = self._new_worker_client(*key)
        self._obs.inc(obs_names.WORKER_REVIVALS_TOTAL)
        # A rejoining daemon starts over on probation: suspect until it
        # posts recover_consecutive good observations.
        health = self._host_health.setdefault(key,
                                              HostHealth(self._health_cfg))
        health.reset_probation()
        self.revive_workers(ids, host["worker_type"])
        now = self.get_current_timestamp()
        for worker_id in ids:
            self._worker_connections[worker_id] = client
            self.workers.last_seen[worker_id] = now
        host["client"] = client
        host["probe_failures"] = 0
        self._cv.notify_all()
        return list(ids)

    @staticmethod
    def _close_host_client(host) -> None:
        """Close a replaced client's channel — on preemptible capacity
        worker churn is routine, and each unclosed channel leaks sockets
        plus reconnect polling to a dead endpoint in the long-lived
        scheduler process."""
        old = host.get("client")
        if old is not None and hasattr(old, "close"):
            try:
                old.close()
            except Exception as e:  # noqa: BLE001 - best-effort cleanup,
                # but say so: a close that reliably fails here would
                # leak a channel per churn event, invisibly.
                logger.debug("closing replaced worker channel failed: %s", e)

    # ------------------------------------------------------------------
    # Worker liveness
    # ------------------------------------------------------------------

    def _liveness_loop(self):
        """Monitor thread: piggybacked heartbeats cover the common case;
        a host silent past worker_timeout_s gets an active Ping with a
        short deadline, and worker_probe_failures consecutive misses
        retire it."""
        interval = self._config.heartbeat_interval_s
        while not self._done_event.wait(interval):
            try:
                self._probe_workers()
            except Exception:  # noqa: BLE001 - monitor must never die
                self.log.exception("liveness monitor iteration failed")

    def _probe_workers(self):
        now = self.get_current_timestamp()
        with self._lock:
            stale, dead, quarantined = [], [], []
            job_stamps = self._inflight_job_stamp_by_host()
            for key, host in self._worker_hosts.items():
                live = [i for i in host["worker_ids"]
                        if i not in self.workers.dead]
                if not live:
                    if any(i in self.workers.quarantined
                           for i in host["worker_ids"]):
                        # Quarantined host: alive but distrusted. Keep
                        # probing — death during quarantine converts to
                        # a plain retirement, and a completed backoff
                        # releases it on probation.
                        quarantined.append((key, host))
                        continue
                    # Fully-retired host: keep probing. A transient
                    # network partition retires a healthy daemon that
                    # will never re-register (it registers once, at
                    # startup) — the heal must restore its capacity.
                    dead.append((key, host))
                    continue
                last = max(self.workers.last_seen.get(i, 0.0) for i in live)
                age = max(now - last, 0.0)
                self._obs.set_gauge(obs_names.WORKER_HEARTBEAT_AGE_SECONDS,
                                    age, host=f"{key[0]}:{key[1]}")
                self._set_breaker_gauge(key, host)
                # Health feed (asymmetric: silence is only evidence when
                # the host SHOULD be talking): a host with in-round work
                # stamps a JOB heartbeat on every InitJob / lease
                # renewal / Done — and a successful Ping cannot refresh
                # those stamps, so a job-heartbeat age beyond a round +
                # buffer is a gray signal even while Ping keeps
                # answering (the wedged-mid-round host). Idle hosts feed
                # nothing.
                signal_window = self._time_per_iteration + (
                    self._config.job_completion_buffer_s
                    if self._config.job_completion_buffer_s is not None
                    else JOB_COMPLETION_BUFFER_TIME)
                job_stamp = job_stamps.get(key)
                if job_stamp is not None:
                    job_age = max(now - job_stamp, 0.0)
                    if job_age > signal_window:
                        # Graded: 0.5 at one signal window (already
                        # under the suspect threshold, so suspicion
                        # accumulates), falling to 0.0 at two windows.
                        self._health_observe(
                            key,
                            max(0.0, 1.0 - 0.5 * job_age / signal_window),
                            reason="job-heartbeat-age")
                if now - last >= self._config.worker_timeout_s:
                    stale.append((key, host))
        for key, host in stale + dead + quarantined:
            retired = (key, host) in dead
            in_quarantine = (key, host) in quarantined
            try:
                # Probe outside the lock: the deadline bounds it, but the
                # round pipeline must not stall behind a probe. The
                # client's circuit breaker rate-limits probes to a
                # retired host to one half-open attempt per reset window.
                host["client"].ping(
                    deadline_s=self._config.worker_probe_deadline_s)
            except WORKER_RPC_ERRORS:
                if retired:
                    continue  # still dead
                with self._cv:
                    if host is not self._worker_hosts.get(key):
                        continue  # re-registered while we probed
                    host["probe_failures"] += 1
                    self.log.warning(
                        "worker %s:%d missed probe %d/%d", key[0], key[1],
                        host["probe_failures"],
                        self._config.worker_probe_failures)
                    if (host["probe_failures"]
                            >= self._config.worker_probe_failures):
                        if in_quarantine:
                            # The quarantined daemon stopped answering:
                            # gray failure turned black. Convert to a
                            # plain retirement (capacity is already out;
                            # only the marker and lifecycle change).
                            self._clear_quarantine_marker(key,
                                                          reason="dead")
                        else:
                            self._retire_worker_host(key)
            else:
                with self._cv:
                    if host is not self._worker_hosts.get(key):
                        continue
                    if retired:
                        self.log.warning(
                            "retired worker %s:%d answered a probe "
                            "(partition healed); reviving", key[0], key[1])
                        self._revive_worker_host(key)
                        continue
                    host["probe_failures"] = 0
                    if in_quarantine:
                        self._maybe_release_quarantine(key)
                        continue
                    stamp = self.get_current_timestamp()
                    for i in host["worker_ids"]:
                        if i not in self.workers.dead:
                            self.workers.last_seen[i] = stamp

    @requires_lock
    def _retire_worker_host(self, key) -> None:
        """Declare a host dead: pull its chips from capacity, fail its
        in-round micro-tasks (requeue), and prune it from the next
        round's plan. Must hold the lock; notifies round waiters."""
        host = self._worker_hosts.get(key)
        if host is None:
            return
        if any(i in self.workers.quarantined for i in host["worker_ids"]):
            # Retiring a quarantined host (shape-change re-registration,
            # operator action): it is dead now, not merely distrusted.
            self._clear_quarantine_marker(key, reason="dead")
        dead_ids = [i for i in host["worker_ids"]
                    if i not in self.workers.dead]
        if not dead_ids:
            return
        self.log.warning("worker %s:%d presumed dead; retiring chips %s",
                         key[0], key[1], dead_ids)
        self._obs.inc(obs_names.WORKER_RETIREMENTS_TOTAL)
        # Drop the host's per-host gauge series AND its classifier
        # entry: a frozen last-known heartbeat age / breaker state /
        # health score would keep a dead host looking live on /metrics
        # and /healthz forever (revival recreates the entry fresh).
        self._drop_host_series(key, health_too=True)
        self._host_health.pop(key, None)
        self.deregister_workers(dead_ids)
        for worker_id in dead_ids:
            self._remove_available_worker(worker_id)
        self._fail_jobs_on_dead_workers(set(dead_ids))
        self._cv.notify_all()

    @requires_lock
    def _retire_worker_by_id(self, worker_id: int) -> None:
        """Retire the host that owns `worker_id` (dispatch-failure path).
        Must hold the lock."""
        for key, host in self._worker_hosts.items():
            if worker_id in host["worker_ids"]:
                self._retire_worker_host(key)
                return
        # No host record (unit tests wire connections directly): still
        # pull the single chip and fail its jobs.
        self.deregister_workers([worker_id])
        self._remove_available_worker(worker_id)
        self._fail_jobs_on_dead_workers({worker_id})
        self._cv.notify_all()

    @requires_lock
    def _fail_jobs_on_dead_workers(self, dead_ids: set) -> None:
        """Mark every micro-task scheduled on a dead chip failed-in-round
        (synthesized zero-step done, so `_end_round` completes and the
        job is requeued by the next allocation), and drop dead chips
        from the next round's plan. Must hold the lock."""
        if self.rounds.next_assignments is not None:
            for job_id in [j for j, w in self.rounds.next_assignments.items()
                           if set(w) & dead_ids]:
                planned_ids = self.rounds.next_assignments[job_id]
                del self.rounds.next_assignments[job_id]
                self._redispatch_assignments.pop(job_id, None)
                self.rounds.extended_leases.discard(job_id)
                # An early-dispatched gang may already be LAUNCHED on the
                # surviving hosts; once pruned from the assignment maps
                # no watchdog covers those ranks, and orphans blocked in
                # gang rendezvous would hold their chips and wedge every
                # later dispatch queued behind them. Kill them now.
                for worker_id in planned_ids:
                    if worker_id in dead_ids or worker_id in self.workers.dead:
                        continue
                    client = self._worker_connections.get(worker_id)
                    if client is None:
                        continue
                    for m in job_id.singletons():
                        try:
                            # One short attempt: this best-effort kill
                            # runs under the scheduler lock, and a full
                            # retry budget here would stall the round
                            # pipeline behind an unresponsive host.
                            client.kill_job(
                                m.integer_job_id(),
                                deadline_s=self._config
                                .worker_probe_deadline_s)
                        except WORKER_RPC_ERRORS:
                            break  # that host is failing too; probe reaps it
        for job_id, worker_ids in list(self.rounds.current_assignments.items()):
            dead_members = [w for w in worker_ids if w in dead_ids]
            if not dead_members or job_id in self.rounds.completed_in_round:
                continue
            if not any(m in self.acct.jobs for m in job_id.singletons()):
                continue
            reported = {u[0] for u in self._in_progress_updates.get(job_id, [])}
            missing = [w for w in dead_members if w not in reported]
            if not missing:
                continue
            self.log.warning(
                "[Worker failed] job %s lost chips %s mid-round; marking "
                "failed-in-round and requeuing", job_id, missing)
            self._obs.inc(obs_names.JOBS_REQUEUED_TOTAL,
                          reason="worker_dead")
            # The crash is the WORKER's fault: pre-decrement the job's
            # failure counter so the synthesized zero-step micro-task's
            # +1 nets to zero and worker churn can never drop an
            # innocent job via MAX_FAILED_ATTEMPTS. Pre-decrement (not
            # post-restore): the increment may land NOW (sf=1 aggregate
            # completes inside this synthesis) or LATER (a gang's
            # surviving members report afterwards), and a post-restore
            # would miss the late case — and could even miss the job
            # entirely if the +1 pushed it over the threshold and
            # removed it before any restore ran. The decrement may go
            # transiently negative (count 0 -> -1): the pending +1
            # brings it back to 0, and the only other readers are the
            # >= MAX_FAILED_ATTEMPTS check and the success-path reset
            # to 0, both safe against a negative. Compensated at most
            # ONCE per job per failed round (_failure_compensated,
            # cleared on dispatch): a gang spanning two hosts that die
            # in separate retirement events still triggers only one +1
            # when its aggregate finally completes. Pairs are skipped —
            # the failure path never increments pair keys.
            if (not job_id.is_pair()
                    and job_id in self.acct.failures
                    and job_id not in self._failure_compensated):
                self._failure_compensated.add(job_id)
                self.acct.failures[job_id] -= 1
                # The synthesized zero-step done below journals as a
                # failed micro-task (+1 on replay); journal the
                # compensation too or a recovered scheduler would charge
                # the job for its worker's crash.
                self._emit("failure_comp",
                           int_id=job_id.integer_job_id())
            zeros = [0 for _ in job_id.singletons()]
            for worker_id in missing:
                self.done_callback(job_id, worker_id, zeros, zeros)
            # done_callback returns chips to the available pool; dead
            # ones must not go back.
            for worker_id in missing:
                self._remove_available_worker(worker_id)
            for m in job_id.singletons():
                if m.integer_job_id() in self._job_timelines:
                    self._job_timelines[m.integer_job_id()].append(
                        f"t={self.get_current_timestamp():.1f} "
                        f"WORKER_FAILED chips={missing} requeued")

    # ------------------------------------------------------------------
    # Gray-failure health scoring + worker quarantine
    # ------------------------------------------------------------------

    @requires_lock
    def _inflight_job_stamp_by_host(self) -> dict:
        """Host key -> newest JOB-level heartbeat stamp (InitJob /
        UpdateLease / Done / dispatch time, self._last_heartbeat) among
        the micro-tasks currently in flight on that host's chips. A
        successful Ping refreshes workers.last_seen but can NEVER
        refresh these, so their age is the honest 'working but silent'
        gray signal — a host wedged mid-round while still answering
        probes goes stale here and nowhere else. Must hold the lock."""
        worker_to_key = {w: key
                         for key, host in self._worker_hosts.items()
                         for w in host["worker_ids"]}
        out: dict = {}
        for job_id, ids in self.rounds.current_assignments.items():
            if job_id in self.rounds.completed_in_round:
                continue
            stamps = [self._last_heartbeat[m]
                      for m in job_id.singletons()
                      if m in self._last_heartbeat]
            if not stamps:
                continue
            newest = max(stamps)
            for w in ids:
                key = worker_to_key.get(w)
                if key is not None:
                    out[key] = max(out.get(key, 0.0), newest)
        return out

    @requires_lock
    def _host_key_for_worker(self, worker_id: int):
        for key, host in self._worker_hosts.items():
            if worker_id in host["worker_ids"]:
                return key
        return None

    def _set_breaker_gauge(self, key, host) -> None:
        breaker = getattr(host.get("client"), "breaker", None)
        if breaker is not None:
            value = {"closed": 0.0, "half-open": 1.0,
                     "open": 2.0}.get(breaker.state, 0.0)
            self._obs.set_gauge(obs_names.WORKER_BREAKER_STATE, value,
                                host=f"{key[0]}:{key[1]}")

    def _drop_host_series(self, key, health_too: bool = False) -> None:
        """Remove a host's per-host gauge series from /metrics: retired
        and quarantined hosts must stop exposing their last-known
        heartbeat age / breaker state instead of reporting it forever.
        The health score survives quarantine (`health_too=False`) — it
        is the quarantined host's recovery signal."""
        host_label = f"{key[0]}:{key[1]}"
        self._obs.registry.remove_series(
            obs_names.WORKER_HEARTBEAT_AGE_SECONDS, host=host_label)
        self._obs.registry.remove_series(
            obs_names.WORKER_BREAKER_STATE, host=host_label)
        if health_too:
            self._obs.registry.remove_series(
                obs_names.WORKER_HEALTH_SCORE, host=host_label)

    @requires_lock
    def _health_observe(self, key, sample: float, reason: str) -> None:
        """Feed one 0..1 sample into a host's health classifier and act
        on the verdict: a transition to `degraded` quarantines the
        host. Must hold the lock."""
        if not self._health_enabled:
            return
        health = self._host_health.get(key)
        if health is None:
            return
        transition = health.observe(sample)
        self._obs.set_gauge(obs_names.WORKER_HEALTH_SCORE, health.score,
                            host=f"{key[0]}:{key[1]}")
        if transition is None:
            return
        self._obs.inc(obs_names.WORKER_HEALTH_TRANSITIONS_TOTAL,
                      to=transition)
        self.log.warning(
            "worker %s:%d health -> %s (score %.3f after %s sample %.3f)",
            key[0], key[1], transition, health.score, reason, sample)
        if transition == HEALTH_DEGRADED:
            self._quarantine_worker_host(key)

    @requires_lock
    def _health_note_rate(self, worker_id: int, job_id: JobIdPair,
                          steps: int, exec_time: float) -> None:
        """Score one completed micro-task's observed steps/s against the
        fleet-reference rate for the same (job_type, scale_factor,
        worker_type). The reference is a decayed max across hosts, so a
        straggler is measured against its healthy peers (and against
        its own past self on a one-host cluster), not against the EMA
        table it is actively dragging down. Must hold the lock."""
        if not self._health_enabled or job_id.is_pair():
            return
        if steps <= 0 or exec_time <= 0:
            return  # failure signal, not a rate measurement
        job = self.acct.jobs.get(job_id)
        if job is None or worker_id not in self.workers.id_to_type:
            return
        key = self._host_key_for_worker(worker_id)
        if key is None:
            return
        rate = steps / exec_time
        ref_key = (job.job_type, job.scale_factor,
                   self.workers.id_to_type[worker_id])
        ref = self._fleet_rate.get(ref_key)
        if ref is None or ref <= 0:
            self._fleet_rate[ref_key] = rate
            self._health_observe(key, 1.0, reason="throughput")
            return
        sample = min(rate / ref, 1.0)
        self._fleet_rate[ref_key] = max(
            rate, ref * self._health_cfg.rate_ref_decay)
        self._health_observe(key, sample, reason="throughput")

    @requires_lock
    def _health_note_dispatch(self, worker_id: int, latency_s: float) -> None:
        """Dispatch-latency health feed: fast RunJob round trips carry
        no signal (feed nothing); one inside striking distance of the
        reference budget is interconnect/daemon trouble even when it
        succeeds. Must hold the lock."""
        if not self._health_enabled:
            return
        ref = self._health_cfg.dispatch_latency_ref_s
        if ref <= 0 or latency_s < 0.1 * ref:
            return
        key = self._host_key_for_worker(worker_id)
        if key is not None:
            self._health_observe(
                key, max(0.0, 1.0 - latency_s / ref),
                reason="dispatch-latency")

    @requires_lock
    def _quarantine_worker_host(self, key) -> None:
        """Quarantine a degraded-but-alive host: pull its chips from
        assignable capacity through the PR 1 deregister/requeue
        machinery (in-round micro-tasks synthesized failed + requeued
        with NO failure charge), kill the straggling processes through
        the still-reachable daemon, and start the probed release
        backoff. Journaled, so quarantine survives --resume. Must hold
        the lock."""
        host = self._worker_hosts.get(key)
        if host is None:
            return
        ids = [i for i in host["worker_ids"]
               if i not in self.workers.dead]
        if not ids:
            return
        self.log.warning(
            "worker %s:%d QUARANTINED (gray failure): chips %s leave "
            "assignable capacity; daemon stays probed for recovery",
            key[0], key[1], ids)
        self._obs.inc(obs_names.QUARANTINE_EVENTS_TOTAL,
                      action="quarantine")
        # The straggler's in-flight processes burn the chip and would
        # report a late Done (rejected by the dispatch stamps, but why
        # wait): kill them through the daemon, which — unlike a dead
        # host's — is reachable. Best-effort short deadline: the lock
        # is held.
        victims = []
        for job_id, worker_ids in list(
                self.rounds.current_assignments.items()):
            if (set(worker_ids) & set(ids)
                    and job_id not in self.rounds.completed_in_round):
                victims.extend(m.integer_job_id()
                               for m in job_id.singletons()
                               if m in self.acct.jobs)
        for int_id in victims:
            try:
                host["client"].kill_job(
                    int_id,
                    deadline_s=self._config.worker_probe_deadline_s)
            except WORKER_RPC_ERRORS:
                break  # daemon unreachable after all; probes decide
        self.workers.quarantined.update(ids)
        self.deregister_workers(ids)
        for worker_id in ids:
            self._remove_available_worker(worker_id)
        self._fail_jobs_on_dead_workers(set(ids))
        host["quarantined_at"] = self.get_current_timestamp()
        backoff = host.get("quarantine_backoff_s")
        host["quarantine_backoff_s"] = (
            self._health_cfg.quarantine_backoff_s if backoff is None
            else min(backoff * 2.0,
                     self._health_cfg.quarantine_backoff_max_s))
        host["probe_failures"] = 0
        self._drop_host_series(key)  # health score stays live
        self._obs.set_gauge(obs_names.QUARANTINED_CHIPS,
                            len(self.workers.quarantined))
        self._emit("worker_quarantined", addr=key[0], port=key[1],
                   worker_type=host["worker_type"],
                   worker_ids=list(ids),
                   ts=self.get_current_timestamp())
        self._cv.notify_all()

    @requires_lock
    def _maybe_release_quarantine(self, key) -> None:
        """A quarantined host answered a probe: release it on probation
        once its backoff has elapsed. A ping proves liveness, not
        compute speed — so the released host comes back `suspect`
        (serving keeps avoiding it) and must re-earn `healthy` through
        real observed throughput; a still-slow host is re-quarantined
        by the same classifier with a doubled backoff. Must hold the
        lock."""
        host = self._worker_hosts.get(key)
        if host is None or "quarantined_at" not in host:
            return
        now = self.get_current_timestamp()
        backoff = host.get("quarantine_backoff_s",
                           self._health_cfg.quarantine_backoff_s)
        if now - host["quarantined_at"] < backoff:
            return
        ids = [i for i in host["worker_ids"]
               if i in self.workers.quarantined]
        if not ids:
            return
        self.log.warning(
            "worker %s:%d released from quarantine on probation after "
            "%.0fs (suspect until throughput recovers)", key[0], key[1],
            now - host["quarantined_at"])
        self._obs.inc(obs_names.QUARANTINE_EVENTS_TOTAL, action="release")
        del host["quarantined_at"]
        health = self._host_health.setdefault(key,
                                              HostHealth(self._health_cfg))
        health.reset_probation()
        self._obs.inc(obs_names.WORKER_HEALTH_TRANSITIONS_TOTAL,
                      to=health.state)
        # revive_workers clears the quarantined marker and restores
        # capacity; the explicit event keeps replay (and the journal-
        # coverage invariant) in step with the live transition.
        self.revive_workers(ids, host["worker_type"])
        now_ts = self.get_current_timestamp()
        for worker_id in ids:
            self.workers.last_seen[worker_id] = now_ts
        self._obs.set_gauge(obs_names.QUARANTINED_CHIPS,
                            len(self.workers.quarantined))
        self._emit("worker_unquarantined", addr=key[0], port=key[1],
                   worker_type=host["worker_type"],
                   worker_ids=list(ids), reason="released", ts=now_ts)
        self._cv.notify_all()

    @requires_lock
    def _clear_quarantine_marker(self, key, reason: str) -> None:
        """Drop a host's quarantine marker WITHOUT restoring capacity:
        the host died in quarantine (or was retired / re-registered).
        The chips stay in workers.dead; only the lifecycle bookkeeping
        changes. Must hold the lock."""
        host = self._worker_hosts.get(key)
        if host is None:
            return
        ids = [i for i in host["worker_ids"]
               if i in self.workers.quarantined]
        if not ids:
            return
        self.log.warning("worker %s:%d leaves quarantine (%s); chips %s "
                         "remain out of capacity", key[0], key[1], reason,
                         ids)
        self._obs.inc(obs_names.QUARANTINE_EVENTS_TOTAL, action=reason)
        if reason == "dead":
            # Gray turned black: this IS a retirement (capacity left at
            # quarantine time, so _retire_worker_host's early return
            # would skip both of these) — count it, and drop the health
            # series a quarantined host keeps as its recovery signal,
            # or the dead host's last score is exposed forever.
            self._obs.inc(obs_names.WORKER_RETIREMENTS_TOTAL)
            self._drop_host_series(key, health_too=True)
            self._host_health.pop(key, None)
        for worker_id in ids:
            self.workers.quarantined.discard(worker_id)
        host.pop("quarantined_at", None)
        self._obs.set_gauge(obs_names.QUARANTINED_CHIPS,
                            len(self.workers.quarantined))
        self._emit("worker_unquarantined", addr=key[0], port=key[1],
                   worker_type=host["worker_type"],
                   worker_ids=list(ids), reason=reason,
                   ts=self.get_current_timestamp())

    @requires_lock
    def _replay_worker_quarantined(self, data: dict) -> None:
        """Replay: re-mark the chips quarantined (capacity was already
        removed by the paired workers_retired event) and restart the
        release clock conservatively at recovery time. Runs under the
        recovery lock."""
        ids = [int(i) for i in data["worker_ids"]]
        self.workers.quarantined.update(
            i for i in ids if i in self.workers.dead)
        key = (data["addr"], int(data["port"]))
        host = self._worker_hosts.get(key)
        if host is not None:
            host["quarantined_at"] = self.get_current_timestamp()
            host.setdefault("quarantine_backoff_s",
                            self._health_cfg.quarantine_backoff_s)
            health = self._host_health.setdefault(
                key, HostHealth(self._health_cfg))
            health.state = HEALTH_DEGRADED
            health.samples = max(health.samples,
                                 self._health_cfg.min_samples)

    @requires_lock
    def _replay_worker_unquarantined(self, data: dict) -> None:
        """Replay: drop the marker. Capacity (when the release restored
        it) is replayed by the paired workers_revived event, which
        already clears the marker too — this handler covers the
        marker-only paths (death in quarantine, re-registration). Runs
        under the recovery lock."""
        for i in data["worker_ids"]:
            self.workers.quarantined.discard(int(i))
        host = self._worker_hosts.get((data["addr"], int(data["port"])))
        if host is not None:
            host.pop("quarantined_at", None)

    def suspect_worker_ids(self) -> frozenset:
        """Chips on hosts currently classified suspect or degraded —
        the serving tier's replica placement avoids these (a latency-SLO
        replica pinned to a straggler violates its SLO every round the
        training tier would merely run slow)."""
        with self._lock:
            if not self._health_enabled:
                return frozenset()
            out = set()
            for key, health in self._host_health.items():
                if health.state != HEALTH_HEALTHY:
                    host = self._worker_hosts.get(key)
                    if host is not None:
                        out.update(host["worker_ids"])
            return frozenset(out)

    def _init_job_callback(self, job_id: JobIdPair):
        """Grant the initial lease (reference: scheduler.py:3880-4048)."""
        with self._cv:
            if job_id not in self.acct.jobs:
                return (0, 0.0, 0.0)
            if self._is_recovery_orphan(job_id):
                # Trainer spawned by the pre-crash incarnation coming up
                # after the restart: zero lease — its round was requeued
                # at recovery and a fresh dispatch will respawn it.
                self.log.warning("zero lease for pre-restart init of job "
                                 "%s (round requeued at recovery)", job_id)
                return (0, 0.0, 0.0)
            # If the job was dispatched early for the *next* round, wait for
            # its current-round run (or a colocated partner) to finish.
            while True:
                next_combo = None
                if self.rounds.next_assignments is not None:
                    for combo in self.rounds.next_assignments:
                        if job_id.overlaps_with(combo):
                            next_combo = combo
                            break
                blocked = False
                if next_combo is not None:
                    for combo in self.rounds.current_assignments:
                        for m in next_combo.singletons():
                            if (m.overlaps_with(combo) and combo not in
                                    self.rounds.completed_in_round):
                                blocked = True
                if blocked:
                    self._cv.wait()
                else:
                    break

            self.acct.latest_timestamps[job_id] = self.get_current_timestamp()
            for m in job_id.singletons():
                self._running_jobs.add(m)
                self._last_heartbeat[m] = self.get_current_timestamp()
                self._ever_signaled.add(m)

            job = self.acct.jobs[job_id]
            remaining = int(math.ceil(
                self._get_remaining_steps(job_id) / job.scale_factor))
            now = self.get_current_timestamp()
            round_end = self._current_round_start_time + self._time_per_iteration
            time_left = max(round_end - now, 0.0)

            def grant(steps, duration, extra):
                # Audit record (replay is a no-op; lease terms are
                # re-derived on redispatch after a restart), so it rides
                # the non-fsync path — an Init RPC must not pay a disk
                # barrier under the scheduler lock for telemetry.
                self._emit_audit("lease_granted",
                                 key=encode_job_key(job_id),
                                 steps=steps, duration=duration, ts=now)
                return (steps, duration, extra)

            if self.rounds.next_assignments is not None and next_combo is not None:
                # Early dispatch for the next round: full round + leftover.
                return grant(remaining, self._time_per_iteration, time_left)
            if time_left > 0:
                # Floor clamped to the round duration: with short rounds
                # (< INIT_LEASE_FLOOR_S) an unclamped floor would overrun
                # every round and delay the next dispatch on this chip.
                floor = min(INIT_LEASE_FLOOR_S, self._time_per_iteration)
                return grant(remaining, max(time_left, floor), 0.0)
            # Init in the gap between rounds.
            return grant(remaining,
                         self._time_per_iteration - EARLY_INIT_THRESHOLD,
                         time_left)

    def _update_lease_callback(self, job_id: JobIdPair, worker_id: int,
                               steps: int, duration: float, max_steps: int,
                               max_duration: float, measured_reports=None):
        """Renew a lease (reference: scheduler.py:4050-4180).

        `measured_reports` (serving replicas only): sketch-delta wire
        lines piggybacked on the renewal heartbeat — a sticky replica's
        extended lease means Done only fires at drain, so renewals are
        its per-round measured-telemetry channel. Ingested before any
        early return below: the telemetry was measured regardless of
        what this renewal decides."""
        with self._lock:
            if (measured_reports
                    and self._serving_tier is not None
                    and job_id in self._serving_job_ids):
                from ..serving import measured as measured_mod
                for delta in measured_mod.find_reports(measured_reports):
                    self._serving_tier.ingest_measured(job_id, delta)
            if job_id not in self.acct.jobs:
                return (0, 0.0, 0.0, 0.0)
            if worker_id in self.workers.dead:
                # Orphaned trainer: its daemon's host was retired and the
                # job requeued (possibly already re-running elsewhere),
                # but the training process outlived the daemon (its own
                # session) and cannot be killed through the dead daemon.
                # Grant a zero lease so it checkpoints and exits instead
                # of racing the redispatched copy — and keep it out of
                # the gang consensus slots below.
                self.log.warning("expiring lease of orphaned job %s on "
                                 "dead worker %d", job_id, worker_id)
                return (0, 0.0, 0.0, 0.0)
            if self._is_recovery_orphan(job_id, worker_id):
                # Pre-crash trainer still holding a lease this restarted
                # scheduler never granted: expire it so the process
                # checkpoints and exits instead of racing the requeued
                # copy for the checkpoint file.
                self.log.warning("expiring pre-restart lease of job %s "
                                 "(worker %d); its round was requeued at "
                                 "recovery", job_id, worker_id)
                return (0, 0.0, 0.0, 0.0)
            job = self.acct.jobs[job_id]
            run_time_so_far = int(
                sum(self.acct.run_time_per_worker[job_id].values())
                / job.scale_factor)
            deadline = int(job.duration * DEADLINE_SLACK)
            self._lease_update_requests.setdefault(job_id, [])
            update_id = len(self._lease_update_requests[job_id])
            self._lease_update_requests[job_id].append(
                (steps, duration, max_steps, max_duration))
            self._last_heartbeat[job_id] = self.get_current_timestamp()
            self._ever_signaled.add(job_id)
            # Piggybacked worker heartbeat: the renewal proves the chip's
            # host is alive (dead ids excluded above).
            if worker_id in self.workers.id_to_type:
                self.workers.last_seen[worker_id] = (
                    self.get_current_timestamp())

            scale_factor = job.scale_factor
            remaining = int(math.ceil(
                self._get_remaining_steps(job_id) / scale_factor))
            now = self.get_current_timestamp()
            round_end = self._current_round_start_time + self._time_per_iteration
            time_left = max(0.0, round_end - now)

            # Track in-lease progress so the planner sees fresh epochs even
            # under extended leases.
            self._steps_run_in_current_lease[job_id] = steps * scale_factor

        if steps == 0 or duration == 0:
            return (remaining, time_left, run_time_so_far, deadline)

        with self._lock:
            for combo in self.rounds.extended_leases:
                if job_id.overlaps_with(combo):
                    extended = duration + time_left + self._time_per_iteration
                    return (max_steps, extended, run_time_so_far, deadline)

        if scale_factor == 1:
            return (max_steps, duration + time_left, run_time_so_far, deadline)

        # Multi-chip gang: the first renewer computes the shared step budget;
        # the rest adopt it (first-requester-computes consensus).
        if update_id == 0:
            with self._lock:
                throughput = steps / duration
                self._max_steps_consensus[job_id] = min(
                    remaining, steps + int(time_left * throughput))
                return (self._max_steps_consensus[job_id], INFINITY,
                        run_time_so_far, deadline)
        while True:
            with self._lock:
                consensus = self._max_steps_consensus.get(job_id)
            if consensus is not None:
                return (consensus, INFINITY, run_time_so_far, deadline)
            time.sleep(1)

    def _update_resource_requirement_callback(self, job_id: JobIdPair,
                                              worker_id: int, big_bs: bool,
                                              small_bs: bool):
        with self._cv:
            if job_id not in self._bs_flags:
                return
            if big_bs:
                self._bs_flags[job_id]["big_bs"] = True
            else:
                self._bs_flags[job_id]["small_bs"] = True
            self._emit("bs_flag", int_id=job_id.integer_job_id(),
                       big=bool(big_bs), small=not big_bs)
            self._cv.notify_all()

    @requires_lock
    def _is_duplicate_done(self, job_id: JobIdPair, worker_id: int) -> bool:
        """True when this (job, worker) already had a report accepted for
        its latest dispatch (see _dispatch_stamp)."""
        dispatched = self._dispatch_stamp.get((job_id, worker_id))
        accepted = self._done_stamp.get((job_id, worker_id))
        return (dispatched is not None and accepted is not None
                and accepted == dispatched)

    @requires_lock
    def _job_assigned(self, job_id: JobIdPair,
                      worker_id: Optional[int] = None) -> bool:
        """Whether a current/next/redispatch assignment covers job_id —
        on worker_id's chip specifically when given, on any worker
        otherwise. Must hold the lock."""
        maps = [self.rounds.current_assignments,
                self._redispatch_assignments]
        if self.rounds.next_assignments is not None:
            maps.append(self.rounds.next_assignments)
        return any(job_id.overlaps_with(combo)
                   and (worker_id is None or worker_id in ids)
                   for m in maps for combo, ids in m.items())

    @requires_lock
    def _is_recovery_orphan(self, job_id: JobIdPair,
                            worker_id: Optional[int] = None) -> bool:
        """Whether an Init/UpdateLease should be treated as coming from
        a pre-crash orphan trainer and given a zero lease.

        With `worker_id` (lease renewals), the job must be assigned to
        THAT worker: after the requeued job is redispatched elsewhere,
        the pre-crash copy on its old (live) worker must still be
        expired, or two copies train concurrently racing the checkpoint
        file. Init has no worker identity, so it falls back to the
        job-level check.

        Time-bounded: pre-crash trainers identify themselves within one
        startup window (Init) or one lease renewal (UpdateLease) of the
        restart. Past that window the gate stands down and the normal
        (pre-durability) semantics resume — a permanently armed gate
        would also zero-lease THIS incarnation's own slow-initializing
        trainers whose round rolled during a long compile, livelocking
        them on kill/requeue forever. Must hold the lock."""
        if not self._recovered or self._job_assigned(job_id, worker_id):
            return False
        window = max(self._config.first_init_grace_s or 0.0,
                     2.0 * self._time_per_iteration
                     + (self._config.job_completion_buffer_s
                        if self._config.job_completion_buffer_s is not None
                        else JOB_COMPLETION_BUFFER_TIME))
        return self.get_current_timestamp() - self._recovered_at < window

    def done_callback(self, job_id, worker_id, all_num_steps,
                      all_execution_times, iterator_logs=None):
        with self._cv:
            # Post-restart gate: a report whose dispatch this scheduler
            # incarnation never made is a pre-crash orphan (its round
            # was conservatively requeued at recovery; accepting it
            # would double-credit the redispatched copy's work).
            if (self._recovered
                    and (job_id, worker_id) not in self._dispatch_stamp):
                self.log.warning(
                    "discarding pre-restart completion for job %s from "
                    "worker %d (no dispatch this incarnation)",
                    job_id, worker_id)
                return
            # Duplicate guard, checked BEFORE the boundary wait (an
            # at-least-once retry must be rejected now, not parked until
            # the round rolls, where it would race the next dispatch's
            # stamp) and re-checked after it (concurrent original +
            # retry both entering pre-acceptance).
            if self._is_duplicate_done(job_id, worker_id):
                self.log.warning("discarding duplicate completion for job "
                               "%s from worker %d", job_id, worker_id)
                return
            # If the job was dispatched for round r+1 and finished before
            # round r closed, wait for the round boundary.
            while (job_id not in self.rounds.current_assignments
                   or job_id in self.rounds.completed_in_round):
                if (job_id not in self.rounds.current_assignments
                        and self.rounds.next_assignments is not None
                        and job_id not in self.rounds.next_assignments):
                    self.log.warning("discarding completion for unscheduled job %s",
                                   job_id)
                    return
                self._cv.wait()

            if self._is_duplicate_done(job_id, worker_id):
                self.log.warning("discarding duplicate completion for job "
                               "%s from worker %d", job_id, worker_id)
                return
            # Consume this dispatch's sequence number (0 = accepted with
            # no recorded dispatch: direct-call/unit paths stay open).
            self._done_stamp[(job_id, worker_id)] = (
                self._dispatch_stamp.get((job_id, worker_id), 0))

            for m in job_id.singletons():
                if m in self.acct.jobs:
                    self.acct.latest_timestamps[m] = self.get_current_timestamp()
                    self._last_heartbeat[m] = self.get_current_timestamp()
                    self._ever_signaled.add(m)
                self._kill_rearm_counts.pop(m, None)
            # The deferral counter is keyed by the assignment combo (a
            # pair for packed jobs) — clear that key too.
            self._kill_rearm_counts.pop(job_id, None)
            # Piggybacked worker heartbeat (synthesized dones for dead
            # chips are not stamped — id is no longer in last_seen).
            if worker_id in self.workers.last_seen:
                self.workers.last_seen[worker_id] = (
                    self.get_current_timestamp())
            self._available_workers.put(worker_id)

            timer = self._completion_events.pop(job_id, None)
            if timer is not None:
                timer.cancel()

            super().done_callback(job_id, worker_id, all_num_steps,
                                  all_execution_times,
                                  iterator_logs=iterator_logs)

            for m in job_id.singletons():
                self._lease_update_requests[m] = []
                self._max_steps_consensus[m] = None

            # Early finisher holding an extended lease must be re-dispatched
            # for the round it was already granted.
            is_active = any(m in self.acct.jobs for m in job_id.singletons())
            if is_active and job_id in self.rounds.extended_leases:
                self._redispatch_assignments[job_id] = (
                    self.rounds.next_assignments[job_id])
            # Gray-failure feed, LAST: the micro-task is fully accounted
            # (completed_in_round, chip back in the pool), so a degraded
            # verdict's quarantine sees consistent round state when it
            # requeues the host's other work and drains the pool.
            if (not job_id.is_pair() and all_num_steps
                    and all_execution_times):
                self._health_note_rate(worker_id, job_id,
                                       int(all_num_steps[0]),
                                       float(all_execution_times[0]))
                self._history_note_rate(worker_id, job_id,
                                        int(all_num_steps[0]),
                                        float(all_execution_times[0]))
            self._cv.notify_all()

    @requires_lock
    def _history_note_rate(self, worker_id: int, job_id: JobIdPair,
                           steps: int, exec_time: float) -> None:
        """Telemetry-history observation feed: one observed steps/s
        point per completed micro-task, keyed by (job_type, batch_size,
        scale_factor, worker_type) — the learned-oracle training row
        (ROADMAP item 2). Recorded regardless of the health classifier
        (history is measurement, not mitigation). Must hold the lock."""
        if self._history is None or steps <= 0 or exec_time <= 0:
            return
        if worker_id not in self.workers.id_to_type:
            return
        a = self.acct
        job = a.jobs.get(job_id)  # may already be completed/removed
        self._history.record_observation(
            job_type=(job.job_type if job is not None
                      else a.original_job_type.get(job_id, "?")),
            batch_size=(job.batch_size if job is not None
                        else a.original_bs.get(job_id)),
            scale_factor=(job.scale_factor if job is not None else len(
                self.rounds.current_assignments.get(job_id, (0,)))),
            worker_type=self.workers.id_to_type[worker_id],
            steps_per_s=steps / exec_time,
            round_id=self.rounds.num_completed_rounds)

    @requires_lock
    def _inflight_elapsed_times(self, current_time: float):
        """Unaccounted time of currently-running microtasks, charged into
        the priority fractions (reference: scheduler.py:3640-3666). Done
        callbacks only arrive when a process exits, so without this a
        lease-extended job looks like it has received no time at all and
        sticky placement would re-extend it until completion, starving
        the queue (observed as sequential JCTs in the CPU loopback
        fidelity run)."""
        inflight_job: dict = {}
        inflight_worker: dict = {}
        for job_id, worker_ids in self.rounds.current_assignments.items():
            # Only microtasks whose process is still alive: an exited
            # job stays in current_assignments until the round boundary,
            # but its real time was already charged by its done
            # callback — counting idle tail time would double-charge.
            # For colocated pairs, any still-running member keeps the
            # combo in flight (its peer's exit does not free the chip),
            # and the combo is charged once, from the latest dispatch
            # stamp among the running members.
            running = [m for m in job_id.singletons()
                       if m in self._running_jobs
                       and self.acct.latest_timestamps.get(m) is not None]
            if not running or not worker_ids:
                continue
            dispatch = max(self.acct.latest_timestamps[m] for m in running)
            elapsed = current_time - max(dispatch, self._last_reset_time)
            if elapsed <= 0:
                continue
            wt = self.workers.id_to_type[worker_ids[0]]
            per_wt = inflight_job.setdefault(job_id, {})
            per_wt[wt] = per_wt.get(wt, 0.0) + elapsed
            inflight_worker[wt] = inflight_worker.get(wt, 0.0) + elapsed
        return inflight_job, inflight_worker

    # ------------------------------------------------------------------
    # Allocation thread
    # ------------------------------------------------------------------

    def _allocation_thread(self):
        while not self._done_event.is_set():
            with self._cv:
                while not self._need_to_update_allocation:
                    self._cv.wait(timeout=1.0)
                    if self._done_event.is_set():
                        return
                state = self._allocation_state()
            try:
                allocation = self._compute_allocation(state)
            except Exception:  # noqa: BLE001 - the allocation thread is
                # a singleton: if a pathological solve kills it, the
                # scheduler wedges forever (run() waits on the update
                # flag). Keep the previous allocation and retry on the
                # next trigger instead.
                self.log.exception("allocation solve failed; keeping "
                                   "previous allocation")
                allocation = None
            with self._cv:
                if allocation is not None:
                    self._allocation = allocation
                self._need_to_update_allocation = False
                self._cv.notify_all()

    # ------------------------------------------------------------------
    # Pipelined planning (shockwave policy only)
    # ------------------------------------------------------------------

    def _planner_solve_loop(self):
        """Background MILP solver: waits for a prepared request, solves
        it OUTSIDE the scheduler lock, and parks the result for the
        round loop to commit at the next re-solve point."""
        while not self._done_event.is_set():
            with self._cv:
                while self._planner_request is None:
                    self._cv.wait(timeout=1.0)
                    if self._done_event.is_set():
                        return
                request = self._planner_request
                self._planner_request = None
            try:
                result = self._shockwave_planner.solve_prepared(
                    request, pipelined=True)
            except Exception:  # noqa: BLE001 - the solve thread is a
                # singleton: if a pathological instance kills it, every
                # later re-solve round would fall back forever. Drop
                # this request (the planner serves its cached schedule)
                # and keep the thread alive for the next kick.
                self.log.exception("pipelined planner solve failed; "
                                   "round will use the cached schedule")
                result = None
            with self._cv:
                if result is not None:
                    self._planner_result = result
                self._planner_busy = False
                self._cv.notify_all()

    @requires_lock
    def _commit_planner_result(self):
        """Install a finished background solve into the planner (round
        loop thread, under the lock)."""
        if self._planner_result is not None:
            self._shockwave_planner.commit_result(self._planner_result)
            self._planner_result = None

    @requires_lock
    def _maybe_kick_planner_solve(self):
        """At round start: if this round's re-solve point needs a fresh
        schedule, snapshot the inputs NOW and hand them to the solve
        thread, so the solve wall overlaps round execution."""
        planner = self._shockwave_planner
        if planner is None or not planner.pipelined:
            return
        self._commit_planner_result()
        if (self._planner_busy or self._is_final_round()
                or not planner.needs_resolve()):
            return
        request = planner.prepare_solve()
        if request is None:
            return
        self._planner_request = request
        self._planner_busy = True
        self._cv.notify_all()

    # ------------------------------------------------------------------
    # Round pipeline
    # ------------------------------------------------------------------

    @requires_lock
    def _try_dispatch_job(self, job_id: JobIdPair, worker_ids: Tuple[int, ...],
                          next_round: bool = False):
        if not next_round or job_id not in self.rounds.current_assignments:
            self._in_progress_updates[job_id] = []
            self._iterator_log_buffers.pop(job_id, None)
            for m in job_id.singletons():
                self._lease_update_requests[m] = []
                self._max_steps_consensus[m] = None

        scale_factor = len(worker_ids)
        round_id = self.rounds.num_completed_rounds + (1 if next_round else 0)
        coordinator = None
        if scale_factor > 1:
            head = self._worker_connections[worker_ids[0]]
            port = BASE_JOB_PORT + self._port_offset
            self._port_offset = (self._port_offset + 1) % (MAX_PORT - BASE_JOB_PORT)
            coordinator = f"{head.addr}:{port}"

        for m in job_id.singletons():
            # The liveness clock starts at dispatch: process launch +
            # imports + jit compile all happen before the first RPC.
            self._last_heartbeat[m] = self.get_current_timestamp()
            self._ever_signaled.discard(m)  # cold spawn: init grace re-arms
            self._kill_rearm_counts.pop(m, None)  # fresh deferral budget
        self._kill_rearm_counts.pop(job_id, None)  # combo key (packed pair)
        self._failure_compensated.discard(job_id)
        # Stamp EVERY rank before any RPC: if rank k's dispatch fails,
        # the synthesized failed-in-round completions cover all ranks —
        # including ranks > k that were never reached — and an unstamped
        # rank's synthesis would be rejected as a duplicate of the
        # previous dispatch's accepted report, wedging the round.
        for worker_id in worker_ids:
            self._dispatch_seq += 1
            self._dispatch_stamp[(job_id, worker_id)] = self._dispatch_seq
        slow_dispatches = []
        for rank, worker_id in enumerate(worker_ids):
            descriptions = []
            for m in job_id.singletons():
                job = self.acct.jobs[m]
                command = job.command
                if scale_factor > 1:
                    # Multi-chip gang: coordinator rendezvous for
                    # jax.distributed.initialize.
                    command += (f" --coordinator {coordinator}"
                                f" --num_processes {scale_factor}"
                                f" --process_id {rank}")
                descriptions.append(dict(
                    job_id=m.integer_job_id(), command=command,
                    working_directory=job.working_directory,
                    needs_data_dir=job.needs_data_dir,
                    num_steps_arg=job.num_steps_arg,
                    num_steps=job.total_steps, mode=job.mode))
            dispatch_start = self._obs.clock()
            try:
                if self._trace_propagation and self._obs.enabled:
                    # One span per dispatch RPC, nested under the
                    # round's dispatch phase (or the round root at
                    # startup); its context + send timestamp ride the
                    # RPC metadata so the worker daemon's runjob span
                    # parents here and the merge can align clocks.
                    from ..obs import propagation
                    parent = (self._obs.tracer.current_context()
                              or self._round_ctx)
                    with self._obs.tracer.span(
                            obs_names.SPAN_RUNJOB_RPC, parent=parent,
                            round=round_id, worker=worker_id,
                            jobs=[m.integer_job_id()
                                  for m in job_id.singletons()]) as rpc_ctx:
                        self._worker_connections[worker_id].run_job(
                            descriptions, worker_id, round_id,
                            metadata_extra=propagation.rpc_metadata(
                                rpc_ctx, send_ts=dispatch_start))
                else:
                    self._worker_connections[worker_id].run_job(
                        descriptions, worker_id, round_id)
            except WORKER_RPC_ERRORS as e:
                if self._is_stale_epoch_error(e):
                    # The worker has seen a higher leader epoch: a
                    # standby promoted over us. Do NOT retire the
                    # (healthy) host or charge the job — stop being
                    # the leader. The successor's conservative
                    # recovery already owns every in-flight round.
                    self._obs.inc(obs_names.DISPATCHES_TOTAL,
                                  outcome="fenced")
                    if self._ha is not None:
                        self._ha.fence_now()
                    else:  # fenced reply without an HA controller:
                        # still stop dispatching (defensive)
                        self._on_ha_fenced(-1)
                    return
                self._obs.inc(obs_names.DISPATCHES_TOTAL,
                              outcome=("unavailable"
                                       if isinstance(e, RpcUnavailableError)
                                       else "rejected"))
                if isinstance(e, RpcUnavailableError):
                    # Graceful degradation: the worker is unreachable
                    # (retry budget exhausted or circuit open). Retire
                    # its host — which fails this job in-round / prunes
                    # it from the next plan so it requeues.
                    self.log.warning("dispatch of job %s to worker %d "
                                     "failed (%s); retiring its host",
                                     job_id, worker_id, e)
                    self._retire_worker_by_id(worker_id)
                else:
                    # Application-level rejection: the daemon ANSWERED
                    # (e.g. its RunJob handler raised). The host is
                    # healthy — retiring it would fail every other job
                    # there and flap capacity — so fail only THIS job's
                    # round and charge it the attempt (persistent bad
                    # dispatches are dropped via MAX_FAILED_ATTEMPTS).
                    self.log.error("worker %d rejected dispatch of job %s "
                                   "(%s); failing it in-round", worker_id,
                                   job_id, e)
                    self._fail_dispatch_in_round(job_id, worker_ids,
                                                 next_round)
                # Either way, kill the ranks already dispatched to live
                # workers: once the job leaves the assignment maps no
                # watchdog covers them, and an orphan blocked in gang
                # rendezvous would hold its chip and stall every later
                # dispatch queued behind it.
                for dispatched_id in worker_ids[:rank]:
                    client = self._worker_connections.get(dispatched_id)
                    if client is None or dispatched_id in self.workers.dead:
                        continue
                    for m in job_id.singletons():
                        try:
                            # One short attempt (lock held; see above).
                            client.kill_job(
                                m.integer_job_id(),
                                deadline_s=self._config
                                .worker_probe_deadline_s)
                        except WORKER_RPC_ERRORS:
                            break  # host unreachable too; probe reaps it
                return
            dispatch_latency = max(self._obs.clock() - dispatch_start, 0.0)
            self._obs.observe(obs_names.DISPATCH_LATENCY_SECONDS,
                              dispatch_latency)
            self._obs.inc(obs_names.DISPATCHES_TOTAL, outcome="ok")
            slow_dispatches.append((worker_id, dispatch_latency))
            if not next_round:
                self._remove_available_worker(worker_id)
        # Health feed AFTER the whole gang is dispatched: a degraded
        # verdict mid-loop would quarantine the host, synthesize this
        # job failed and prune it from the assignment maps while the
        # loop keeps launching its remaining ranks — orphan processes
        # no watchdog covers, racing the requeued copy. Fed here, a
        # quarantine sees a fully-dispatched job and the standard
        # victim-kill/requeue machinery handles it consistently.
        for worker_id, dispatch_latency in slow_dispatches:
            self._health_note_dispatch(worker_id, dispatch_latency)

    @requires_lock
    def _fail_dispatch_in_round(self, job_id: JobIdPair, worker_ids,
                                next_round: bool) -> None:
        """Fail one job's round after a rejected dispatch, leaving its
        (healthy) host in service. Must hold the lock."""
        if next_round:
            if (self.rounds.next_assignments is not None
                    and job_id in self.rounds.next_assignments):
                del self.rounds.next_assignments[job_id]
            self._redispatch_assignments.pop(job_id, None)
            self.rounds.extended_leases.discard(job_id)
            return
        if (job_id not in self.rounds.current_assignments
                or job_id in self.rounds.completed_in_round):
            return
        self._obs.inc(obs_names.JOBS_REQUEUED_TOTAL,
                      reason="dispatch_rejected")
        reported = {u[0] for u in self._in_progress_updates.get(job_id, [])}
        zeros = [0 for _ in job_id.singletons()]
        for worker_id in worker_ids:
            if worker_id not in reported:
                self.done_callback(job_id, worker_id, zeros, zeros)

    def _remove_available_worker(self, worker_id):
        try:
            # Drain this specific id (queue holds unique ids).
            items = []
            while True:
                item = self._available_workers.get_nowait()
                if item == worker_id:
                    break
                items.append(item)
            for item in items:
                self._available_workers.put(item)
        except queue.Empty:
            for item in items:
                self._available_workers.put(item)

    @requires_lock
    def _maybe_new_round_ctx(self) -> None:
        """Open this round's fleet-trace root context (idempotent per
        round; no-op unless obs_trace_dir propagation is on). The root
        span itself is recorded at round end with the round's real
        bounds (record_span), so children can link to it while it is
        still open."""
        if not self._trace_propagation or not self._obs.enabled:
            return
        current = self.rounds.num_completed_rounds
        if self._round_ctx is not None and self._round_ctx_round == current:
            return
        from ..obs import propagation
        self._round_ctx = propagation.new_root_context()
        self._round_ctx_round = current
        self._round_ctx_started = self.get_current_timestamp()

    @requires_lock
    def _close_round_ctx(self) -> None:
        """Record the round root span (round start -> now) and retire
        the context."""
        if self._round_ctx is None:
            return
        self._obs.tracer.record_span(
            obs_names.SPAN_ROUND, ts=self._round_ctx_started,
            dur=self.get_current_timestamp() - self._round_ctx_started,
            context=self._round_ctx, round=self._round_ctx_round)
        self._round_ctx = None

    @requires_lock
    def _begin_round(self):
        self._current_round_start_time = self.get_current_timestamp()
        self._maybe_new_round_ctx()
        self._maybe_kick_planner_solve()
        for job_id in self.rounds.current_assignments:
            for m in job_id.singletons():
                self._lease_update_requests[m] = []
                self._max_steps_consensus[m] = None
        # list(): a dispatch failure retires the worker's host, which may
        # prune entries from this very dict.
        for job_id, worker_ids in list(self._redispatch_assignments.items()):
            if any(m in self.acct.jobs for m in job_id.singletons()):
                self.log.info("re-dispatching early-finished job %s", job_id)
                self._try_dispatch_job(job_id, worker_ids)
        self._redispatch_assignments = collections.OrderedDict()
        self.log.info("*** START ROUND %d ***", self.rounds.num_completed_rounds)

    @requires_lock
    def _is_final_round(self):
        return (self._config.max_rounds is not None
                and self.rounds.num_completed_rounds + 1 == self._config.max_rounds)

    @requires_lock
    def _mid_round(self):
        """Recompute next round's schedule, extend leases, dispatch early."""
        if self._is_final_round():
            self.rounds.extended_leases = set()
            return
        round_end = self._current_round_start_time + self._time_per_iteration
        round_id = self.rounds.num_completed_rounds

        with self._obs.phase(obs_names.SPAN_SOLVE, parent=self._round_ctx,
                             round=round_id):
            # Pipelined planning: the MILP ran on the background thread
            # since round start; commit it here if it finished (the
            # planner serves its deadline fallback otherwise), so this
            # phase span now measures selection + assignment, not the
            # solve wall.
            if self._shockwave_planner is not None:
                self._commit_planner_result()
            self.rounds.next_assignments = self._schedule_jobs_on_workers()

        for job_id in self.rounds.current_assignments:
            if any(m in self.acct.jobs for m in job_id.singletons()):
                self.rounds.num_lease_opportunities += 1

        for job_id in self.rounds.current_assignments:
            current = set(self.rounds.current_assignments[job_id])
            if (job_id in self.rounds.next_assignments
                    and job_id not in self.rounds.completed_in_round):
                if current == set(self.rounds.next_assignments[job_id]):
                    self.rounds.extended_leases.add(job_id)
                    self.rounds.num_lease_extensions += 1
                else:
                    self.rounds.extended_leases.discard(job_id)
            else:
                self.rounds.extended_leases.discard(job_id)

        # list(): a dispatch failure retires the worker's host, which
        # prunes that host's entries from next_assignments.
        with self._obs.phase(obs_names.SPAN_DISPATCH,
                             parent=self._round_ctx, round=round_id):
            for job_id, worker_ids in list(
                    self.rounds.next_assignments.items()):
                if job_id not in self.rounds.next_assignments:
                    continue  # pruned by a dead-worker retirement above
                if not any(m in self.acct.jobs
                           for m in job_id.singletons()):
                    continue
                if (job_id not in self.rounds.extended_leases
                        or job_id in self.rounds.completed_in_round):
                    self._try_dispatch_job(job_id, worker_ids,
                                           next_round=True)

        self._schedule_completion_events(round_end)

    @requires_lock
    def _schedule_completion_events(self, round_end):
        """Watchdogs: kill jobs that miss the round deadline; synthesize
        completion for jobs with extended leases."""
        now = self.get_current_timestamp()
        for job_id in self.rounds.current_assignments:
            if not any(m in self.acct.jobs for m in job_id.singletons()):
                continue
            if job_id in self.rounds.completed_in_round:
                continue
            delay = round_end - now
            if job_id not in self.rounds.extended_leases:
                delay += (self._config.job_completion_buffer_s
                          if self._config.job_completion_buffer_s is not None
                          else JOB_COMPLETION_BUFFER_TIME)
                action = self._kill_job
            else:
                action = self._done_callback_extended_lease
            timer = threading.Timer(max(delay, 0.0), action, args=(job_id,))
            timer.daemon = True
            timer.start()
            self._completion_events[job_id] = timer

    @requires_lock
    def _end_round(self):
        """Wait for all scheduled jobs to complete, then roll the round."""
        round_id = self.rounds.num_completed_rounds
        jobs_to_complete = {
            job_id for job_id in self.rounds.current_assignments
            if any(m in self.acct.jobs for m in job_id.singletons())}
        with self._obs.phase(obs_names.SPAN_WAIT, parent=self._round_ctx,
                             round=round_id):
            while not jobs_to_complete.issubset(
                    self.rounds.completed_in_round):
                if self._ha_fenced:
                    # Deposed mid-round: the outstanding completions
                    # now belong to the successor (workers re-resolved
                    # their report channel); waiting here would wedge
                    # the fenced exit forever.
                    return
                # Bounded wait: completion normally arrives with a
                # notify (done callback, watchdog, or dead-worker
                # retirement), but round liveness must not hinge on
                # never missing one.
                self._cv.wait(timeout=5.0)
        with self._obs.phase(obs_names.SPAN_END_ROUND,
                             parent=self._round_ctx, round=round_id):
            self._finish_round()

    @requires_lock
    def _finish_round(self):
        """Post-wait half of the round roll: free extended-lease chips,
        reserve next-round chips, sleep out the boundary, advance."""
        for job_id in list(self.rounds.extended_leases):
            if job_id in self.acct.jobs:
                for worker_id in self.rounds.current_assignments[job_id]:
                    if worker_id not in self.workers.dead:
                        self._available_workers.put(worker_id)
            self.rounds.extended_leases.discard(job_id)

        if not self._is_final_round():
            assert self.rounds.next_assignments is not None
            for job_id, worker_ids in self.rounds.next_assignments.items():
                if any(m in self.acct.jobs for m in job_id.singletons()):
                    if job_id in self._redispatch_assignments:
                        continue
                    for worker_id in worker_ids:
                        self._remove_available_worker(worker_id)
            now = self.get_current_timestamp()
            remaining = (self._current_round_start_time
                         + self._time_per_iteration - now)
            if remaining > 0:
                self._cv.release()
                try:
                    time.sleep(remaining)
                finally:
                    self._cv.acquire()

        self._close_round_ctx()
        self.rounds.num_completed_rounds += 1
        self.rounds.completed_in_round = set()
        self.rounds.current_assignments = self.rounds.next_assignments or (
            collections.OrderedDict())
        self.rounds.next_assignments = None
        self._emit("round_ended", round=self.rounds.num_completed_rounds)
        if self._history is not None:
            # Sample every registered metric into the telemetry-history
            # ring (and run the burn-rate checks) once per round; the
            # periodic flush is one atomic rewrite, same order of cost
            # as the compacting snapshot below.
            self._history.sample_round(self.rounds.num_completed_rounds)
            if self._serving_tier is not None:
                # Measured serving rows (per service, rounds with
                # samples): the latency-calibration / mu-estimation
                # training set, served as /history.json "serving".
                for row in self._serving_tier.take_measured_rows():
                    self._history.record_serving(
                        row, self.rounds.num_completed_rounds)
        self._maybe_snapshot()
        if self._whatif is not None:
            # Pay only the state-copy cost under the lock (the
            # `whatif_fork` phase); twin rollouts run on the what-if
            # thread against the detached blob.
            work = self._whatif.maybe_capture_locked()
            if work is not None:
                self._whatif_work.put(work)
        self._obs_update_round_gauges()
        self._cv.notify_all()
        self.log.info("*** END ROUND %d ***", self.rounds.num_completed_rounds - 1)

    def _kill_job(self, job_id: JobIdPair):
        with self._cv:
            if job_id not in self.rounds.current_assignments:
                return
            if job_id not in self._completion_events:
                if (job_id in self.rounds.completed_in_round
                        and job_id not in self.rounds.extended_leases):
                    return
            grace = self._config.first_init_grace_s
            if grace and not any(m in self._ever_signaled
                                 for m in job_id.singletons()):
                dispatched = min((self._last_heartbeat.get(m, 0.0)
                                  for m in job_id.singletons()), default=0.0)
                waited = self.get_current_timestamp() - dispatched
                if waited < grace:
                    # Cold dispatch through a relayed TPU can spend minutes
                    # in backend init waiting for the chip grant; killing
                    # the waiter (SIGKILL) wedges the relay so the NEXT
                    # dispatch hangs too — a kill->wedge->kill livelock
                    # observed live on the v5e tunnel. Re-arm instead.
                    self.log.warning(
                        "job %s silent %.0fs after dispatch; granting "
                        "first-init grace (%.0fs)", job_id, waited, grace)
                    timer = threading.Timer(max(grace - waited, 1.0),
                                            self._kill_job, args=(job_id,))
                    timer.daemon = True
                    timer.start()
                    self._completion_events[job_id] = timer
                    return
            # A job that signaled moments ago (e.g. its first InitJob landed
            # just before the re-armed grace timer fired) is alive and mid-
            # checkpoint, not unresponsive: give it a short re-arm window
            # instead of killing it seconds after its first RPC. The
            # deferrals are CAPPED per dispatch (counter cleared on
            # dispatch/done): a job that keeps renewing its lease but
            # never honors expiry would otherwise re-arm forever and hold
            # _end_round hostage.
            now = self.get_current_timestamp()
            freshness = (self._config.kill_heartbeat_freshness_s
                         if self._config.kill_heartbeat_freshness_s
                         is not None else KILL_HEARTBEAT_FRESHNESS_S)
            youngest = max((self._last_heartbeat.get(m, 0.0)
                            for m in job_id.singletons()), default=0.0)
            rearms = self._kill_rearm_counts.get(job_id, 0)
            if (now - youngest < freshness
                    and rearms < self._config.max_kill_rearms):
                self._kill_rearm_counts[job_id] = rearms + 1
                timer = threading.Timer(freshness, self._kill_job,
                                        args=(job_id,))
                timer.daemon = True
                timer.start()
                self._completion_events[job_id] = timer
                return
            if rearms >= self._config.max_kill_rearms:
                self.log.warning(
                    "job %s exhausted %d freshness deferrals; killing "
                    "despite recent heartbeat", job_id, rearms)
            self.log.warning("killing unresponsive job %s", job_id)
            self._obs.inc(obs_names.JOB_KILLS_TOTAL)
            worker_ids = self.rounds.current_assignments[job_id]
            self._kill_rearm_counts.pop(job_id, None)
            servers = set()
            for worker_id in worker_ids:
                client = self._worker_connections.get(worker_id)
                if client is None or worker_id in self.workers.dead:
                    continue
                if (client.addr, client.port) not in servers:
                    for m in job_id.singletons():
                        try:
                            client.kill_job(m.integer_job_id())
                        except WORKER_RPC_ERRORS as e:
                            # Can't reach the worker to kill: proceed to
                            # the synthesized completion below — round
                            # liveness must not depend on a dead daemon.
                            self.log.warning("kill of %s on worker %d "
                                             "unreachable (%s)", m,
                                             worker_id, e)
                            break
                    servers.add((client.addr, client.port))
            self._completion_events.pop(job_id, None)
            prev_round = self.rounds.num_completed_rounds
            self._cv.wait(timeout=self._config.kill_wait_s)
            killed = (self.rounds.num_completed_rounds != prev_round
                      or job_id in self.rounds.completed_in_round)
            if killed:
                return
            all_ids = set(self.rounds.current_assignments[job_id])
            reported = {u[0] for u in self._in_progress_updates.get(job_id, [])}
            missing = all_ids - reported
        zeros = [0 for _ in job_id.singletons()]
        for worker_id in missing:
            self.done_callback(job_id, worker_id, zeros, zeros)

    def _done_callback_extended_lease(self, job_id: JobIdPair):
        """Round-boundary completion for jobs running across rounds on an
        extended lease (they never exit, so no worker Done arrives)."""
        kill = False
        with self._cv:
            if not any(m in self.acct.jobs for m in job_id.singletons()):
                return
            # Liveness by heartbeat age, not by per-round renewal count:
            # InitJob / UpdateLease / Done all stamp a heartbeat. On TPU
            # the first dispatch can spend most of a round inside jit
            # compilation before the first step, and a renewed lease's 75%
            # checkpoint can legitimately skip a round boundary, so the
            # reference's "no renewal this round => dead" rule
            # (scheduler.py:4313-4339) produces spurious kills here.
            now = self.get_current_timestamp()
            # Only live members count, and a missing stamp defaults to
            # `now`, not 0: when one job of a packed pair has already
            # completed (its heartbeat entry removed), a 0.0 default
            # would read as an ~epoch-old heartbeat and instantly kill
            # the surviving job.
            oldest = min((self._last_heartbeat.get(m, now)
                          for m in job_id.singletons()
                          if m in self.acct.jobs), default=now)
            if now - oldest > (self._time_per_iteration
                               + (self._config.job_completion_buffer_s
                                  if self._config.job_completion_buffer_s
                                  is not None
                                  else JOB_COMPLETION_BUFFER_TIME)):
                # No signal for over a round: job is unresponsive.
                kill = True
            elif job_id in self._completion_events:
                self.rounds.completed_in_round.add(job_id)
                del self._completion_events[job_id]
                for m in job_id.singletons():
                    self._lease_update_requests[m] = []
                    self._max_steps_consensus[m] = None
            if not kill:
                self._cv.notify_all()
        if kill:
            self._kill_job(job_id)

    # ------------------------------------------------------------------
    # Main loop
    # ------------------------------------------------------------------

    def run(self):
        """Drive the round mechanism until max_rounds (or forever), or
        until fenced by a promoted standby (ha_fenced tells the driver
        which exit this was)."""
        with self._cv:
            while not (self.acct.jobs or self._serving_live()) or (
                    self._expected_num_workers is not None
                    and len(self.workers.worker_ids) < self._expected_num_workers):
                if self._ha_fenced:
                    self._done_event.set()
                    return
                self._cv.wait()
            if self._policy.name != "shockwave":
                while self._need_to_update_allocation:
                    self._cv.wait()
            planner = self._shockwave_planner
            if (planner is not None and planner.pipelined
                    and planner.needs_resolve()):
                # Startup solve, inline: no round is executing yet, so
                # there is nothing to overlap with — solve before the
                # first dispatch rather than running round 0 on the
                # backfill fallback.
                request = planner.prepare_solve()
                if request is not None:
                    planner.commit_result(planner.solve_prepared(request))
            self.rounds.current_assignments = self._schedule_jobs_on_workers()
            if self._shockwave_planner is not None:
                self._shockwave_planner.increment_round()
            self._maybe_new_round_ctx()
            for job_id, worker_ids in self.rounds.current_assignments.items():
                self._try_dispatch_job(job_id, worker_ids)

        while True:
            with self._cv:
                if self._ha_fenced:
                    break
                final = self._is_final_round()
                self._maybe_new_round_ctx()
                with self._obs.phase(obs_names.SPAN_BEGIN_ROUND,
                                     parent=self._round_ctx,
                                     round=self.rounds.num_completed_rounds):
                    self._begin_round()
            time.sleep(self._time_per_iteration * SCHEDULE_RECOMPUTE_FRACTION)
            with self._cv:
                if self._ha_fenced:
                    break
                self._mid_round()
                if self._shockwave_planner is not None:
                    # Set of immutable JobIdPairs consumed for membership
                    # only — a shallow set copy isolates it from
                    # _finish_round's discard()s; deepcopy did the same
                    # job with per-element memoization overhead.
                    extended = set(self.rounds.extended_leases)
                self._end_round()
                if self._shockwave_planner is not None:
                    self._update_shockwave_planner_physical(extended)
                idle = not self.acct.jobs and not self._serving_live()
            if final or idle and self._config.max_rounds is None:
                if final or self._all_done():
                    break
        self._done_event.set()

    def _all_done(self):
        with self._lock:
            return not self.acct.jobs and not self._serving_live()

    # ------------------------------------------------------------------
    # What-if background rollouts
    # ------------------------------------------------------------------

    def _whatif_loop(self):
        """Consume captured fork blobs and roll them OFF the scheduler
        lock; a committed knob value re-takes the lock briefly (the
        plane's commit_lock). The thread must never die — the round
        pipeline keeps producing work items either way."""
        while not self._done_event.is_set():
            try:
                work = self._whatif_work.get(timeout=1.0)
            except queue.Empty:
                continue
            try:
                if work[0] == "advise":
                    _, blob, job_bytes, now = work
                    import pickle as _pickle
                    self._whatif.advise_admission(
                        blob, _pickle.loads(job_bytes), now)
                else:
                    self._whatif.run_background_step(work,
                                                     commit_lock=self._lock)
            except Exception:  # noqa: BLE001 - advisory plane: a bad
                # rollout must never take the control plane with it
                self.log.exception("what-if background step failed")

    @requires_lock
    def _update_shockwave_planner_physical(self, extended_leases):
        """Physical variant: account in-lease steps for extended leases
        (reference: scheduler.py:2294-2331)."""
        planner = self._shockwave_planner
        scheduled = self._scheduled_jobs_in_prev_round or []
        from ..core import constants
        for int_id in scheduled:
            job_id = JobIdPair(int_id)
            if job_id in self._completed_jobs:
                if int_id in planner.metadata:
                    planner.mark_progress(int_id, planner.metadata[int_id].epochs)
                continue
            if job_id not in self.acct.jobs:
                continue
            steps = sum(self.acct.steps_run.get(job_id, {}).values())
            if job_id in extended_leases:
                steps += self._steps_run_in_current_lease.get(job_id, 0)
            job = self.acct.jobs[job_id]
            epoch = math.floor(
                steps / constants.steps_per_epoch(job.model, job.batch_size))
            planner.mark_progress(int_id, epoch)
        active = {j.integer_job_id() for j in self.acct.jobs}
        for int_id in active - set(scheduled):
            planner.add_waiting_delay(int_id, self._time_per_iteration)
        planner.increment_round()
        self._rounds_since_reopt += 1
        from .scheduler import REOPT_ROUNDS
        if self._shockwave_job_completed or self._rounds_since_reopt >= REOPT_ROUNDS:
            self._shockwave_job_completed = False
            self._rounds_since_reopt = 0
            planner.request_resolve()

    def shutdown(self):
        self._done_event.set()
        if self._ha is not None:
            # Stop renewing the lease FIRST: a clean shutdown should
            # let a standby take over one TTL later, not keep looking
            # alive from beyond the grave.
            self._ha.stop()
        if self._config.obs_trace_path:
            try:
                self._obs.tracer.export_chrome_trace(
                    self._config.obs_trace_path)
            except OSError:
                self.log.exception("obs trace export to %s failed",
                                   self._config.obs_trace_path)
        if self._history is not None:
            try:
                self._history.flush()
            except OSError:
                self.log.exception("telemetry-history flush failed")
        if self._config.obs_trace_dir:
            # Fleet-trace collection: write this scheduler's span shard
            # beside the worker/trainer shards and fuse everything into
            # one merged Perfetto trace. Telemetry only — a failed
            # merge must never fail the shutdown.
            try:
                from ..obs.merge import merge_directory
                from ..obs.shard import export_tracer_shard
                export_tracer_shard(self._config.obs_trace_dir,
                                    "scheduler", self._obs.tracer,
                                    obs=self._obs)
                summary = merge_directory(self._config.obs_trace_dir,
                                          obs=self._obs)
                self.log.info(
                    "fleet trace merged: %d shards, %d spans -> %s",
                    summary["shards"], summary["spans"], summary["out"])
            except Exception:  # noqa: BLE001 - telemetry collection
                # must never take the shutdown path down
                self.log.exception("fleet-trace collection failed")
        if self._obs_server is not None:
            self._obs_server.stop()
        # Snapshot the client set under the lock (a re-registration RPC
        # may be rebuilding host channels concurrently), then shut the
        # clients down outside it — each shutdown is a bounded RPC, and
        # holding the lock across it would stall any in-flight handler.
        # A FENCED ex-leader skips this entirely: the workers belong to
        # the promoted successor now, and a zombie's parting Shutdown
        # would take the live fleet down with it (the worker-side epoch
        # fence also rejects it, but not every worker may have seen the
        # new epoch yet).
        with self._lock:
            clients = (set() if self._ha_fenced
                       else set(self._worker_connections.values()))
        for client in clients:
            client.shutdown()
        self._server.stop(grace=1)
        if self._durability is not None:
            self._durability.close()
