"""A fully-parallel transformer training step: dp x pp x tp x sp x ep.

Demonstrates (and dry-runs) the framework's multi-chip execution model in
one jitted step:
- batch sharded over `dp` (XLA all-reduces grads on ICI),
- a stack of residual MLP blocks pipelined over `pp` (GPipe microbatch
  schedule, ppermute activation hops — parallel/pipeline.py),
- MLP hidden dimension sharded over `tp` (XLA inserts the reduce-scatter/
  all-gather pair around the two matmuls),
- sequence sharded over `sp` with ring attention (explicit ppermute ring),
- an MoE layer with experts sharded over `ep` (all-to-all dispatch —
  parallel/moe.py).

Size-1 axes degrade gracefully, so the same builder serves everything
from single-chip to a full 5-axis mesh. Used by
`__graft_entry__.dryrun_multichip` and as the template for scaling
workloads past data parallelism.
"""
from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .moe import moe_mlp
from .pipeline import pipeline_apply
from .ring_attention import ring_attention


def build_multi_parallel_train_step(mesh: Mesh, vocab: int = 1024,
                                    dim: int = 128, heads: int = 8,
                                    mlp_dim: int = 512, seq_len: int = 64,
                                    batch: int = 8, n_experts: int = None,
                                    num_microbatches: int = None):
    """Returns (step_fn, state, example_batch), all mesh-sharded."""
    assert dim % heads == 0
    head_dim = dim // heads
    pp = mesh.shape.get("pp", 1)
    ep = mesh.shape.get("ep", 1)
    if n_experts is None:
        n_experts = max(2 * ep, 2)
    if num_microbatches is None:
        num_microbatches = max(2 * pp, 2)
    rng = np.random.RandomState(0)

    def init(shape, scale=0.02):
        return jnp.asarray(rng.normal(0, scale, shape), jnp.float32)

    params = {
        "embed": init((vocab, dim)),
        "wq": init((dim, heads, head_dim)),
        "wk": init((dim, heads, head_dim)),
        "wv": init((dim, heads, head_dim)),
        "wo": init((heads, head_dim, dim)),
        "w1": init((dim, mlp_dim)),   # hidden dim sharded over tp
        "w2": init((mlp_dim, dim)),
        # Pipelined residual MLP stack: one (w_in, w_out) pair per stage.
        "pp_w1": init((pp, dim, mlp_dim)),
        "pp_w2": init((pp, mlp_dim, dim)),
        # MoE layer: experts sharded over ep.
        "router": init((dim, n_experts)),
        "moe_w1": init((n_experts, dim, mlp_dim)),
        "moe_w2": init((n_experts, mlp_dim, dim)),
        "out": init((dim, vocab)),
    }
    param_specs = {
        "embed": P(), "wq": P(), "wk": P(), "wv": P(), "wo": P(),
        "w1": P(None, "tp"), "w2": P("tp", None),
        "pp_w1": P("pp"), "pp_w2": P("pp"),
        "router": P(),
        "moe_w1": P("ep"), "moe_w2": P("ep"),
        "out": P(),
    }
    param_shardings = {k: NamedSharding(mesh, s) for k, s in param_specs.items()}
    params = {k: jax.device_put(v, param_shardings[k]) for k, v in params.items()}

    batch_sharding = NamedSharding(mesh, P("dp", "sp"))
    tokens = jnp.asarray(rng.randint(1, vocab, (batch, seq_len)), jnp.int32)
    targets = jnp.asarray(rng.randint(1, vocab, (batch, seq_len)), jnp.int32)
    example = (jax.device_put(tokens, batch_sharding),
               jax.device_put(targets, batch_sharding))

    def pp_block(stage, x):
        h = jax.nn.gelu(jnp.einsum("bsd,df->bsf", x, stage["w1"]))
        return x + jnp.einsum("bsf,fd->bsd", h, stage["w2"])

    def forward(params, tokens):
        x = params["embed"][tokens]  # (b, s, d)
        q = jnp.einsum("bsd,dhk->bshk", x, params["wq"])
        k = jnp.einsum("bsd,dhk->bshk", x, params["wk"])
        v = jnp.einsum("bsd,dhk->bshk", x, params["wv"])
        attn = ring_attention(q, k, v, mesh, axis_name="sp", causal=True)
        x = x + jnp.einsum("bshk,hkd->bsd", attn, params["wo"])
        # Tensor-parallel MLP: w1 column-sharded, w2 row-sharded over tp.
        h = jax.nn.gelu(jnp.einsum("bsd,df->bsf", x, params["w1"]))
        x = x + jnp.einsum("bsf,fd->bsd", h, params["w2"])
        # Pipeline-parallel residual stack over pp.
        x = pipeline_apply(
            {"w1": params["pp_w1"], "w2": params["pp_w2"]}, x, mesh,
            num_microbatches=num_microbatches, stage_fn=pp_block)
        # Expert-parallel MoE layer over ep.
        moe_out, aux = moe_mlp(x, params["router"], params["moe_w1"],
                               params["moe_w2"], mesh)
        x = x + moe_out
        return jnp.einsum("bsd,dv->bsv", x, params["out"]), aux

    def loss_fn(params, tokens, targets):
        logits, aux = forward(params, tokens)
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.mean(jnp.take_along_axis(logp, targets[..., None],
                                            axis=-1))
        return nll + 1e-2 * aux

    lr = 1e-2

    def step_fn(params, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        new_params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new_params, loss

    step = jax.jit(
        step_fn,
        in_shardings=(param_shardings, batch_sharding, batch_sharding),
        out_shardings=(param_shardings, NamedSharding(mesh, P())),
        donate_argnums=(0,))
    return step, params, example
