"""Trace file IO.

A trace is a TSV with one job per line and 12 fields:
job_type, command, working_directory, num_steps_arg, needs_data_dir,
total_steps, scale_factor, mode, priority_weight, SLO, duration,
arrival_time (reference: scheduler/utils.py:1446-1497). SLO < 0 means none.
"""
from __future__ import annotations

from typing import List, Tuple

from .job import Job


def parse_trace(trace_file: str) -> Tuple[List[Job], List[float]]:
    jobs: List[Job] = []
    arrival_times: List[float] = []
    with open(trace_file) as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            fields = line.split("\t")
            if len(fields) != 12:
                raise ValueError(f"expected 12 trace fields, got {len(fields)}: {line!r}")
            (job_type, command, working_directory, num_steps_arg, needs_data_dir,
             total_steps, scale_factor, mode, priority_weight, slo, duration,
             arrival_time) = fields
            if int(scale_factor) < 1:
                raise ValueError(f"scale_factor must be >= 1: {line!r}")
            jobs.append(Job(
                job_id=None,
                job_type=job_type,
                command=command,
                working_directory=working_directory,
                num_steps_arg=num_steps_arg,
                needs_data_dir=bool(int(needs_data_dir)),
                total_steps=int(total_steps),
                duration=duration,
                scale_factor=int(scale_factor),
                mode=mode,
                priority_weight=float(priority_weight),
                SLO=float(slo),
            ))
            arrival_times.append(float(arrival_time))
    return jobs, arrival_times


def job_to_trace_line(job: Job, arrival_time: float) -> str:
    slo = -1.0 if job.SLO is None else job.SLO
    fields = [
        job.job_type, job.command, job.working_directory, job.num_steps_arg,
        str(int(job.needs_data_dir)), str(job.total_steps),
        str(job.scale_factor), job.mode, str(int(job.priority_weight)),
        f"{slo:f}", str(job.duration), f"{arrival_time:f}",
    ]
    return "\t".join(fields)
