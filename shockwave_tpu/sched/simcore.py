"""Vectorized simulator hot-loop passes.

The discrete-event simulator's per-round cost is dominated by per-job
Python loops: the priority recompute, the round-queue build + tuple
sort, the per-round schedule-membership bookkeeping, and the per-worker
micro-task completion staging (profile evidence in EXPERIMENTS.md
"Fleet-scale simulation"). This module batches those passes into numpy
— the same recipe `shockwave/milp.py` applied to MILP assembly — while
the scheduler retains the original scalar code as the reference oracle
(`SchedulerConfig.vectorized_sim=False` or ``SWTPU_SCALAR_SIM=1``).

Bit-identity contract: every function here performs the *same IEEE-754
operations in the same order* as its scalar counterpart in
``scheduler.py`` — elementwise numpy float64 division/multiplication is
identical to CPython float arithmetic, ``np.lexsort`` over negated keys
reproduces the stable ``sorted(..., reverse=True)`` tuple order, and
integer bookkeeping is exact. The regression suite
(tests/test_sim_vectorized.py) pins scalar-vs-vectorized equality for
every policy in reproduce/pickles plus the serving mixed trace, and the
canonical 120-job replays are pinned against the committed pickles.

Heterogeneous clusters: every pass here iterates
``sched.workers.worker_types`` and keys its per-type state
(priorities, allocations, worker-type time, completion staging) by
worker type, so a mixed multi-generation ``cluster_spec`` (e.g.
``{"v5-lite": 16, "v5": 8}``) runs through the same code paths as a
single-generation one — there is no single-type fast path to diverge
from the scalar reference. tests/test_oracle.py pins scalar-vs-
vectorized parity on a mixed two-generation spec.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from ..core.adaptation import gns_bs_at
from ..core.job import JobIdPair

_EMPTY: dict = {}


def update_priorities(sched, inflight_job: dict, inflight_worker: dict) -> None:
    """Vectorized body of ``Scheduler._update_priorities``'s per-job
    loop (non-packing policies: scalar throughput entries only).

    priority = alloc / (job_time / worker_time), with the scalar path's
    zero-priority guards (job absent from the allocation, zero
    allocation, zero throughput) and the newly-added-job boost
    (alloc * 1e9 when the job has no received fraction yet).
    """
    acct = sched.acct
    alloc_map = sched._allocation
    throughputs = sched._throughputs
    no_inflight = not inflight_job  # simulation: always empty
    for wt in sched.workers.worker_types:
        prio_map = sched._priorities[wt]
        keys = list(prio_map)
        n = len(keys)
        if not n:
            continue
        worker_time = (acct.worker_type_time.get(wt, 0.0)
                       + inflight_worker.get(wt, 0.0))
        # One hash per (job, map) — each key is looked up once and the
        # resulting entry dicts are reused across the arrays below.
        alloc_entries = [alloc_map.get(k) for k in keys]
        jt_maps = [acct.job_time.get(k) for k in keys]
        in_alloc = np.fromiter((e is not None for e in alloc_entries),
                               dtype=bool, count=n)
        alloc = np.fromiter(
            (e[wt] if e is not None else 0.0 for e in alloc_entries),
            dtype=np.float64, count=n)
        tput = np.fromiter((throughputs[k][wt] for k in keys),
                           dtype=np.float64, count=n)
        has_jt = np.fromiter((m is not None and wt in m for m in jt_maps),
                             dtype=bool, count=n)
        if no_inflight:
            job_time = np.fromiter(
                (m[wt] if (m is not None and wt in m) else 0.0
                 for m in jt_maps), dtype=np.float64, count=n)
        else:
            job_time = np.fromiter(
                ((m[wt] + inflight_job.get(k, _EMPTY).get(wt, 0.0))
                 if (m is not None and wt in m) else 0.0
                 for k, m in zip(keys, jt_maps)),
                dtype=np.float64, count=n)
        fraction = np.zeros(n)
        if worker_time > 0:
            np.divide(job_time, worker_time, out=fraction, where=has_jt)
        # Newly added job (no received fraction yet): alloc * 1e9.
        out = alloc * 1e9
        np.divide(alloc, fraction, out=out, where=fraction > 0.0)
        out[~in_alloc | (alloc == 0.0) | (tput == 0.0)] = 0.0
        # tolist() yields python floats with the exact same bit
        # patterns; rebuilding the dict preserves key insertion order.
        sched._priorities[wt] = dict(zip(keys, out.tolist()))


def build_round_queue(sched, worker_types: Sequence[str]) -> list:
    """The scalar queue of ``_select_jobs_for_round`` — per worker type,
    jobs ordered by (priority, deficit, allocation) descending — built
    with one ``np.lexsort`` per worker type instead of n-tuple
    construction + comparison sort.

    ``np.lexsort`` is stable ascending on its last key first; sorting
    by the negated keys therefore reproduces
    ``sorted(entries, key=(p, d, a), reverse=True)`` exactly, including
    insertion-order preservation among fully tied entries (both sorts
    are stable; negation of IEEE doubles is exact).
    """
    queue: list = []
    for wt in worker_types:
        prio_map = sched._priorities[wt]
        keys = list(prio_map)
        n = len(keys)
        if not n:
            continue
        deficit_map = sched._deficits[wt]
        alloc_map = sched._allocation
        # values() iterates in the same insertion order as list(prio_map)
        # — zero per-key hashing for the priority column.
        p = np.fromiter(prio_map.values(), dtype=np.float64, count=n)
        d = np.fromiter((deficit_map[k] for k in keys),
                        dtype=np.float64, count=n)
        alloc_entries = [alloc_map.get(k) for k in keys]
        a = np.fromiter(
            (e.get(wt, 0.0) if e is not None else 0.0
             for e in alloc_entries), dtype=np.float64, count=n)
        order = np.lexsort((-a, -d, -p))
        queue.extend((keys[i], wt, p[i]) for i in order)
    return queue


def select_jobs_for_round(sched, worker_types: List[str],
                          reserved: Optional[Dict[str, int]] = None) -> dict:
    """Vectorized ``_select_jobs_for_round`` for policy-driven (non-
    shockwave) rounds: identical greedy consumption over the lexsorted
    queue. The shockwave branch stays scalar in the scheduler (it is
    planner-driven and O(selected), not O(jobs))."""
    reserved = reserved or {}
    scheduled: Dict[str, list] = {wt: [] for wt in worker_types}
    workers_left = {wt: sched.workers.cluster_spec[wt]
                    - reserved.get(wt, 0) for wt in worker_types}
    total_left = sum(workers_left.values())
    already: Set[JobIdPair] = set()
    policy_name = sched._policy.name
    is_fifo = policy_name.startswith("FIFO")
    jobs = sched.acct.jobs
    throughputs = sched._throughputs

    for job_id, wt, priority in build_round_queue(sched, worker_types):
        if total_left == 0:
            # No capacity anywhere: the scalar loop keeps scanning but
            # can assign nothing more (pure no-op iterations).
            break
        if workers_left[wt] == 0:
            continue
        if not job_id.is_pair():
            # Non-pair fast path (every policy outside packing mode):
            # members == (job_id,), so the set algebra collapses.
            if job_id in already:
                continue
            if throughputs[job_id][wt] <= 0:
                continue
            if is_fifo and priority <= 0.0:
                continue
            scale_factor = jobs[job_id].scale_factor
            if scale_factor > workers_left[wt]:
                if policy_name == "Isolated_plus":
                    break  # strict priority order
                continue
            workers_left[wt] -= scale_factor
            total_left -= scale_factor
            already.add(job_id)
            scheduled[wt].append((job_id, scale_factor))
            continue
        members = job_id.singletons()
        if any(m in already for m in members):
            continue
        tput = throughputs[job_id][wt]
        if tput[0] <= 0 or tput[1] <= 0:
            continue
        if is_fifo and priority <= 0.0:
            continue
        sfs = {jobs[m].scale_factor for m in members}
        if len(sfs) != 1:
            continue
        scale_factor = sfs.pop()
        if scale_factor > workers_left[wt]:
            if policy_name == "Isolated_plus":
                break  # strict priority order
            continue
        workers_left[wt] -= scale_factor
        total_left -= scale_factor
        already.update(members)
        scheduled[wt].append((job_id, scale_factor))
    return scheduled


def assign_workers(sched, scheduled: dict, worker_types: List[str],
                   serving_assignments=None):
    """``_assign_workers`` with a flat per-type chip pool and an index
    pointer instead of nested per-server list pops.

    The scalar ``_take_workers`` walks server lists popping chip ids —
    consuming skipped (sticky-reserved) chips permanently; a flattened
    pool with a monotone cursor visits the exact same chips in the
    exact same order, so the produced assignment sequence (and the
    OrderedDict insertion order consumers rely on) is identical.
    """
    import collections
    new_assignments = collections.OrderedDict(serving_assignments or ())
    reserved_chips = {w for ids in new_assignments.values() for w in ids}
    current = sched.rounds.current_assignments
    id_to_type = sched.workers.id_to_type
    prev_types = {job_id: id_to_type[ids[0]]
                  for job_id, ids in current.items()}
    dead = sched.workers.dead
    is_shockwave = sched._policy.name == "shockwave"
    alloc_map = sched._allocation

    for wt in worker_types:
        scheduled[wt].sort(key=lambda x: x[1], reverse=True)
        entries = scheduled[wt]
        if not entries:
            continue
        if reserved_chips:
            pool = [w for s in sched.workers.type_to_server_ids[wt]
                    for w in s if w not in reserved_chips]
        else:
            pool = [w for s in sched.workers.type_to_server_ids[wt]
                    for w in s]
        assigned = set(reserved_chips)
        pos = 0
        npool = len(pool)
        for current_sf in sorted({sf for _, sf in entries}, reverse=True):
            # Sticky pass: keep jobs on their previous workers — unless
            # any of those chips has since been marked dead.
            for job_id, sf in entries:
                if sf != current_sf or prev_types.get(job_id) != wt:
                    continue
                prev_ids = current[job_id]
                if any(w in dead for w in prev_ids):
                    continue
                if all(w not in assigned for w in prev_ids):
                    new_assignments[job_id] = prev_ids
                    assigned.update(prev_ids)
            # Fill pass.
            for job_id, sf in entries:
                if sf != current_sf or job_id in new_assignments:
                    continue
                if not is_shockwave and job_id not in alloc_map:
                    continue
                taken = []
                while len(taken) < sf and pos < npool:
                    w = pool[pos]
                    pos += 1
                    if w not in assigned:
                        taken.append(w)
                        assigned.add(w)
                if len(taken) < sf:
                    raise RuntimeError(
                        f"could not assign workers to {job_id}")
                new_assignments[job_id] = tuple(taken)
                if is_shockwave:
                    alloc_map.setdefault(job_id, {})[wt] = -1.0

    # Invariant: each chip assigned at most once.
    seen: Dict[int, int] = {}
    for ids in new_assignments.values():
        for w in ids:
            seen[w] = seen.get(w, 0) + 1
            if seen[w] > 1:
                raise RuntimeError(f"worker {w} multiply assigned")

    if sched._simulate:
        now = sched.get_current_timestamp()
        latest = sched.acct.latest_timestamps
        running = sched._running_jobs
        for job_id in new_assignments:
            for m in job_id.singletons():
                latest[m] = now
                running.add(m)
    return new_assignments


def record_round(sched, int_assignments: Dict) -> None:
    """``_record_round`` with O(1) schedule membership: the scalar path
    re-scans the round's key set (including packed-pair tuple keys) for
    every active job; one flattened id set answers all of them."""
    sched.rounds.per_round_schedule.append(int_assignments)
    sched.rounds.jobs_in_round.append(len(sched.acct.jobs))
    in_round: Set[int] = set()
    for k in int_assignments:
        if isinstance(k, tuple):
            in_round.update(k)
        else:
            in_round.add(k)
    num_scheduled = sched.rounds.num_scheduled_rounds
    num_queued = sched.rounds.num_queued_rounds
    for job_id in sched.acct.jobs:
        int_id = job_id.integer_job_id()
        if int_id in in_round:
            num_scheduled[int_id] += 1
        else:
            num_queued[int_id] += 1
    sched._emit("round_recorded", round=sched.rounds.num_completed_rounds,
                assignments=[
                    [list(k) if isinstance(k, tuple) else k, list(ids)]
                    for k, ids in int_assignments.items()])


def complete_microtask_batch(sched, job_id, worker_ids: Sequence[int],
                             per_worker_steps: Sequence[Sequence[int]],
                             all_execution_times: Sequence[float]) -> None:
    """One simulated micro-task completion, batched.

    Equivalent to the scalar drain's ``scale_factor`` separate
    ``done_callback`` calls: the per-call staging protocol
    (``_in_progress_updates`` append + length check) is skipped, the
    per-(member, worker) run-time accumulation and the final
    aggregation (``_finalize_microtask``) are performed identically.
    Falls back to the per-call path when the recorded assignment width
    disagrees with the dispatch (the scalar path would then finalize
    per call).
    """
    recorded = sched.rounds.current_assignments.get(job_id)
    if recorded is None or len(recorded) != len(worker_ids):
        for i, worker_id in enumerate(worker_ids):
            sched.done_callback(job_id, worker_id,
                                list(per_worker_steps[i]),
                                list(all_execution_times))
        return
    a = sched.acct
    run_time = float(np.max(all_execution_times))
    members = job_id.singletons()
    for m in members:
        rtpw = a.run_time_per_worker.setdefault(m, {})
        for w in worker_ids:
            rtpw[w] = rtpw.get(w, 0.0) + run_time
    if not any(m in a.jobs for m in members):
        return
    # The scalar path's finalizing call is the last dispatched worker's.
    worker_type = sched.workers.id_to_type[worker_ids[-1]]
    scale_factor = len(recorded)
    updates = sorted(
        ((w, list(steps), [float(t) for t in all_execution_times])
         for w, steps in zip(worker_ids, per_worker_steps)),
        key=lambda u: u[0])
    sched._in_progress_updates[job_id] = []
    sched._finalize_microtask(job_id, worker_type, scale_factor, updates)


def projected_unfairness(sched, now: float,
                         cf: Optional[float] = None) -> float:
    """Worst elapsed-so-far finish-time-fairness lower bound over the
    ACTIVE (non-serving) jobs: elapsed / (exclusive * static contention)
    — the what-if plane's starvation signal for jobs that have not
    completed within a rollout horizon (completed jobs carry their real
    rho, scored in the plane). `cf` pins the contention factor: an
    admission decision must compare its with/without legs under ONE
    reference (the candidate-inclusive trace count), not each twin's
    own drifting count. One vectorized pass; a K-sample admission
    decision scores this for every candidate rollout, so the per-job
    Python loop would sit on the decision's critical path at fleet
    scale."""
    profiles = sched._profiles
    num_chips = len(sched.workers.worker_ids)
    if not profiles or not num_chips:
        return 0.0
    serving = sched._serving_job_ids
    starts = sched.acct.start_timestamps
    # _profile_for: honors the admission-order remap; None for serving
    # trace lines (no epoch structure) and out-of-range ids.
    entries = [(j, sched._profile_for(j.integer_job_id()))
               for j in sched.acct.jobs if j not in serving]
    rows = [(starts[j], sum(p["duration_every_epoch"]))
            for j, p in entries if p is not None]
    if not rows:
        return 0.0
    start = np.fromiter((r[0] for r in rows), dtype=np.float64,
                        count=len(rows))
    exclusive = np.fromiter((r[1] for r in rows), dtype=np.float64,
                            count=len(rows))
    if cf is None:
        cf = max(1.0, sched._num_jobs_in_trace / num_chips)
    valid = exclusive > 0.0
    if not valid.any():
        return 0.0
    rho = (now - start[valid]) / (exclusive[valid] * cf)
    return float(np.max(rho))


def simulate_gns(sched, job_id) -> None:
    """O(1)-per-epoch GNS oracle: same decision as the scalar
    ``_simulate_gns`` (which rebuilds the whole per-epoch schedule every
    round) via ``adaptation.gns_bs_at`` point queries."""
    job = sched.acct.jobs[job_id]
    model, bs = job.model, job.batch_size
    bs0 = sched.acct.original_bs[job_id]
    epoch = sched._current_epoch(job_id)
    num_epochs = max(760, epoch + 2)
    if (gns_bs_at(model, bs0, num_epochs, job.scale_factor, epoch + 1) > bs
            or gns_bs_at(model, bs0, num_epochs, job.scale_factor,
                         epoch) > bs):
        if not sched._at_max_bs(model, bs):
            sched._bs_flags[job_id]["big_bs"] = True
