"""Trace file IO, including the serving job class.

A trace is a TSV with one job per line and 12 fields:
job_type, command, working_directory, num_steps_arg, needs_data_dir,
total_steps, scale_factor, mode, priority_weight, SLO, duration,
arrival_time (reference: scheduler/utils.py:1446-1497). SLO < 0 means none.

Serving jobs (the latency-SLO inference class, shockwave_tpu/serving/)
ride the same 12 fields with reinterpreted semantics:

- ``mode`` is ``"serving"`` (SERVING_MODE);
- ``SLO`` is the p99 latency target in SECONDS (not the training class's
  completion-deadline multiplier);
- ``duration`` is the service lifetime in seconds — the service retires
  when it elapses, there is no step budget to finish;
- ``command`` is the runnable replica invocation
  (workloads/serving/serve.py) and doubles as the carrier of the
  service's load-curve and capacity parameters (`serving_command` /
  `parse_serving_command` below), so a trace line is self-contained and
  the identical parameters drive the simulator's analytic model and the
  physical replica process.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from .job import Job

#: Job.mode value marking the latency-SLO serving class.
SERVING_MODE = "serving"

#: Model token of serving job types ("Serving (batch size N)").
SERVING_MODEL = "Serving"

#: Flags of `serving_command` that carry float values.
_SERVING_FLOAT_FLAGS = frozenset({
    "base_rps", "peak_rps", "period_s", "phase_s", "spike_mult",
    "spike_duration_s", "decode_tokens_per_s",
})
#: Flags of `serving_command` that carry int values.
_SERVING_INT_FLAGS = frozenset({
    "tokens_per_request", "max_replicas", "spike_seed", "num_spikes",
    "batch_size", "replica_of", "replica_index",
})


def parse_trace(trace_file: str) -> Tuple[List[Job], List[float]]:
    jobs: List[Job] = []
    arrival_times: List[float] = []
    with open(trace_file) as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            fields = line.split("\t")
            if len(fields) != 12:
                raise ValueError(f"expected 12 trace fields, got {len(fields)}: {line!r}")
            (job_type, command, working_directory, num_steps_arg, needs_data_dir,
             total_steps, scale_factor, mode, priority_weight, slo, duration,
             arrival_time) = fields
            if int(scale_factor) < 1:
                raise ValueError(f"scale_factor must be >= 1: {line!r}")
            jobs.append(Job(
                job_id=None,
                job_type=job_type,
                command=command,
                working_directory=working_directory,
                num_steps_arg=num_steps_arg,
                needs_data_dir=bool(int(needs_data_dir)),
                total_steps=int(total_steps),
                duration=duration,
                scale_factor=int(scale_factor),
                mode=mode,
                priority_weight=float(priority_weight),
                SLO=float(slo),
            ))
            arrival_times.append(float(arrival_time))
    return jobs, arrival_times


def is_serving_job(job: Job) -> bool:
    return job.mode == SERVING_MODE


def serving_command(base_rps: float, peak_rps: float, period_s: float,
                    tokens_per_request: int, decode_tokens_per_s: float,
                    max_replicas: int, phase_s: float = 0.0,
                    spikes: Sequence[Tuple[float, float, float]] = (),
                    spike_seed: Optional[int] = None, num_spikes: int = 0,
                    spike_mult: float = 10.0,
                    spike_duration_s: float = 1800.0,
                    batch_size: int = 1) -> str:
    """The runnable replica command, carrying the service parameters.

    `spikes` are explicit (start_offset_s, duration_s, multiplier)
    triples encoded as ``--spike_at start:dur:mult``; alternatively a
    `spike_seed` + `num_spikes` draws them deterministically at parse
    time (serving/load.seeded_spikes)."""
    parts = [
        "python3 serve.py",
        f"--batch_size {batch_size}",
        f"--base_rps {base_rps:g}", f"--peak_rps {peak_rps:g}",
        f"--period_s {period_s:g}", f"--phase_s {phase_s:g}",
        f"--tokens_per_request {tokens_per_request}",
        f"--decode_tokens_per_s {decode_tokens_per_s:g}",
        f"--max_replicas {max_replicas}",
    ]
    for start, dur, mult in spikes:
        parts.append(f"--spike_at {start:g}:{dur:g}:{mult:g}")
    if spike_seed is not None and num_spikes > 0:
        parts.append(f"--spike_seed {spike_seed}")
        parts.append(f"--num_spikes {num_spikes}")
        parts.append(f"--spike_mult {spike_mult:g}")
        parts.append(f"--spike_duration_s {spike_duration_s:g}")
    return " ".join(parts)


def parse_serving_command(command: str) -> Dict:
    """Inverse of `serving_command`: the service parameter dict.

    Tolerates extra flags (``--num_steps`` appended by the dispatcher,
    replica markers) — unknown flags are kept as strings so callers can
    inspect them. Raises ValueError on a malformed ``--spike_at``."""
    tokens = command.split()
    params: Dict = {}
    spikes: List[Tuple[float, float, float]] = []
    i = 0
    while i < len(tokens):
        token = tokens[i]
        if not token.startswith("--"):
            i += 1
            continue
        key = token[2:]
        value = tokens[i + 1] if i + 1 < len(tokens) else None
        if key == "spike_at":
            try:
                start, dur, mult = (float(x) for x in value.split(":"))
            except (AttributeError, ValueError):
                raise ValueError(
                    f"malformed --spike_at {value!r} (want start:dur:mult)"
                ) from None
            spikes.append((start, dur, mult))
        elif key in _SERVING_FLOAT_FLAGS:
            params[key] = float(value)
        elif key in _SERVING_INT_FLAGS:
            params[key] = int(value)
        else:
            params[key] = value
        i += 2
    if spikes:
        params["spikes"] = tuple(spikes)
    return params


def serving_service_rate(command: str) -> float:
    """Per-replica service rate mu in requests/s, from the command's
    decode rate and request length. Falls back to 1.0 when the command
    does not carry the parameters (hand-written traces)."""
    params = parse_serving_command(command)
    tokens_per_request = params.get("tokens_per_request", 0)
    decode = params.get("decode_tokens_per_s", 0.0)
    if tokens_per_request and decode > 0:
        return decode / tokens_per_request
    return 1.0


def make_serving_job(base_rps: float, peak_rps: float, period_s: float,
                     lifetime_s: float, slo_p99_s: float,
                     tokens_per_request: int = 64,
                     decode_tokens_per_s: float = 1600.0,
                     max_replicas: int = 8, batch_size: int = 1,
                     **command_kwargs) -> Job:
    """One serving-service trace job (the anchor the scheduler's serving
    tier expands into autoscaled replica jobs)."""
    return Job(
        job_id=None,
        job_type=f"{SERVING_MODEL} (batch size {batch_size})",
        command=serving_command(
            base_rps=base_rps, peak_rps=peak_rps, period_s=period_s,
            tokens_per_request=tokens_per_request,
            decode_tokens_per_s=decode_tokens_per_s,
            max_replicas=max_replicas, batch_size=batch_size,
            **command_kwargs),
        working_directory="serving",
        num_steps_arg="--num_steps",
        needs_data_dir=False,
        total_steps=0,
        duration=lifetime_s,
        scale_factor=1,
        mode=SERVING_MODE,
        priority_weight=1.0,
        SLO=slo_p99_s,
    )


def job_to_trace_line(job: Job, arrival_time: float) -> str:
    slo = -1.0 if job.SLO is None else job.SLO
    fields = [
        job.job_type, job.command, job.working_directory, job.num_steps_arg,
        str(int(job.needs_data_dir)), str(job.total_steps),
        str(job.scale_factor), job.mode, str(int(job.priority_weight)),
        f"{slo:f}", str(job.duration), f"{arrival_time:f}",
    ]
    return "\t".join(fields)
