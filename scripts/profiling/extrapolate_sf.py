#!/usr/bin/env python3
"""Seed scale_factor > 1 oracle rows from measured scaling efficiencies.

The v5e oracle (data/v5e_throughputs.json) is measured on the one
attached chip, so it only has scale_factor = 1 rows; physical
scheduling of a gang job would start from the fabricated
DEFAULT_THROUGHPUT and converge only via online learning. Until a
multi-chip pod is available to measure directly, this script derives a
documented prior for each (job_type, sf) row:

    rate(sf) = rate(1) * sf * efficiency_ref(job_type, sf)

where efficiency_ref comes from the reference's committed multi-GPU
oracle (data/tacc_throughputs.json, a byte copy of
/root/reference/scheduler/tacc_throughputs.json) — its (job_type, sf)
rows are real measurements of DP synchronization cost per family and
batch size. TPU ICI all-reduce has higher bandwidth relative to compute
than the V100 PCIe/NCCL fabric those ratios were measured on, so the
prior is conservative; the scheduler's EMA throughput updates refine it
from the first real gang dispatch onward.

Estimated rows are recorded in __meta__.estimated_rows with their
provenance so they are never mistaken for measurements; existing rows
(measured) are never overwritten.

Usage:
    python scripts/profiling/extrapolate_sf.py \\
        --oracle data/v5e_throughputs.json --worker_type v5e
"""
import argparse
import datetime
import json
import os
import sys

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, REPO)

from shockwave_tpu.core.oracle import parse_job_type_tuple  # noqa: E402


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--oracle", default=os.path.join(
        REPO, "data", "v5e_throughputs.json"))
    p.add_argument("--worker_type", default="v5e")
    p.add_argument("--ratios", default=os.path.join(
        REPO, "data", "tacc_throughputs.json"),
        help="oracle whose (job_type, sf) rows provide scaling ratios")
    p.add_argument("--ratio_worker", default="v100")
    p.add_argument("--sfs", type=int, nargs="+", default=[2, 4, 8])
    args = p.parse_args()

    with open(args.ratios) as f:
        ref = json.load(f)[args.ratio_worker]
    eff = {}  # (family, sf) -> measured efficiency vs sf * rate(1)
    base_rate = {}
    for key_str, entry in ref.items():
        key = parse_job_type_tuple(key_str)
        if key and entry.get("null"):
            if key[1] == 1:
                base_rate[key[0]] = entry["null"]
    for key_str, entry in ref.items():
        key = parse_job_type_tuple(key_str)
        if (key and entry.get("null") and key[1] > 1
                and base_rate.get(key[0])):
            eff[key] = entry["null"] / (base_rate[key[0]] * key[1])

    with open(args.oracle) as f:
        oracle = json.load(f)
    rows = oracle[args.worker_type]
    added = {}
    for key_str in list(rows):
        key = parse_job_type_tuple(key_str)
        if key is None or key[1] != 1:
            continue
        rate1 = rows[key_str].get("null")
        if not rate1:
            continue
        for sf in args.sfs:
            new_key = str((key[0], sf))
            if new_key in rows:
                continue  # never overwrite a measured row
            e = eff.get((key[0], sf))
            if e is None:
                continue  # family has no reference scaling measurement
            rows[new_key] = {"null": round(rate1 * sf * e, 4)}
            added[new_key] = {"from_sf1": rate1,
                              "reference_efficiency": round(e, 4)}

    meta = oracle.setdefault("__meta__", {})
    est = meta.setdefault("estimated_rows", {}).setdefault(
        args.worker_type, {})
    est.update(added)
    meta.setdefault("estimated_rows_note", (
        "rate(sf) = measured_rate(1) * sf * reference_efficiency(job, sf); "
        "efficiencies from the reference's measured multi-GPU oracle "
        f"({os.path.relpath(args.ratios, REPO)}[{args.ratio_worker}]). "
        "Conservative prior for ICI; refined online by EMA updates."))
    meta["estimated_rows_updated_at"] = datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds")

    with open(args.oracle, "w") as f:
        json.dump(oracle, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"added {len(added)} estimated rows to "
          f"{args.oracle}[{args.worker_type}]")


if __name__ == "__main__":
    main()
