"""Dynamic Eisenberg-Gale scheduling MILP on scipy/HiGHS.

Plans a boolean job x round schedule over a future horizon maximizing
approximate Nash social welfare over per-job training *progress*, with a
makespan regularizer and finish-time-fairness (FTF) constraints
(reference: scheduler/shockwave.py:288-711). The reference encodes this
in cvxpy and solves with Gurobi; here the model is assembled as sparse
matrices for scipy.optimize.milp (HiGHS), with the same infeasibility
fallback chain: drop FTF constraints, boost utilities of rho-violating
jobs by ratio**lambda, re-solve, then re-rank rounds to front-load
high-priority jobs.

Model per job j (horizon R rounds, log-approximation bases B):
  x[j,r] in {0,1}   job scheduled in round r
  p[j] >= 0         planned progress in epochs
  w[j,b] >= 0       SOS2-ish cursor weights over the log bases
  z[j,b] in {0,1}   which (at most 2, adjacent) bases are active
  s[j] >= 0         remaining runtime after the plan

  p[j] * dur[j] <= round_duration * sum_r x[j,r]
  sum_b w[j,b] * base[b] = (progress[j] + p[j]) / epochs[j]
  sum_b w[j,b] = 1;  w[j,b] <= z[j,b];  sum_b z[j,b] <= 2
  z[j,l] + z[j,r] <= 1 for |l-r| >= 2           (adjacency)
  s[j] >= D[j] - p[j] * dur[j]                  (D = Dirichlet remaining)
  s[j] <= (rhomax * runavg[j] - T_next) * share (FTF; first attempt only)
  sum_j nworkers[j] * x[j,r] <= ngpus           (capacity per round)

  maximize sum_j prio[j] * (sum_b w[j,b]*log(base[b])) / (njobs*R) - k*max_j s[j]
"""
from __future__ import annotations

import logging
import math
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np
from scipy import sparse
from scipy.optimize import Bounds, LinearConstraint, milp

logger = logging.getLogger("shockwave_tpu.shockwave")


@dataclass
class MilpOptions:
    rel_gap: float = 1e-3
    timeout: float = 15.0
    rhomax: float = 1.0
    k: float = 1e-3
    lam: float = 12.0
    logapx_bases: Sequence[float] = (0.0, 0.2, 0.4, 0.6, 0.8, 1.0)
    logapx_origin: float = 1e-6
    # Per-solve wall budget bound, in round-durations. 0.5 keeps a hard
    # instance from stalling a PHYSICAL round loop; pure simulation can
    # raise it (config key "solver_budget_cap_rounds") — at 900 jobs the
    # single-threaded half-round budget is 6x less solver compute than
    # the reference's 15 s x 24 Gurobi threads and measurably starves
    # incumbent quality (gap 6.8e-2, no-incumbent greedy fallbacks).
    budget_cap_rounds: float = 0.5


@dataclass
class SolveStats:
    """Per-plan_schedule solve-quality telemetry (the reference bounds
    its solver with MIPGap/TimeLimit, configurations/tacc_32gpus.json,
    but never records what the solver actually achieved; scale runs
    need that to prove the fallback chain stays cold).

    `path` is the outcome of the fallback chain:
      ftf            — first attempt (with FTF constraints) solved
      relaxed        — FTF infeasible/timed out; relaxed solve succeeded
      relaxed_retry  — relaxed solve needed the long-budget retry
      greedy         — every MILP failed; greedy fallback schedule
    """
    round_index: int
    njobs: int
    path: str
    wall_s: float
    status: Optional[int] = None       # scipy milp status of final solve
    mip_gap: Optional[float] = None    # achieved relative gap, if exposed
    ftf_infeasible: bool = False       # FTF caps provably infeasible
    # Solver EXCEPTION (not mere infeasibility) swallowed by the guard
    # around _solve: the round loop degraded to the next fallback arm
    # instead of dying. "<ExcType>: <msg>" of the last raise, else None.
    error: Optional[str] = None


def finish_time_momentumed_average(series, round_index, momentum=0.9) -> float:
    """Running average of finish-time estimates weighted by how long each
    estimate was current, blended with the latest estimate
    (reference: shockwave.py:480-501)."""
    assert len(series) > 0
    rounds = [r for r, _ in series] + [round_index]
    windows = np.diff(rounds)
    if windows.max(initial=0) == 0:
        probs = [1.0]
    else:
        probs = (windows / windows.sum()).tolist()
    values = [v for _, v in series]
    running = sum(p * v for p, v in zip(probs, values))
    return momentum * running + (1.0 - momentum) * values[-1]


class _Layout:
    """Variable indexing for the MILP."""

    def __init__(self, njobs: int, nrounds: int, nbases: int):
        self.R, self.B = nrounds, nbases
        self.stride = nrounds + 1 + 2 * nbases + 1
        self.njobs = njobs
        self.n = njobs * self.stride + 1  # + global t

    def x(self, j, r): return j * self.stride + r
    def p(self, j): return j * self.stride + self.R
    def w(self, j, b): return j * self.stride + self.R + 1 + b
    def z(self, j, b): return j * self.stride + self.R + 1 + self.B + b
    def s(self, j): return j * self.stride + self.R + 1 + 2 * self.B
    @property
    def t(self): return self.n - 1


class _FailedSolve:
    """Result shim for a solver that RAISED (scipy/HiGHS internal error,
    numerical blow-up, ...): looks like a failed `milp` result so the
    existing fallback chain (relax -> greedy) handles it, and carries
    the exception text into SolveStats.error."""

    x = None
    status = None
    mip_gap = None

    def __init__(self, error: str):
        self.error = error


def _solve(c, A_ub, b_ub, A_eq, b_eq, integrality, ub, opts: MilpOptions,
           timeout_scale: float = 1.0):
    constraints = []
    if len(b_ub):
        constraints.append(LinearConstraint(A_ub, -np.inf, b_ub))
    if len(b_eq):
        constraints.append(LinearConstraint(A_eq, b_eq, b_eq))
    try:
        res = milp(
            c, constraints=constraints, integrality=integrality,
            bounds=Bounds(np.zeros_like(ub), ub),
            options={"time_limit": opts.timeout * timeout_scale,
                     "mip_rel_gap": opts.rel_gap, "presolve": True},
        )
    except Exception as e:  # noqa: BLE001 - a solver crash must not kill
        # the round loop: degrade through the fallback chain instead.
        logger.warning("MILP solver raised %s: %s; treating as failed "
                       "solve", type(e).__name__, e)
        return _FailedSolve(f"{type(e).__name__}: {e}")
    return res


def plan_schedule(jobs, round_index: int, future_nrounds: int,
                  round_duration: float, ngpus: int, share_series: List[list],
                  opts: MilpOptions,
                  stats_out: Optional[list] = None) -> np.ndarray:
    """Returns a boolean (njobs x future_nrounds) schedule matrix.

    With `stats_out`, appends one SolveStats record describing which
    arm of the fallback chain produced the schedule and the solver's
    achieved quality (status / MIP gap / wall time)."""
    import time as _time
    # Solve wall time is telemetry riding a journaled SolveStats record:
    # replay reads the journaled outcome, never re-times the solve.
    _t0 = _time.monotonic()  # swtpu-check: ignore[determinism]

    def _record(path, res=None, ftf_infeasible=False):
        if stats_out is not None:
            gap = getattr(res, "mip_gap", None) if res is not None else None
            stats_out.append(SolveStats(
                round_index=round_index, njobs=len(jobs), path=path,
                wall_s=round(_time.monotonic() - _t0, 3),  # swtpu-check: ignore[determinism]
                status=getattr(res, "status", None) if res is not None
                else None,
                mip_gap=None if gap is None else float(gap),
                ftf_infeasible=ftf_infeasible,
                error=getattr(res, "error", None) if res is not None
                else None))
    njobs = len(jobs)
    bases = list(opts.logapx_bases)
    assert bases[0] == 0.0
    base_logs = [math.log(opts.logapx_origin)] + [math.log(b) for b in bases[1:]]
    L = _Layout(njobs, future_nrounds, len(bases))

    nworkers = [job.nworkers for job in jobs]
    durations = [job.interpolated_epoch_duration() for job in jobs]
    dirichlet = [job.dirichlet_posterior_remaining_runtime() for job in jobs]
    progress = [job.epoch_progress for job in jobs]
    epochs = [job.epochs for job in jobs]

    future_share = min(1.0, ngpus / njobs)
    next_sched_time = round_duration * (round_index + future_nrounds)
    runavg = [finish_time_momentumed_average(share_series[j], round_index)
              for j in range(njobs)]
    ftf_caps = [(opts.rhomax * runavg[j] - next_sched_time) * future_share
                for j in range(njobs)]

    def assemble(priorities, with_ftf: bool):
        rows_ub, cols_ub, vals_ub, b_ub = [], [], [], []
        rows_eq, cols_eq, vals_eq, b_eq = [], [], [], []

        def add_ub(entries, rhs):
            r = len(b_ub)
            for col, val in entries:
                rows_ub.append(r); cols_ub.append(col); vals_ub.append(val)
            b_ub.append(rhs)

        def add_eq(entries, rhs):
            r = len(b_eq)
            for col, val in entries:
                rows_eq.append(r); cols_eq.append(col); vals_eq.append(val)
            b_eq.append(rhs)

        # Capacity per round.
        for r in range(future_nrounds):
            add_ub([(L.x(j, r), nworkers[j]) for j in range(njobs)], ngpus)

        for j in range(njobs):
            # Planned runtime bounded by scheduled rounds.
            add_ub([(L.p(j), durations[j])]
                   + [(L.x(j, r), -round_duration) for r in range(future_nrounds)], 0.0)
            # Log approximation cursor.
            add_eq([(L.w(j, b), bases[b]) for b in range(L.B)]
                   + [(L.p(j), -1.0 / epochs[j])], progress[j] / epochs[j])
            add_eq([(L.w(j, b), 1.0) for b in range(L.B)], 1.0)
            for b in range(L.B):
                add_ub([(L.w(j, b), 1.0), (L.z(j, b), -1.0)], 0.0)
            add_ub([(L.z(j, b), 1.0) for b in range(L.B)], 2.0)
            for lo in range(L.B - 2):
                for hi in range(lo + 2, L.B):
                    add_ub([(L.z(j, lo), 1.0), (L.z(j, hi), 1.0)], 1.0)
            # Remaining runtime after plan.
            add_ub([(L.s(j), -1.0), (L.p(j), -durations[j])], -dirichlet[j])
            # Makespan regularizer linkage.
            add_ub([(L.s(j), 1.0), (L.t, -1.0)], 0.0)
            if with_ftf:
                if ftf_caps[j] < 0:
                    return None  # provably infeasible
                add_ub([(L.s(j), 1.0)], ftf_caps[j])

        A_ub = sparse.coo_matrix((vals_ub, (rows_ub, cols_ub)),
                                 shape=(len(b_ub), L.n)).tocsr()
        A_eq = sparse.coo_matrix((vals_eq, (rows_eq, cols_eq)),
                                 shape=(len(b_eq), L.n)).tocsr()

        c = np.zeros(L.n)
        for j in range(njobs):
            for b in range(L.B):
                c[L.w(j, b)] = -priorities[j] * base_logs[b] / (njobs * future_nrounds)
        c[L.t] = opts.k

        integrality = np.zeros(L.n)
        ub = np.full(L.n, np.inf)
        for j in range(njobs):
            for r in range(future_nrounds):
                integrality[L.x(j, r)] = 1
                ub[L.x(j, r)] = 1
            for b in range(L.B):
                integrality[L.z(j, b)] = 1
                ub[L.z(j, b)] = 1
                ub[L.w(j, b)] = 1
        return c, A_ub, np.array(b_ub), A_eq, np.array(b_eq), integrality, ub

    # The reference gives Gurobi a flat 15 s on 24 threads
    # (configurations/*.json); single-threaded HiGHS needs the budget to
    # grow with the boolean count or large instances (hundreds of jobs)
    # time out with no incumbent at all. Canonical-scale problems
    # (<= 120 jobs) keep the reference budget exactly. Budgets stay
    # bounded by budget_cap_rounds round-durations per solve (2x that
    # for the one no-incumbent retry); at the 0.5 default — which
    # physical mode enforces (sched/scheduler.py clamps the config) — a
    # hard instance can never stall the round loop beyond half a round
    # per solve / one full round for the retry.
    timeout_scale = max(1.0, njobs / 120.0)
    cap = round_duration * opts.budget_cap_rounds
    solve_budget = min(opts.timeout * timeout_scale, cap)
    retry_budget = min(4.0 * solve_budget, 2.0 * cap)
    scale = solve_budget / opts.timeout

    # -- first attempt: with FTF constraints ------------------------------
    ones = [1.0] * njobs
    model = assemble(ones, with_ftf=True)
    res = None
    if model is not None:
        res = _solve(*model, opts, scale)
    if model is not None and res.x is not None and res.status in (0, 1):
        x = _extract(res.x, L, njobs, future_nrounds)
        _record("ftf", res)
        return x

    # -- fallback: relax FTF, boost violating jobs' utilities -------------
    if res is not None and getattr(res, "error", None):
        logger.info("FTF solve raised (%s) at round %d; relaxing",
                    res.error, round_index)
    elif res is not None and res.x is None and res.status == 1:
        logger.info("FTF solve timed out with no incumbent at round %d; "
                    "relaxing", round_index)
    else:
        logger.info("FTF constraints infeasible at round %d; relaxing",
                    round_index)
    ftf_infeasible = model is None
    priorities = _relaxation_priorities(
        jobs, dirichlet, runavg, round_index, round_duration, future_share,
        opts.rhomax, opts.lam)
    model = assemble(priorities, with_ftf=False)
    res = _solve(*model, opts, scale)
    retried = False
    if res.x is None and res.status == 1:
        # Timed out before finding any incumbent: one longer attempt is
        # much better than degrading to the greedy schedule.
        logger.info("relaxed MILP hit its time limit; retrying at %.0fs",
                    retry_budget)
        res = _solve(*model, opts, retry_budget / opts.timeout)
        retried = True
    if res.x is None:
        logger.warning("relaxed MILP failed (%s); greedy fallback", res.status)
        _record("greedy", res, ftf_infeasible)
        return _greedy_fallback(jobs, future_nrounds, ngpus, dirichlet)
    x = _extract(res.x, L, njobs, future_nrounds)
    _record("relaxed_retry" if retried else "relaxed", res, ftf_infeasible)
    return _rank_in_schedule(x, priorities, nworkers, ngpus, opts,
                             time_limit=solve_budget)


def _extract(xvec, L, njobs, nrounds) -> np.ndarray:
    out = np.zeros((njobs, nrounds), dtype=bool)
    for j in range(njobs):
        for r in range(nrounds):
            out[j, r] = round(xvec[L.x(j, r)]) == 1
    return out


def _relaxation_priorities(jobs, dirichlet, runavg, round_index,
                           round_duration, future_share, rhomax, lam):
    """Priority = projected-rho**lambda for jobs violating rhomax
    (reference: shockwave.py:830-911)."""
    PRIORITY_M = 1e2
    priorities = []
    round_time = round_duration * round_index
    for j, job in enumerate(jobs):
        job.calibrate_profiled_epoch_duration()
        remaining = dirichlet[j]
        projected_finish = round_time + remaining / future_share
        # Guarded divide: a degenerate zero fair-share finish average
        # (sub-epoch jobs, metadata.py) must not crash the solve. No
        # cap: the pinned canonical replay ranks by astronomically
        # large priorities for near-done jobs, and capping would
        # reorder those ties.
        ratio = projected_finish / max(runavg[j], 1e-6)
        if ratio > rhomax:
            power = PRIORITY_M if remaining < round_duration else lam
            try:
                priority = ratio ** power
            except OverflowError:
                # Degenerate runavg (sub-epoch jobs) can push the ratio
                # past float range at power 100.
                priority = 1e300
            priorities.append(priority)
        else:
            priorities.append(1.0)
    # Only RELATIVE priorities matter — they are NSW objective weights
    # (scale-invariant trade-offs) and rank keys — but their absolute
    # magnitude reaches HiGHS as objective coefficients, and ratio**100
    # boosts (up to the 1e300 overflow guard) make HiGHS return
    # "model_status Unknown" instantly, silently degrading every such
    # re-solve to the greedy fallback schedule (found by the round-5
    # solve telemetry: 12/16 solves on the 12-job fidelity trace).
    # Normalizing the maximum to 1e6 preserves the exact ranking and
    # relative weighting while keeping coefficients in HiGHS's
    # comfortable range.
    top = max(priorities)
    if top > 1e6:
        scale = 1e6 / top
        priorities = [p * scale for p in priorities]
    return priorities


def _rank_in_schedule(x: np.ndarray, priorities, nworkers, ngpus,
                      opts: MilpOptions,
                      time_limit: Optional[float] = None) -> np.ndarray:
    """Second MILP: keep each job's number of scheduled rounds but permute
    rounds so high-priority jobs run earlier (reference: shockwave.py:714-793).
    `time_limit` inherits the (scaled, round-bounded) budget of the main
    solve — this model has the same njobs x nrounds boolean count."""
    njobs, nrounds = x.shape
    counts = x.sum(axis=1)
    if not np.any(counts > 0):
        return x

    n = njobs * nrounds
    rows_ub, cols_ub, vals_ub, b_ub = [], [], [], []
    rows_eq, cols_eq, vals_eq, b_eq = [], [], [], []
    for r in range(nrounds):
        row = len(b_ub)
        for j in range(njobs):
            rows_ub.append(row); cols_ub.append(j * nrounds + r)
            vals_ub.append(nworkers[j])
        b_ub.append(ngpus)
    for j in range(njobs):
        row = len(b_eq)
        for r in range(nrounds):
            rows_eq.append(row); cols_eq.append(j * nrounds + r); vals_eq.append(1.0)
        b_eq.append(float(counts[j]))

    c = np.zeros(n)
    for j in range(njobs):
        if counts[j] > 0:
            for r in range(nrounds):
                c[j * nrounds + r] = priorities[j] * r / counts[j]

    try:
        res = milp(
            c,
            constraints=[
                LinearConstraint(
                    sparse.coo_matrix((vals_ub, (rows_ub, cols_ub)), shape=(len(b_ub), n)).tocsr(),
                    -np.inf, np.array(b_ub)),
                LinearConstraint(
                    sparse.coo_matrix((vals_eq, (rows_eq, cols_eq)), shape=(len(b_eq), n)).tocsr(),
                    np.array(b_eq), np.array(b_eq)),
            ],
            integrality=np.ones(n),
            bounds=Bounds(np.zeros(n), np.ones(n)),
            options={"time_limit": time_limit or opts.timeout,
                     "mip_rel_gap": opts.rel_gap, "presolve": True},
        )
    except Exception as e:  # noqa: BLE001 - ranking is an optimization;
        # the unranked schedule is valid, so never die for it.
        logger.warning("rank-in-schedule MILP raised %s: %s; keeping "
                       "unranked schedule", type(e).__name__, e)
        return x
    if res.x is None:
        logger.warning("rank-in-schedule MILP failed (%s); "
                       "keeping unranked schedule", res.status)
        return x
    return np.round(res.x.reshape((njobs, nrounds))).astype(bool)


def _greedy_fallback(jobs, nrounds, ngpus, dirichlet) -> np.ndarray:
    """Last-resort heuristic: longest remaining runtime first, every round."""
    njobs = len(jobs)
    order = sorted(range(njobs), key=lambda j: -dirichlet[j])
    x = np.zeros((njobs, nrounds), dtype=bool)
    for r in range(nrounds):
        free = ngpus
        for j in order:
            if jobs[j].nworkers <= free:
                x[j, r] = True
                free -= jobs[j].nworkers
            if free <= 0:
                break
    return x
