"""RPC resilience: per-call deadlines, bounded retry, circuit breakers.

Every control-plane RPC in this runtime used to block indefinitely on a
dead peer: a worker crash mid-round left the scheduler's dispatch (or a
training job's lease renewal) hung inside a deadline-less gRPC call, and
`_end_round` never regained liveness. This module is the single place
that policy lives:

- `RetryPolicy`: per-attempt deadline + bounded exponential backoff over
  a total wall-clock budget. Backoff applies FULL JITTER (uniform in
  [0, bounded-exponential]) so a healed partition does not turn every
  worker's queued retry into one synchronized storm at the scheduler;
  the jitter RNG is injectable (`call_with_retry(rng=...)` /
  `seed_backoff_jitter`) so seeded tests stay deterministic, and the
  deterministic upper bound is unchanged — return-time BOUNDS asserted
  by fault-injection tests still hold.
- `CircuitBreaker`: per-peer-channel failure counter. After
  `failure_threshold` consecutive transport failures the circuit opens
  and calls fail fast (`CircuitOpenError`) for `reset_timeout_s`; the
  first call after that window is a half-open probe whose outcome closes
  or re-opens the circuit. This keeps a dead worker from costing every
  scheduler round a full retry budget.
- `call_with_retry`: drives a gRPC callable under a policy + breaker.

Only transport-level status codes (UNAVAILABLE, DEADLINE_EXCEEDED) are
retried and counted against the breaker; any other status means the peer
is alive and the error is the caller's to handle.

Knobs are also readable from the environment (`SWTPU_RPC_*`) so the
job-side lease iterator — which has no config object — gets deadlines
too (see `policy_from_env`).
"""
from __future__ import annotations

import logging
import os
import random
import threading
import time
from dataclasses import dataclass, replace
from typing import Optional

import grpc

from ..obs import get_observability
from ..obs import names as obs_names

logger = logging.getLogger("shockwave_tpu.runtime")


def _method_label(method: str) -> str:
    """Bounded-cardinality metric label for a call site: the RPC name
    without the peer address (`worker 10.0.0.3:50061/RunJob` ->
    `RunJob`)."""
    return method.rsplit("/", 1)[-1]

#: Transport-level failures: the peer may be dead or unreachable. Anything
#: else (INVALID_ARGUMENT, INTERNAL, ...) proves the peer answered.
RETRYABLE_CODES = frozenset({
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
})


def is_retryable(error: Exception) -> bool:
    return (isinstance(error, grpc.RpcError)
            and error.code() in RETRYABLE_CODES)


class RpcUnavailableError(RuntimeError):
    """The peer stayed unreachable through the whole retry budget."""

    def __init__(self, method: str, attempts: int, last_code=None):
        super().__init__(
            f"{method} unreachable after {attempts} attempt(s)"
            f" (last status: {last_code})")
        self.method = method
        self.attempts = attempts
        self.last_code = last_code


class CircuitOpenError(RpcUnavailableError):
    """Failed fast: the peer's circuit breaker is open."""

    def __init__(self, method: str):
        RuntimeError.__init__(self, f"{method}: circuit open (peer presumed dead)")
        self.method = method
        self.attempts = 0
        self.last_code = None


@dataclass(frozen=True)
class RetryPolicy:
    #: gRPC deadline applied to every individual attempt.
    deadline_s: float = 20.0
    #: Wall-clock budget across all attempts (including backoff sleeps).
    total_budget_s: float = 60.0
    max_attempts: int = 4
    backoff_base_s: float = 0.25
    backoff_multiplier: float = 2.0
    backoff_max_s: float = 5.0

    def backoff_bound(self, attempt: int) -> float:
        """Deterministic bounded-exponential CEILING of the backoff
        before attempt N+1 (what budget math and test bounds use)."""
        return min(self.backoff_base_s * self.backoff_multiplier ** attempt,
                   self.backoff_max_s)

    def backoff(self, attempt: int,
                rng: Optional[random.Random] = None) -> float:
        """Backoff before attempt N+1: full jitter, uniform in
        (0, backoff_bound]. Without an RNG the deterministic ceiling is
        returned (legacy behavior; exact-bound tests use this)."""
        bound = self.backoff_bound(attempt)
        if rng is None:
            return bound
        # Floor at 1% of the bound: a zero draw would hammer the peer
        # with a same-instant retry, defeating the backoff entirely.
        return bound * max(rng.random(), 0.01)

    def one_shot(self) -> "RetryPolicy":
        """Same deadline, no retries — for liveness probes, where the
        monitor loop owns the retry cadence."""
        return replace(self, max_attempts=1, total_budget_s=self.deadline_s)


def _jitter_seed_from_env() -> Optional[int]:
    raw = os.environ.get("SWTPU_RPC_JITTER_SEED")
    if raw is None or raw == "":
        return None
    try:
        return int(raw)
    except ValueError:
        logger.warning("ignoring non-integer SWTPU_RPC_JITTER_SEED=%r "
                       "(backoff jitter falls back to OS entropy)", raw)
        return None


#: Process-wide jitter RNG for retry backoff. Seedable twice over: via
#: `seed_backoff_jitter()` (tests) or `SWTPU_RPC_JITTER_SEED` (the
#: dispatcher exports env into training processes, so a whole seeded
#: drill gets reproducible retry timing end to end).
_jitter_rng = random.Random(_jitter_seed_from_env())


def seed_backoff_jitter(seed: Optional[int]) -> None:
    """Re-seed the process-wide backoff-jitter RNG (None = OS entropy).
    Retry timing after this call is a pure function of the seed and the
    failure sequence — what seeded chaos drills assert against."""
    _jitter_rng.seed(seed)


def policy_from_env(default: RetryPolicy = RetryPolicy()) -> RetryPolicy:
    """RetryPolicy with `SWTPU_RPC_*` environment overrides (the
    dispatcher exports these into training processes, so the lease
    iterator inherits the cluster's RPC budget without a config file)."""

    def _f(name, fallback):
        raw = os.environ.get(name)
        if raw is None or raw == "":
            return fallback
        try:
            return float(raw)
        except ValueError:
            logger.warning("ignoring non-numeric %s=%r", name, raw)
            return fallback

    deadline_s = _f("SWTPU_RPC_DEADLINE_S", default.deadline_s)
    total_budget_s = _f("SWTPU_RPC_BUDGET_S", default.total_budget_s)
    # Invariant: the budget covers at least one full-deadline attempt
    # plus a retry window — otherwise a raised deadline (e.g. the
    # dispatcher's round-scaled export) would silently disable retries.
    total_budget_s = max(total_budget_s, 1.5 * deadline_s)
    return replace(
        default,
        deadline_s=deadline_s,
        total_budget_s=total_budget_s,
        max_attempts=int(_f("SWTPU_RPC_RETRIES", default.max_attempts)),
        backoff_base_s=_f("SWTPU_RPC_BACKOFF_S", default.backoff_base_s),
    )


class CircuitBreaker:
    """Consecutive-transport-failure circuit for one peer channel.

    closed -> (failure_threshold consecutive failures) -> open
    open   -> (reset_timeout_s elapsed) -> half-open: one probe call
    half-open -> success -> closed | failure -> open again
    """

    def __init__(self, failure_threshold: int = 3, reset_timeout_s: float = 10.0,
                 clock=time.monotonic):
        self.failure_threshold = max(int(failure_threshold), 1)
        self.reset_timeout_s = reset_timeout_s
        self._clock = clock
        self._lock = threading.Lock()
        self._consecutive_failures = 0
        self._opened_at: float | None = None
        self._half_open_probe_inflight = False

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if self._clock() - self._opened_at >= self.reset_timeout_s:
                return "half-open"
            return "open"

    def allow(self) -> bool:
        """Whether a call may proceed; in half-open, admits one probe."""
        with self._lock:
            if self._opened_at is None:
                return True
            if self._clock() - self._opened_at < self.reset_timeout_s:
                return False
            if self._half_open_probe_inflight:
                return False
            self._half_open_probe_inflight = True
        get_observability().inc(obs_names.BREAKER_TRANSITIONS_TOTAL,
                                to="half_open")
        return True

    def record_success(self) -> None:
        with self._lock:
            was_open = self._opened_at is not None
            self._consecutive_failures = 0
            self._opened_at = None
            self._half_open_probe_inflight = False
        if was_open:
            get_observability().inc(obs_names.BREAKER_TRANSITIONS_TOTAL,
                                    to="closed")

    def reset(self) -> None:
        """Forget all failure history — for ENDPOINT CHANGES, not for
        recoveries. A breaker's failure count is evidence about one
        peer incarnation; when the peer's address or leader epoch
        changes (scheduler failover, worker re-registration), carrying
        an open circuit forward would fail the first calls to the NEW,
        healthy incarnation fast — the stale-breaker pile-up that
        turned every failover into a round of spurious retirements."""
        with self._lock:
            was_open = self._opened_at is not None
            self._consecutive_failures = 0
            self._opened_at = None
            self._half_open_probe_inflight = False
        if was_open:
            get_observability().inc(obs_names.BREAKER_TRANSITIONS_TOTAL,
                                    to="closed")

    def record_failure(self) -> None:
        with self._lock:
            was_open = self._opened_at is not None
            # A failure with a probe in flight is a failed half-open
            # probe re-opening the circuit — a real open transition that
            # must be counted, or a breaker flapping open N times reads
            # as one open event.
            probe_failed = self._half_open_probe_inflight
            self._consecutive_failures += 1
            self._half_open_probe_inflight = False
            if (self._consecutive_failures >= self.failure_threshold
                    or self._opened_at is not None):
                # A half-open probe failure re-opens immediately; restart
                # the reset window from now.
                self._opened_at = self._clock()
            opened = (self._opened_at is not None
                      and (not was_open or probe_failed))
        if opened:
            get_observability().inc(obs_names.BREAKER_TRANSITIONS_TOTAL,
                                    to="open")


#: gRPC metadata key carrying the fenced leader epoch on every
#: scheduler->worker RPC (control-plane HA; see sched/ha.py).
EPOCH_METADATA_KEY = "swtpu-leader-epoch"

#: Fence verdicts (EpochFence.observe).
EPOCH_OK = "ok"
EPOCH_ADVANCED = "advanced"
EPOCH_STALE = "stale"


class EpochFence:
    """Monotonic leader-epoch tracker — the worker-side half of fenced
    failover. Every dispatch-effecting RPC carries the sender's epoch;
    the fence remembers the highest ever seen and classifies each
    arrival: ``ok`` (current leader), ``advanced`` (a new leader's
    first contact — the observer should re-resolve endpoints and reset
    breakers), ``stale`` (a deposed leader that has not noticed its
    fencing — the server MUST reject, or a wedged-but-alive old leader
    could double-dispatch work the new leader also placed)."""

    def __init__(self, initial: int = 0):
        self._lock = threading.Lock()
        self._epoch = int(initial)

    @property
    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def observe(self, epoch: int) -> str:
        epoch = int(epoch)
        with self._lock:
            if epoch < self._epoch:
                return EPOCH_STALE
            if epoch > self._epoch:
                self._epoch = epoch
                return EPOCH_ADVANCED
            return EPOCH_OK


def call_with_retry(callable_, request, *, method: str,
                    policy: RetryPolicy,
                    breaker: CircuitBreaker | None = None,
                    retryable=RETRYABLE_CODES,
                    clock=time.monotonic, sleep=time.sleep,
                    rng: Optional[random.Random] = None,
                    metadata=None):
    """Invoke a gRPC unary callable under deadline/retry/breaker policy.

    Raises `CircuitOpenError` without touching the network when the
    breaker is open, and `RpcUnavailableError` once the retry budget is
    exhausted; non-retryable RpcErrors propagate unchanged (the peer is
    alive — its answer is the caller's business).

    `retryable` narrows which status codes are retried: non-idempotent
    calls (e.g. Done, whose handler blocks on the round boundary) pass
    {UNAVAILABLE} only, so a deadline expiry — where the server may
    still be processing the first attempt — is never replayed.

    Backoff sleeps draw full jitter from `rng` (default: the process
    RNG, seedable via `seed_backoff_jitter` / SWTPU_RPC_JITTER_SEED) so
    many peers retrying the same healed partition fan out instead of
    landing as one synchronized storm. Budget exhaustion is still
    decided against the deterministic `backoff_bound`, keeping the
    worst-case return time independent of the draw.
    """
    start = clock()
    last_code = None
    attempt = 0
    while True:
        if breaker is not None and not breaker.allow():
            raise CircuitOpenError(method)
        remaining = policy.total_budget_s - (clock() - start)
        if attempt > 0 and remaining <= 0:
            get_observability().inc(obs_names.RPC_UNAVAILABLE_TOTAL,
                                    method=_method_label(method))
            raise RpcUnavailableError(method, attempt, last_code)
        deadline = (min(policy.deadline_s, remaining) if attempt > 0
                    else policy.deadline_s)
        kwargs = {"timeout": max(deadline, 0.001)}
        if metadata is not None:
            # Only pass the kwarg when set: fault-test fakes (and some
            # instrumented stubs) accept (request, timeout=...) only.
            kwargs["metadata"] = metadata
        try:
            response = callable_(request, **kwargs)
        except grpc.RpcError as e:
            if not (isinstance(e, grpc.RpcError) and e.code() in retryable):
                # The peer ANSWERED (application-level error): transport
                # is healthy, so close the breaker — critically, this
                # also releases a half-open probe slot, which would
                # otherwise leak and wedge the circuit open forever.
                if breaker is not None:
                    breaker.record_success()
                raise
            last_code = e.code()
            attempt += 1
            if breaker is not None:
                breaker.record_failure()
            backoff = policy.backoff(attempt - 1,
                                     rng if rng is not None else _jitter_rng)
            out_of_budget = ((clock() - start)
                             + policy.backoff_bound(attempt - 1)
                             >= policy.total_budget_s)
            if attempt >= policy.max_attempts or out_of_budget:
                get_observability().inc(obs_names.RPC_UNAVAILABLE_TOTAL,
                                        method=_method_label(method))
                raise RpcUnavailableError(method, attempt, last_code) from e
            get_observability().inc(obs_names.RPC_RETRIES_TOTAL,
                                    method=_method_label(method))
            logger.debug("%s attempt %d failed (%s); retrying in %.2fs",
                         method, attempt, last_code, backoff)
            sleep(backoff)
            continue
        if breaker is not None:
            breaker.record_success()
        return response


# ----------------------------------------------------------------------
# Gray-failure health scoring (detection half of worker quarantine)
# ----------------------------------------------------------------------

#: Health states, in decreasing order of trust. `suspect` keeps the
#: worker schedulable for training but serving replica placement avoids
#: it; `degraded` quarantines the host (sched/physical.py).
HEALTH_HEALTHY = "healthy"
HEALTH_SUSPECT = "suspect"
HEALTH_DEGRADED = "degraded"


@dataclass(frozen=True)
class HealthConfig:
    """Knobs of the per-host gray-failure classifier (EWMA + hysteresis)
    and of the quarantine lifecycle built on it. Defaults detect a
    worker running at ~10% speed within 3-4 scored observations (about
    that many rounds when the host runs a job every round) without
    flapping on a single slow round. README "Gray failures & chaos
    testing" documents each knob."""
    #: EWMA smoothing of the 0..1 health samples (higher = reacts
    #: faster, flaps easier).
    ewma_alpha: float = 0.45
    #: Score below which the host becomes `suspect` (serving replica
    #: placement starts avoiding it).
    suspect_below: float = 0.6
    #: Score below which the host is a quarantine candidate.
    degraded_below: float = 0.3
    #: Score at or above which a suspect/degraded host may return to
    #: `healthy` (hysteresis: strictly above suspect_below).
    recover_above: float = 0.8
    #: Observations required before the classifier may leave `healthy`
    #: (one anomalous first sample must not quarantine a cold host).
    min_samples: int = 3
    #: Consecutive sub-degraded scores required to enter `degraded`.
    degraded_consecutive: int = 2
    #: Consecutive recovered scores required to return to `healthy`.
    recover_consecutive: int = 2
    #: Dispatch RPC wall time scoring 0.0 (healthy dispatches are
    #: milliseconds; a multi-second RunJob round trip is an interconnect
    #: or daemon symptom).
    dispatch_latency_ref_s: float = 5.0
    #: Per-(job_type, scale_factor, worker_type) fleet reference rate
    #: decay per observation: the reference tracks the FASTEST recent
    #: observation (max(obs, ref * decay)), so one degraded host cannot
    #: drag the yardstick it is measured against down with itself.
    rate_ref_decay: float = 0.995
    #: Quarantine release probation: how long a freshly quarantined host
    #: sits out before being released back to capacity as `suspect`
    #: (a ping cannot prove compute speed, so release is probational —
    #: still-slow hosts are re-quarantined by the same classifier and
    #: the backoff doubles, up to the cap).
    quarantine_backoff_s: float = 120.0
    quarantine_backoff_max_s: float = 1800.0

    @classmethod
    def from_dict(cls, config: Optional[dict]) -> "HealthConfig":
        if not config:
            return cls()
        # "_"-prefixed keys are comments (config-file convention, same
        # as the sweep configs) — a copied reference block must load.
        config = {k: v for k, v in config.items()
                  if not k.startswith("_")}
        known = {f for f in cls.__dataclass_fields__}
        unknown = set(config) - known
        if unknown:
            raise ValueError(
                f"unknown worker-health option(s): {sorted(unknown)}")
        return cls(**config)

    def with_quarantine_backoff(self, backoff_s: float) -> "HealthConfig":
        """A copy with a retuned quarantine release backoff — the
        gray-failure knob the what-if plane's auto-tuner commits
        (whatif/knobs.py). The config is frozen by design, so live
        retuning goes through replacement; the caller re-points the
        scheduler's `_health_cfg` AND each HostHealth's `config` so
        in-flight classifiers score against the new value. Clamped to
        (0, quarantine_backoff_max_s]."""
        from dataclasses import replace
        if backoff_s <= 0:
            raise ValueError(
                f"quarantine backoff must be positive, got {backoff_s!r}")
        return replace(self, quarantine_backoff_s=min(
            float(backoff_s), self.quarantine_backoff_max_s))


class HostHealth:
    """EWMA + hysteresis health classifier for one worker host.

    Scored samples in [0, 1] arrive from three telemetry feeds obs
    already collects (sched/physical.py): observed steps/s vs the
    fleet-reference rate for the same (job_type, scale_factor), RunJob
    dispatch latency, and working-host heartbeat age. The classifier is
    a pure state machine over those samples — no clocks, no RNG — so
    identical telemetry always produces identical verdicts (the chaos
    campaign's byte-reproducibility leans on this).

    healthy --(score < suspect_below, >= min_samples)--> suspect
    suspect --(score < degraded_below for degraded_consecutive)--> degraded
    degraded/suspect --(score >= recover_above for recover_consecutive)
        --> healthy
    """

    def __init__(self, config: Optional[HealthConfig] = None):
        self.config = config or HealthConfig()
        self.score = 1.0
        self.state = HEALTH_HEALTHY
        self.samples = 0
        self._below_degraded = 0
        self._above_recover = 0

    def observe(self, sample: float) -> Optional[str]:
        """Fold one 0..1 sample in; returns the new state when this
        observation caused a transition, else None."""
        cfg = self.config
        sample = min(max(float(sample), 0.0), 1.0)
        self.samples += 1
        self.score = (cfg.ewma_alpha * sample
                      + (1.0 - cfg.ewma_alpha) * self.score)
        self._below_degraded = (self._below_degraded + 1
                                if self.score < cfg.degraded_below else 0)
        self._above_recover = (self._above_recover + 1
                               if self.score >= cfg.recover_above else 0)
        previous = self.state
        if self.samples >= cfg.min_samples:
            if (self.state != HEALTH_DEGRADED
                    and self._below_degraded >= cfg.degraded_consecutive):
                self.state = HEALTH_DEGRADED
            elif (self.state == HEALTH_HEALTHY
                    and self.score < cfg.suspect_below):
                self.state = HEALTH_SUSPECT
            elif (self.state != HEALTH_HEALTHY
                    and self._above_recover >= cfg.recover_consecutive):
                self.state = HEALTH_HEALTHY
        return self.state if self.state != previous else None

    def reset_probation(self) -> None:
        """Re-admit after quarantine: the host starts over as `suspect`
        with a neutral-but-wary score — it must re-earn `healthy`
        through recover_consecutive good observations, and one bad
        observation re-degrades it quickly."""
        self.score = max(self.score, self.config.suspect_below)
        self.state = HEALTH_SUSPECT
        self.samples = max(self.samples, self.config.min_samples)
        self._below_degraded = 0
        self._above_recover = 0
