"""Input pipelines: real dataset loaders with synthetic fallbacks.

CIFAR-10 (pickled python batches or .npz) and wikitext-2 (tokens files)
load from disk when a data directory containing them is passed —
matching the reference's torchvision/corpus loaders
(workloads/pytorch/image_classification/cifar10/main.py:118-137,
language_modeling/word_language_model/data.py). When no directory is
given or the files are absent (CI, benchmarks, dry runs), deterministic
synthetic batches of the right shapes are produced on host instead —
the reference's GavelIterator had the same synthetic-data escape hatch
(gavel_iterator.py:89-92). Loaders expose `.synthetic` so the lease
iterator only caches batches on the synthetic path.

Real formats supported per family:
  cifar10     pickled python batches (cifar-10-batches-py/) or cifar10.npz
  imagenet    train/<class>/ image folders, decoded lazily per batch
  wikitext2   wiki.train.tokens / train.txt word stream
  multi30k    train.de/train.en parallel sentence files (reference
              preprocesses these into multi30k.atok.low.pt with torchtext;
              we tokenize the raw pair files directly)
  ml20m       pro_sg/train.csv (uid,sid) interaction list, the VAE-CF
              preprocessing the reference's recoder consumes
              (workloads/pytorch/recommendation/recoder/)
  monet2photo trainA/ + trainB/ image folders (PIL) or monet2photo.npz
"""
from __future__ import annotations

import os
import pickle
from typing import Optional, Sequence

import numpy as np


class SyntheticBatches:
    """A fixed-length epoch of host-generated batches.

    SWTPU_SYNTH_EPOCH_BATCHES overrides the epoch length — epoch-driven
    mechanisms (the Accordion monitor decides once per epoch) are
    untestable end-to-end on CPU against dataset-sized epochs."""

    synthetic = True

    def __init__(self, make_batch, batches_per_epoch: int, seed: int = 0):
        self._make_batch = make_batch
        override = int(os.environ.get("SWTPU_SYNTH_EPOCH_BATCHES", "0"))
        self._len = override if override > 0 else max(1, batches_per_epoch)
        rng = np.random.RandomState(seed)
        # One real batch, reused; keeps host CPU out of the hot loop.
        self._batch = make_batch(rng)

    def __len__(self):
        return self._len

    def __iter__(self):
        for _ in range(self._len):
            yield self._batch


class ArrayBatches:
    """An epoch over in-memory arrays, reshuffled each epoch. Partial
    trailing batches are dropped: every yielded batch has the full
    batch_size leading dim, as fixed-shape jit/sharding requires."""

    synthetic = False

    def __init__(self, arrays: Sequence[np.ndarray], batch_size: int,
                 seed: int = 0, shuffle: bool = True):
        self._arrays = arrays
        self._bs = batch_size
        self._rng = np.random.RandomState(seed)
        self._shuffle = shuffle
        self._n = arrays[0].shape[0]
        if self._n < batch_size:
            raise ValueError(
                f"dataset has {self._n} samples < batch_size {batch_size}")

    def __len__(self):
        return self._n // self._bs

    def __iter__(self):
        order = (self._rng.permutation(self._n) if self._shuffle
                 else np.arange(self._n))
        for i in range(len(self)):
            idx = order[i * self._bs:(i + 1) * self._bs]
            yield tuple(a[idx] for a in self._arrays)


def _decode_image(path: str, size: int, scale: float,
                  offset: float) -> np.ndarray:
    """Decode one image file to (size, size, 3) float32 as
    pixel/scale + offset (classification: /255 in [0,1]; GAN tanh
    range: /127.5 - 1)."""
    from PIL import Image
    with Image.open(path) as im:
        im = im.convert("RGB").resize((size, size))
        return np.asarray(im, np.float32) / scale + offset


class SparseRowBatches:
    """Epochs of dense multi-hot rows densified per batch from per-row
    item-index lists. ML-20M's full user×item matrix is ~9 GB dense, so
    rows stay sparse on host and only each (batch, num_items) slab is
    materialized. Reshuffles each epoch; drops the partial tail batch."""

    synthetic = False

    def __init__(self, rows: Sequence[np.ndarray], num_items: int,
                 batch_size: int, seed: int = 0):
        if len(rows) < batch_size:
            raise ValueError(
                f"dataset has {len(rows)} rows < batch_size {batch_size}")
        self._rows = rows
        self._num_items = num_items
        self._bs = batch_size
        self._rng = np.random.RandomState(seed)

    def __len__(self):
        return len(self._rows) // self._bs

    def __iter__(self):
        order = self._rng.permutation(len(self._rows))
        for i in range(len(self)):
            batch = np.zeros((self._bs, self._num_items), np.float32)
            for j, r in enumerate(order[i * self._bs:(i + 1) * self._bs]):
                batch[j, self._rows[r]] = 1.0
            yield (batch,)


class UnpairedBatches:
    """Two independently shuffled domains (CycleGAN A/B); each epoch
    yields min(len(A), len(B)) // batch_size unpaired (a, b) batches.
    Each domain is either an in-memory array or a list of image paths
    decoded lazily per batch (an epoch touches only min(len(A), len(B))
    images, so eagerly decoding a large domain would waste minutes and
    GBs at every lease re-dispatch)."""

    synthetic = False

    def __init__(self, a, b, batch_size: int, image_size: int = 128,
                 seed: int = 0):
        if min(len(a), len(b)) < batch_size:
            raise ValueError("domain smaller than batch_size")
        self._a, self._b = a, b
        self._bs = batch_size
        self._size = image_size
        self._rng = np.random.RandomState(seed)

    def __len__(self):
        return min(len(self._a), len(self._b)) // self._bs

    def _take(self, domain, idx):
        if isinstance(domain, np.ndarray):
            return domain[idx]
        out = np.empty((len(idx), self._size, self._size, 3), np.float32)
        for j, r in enumerate(idx):
            out[j] = _decode_image(domain[r], self._size, 127.5, -1.0)
        return out

    def __iter__(self):
        oa = self._rng.permutation(len(self._a))
        ob = self._rng.permutation(len(self._b))
        for i in range(len(self)):
            sl = slice(i * self._bs, (i + 1) * self._bs)
            yield self._take(self._a, oa[sl]), self._take(self._b, ob[sl])


def _load_cifar10(data_dir: str) -> Optional[tuple]:
    """Read CIFAR-10 from `data_dir`: either the standard pickled python
    batches (cifar-10-batches-py/data_batch_*) or a cifar10.npz with
    images/labels arrays. Returns (images NHWC float32 in [0,1], labels
    int32) or None when absent."""
    batch_dir = None
    for cand in (data_dir, os.path.join(data_dir, "cifar-10-batches-py")):
        if os.path.exists(os.path.join(cand, "data_batch_1")):
            batch_dir = cand
            break
    if batch_dir is not None:
        images, labels = [], []
        for i in range(1, 6):
            with open(os.path.join(batch_dir, f"data_batch_{i}"), "rb") as f:
                d = pickle.load(f, encoding="bytes")
            images.append(np.asarray(d[b"data"], np.uint8))
            labels.append(np.asarray(d[b"labels"], np.int64))
        x = np.concatenate(images).reshape(-1, 3, 32, 32)
        x = x.transpose(0, 2, 3, 1).astype(np.float32) / 255.0
        y = np.concatenate(labels).astype(np.int32)
        return x, y
    npz = os.path.join(data_dir, "cifar10.npz")
    if os.path.exists(npz):
        d = np.load(npz)
        x = np.asarray(d["images"], np.float32)
        if x.max() > 1.5:
            x = x / 255.0
        return x, np.asarray(d["labels"], np.int32)
    return None


def cifar10(batch_size: int, data_dir: Optional[str] = None,
            dataset_size: int = 50000, seed: int = 0):
    if data_dir:
        real = _load_cifar10(data_dir)
        if real is not None and real[0].shape[0] >= batch_size:
            return ArrayBatches(real, batch_size, seed)

    def make(rng):
        return (rng.rand(batch_size, 32, 32, 3).astype(np.float32),
                rng.randint(0, 10, size=(batch_size,)).astype(np.int32))
    return SyntheticBatches(make, dataset_size // batch_size, seed)


class LazyImageFolderBatches:
    """ImageFolder-style epochs decoded lazily per batch: train/<class>/
    image files, label = class-dir index. The full dataset never sits in
    RAM (ImageNet is ~150 GB decoded) — only each (batch, size, size, 3)
    slab, matching the torchvision ImageFolder+DataLoader behavior the
    reference relies on. Shuffles each epoch; drops the partial tail."""

    synthetic = False

    def __init__(self, files: Sequence[str], labels: np.ndarray,
                 batch_size: int, image_size: int = 224, seed: int = 0):
        if len(files) < batch_size:
            raise ValueError(
                f"dataset has {len(files)} images < batch_size {batch_size}")
        self._files = files
        self._labels = labels
        self._bs = batch_size
        self._size = image_size
        self._rng = np.random.RandomState(seed)

    def __len__(self):
        return len(self._files) // self._bs

    def __iter__(self):
        order = self._rng.permutation(len(self._files))
        for i in range(len(self)):
            idx = order[i * self._bs:(i + 1) * self._bs]
            batch = np.empty((self._bs, self._size, self._size, 3),
                             np.float32)
            for j, r in enumerate(idx):
                batch[j] = _decode_image(self._files[r], self._size,
                                         255.0, 0.0)
            yield batch, self._labels[idx].astype(np.int32)


def _scan_image_folder(data_dir: str) -> Optional[tuple]:
    """(files, labels) from a train/<class>/* tree (or <class>/* directly
    under data_dir). Returns None when no class dirs with images exist."""
    try:
        from PIL import Image  # noqa: F401 - decoding needs PIL later
    except ImportError:
        return None
    exts = (".jpg", ".jpeg", ".png", ".bmp")
    for root in (os.path.join(data_dir, "train"), data_dir):
        if not os.path.isdir(root):
            continue
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        files, labels = [], []
        for ci, cls in enumerate(classes):
            cdir = os.path.join(root, cls)
            for name in sorted(os.listdir(cdir)):
                if name.lower().endswith(exts):
                    files.append(os.path.join(cdir, name))
                    labels.append(ci)
        if files:
            return files, np.asarray(labels, np.int64)
    return None


def imagenet(batch_size: int, dataset_size: int = 100000, seed: int = 0,
             data_dir: Optional[str] = None):
    if data_dir:
        scanned = _scan_image_folder(data_dir)
        if scanned is not None and len(scanned[0]) >= batch_size:
            return LazyImageFolderBatches(scanned[0], scanned[1], batch_size,
                                          seed=seed)

    def make(rng):
        return (rng.rand(batch_size, 224, 224, 3).astype(np.float32),
                rng.randint(0, 1000, size=(batch_size,)).astype(np.int32))
    return SyntheticBatches(make, dataset_size // batch_size, seed)


PAD, BOS, EOS, UNK = 0, 1, 2, 3


def _load_multi30k(data_dir: str, src_len: int, tgt_len: int,
                   vocab_cap: int) -> Optional[tuple]:
    """Read the raw Multi30k parallel files (train.de source -> train.en
    target, the reference task's direction). `data_dir` may be the
    directory itself, a file inside it (the trace passes the reference's
    preprocessed .pt path — we use its directory), or a parent holding a
    multi30k/ subdir. Joint frequency-ranked vocab capped at `vocab_cap`
    with PAD/BOS/EOS/UNK reserved; src truncated+padded to src_len, tgt
    wrapped in BOS..EOS and padded to tgt_len."""
    if not os.path.isdir(data_dir):
        # The trace hands us the reference's .pt file path (which we never
        # create); the raw pair files live in its directory.
        data_dir = os.path.dirname(data_dir)
    pair = None
    for cand in (data_dir, os.path.join(data_dir, "multi30k")):
        de, en = (os.path.join(cand, "train.de"), os.path.join(cand, "train.en"))
        if os.path.exists(de) and os.path.exists(en):
            pair = (de, en)
            break
    if pair is None:
        return None
    # Pair lines positionally FIRST, then drop pairs with a blank side:
    # filtering each file independently would shift every pair after a
    # blank line present in only one file.
    with open(pair[0], encoding="utf-8") as f:
        src_raw = f.read().splitlines()
    with open(pair[1], encoding="utf-8") as f:
        tgt_raw = f.read().splitlines()
    pairs = [(s.lower().split(), t.lower().split())
             for s, t in zip(src_raw, tgt_raw) if s.strip() and t.strip()]
    if not pairs:
        return None
    src_lines = [s for s, _ in pairs]
    tgt_lines = [t for _, t in pairs]
    words = [w for ln in src_lines for w in ln]
    words += [w for ln in tgt_lines for w in ln]
    uniq, counts = np.unique(np.asarray(words), return_counts=True)
    keep = uniq[np.argsort(-counts, kind="stable")][: vocab_cap - 4]
    ids = {w: i + 4 for i, w in enumerate(keep)}

    def encode(lines, length, wrap):
        out = np.full((len(lines), length), PAD, np.int32)
        for r, ln in enumerate(lines):
            toks = [ids.get(w, UNK) for w in ln]
            if wrap:
                toks = [BOS] + toks[: length - 2] + [EOS]
            else:
                toks = toks[:length]
            out[r, : len(toks)] = toks
        return out

    return encode(src_lines, src_len, False), encode(tgt_lines, tgt_len, True)


def multi30k(batch_size: int, src_len: int = 32, tgt_len: int = 32,
             vocab: int = 9521, dataset_size: int = 10000, seed: int = 0,
             data_dir: Optional[str] = None):
    if data_dir:
        real = _load_multi30k(data_dir, src_len, tgt_len, vocab)
        if real is not None and real[0].shape[0] >= batch_size:
            return ArrayBatches(real, batch_size, seed)

    def make(rng):
        src = rng.randint(1, vocab, size=(batch_size, src_len)).astype(np.int32)
        tgt = rng.randint(1, vocab, size=(batch_size, tgt_len)).astype(np.int32)
        return src, tgt
    return SyntheticBatches(make, dataset_size // batch_size, seed)


def _load_wikitext2(data_dir: str, seq_len: int,
                    vocab_cap: int) -> Optional[tuple]:
    """Read wikitext-2 word-level LM windows from `data_dir`
    (wiki.train.tokens or train.txt). Builds a frequency-ranked vocab
    capped at `vocab_cap` (rarer words -> <unk>=0) and slices the token
    stream into (seq_len + 1)-long windows, reference-style batchify
    (word_language_model/data.py)."""
    path = None
    for cand in ("wiki.train.tokens", "train.txt",
                 os.path.join("wikitext-2", "wiki.train.tokens")):
        full = os.path.join(data_dir, cand)
        if os.path.exists(full):
            path = full
            break
    if path is None:
        return None
    with open(path, encoding="utf-8") as f:
        words = f.read().split()
    uniq, counts = np.unique(np.asarray(words), return_counts=True)
    keep = uniq[np.argsort(-counts, kind="stable")][: vocab_cap - 1]
    ids = {w: i + 1 for i, w in enumerate(keep)}  # 0 = <unk>
    stream = np.fromiter((ids.get(w, 0) for w in words), np.int32,
                         count=len(words))
    n_windows = (len(stream) - 1) // (seq_len + 1)
    if n_windows == 0:
        return None
    windows = stream[: n_windows * (seq_len + 1)].reshape(
        n_windows, seq_len + 1)
    return (windows[:, :-1], windows[:, 1:])


def wikitext2(batch_size: int, seq_len: int = 35, vocab: int = 33278,
              dataset_size: int = 59675, seed: int = 0,
              data_dir: Optional[str] = None):
    if data_dir:
        real = _load_wikitext2(data_dir, seq_len, vocab)
        if real is not None and real[0].shape[0] >= batch_size:
            return ArrayBatches(real, batch_size, seed)

    def make(rng):
        tokens = rng.randint(1, vocab, size=(batch_size, seq_len + 1)).astype(np.int32)
        return tokens[:, :-1], tokens[:, 1:]
    return SyntheticBatches(make, dataset_size // batch_size, seed)


def _list_image_domain(folder: str) -> Optional[list]:
    """Sorted image paths in `folder`; decoding happens per batch in
    UnpairedBatches (float32 in [-1, 1], CycleGAN's tanh range)."""
    if not os.path.isdir(folder):
        return None
    try:
        from PIL import Image  # noqa: F401 - decoding needs PIL later
    except ImportError:
        return None
    exts = (".jpg", ".jpeg", ".png")
    names = sorted(n for n in os.listdir(folder)
                   if n.lower().endswith(exts))
    if not names:
        return None
    return [os.path.join(folder, n) for n in names]


def _load_monet2photo(data_dir: str, image_size: int) -> Optional[tuple]:
    """trainA/ (paintings) + trainB/ (photos) folders (lazy path lists),
    or monet2photo.npz with A/B arrays."""
    for cand in (data_dir, os.path.join(data_dir, "monet2photo")):
        a = _list_image_domain(os.path.join(cand, "trainA"))
        b = _list_image_domain(os.path.join(cand, "trainB"))
        if a is not None and b is not None:
            return a, b
        npz = os.path.join(cand, "monet2photo.npz")
        if os.path.exists(npz):
            d = np.load(npz)
            a, b = np.asarray(d["A"], np.float32), np.asarray(d["B"], np.float32)
            if a.max() > 1.5:  # stored as uint8 range
                a, b = a / 127.5 - 1.0, b / 127.5 - 1.0
            a, b = (_resize_domain(x, image_size) for x in (a, b))
            return a, b
    return None


def _resize_domain(x: np.ndarray, image_size: int) -> np.ndarray:
    """Match stored images to the generators' (image_size, image_size)
    input; nearest-neighbor index resampling keeps numpy-only."""
    if x.shape[1] == image_size and x.shape[2] == image_size:
        return x
    ih = (np.arange(image_size) * x.shape[1] // image_size)
    iw = (np.arange(image_size) * x.shape[2] // image_size)
    return np.ascontiguousarray(x[:, ih][:, :, iw])


def monet2photo(batch_size: int, image_size: int = 128,
                dataset_size: int = 1193, seed: int = 0,
                data_dir: Optional[str] = None):
    """Unpaired image batches for CycleGAN (domains A=paintings, B=photos)."""
    if data_dir:
        real = _load_monet2photo(data_dir, image_size)
        if real is not None and min(len(real[0]),
                                    len(real[1])) >= batch_size:
            return UnpairedBatches(real[0], real[1], batch_size,
                                   image_size=image_size, seed=seed)

    def make(rng):
        a = (rng.rand(batch_size, image_size, image_size, 3) * 2 - 1)
        b = (rng.rand(batch_size, image_size, image_size, 3) * 2 - 1)
        return a.astype(np.float32), b.astype(np.float32)
    return SyntheticBatches(make, dataset_size // batch_size, seed)


def _load_ml20m(data_dir: str, num_items: int) -> Optional[list]:
    """Read the VAE-CF pro_sg interaction list: train.csv with a header
    and (uid, sid) integer rows. Items are frequency-ranked and capped at
    `num_items` (the model's output width); returns one sorted item-id
    array per user."""
    path = None
    for cand in (data_dir, os.path.join(data_dir, "pro_sg"),
                 os.path.join(data_dir, "ml-20m", "pro_sg")):
        full = os.path.join(cand, "train.csv")
        if os.path.exists(full):
            path = full
            break
    if path is None:
        return None
    try:
        # The real file is ~10M rows; np.loadtxt's C tokenizer parses it
        # in seconds, where genfromtxt's python loop takes minutes — and
        # jobs re-pay loader startup on every lease re-dispatch.
        pairs = np.loadtxt(path, delimiter=",", skiprows=1, dtype=np.int64,
                           usecols=(0, 1), ndmin=2)
    except Exception:  # noqa: BLE001 - malformed file -> synthetic fallback
        return None
    if pairs.shape[0] == 0:
        return None
    uids, sids = pairs[:, 0], pairs[:, 1]
    # Frequency-rank items so the cap keeps the most-interacted ones.
    uniq, inverse, counts = np.unique(sids, return_inverse=True,
                                      return_counts=True)
    rank = np.empty(len(uniq), np.int64)
    rank[np.argsort(-counts, kind="stable")] = np.arange(len(uniq))
    new_sid = rank[inverse]
    keep = new_sid < num_items
    uids, new_sid = uids[keep], new_sid[keep]
    order = np.argsort(uids, kind="stable")
    uids, new_sid = uids[order], new_sid[order]
    bounds = np.searchsorted(uids, np.unique(uids))
    rows = [np.sort(chunk.astype(np.int32))
            for chunk in np.split(new_sid, bounds[1:])]
    return [r for r in rows if r.size]


def ml20m(batch_size: int, num_items: int = 20108, dataset_size: int = 117907,
          seed: int = 0, data_dir: Optional[str] = None):
    if data_dir:
        rows = _load_ml20m(data_dir, num_items)
        if rows is not None and len(rows) >= batch_size:
            return SparseRowBatches(rows, num_items, batch_size, seed)

    def make(rng):
        # ~1% interaction density multi-hot rows.
        rows = (rng.rand(batch_size, num_items) < 0.01).astype(np.float32)
        return (rows,)
    return SyntheticBatches(make, dataset_size // batch_size, seed)
