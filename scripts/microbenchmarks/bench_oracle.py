#!/usr/bin/env python3
"""Learned throughput oracle microbenchmark: fit wall, predictions/s,
online-update cost.

Measures the three costs the oracle charges the control plane:

- **fit** — `ThroughputModel.fit` over a seeded synthetic history
  (the offline `oracle.train` path; closed-form ridge, so this is
  the normal-equation assembly + solve wall),
- **predict** — `predict()` throughput on the fitted model (the
  per-job cold-start cost in `Scheduler._set_initial_throughput`;
  one featurize + dot product + correction lookup),
- **observe** — `observe()` online-correction cost (charged once per
  Done report in `_update_throughput`).

The synthetic history is a pure function of --seed (model families x
batch sizes x scale factors x two worker generations, rates from a
seeded log-normal around an analytic speedup surface), so repeated
runs fit the identical model. Prints ONE JSON line; bench.py embeds
it as the `oracle_phase` row. ``--smoke`` exits nonzero when fit wall
exceeds --max_fit_s or prediction throughput falls below
--min_predictions_per_s (CI floors: the oracle must stay far off the
round-loop critical path).
"""
import argparse
import json
import os
import random
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.obs.logconfig import setup_logging  # noqa: E402
from shockwave_tpu.oracle.model import ThroughputModel  # noqa: E402

FAMILIES = ("LM", "ResNet-18", "ResNet-50", "Transformer",
            "Recommendation", "CycleGAN", "A3C")
BATCH_SIZES = (16, 32, 64, 128)
SCALE_FACTORS = (1, 2, 4, 8)
WORKER_TYPES = (("v5-lite", 1.0), ("v5", 2.25))


def synthetic_rows(seed: int, copies: int):
    """Seeded training rows: every (family, bs, sf, worker type) cell,
    `copies` noisy observations each."""
    rng = random.Random(seed)
    rows = []
    for _ in range(copies):
        for fi, fam in enumerate(FAMILIES):
            base = 2.0 * (fi + 1)
            for bs in BATCH_SIZES:
                for sf in SCALE_FACTORS:
                    for wt, gain in WORKER_TYPES:
                        rate = (base * gain * (bs / 16.0)
                                * sf ** 0.85 * rng.lognormvariate(0.0, 0.05))
                        rows.append((f"{fam} (batch size {bs})",
                                     bs, sf, wt, rate))
    return rows


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--copies", type=int, default=4,
                   help="noisy observations per (family,bs,sf,type) cell")
    p.add_argument("--fits", type=int, default=5)
    p.add_argument("--predictions", type=int, default=20000)
    p.add_argument("--observations", type=int, default=20000)
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--max_fit_s", type=float, default=2.0,
                   help="--smoke: fail when one fit exceeds this")
    p.add_argument("--min_predictions_per_s", type=float, default=2000.0,
                   help="--smoke: fail below this prediction throughput")
    p.add_argument("--output", default=None, help="also write the JSON")
    args = p.parse_args()
    setup_logging("warning")

    rows = synthetic_rows(args.seed, args.copies)

    t0 = time.monotonic()
    for _ in range(args.fits):
        model = ThroughputModel.fit(rows, seed=args.seed)
    fit_wall = time.monotonic() - t0
    mean_fit = fit_wall / max(args.fits, 1)

    # Mixed query stream: in-vocabulary cells plus a never-seen family
    # (the hash-bucket path every cold-start prediction takes).
    queries = []
    rng = random.Random(args.seed + 1)
    for _ in range(args.predictions):
        if rng.random() < 0.25:
            queries.append(("Unseen (batch size 8)", 8, 2, "v5"))
        else:
            fam = rng.choice(FAMILIES)
            bs = rng.choice(BATCH_SIZES)
            queries.append((f"{fam} (batch size {bs})", bs,
                            rng.choice(SCALE_FACTORS),
                            rng.choice(WORKER_TYPES)[0]))
    t0 = time.monotonic()
    for jt, bs, sf, wt in queries:
        model.predict(jt, bs, sf, wt)
    predict_wall = time.monotonic() - t0

    t0 = time.monotonic()
    for i in range(args.observations):
        jt, bs, sf, wt = queries[i % len(queries)]
        model.observe(jt, bs, sf, wt, 1.0 + (i % 7))
    observe_wall = time.monotonic() - t0

    predictions_per_s = (args.predictions / predict_wall
                         if predict_wall > 0 else None)
    line = {
        "training_rows": len(rows),
        "fits": args.fits,
        "fit_wall_s": round(fit_wall, 3),
        "mean_fit_s": round(mean_fit, 5),
        "rmse": model.rmse,
        "predictions": args.predictions,
        "predict_wall_s": round(predict_wall, 3),
        "predictions_per_s": round(predictions_per_s, 1)
        if predictions_per_s is not None else None,
        "observations": args.observations,
        "observe_wall_s": round(observe_wall, 3),
        "observations_per_s": round(args.observations / observe_wall, 1)
        if observe_wall > 0 else None,
    }
    print(json.dumps(line))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(line, f)
            f.write("\n")
    if args.smoke:
        if mean_fit > args.max_fit_s:
            print(f"SMOKE FAIL: mean fit {mean_fit:.3f}s > "
                  f"{args.max_fit_s}s", file=sys.stderr)
            return 1
        if predictions_per_s is not None and \
                predictions_per_s < args.min_predictions_per_s:
            print(f"SMOKE FAIL: {predictions_per_s:.0f} predictions/s < "
                  f"{args.min_predictions_per_s:.0f}", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
