#!/bin/bash
# Scale study: the reference's shipped dynamic traces on 64/128/256-chip
# simulated clusters (reference: reproduce/scale_{64,128,256}gpus.sh,
# paper Fig 9 — 220/460/900-job staggered-arrival traces with per-scale
# Shockwave hyperparameters; the traces and configs are declared copies
# of the reference's inputs, the same provenance pattern as
# data/canonical_120job.trace).
# Usage: reproduce/scale_gpus.sh <64|128|256> [output_dir]
# -e -o pipefail: a failed simulate must abort the script, or the
# solve-quality gate below would happily validate a stale pickle from
# an earlier run and exit 0.
set -eu -o pipefail
cd "$(dirname "$0")/.."
CHIPS=${1:?usage: scale_gpus.sh <64|128|256> [output_dir]}
OUT=${2:-reproduce/pickles/scale_${CHIPS}}
case "$CHIPS" in
    64) TRACE=data/scale_220job.trace ;;
    128) TRACE=data/scale_460job.trace ;;
    256) TRACE=data/scale_900job.trace ;;
    *) echo "unknown scale $CHIPS (64|128|256)"; exit 2 ;;
esac
mkdir -p "$OUT"

for POLICY in shockwave max_min_fairness finish_time_fairness
do
    echo "=== ${CHIPS} chips / $POLICY ==="
    python3 scripts/drivers/simulate.py \
        --trace "$TRACE" \
        --policy "$POLICY" \
        --throughputs data/tacc_throughputs.json \
        --cluster_spec "v100:${CHIPS}" \
        --round_duration 120 \
        --seed 0 \
        --config "configs/scale_${CHIPS}gpus.json" \
        --output "$OUT/${POLICY}.pkl" \
        | tee "$OUT/${POLICY}.json"
done

# Solve-quality gate: at scale the MILP must be producing real
# schedules, not silently degrading to the greedy fallback (the
# reference bounds its solver but never verifies what it achieved).
python3 - "$OUT/shockwave.pkl" <<'EOF'
import pickle, sys
stats = pickle.load(open(sys.argv[1], "rb")).get("milp_solve_stats", [])
assert stats, "no MILP solve telemetry in scale pickle"
paths = [s["path"] for s in stats]
rate = paths.count("greedy") / len(paths)
hist = {p: paths.count(p) for p in sorted(set(paths))}
gaps = [s["mip_gap"] for s in stats if s["mip_gap"] is not None]
print(f"MILP solves={len(paths)} paths={hist} greedy_rate={rate:.1%}"
      + (f" max_gap={max(gaps):.2e}" if gaps else ""))
assert rate < 0.05, f"greedy fallback rate {rate:.1%} >= 5%"
EOF
