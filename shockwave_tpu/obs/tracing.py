"""Nestable span tracer with Chrome-trace (Perfetto) JSON export.

Spans are recorded as complete ("ph": "X") events keyed by thread id, so
nesting falls out of the viewer's per-track stacking. Since the fleet-
tracing work each span additionally carries an explicit identity — a
(trace_id, span_id, parent_id) triple (obs/propagation.SpanContext) —
maintained on a per-thread parent stack, so parent links survive
export, shard files and the cross-process merge, where per-track
stacking cannot reach. A remote parent (another process's span,
arriving via RPC metadata or the dispatcher's env export) is spliced in
with ``span(..., parent=ctx)``. The event buffer is a bounded ring
(oldest spans drop first) so a long-lived scheduler cannot grow without
bound.

The clock is injected (see obs/clock.py): under the simulator's virtual
clock the trace is laid out in simulated seconds; under wall clocks it
lines up with logs and journal records. Export is plain
``json.dump`` — traces are telemetry, not durable state.

View an exported trace in ``chrome://tracing`` / https://ui.perfetto.dev,
or summarize it with ``python -m shockwave_tpu.obs.report <trace>``.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from contextlib import contextmanager
from typing import List, Optional

from .clock import Clock, wall_clock
from .propagation import SpanContext, new_span_id, new_trace_id

#: Default ring size: a 360 s-round physical run emits ~10 spans/round
#: plus one per journal fsync; 200k events covers days of rounds.
DEFAULT_MAX_EVENTS = 200_000


class Tracer:
    def __init__(self, clock: Optional[Clock] = None, enabled: bool = True,
                 max_events: int = DEFAULT_MAX_EVENTS):
        self._clock: Clock = clock or wall_clock
        self._enabled = enabled
        self._events: "deque[dict]" = deque(maxlen=max_events)
        from ..analysis.sanitizer import maybe_wrap
        self._lock = maybe_wrap(threading.Lock(), "Tracer._lock")
        # Per-thread stack of open SpanContexts (parent links).
        self._tls = threading.local()

    # Rides inside pickled scheduler objects (simulation checkpoints);
    # locks are recreated on load.
    def __getstate__(self):
        state = dict(self.__dict__)
        del state["_lock"]
        del state["_tls"]
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        from ..analysis.sanitizer import maybe_wrap
        self._lock = maybe_wrap(threading.Lock(), "Tracer._lock")
        self._tls = threading.local()

    @property
    def enabled(self) -> bool:
        return self._enabled

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current_context(self) -> Optional[SpanContext]:
        """The innermost open span on THIS thread, or None."""
        stack = self._stack()
        return stack[-1] if stack else None

    def _enter_context(self,
                       parent: Optional[SpanContext]) -> SpanContext:
        if parent is None:
            parent = self.current_context()
        if parent is None:
            ctx = SpanContext(trace_id=new_trace_id(),
                              span_id=new_span_id())
        else:
            ctx = SpanContext(trace_id=parent.trace_id,
                              span_id=new_span_id())
        self._tls.parent_of = getattr(self._tls, "parent_of", {})
        self._tls.parent_of[ctx.span_id] = (parent.span_id
                                            if parent else None)
        self._stack().append(ctx)
        return ctx

    @contextmanager
    def span(self, name: str, parent: Optional[SpanContext] = None,
             **args):
        """Record one span covering the block; yields its SpanContext
        (None when disabled) so callers can propagate it across a
        process boundary. `parent` splices a REMOTE parent in; without
        it the enclosing span on this thread is the parent. `args` must
        be JSON-serializable; they land in the trace event's `args` and
        are what the report CLI groups by (e.g. ``round=N``)."""
        if not self._enabled:
            yield None
            return
        t0 = self._clock()
        ctx = self._enter_context(parent)
        try:
            yield ctx
        finally:
            t1 = self._clock()
            stack = self._stack()
            if stack and stack[-1] is ctx:
                stack.pop()
            parent_id = self._tls.parent_of.pop(ctx.span_id, None)
            event = {"name": name, "ts": t0, "dur": max(t1 - t0, 0.0),
                     "tid": threading.get_ident(),
                     "trace_id": ctx.trace_id, "span_id": ctx.span_id,
                     "parent_id": parent_id, "args": args}
            with self._lock:
                self._events.append(event)

    def record_span(self, name: str, ts: float, dur: float,
                    context: Optional[SpanContext] = None,
                    parent: Optional[SpanContext] = None,
                    **args) -> Optional[SpanContext]:
        """Record one span with explicit timestamps — for spans whose
        lifetime does not nest lexically (e.g. the scheduler's whole-
        round root span, closed a phase at a time). `context` pins the
        span's identity (so children created earlier can already have
        linked to it); otherwise a fresh one is allocated under
        `parent`. Returns the span's context (None when disabled)."""
        if not self._enabled:
            return None
        if context is None:
            trace = parent.trace_id if parent else new_trace_id()
            context = SpanContext(trace_id=trace, span_id=new_span_id())
        event = {"name": name, "ts": float(ts),
                 "dur": max(float(dur), 0.0),
                 "tid": threading.get_ident(),
                 "trace_id": context.trace_id,
                 "span_id": context.span_id,
                 "parent_id": parent.span_id if parent else None,
                 "args": args}
        with self._lock:
            self._events.append(event)
        return context

    def events(self) -> List[dict]:
        """Snapshot of recorded spans, oldest first."""
        with self._lock:
            return list(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    @staticmethod
    def event_args(event: dict) -> dict:
        """An event's args with its span identity folded in — the shape
        every export path (Chrome trace, shards) serializes."""
        args = dict(event.get("args") or {})
        for key in ("trace_id", "span_id", "parent_id"):
            if event.get(key) is not None:
                args[key] = event[key]
        return args

    def export_chrome_trace(self, path: str) -> str:
        """Write the buffer as Chrome-trace JSON; returns `path`. Span
        identities ride in each event's args, so parent links survive
        the export (and the merge CLI can walk them)."""
        pid = os.getpid()
        trace = {
            "displayTimeUnit": "ms",
            "traceEvents": [
                {"name": e["name"], "ph": "X", "cat": "swtpu",
                 "ts": e["ts"] * 1e6, "dur": e["dur"] * 1e6,
                 "pid": pid, "tid": e["tid"],
                 "args": self.event_args(e)}
                for e in self.events()],
        }
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            json.dump(trace, f)
        return path
