"""Honest device timing under asynchronous dispatch and remote relays.

Two problems make naive `time.time()` loops lie about step time:
- JAX dispatch is async, so a loop of N steps returns before the device
  has executed them; timing must close with something that provably
  waits for the last value.
- On tunneled/relayed accelerator backends (e.g. a remotely attached
  TPU chip), `jax.block_until_ready` can return without the remote
  execution having finished, and every host<->device materialization
  pays a large fixed round-trip latency (~tens of ms), which would
  swamp small per-step times.

`marginal_step_time` solves both with two-point timing: run two chained
windows of n1 and n2 steps, each closed by materializing one scalar on
the host (a device_get provably round-trips the data), and report
(T2 - T1) / (n2 - n1). The fixed sync/round-trip cost appears in both
windows and cancels exactly; what remains is the steady-state marginal
cost per step. Validated on a v5e chip behind a relay: an 8192^3 bf16
matmul times at 188 TF/s (96% of the 197 TF/s peak) where naive
block_until_ready timing reported a physically impossible 60,000 TF/s.

(The reference's GPU profiler, scheduler/scripts/profiling/
measure_throughput.py, can trust torch.cuda.synchronize; there is no
equivalently trustworthy barrier through a relay, hence this design.)
"""
from __future__ import annotations

import time
from typing import Any, Callable, Tuple


def fetch_scalar(value: Any):
    """Materialize one scalar of `value` on the host, forcing completion
    of every computation it depends on. Unlike block_until_ready, a
    device_get cannot return early: the bytes must exist to be copied."""
    import jax
    import numpy as np

    leaves = jax.tree.leaves(value)
    if not leaves:
        return None
    leaf = leaves[0]
    if getattr(leaf, "size", 1) > 1:
        leaf = leaf.ravel()[0]
    return np.asarray(jax.device_get(leaf))


def marginal_step_time(step_fn: Callable[[Any, Any], Tuple[Any, Any]],
                       state: Any, batch: Any, n1: int = 10, n2: int = 40,
                       warmup: int = 5, min_marginal_s: float = 1.0,
                       max_total_steps: int = 20000) -> float:
    """Steady-state seconds per `step_fn(state, batch) -> (state, loss)`
    step. State must thread through (chained data dependence), so the
    closing fetch waits for the whole window.

    Windows grow adaptively until the marginal time (T2 - T1) covers at
    least `min_marginal_s`: for fast steps, a short marginal window
    would drown in the round-trip latency jitter of the closing fetch
    (tens of ms through a relay), making steps/s estimates swing by 2x.
    """
    # Normalize degenerate windows (e.g. a caller's --steps 1): the
    # method needs two windows with n2 > n1 or the ratio is undefined.
    n1 = max(int(n1), 1)
    if n2 <= n1:
        n2 = n1 * 4

    loss = None
    for _ in range(warmup):
        state, loss = step_fn(state, batch)
    fetch_scalar(loss)

    def window(iters: int, state: Any):
        start = time.perf_counter()
        loss = None
        for _ in range(iters):
            state, loss = step_fn(state, batch)
        fetch_scalar(loss)
        return time.perf_counter() - start, state

    while True:
        t1, state = window(n1, state)
        t2, state = window(n2, state)
        marginal = t2 - t1
        if marginal >= min_marginal_s or n2 >= max_total_steps:
            return max(marginal / (n2 - n1), 1e-9)
        # Estimate per-step cost generously (cap below by the observed
        # marginal) and rescale the windows to cover min_marginal_s.
        dt_est = max(marginal / (n2 - n1), 1e-6)
        n2 = min(int(min_marginal_s / dt_est * 1.5) + n1, max_total_steps)
        n1 = max(n2 // 4, 2)
        if n2 <= n1:  # keep the two windows distinct after rescaling
            n2 = n1 + 1
