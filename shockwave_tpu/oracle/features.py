"""Featurization for the learned throughput model.

One training row ``(job_type, batch_size, scale_factor, worker_type)``
becomes a fixed-width vector:

- a bias term;
- a one-hot over the model *families* seen at fit time ("LM",
  "ResNet-18", ...), with families unseen at fit time hashed into a
  small bucket block (seeded md5, never Python's per-process ``hash``)
  so a cold-start family still gets a deterministic — if low-confidence
  — slot;
- ``log2(batch_size)`` and ``log2(scale_factor)``;
- a one-hot over worker types (per-type intercepts: a v5 is faster than
  a v5-lite at every scale factor);
- a **comm-scaling interaction** per worker *generation*:
  ``log2(scale_factor)`` gated on the generation one-hot. Scaling
  efficiency is a property of the interconnect generation (EQuARX,
  PAPERS.md 2506.17615), so two worker types of the same generation
  share a scale curve and a new type of a known generation inherits it.
"""
from __future__ import annotations

import hashlib
import math
from typing import List

import numpy as np

#: Worker type -> interconnect/compute generation. Types absent here
#: are their own generation (a singleton curve, learned if trained on).
GENERATIONS = {
    "k80": "gpu_kepler",
    "p100": "gpu_pascal",
    "v100": "gpu_volta",
    "cpu": "cpu",
    "v5e": "tpu_v5lite",
    "v5-lite": "tpu_v5lite",
    "v5": "tpu_v5",
}

#: Hash-bucket block width for families unseen at fit time.
FAMILY_HASH_BUCKETS = 4


def family_of(job_type: str) -> str:
    """Model family of an oracle job_type key ("LM (batch size 10)" ->
    "LM"; suffix-less families like "A3C" are their own family)."""
    return job_type.split(" (batch size", 1)[0]


def generation_of(worker_type: str) -> str:
    return GENERATIONS.get(worker_type, worker_type)


def family_bucket(family: str, seed: int) -> int:
    """Deterministic seeded bucket for an out-of-vocabulary family
    (md5, not the interpreter's salted ``hash``)."""
    digest = hashlib.md5(f"{seed}:{family}".encode("utf-8")).hexdigest()
    return int(digest, 16) % FAMILY_HASH_BUCKETS


def _log2(value, floor: float = 1.0) -> float:
    try:
        v = float(value)
    except (TypeError, ValueError):
        v = floor
    return math.log2(max(v, floor))


def feature_dim(families: List[str], worker_types: List[str],
                generations: List[str]) -> int:
    return (1 + len(families) + FAMILY_HASH_BUCKETS + 2
            + len(worker_types) + len(generations))


def featurize(job_type: str, batch_size, scale_factor: int,
              worker_type: str, families: List[str],
              worker_types: List[str], generations: List[str],
              seed: int) -> np.ndarray:
    """The feature vector; vocab lists are the model's (fit-time,
    sorted) vocabularies."""
    fam = family_of(job_type)
    gen = generation_of(worker_type)
    x = np.zeros(feature_dim(families, worker_types, generations),
                 dtype=np.float64)
    x[0] = 1.0
    off = 1
    if fam in families:
        x[off + families.index(fam)] = 1.0
    off += len(families)
    if fam not in families:
        x[off + family_bucket(fam, seed)] = 1.0
    off += FAMILY_HASH_BUCKETS
    x[off] = _log2(batch_size)
    x[off + 1] = _log2(scale_factor)
    log_sf = x[off + 1]
    off += 2
    if worker_type in worker_types:
        x[off + worker_types.index(worker_type)] = 1.0
    off += len(worker_types)
    if gen in generations:
        x[off + generations.index(gen)] = log_sf
    return x
