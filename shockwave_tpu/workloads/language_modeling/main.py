#!/usr/bin/env python3
"""LSTM LM / Wikitext-2 workload (trace: "LM (batch size N)").

CLI parity with the reference's language_modeling main.py — the trace
command is `python3 main.py --cuda --data %s/wikitext2 --batch_size N`
with `--steps` appended by the dispatcher (`--cuda` accepted, ignored).
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 3))

import jax
import jax.numpy as jnp
import optax

from shockwave_tpu.models import data
from shockwave_tpu.models.lm import LSTMLanguageModel
from shockwave_tpu.models.train_common import Trainer, common_parser, parse_args


def main():
    p = common_parser("LSTM LM on Wikitext-2", steps_args=("--steps",))
    # --cuda (trace-command compatibility) comes from common_parser.
    p.add_argument("--data", default=None)
    p.add_argument("--batch_size", type=int, default=20)
    args = parse_args(p)

    model = LSTMLanguageModel()
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, 35), jnp.int32)
    variables = model.init(rng, sample)
    init_state = {"params": variables["params"]}

    def loss_fn(params, state, tokens, targets):
        logits = model.apply({"params": params}, tokens)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()
        return loss, {}

    trainer = Trainer(
        args, loss_fn, init_state,
        data.wikitext2(args.batch_size, data_dir=args.data),
        initial_bs=args.batch_size, max_bs=80, learning_rate=1.0)
    trainer.run()


if __name__ == "__main__":
    main()
