"""Per-round phase summary of an exported Chrome trace.

    python -m shockwave_tpu.obs.report <trace.json> [--phases a,b,...]

Reads a trace written by ``Tracer.export_chrome_trace`` and prints one
row per round with the total seconds spent in each pipeline phase
(solve / dispatch / wait / end_round / journal-fsync by default), plus
per-phase totals, counts and means. Spans that carry no ``round`` arg
(journal fsyncs fire from RPC threads that don't know the round) are
attributed to the round whose [start, next-start) window contains their
start timestamp; spans outside every window land in the "-" row.
"""
from __future__ import annotations

import argparse
import bisect
import json
import sys
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from . import names


def load_spans(path: str) -> List[dict]:
    """Chrome-trace events -> [{name, ts, dur, args}] in seconds."""
    with open(path, encoding="utf-8") as f:
        trace = json.load(f)
    events = trace["traceEvents"] if isinstance(trace, dict) else trace
    spans = []
    for e in events:
        if e.get("ph", "X") != "X":
            continue
        spans.append({"name": e.get("name", "?"),
                      "ts": float(e.get("ts", 0.0)) / 1e6,
                      "dur": float(e.get("dur", 0.0)) / 1e6,
                      "args": e.get("args", {}) or {}})
    return spans


def _round_windows(spans: List[dict]) -> Tuple[List[float], List[int]]:
    """Sorted (start_ts, round) windows from spans that carry a round
    arg, for attributing round-less spans by timestamp."""
    starts: Dict[int, float] = {}
    for s in spans:
        rnd = s["args"].get("round")
        if isinstance(rnd, int):
            starts[rnd] = min(starts.get(rnd, s["ts"]), s["ts"])
    ordered = sorted(starts.items(), key=lambda kv: kv[1])
    return [ts for _, ts in ordered], [rnd for rnd, _ in ordered]


def assign_round(span: dict, window_ts: List[float],
                 window_round: List[int]) -> Optional[int]:
    rnd = span["args"].get("round")
    if isinstance(rnd, int):
        return rnd
    if not window_ts:
        return None
    i = bisect.bisect_right(window_ts, span["ts"]) - 1
    return window_round[i] if i >= 0 else None


def phase_table(spans: List[dict],
                phases: Tuple[str, ...] = names.REPORT_PHASES):
    """-> (sorted round keys, {round: {phase: seconds}},
    {phase: (count, total)})."""
    window_ts, window_round = _round_windows(spans)
    per_round: Dict[object, Dict[str, float]] = defaultdict(
        lambda: defaultdict(float))
    totals: Dict[str, List[float]] = {p: [0, 0.0] for p in phases}
    for s in spans:
        if s["name"] not in phases:
            continue
        rnd = assign_round(s, window_ts, window_round)
        key = rnd if rnd is not None else "-"
        per_round[key][s["name"]] += s["dur"]
        totals[s["name"]][0] += 1
        totals[s["name"]][1] += s["dur"]
    rounds = sorted((k for k in per_round if k != "-"),
                    key=lambda r: int(r))
    if "-" in per_round:
        rounds.append("-")
    return rounds, per_round, {p: (int(c), t)
                               for p, (c, t) in totals.items()}


def render(spans: List[dict],
           phases: Tuple[str, ...] = names.REPORT_PHASES) -> str:
    rounds, per_round, totals = phase_table(spans, phases)
    header = ["round"] + [p for p in phases] + ["row_total"]
    widths = [max(len(h), 13) for h in header]

    def fmt_row(cells):
        return "  ".join(str(c).rjust(w) for c, w in zip(cells, widths))

    lines = [fmt_row(header), fmt_row(["-" * w for w in widths])]
    for rnd in rounds:
        row = [per_round[rnd].get(p, 0.0) for p in phases]
        lines.append(fmt_row([rnd] + [f"{v:.3f}" for v in row]
                             + [f"{sum(row):.3f}"]))
    lines.append(fmt_row(["-" * w for w in widths]))
    total_row = [totals[p][1] for p in phases]
    lines.append(fmt_row(["total_s"] + [f"{v:.3f}" for v in total_row]
                         + [f"{sum(total_row):.3f}"]))
    lines.append(fmt_row(["count"] + [str(totals[p][0]) for p in phases]
                         + [str(sum(totals[p][0] for p in phases))]))
    lines.append(fmt_row(
        ["mean_s"]
        + [f"{(totals[p][1] / totals[p][0]):.4f}" if totals[p][0]
           else "-" for p in phases] + [""]))
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m shockwave_tpu.obs.report",
        description=__doc__.splitlines()[0])
    p.add_argument("trace", help="Chrome-trace JSON exported by the "
                                 "tracer (--obs_trace / "
                                 "export_chrome_trace)")
    p.add_argument("--phases", default=None,
                   help="comma-separated span names to tabulate "
                        f"(default: {','.join(names.REPORT_PHASES)})")
    args = p.parse_args(argv)
    phases = (tuple(s.strip() for s in args.phases.split(",") if s.strip())
              if args.phases else names.REPORT_PHASES)
    spans = load_spans(args.trace)
    if not spans:
        print(f"{args.trace}: no spans", file=sys.stderr)
        return 1
    print(f"{args.trace}: {len(spans)} spans")
    print(render(spans, phases))
    return 0


if __name__ == "__main__":
    sys.exit(main())
