"""Core data model tests: JobIdPair, Job, traces, oracles, adaptation parity."""
import os

import pytest

from shockwave_tpu.core import (
    Job, JobIdPair, parse_trace, read_throughputs, num_epochs_for,
)
from shockwave_tpu.core.adaptation import accordion_bs_schedule, gns_bs_schedule
from shockwave_tpu.core.profiles import build_profiles

DATA = os.path.join(os.path.dirname(__file__), "..", "data")
TRACE = os.path.join(DATA, "canonical_120job.trace")
THROUGHPUTS = os.path.join(DATA, "tacc_throughputs.json")


class TestJobIdPair:
    def test_single(self):
        j = JobIdPair(3)
        assert not j.is_pair()
        assert j.integer_job_id() == 3
        assert j == 3
        assert j.singletons() == (j,)

    def test_pair_normalizes_order(self):
        assert JobIdPair(5, 2) == JobIdPair(2, 5)
        assert hash(JobIdPair(5, 2)) == hash(JobIdPair(2, 5))
        assert JobIdPair(2, 5).as_tuple() == (2, 5)

    def test_mixed_keys_in_dict(self):
        d = {}
        for i in range(50):
            d[JobIdPair(i)] = ("single", i)
        for i in range(20):
            for j in range(i + 1, 20):
                d[JobIdPair(i, j)] = ("pair", i, j)
        assert d[JobIdPair(7)] == ("single", 7)
        assert d[JobIdPair(12, 3)] == ("pair", 3, 12)
        assert len(d) == 50 + 190

    def test_ordering_singles_before_pairs(self):
        assert JobIdPair(9) < JobIdPair(0, 1)
        assert sorted([JobIdPair(1, 2), JobIdPair(3), JobIdPair(0)]) == [
            JobIdPair(0), JobIdPair(3), JobIdPair(1, 2)]

    def test_overlaps(self):
        assert JobIdPair(1).overlaps_with(JobIdPair(1, 7))
        assert not JobIdPair(2).overlaps_with(JobIdPair(1, 7))


class TestJob:
    def test_model_and_bs_parsing(self):
        j = Job(None, "ResNet-18 (batch size 32)", "python3 main.py --batch_size 32")
        assert j.model == "ResNet-18"
        assert j.batch_size == 32

    def test_update_bs_rewrites_last_token(self):
        j = Job(None, "ResNet-18 (batch size 32)",
                "python3 main.py --data_dir=%s/cifar10 --batch_size 32")
        j.update_bs(64)
        assert j.batch_size == 64
        assert j.command.endswith("--batch_size 64")

    def test_update_bs_translation_second_to_last(self):
        j = Job(None, "ResNet-50 (batch size 64)",
                "python3 main.py -j 4 -a resnet50 -b 64 %s/imagenet/")
        j.update_bs(128)
        assert j.command == "python3 main.py -j 4 -a resnet50 -b 128 %s/imagenet/"
        assert j.batch_size == 128


class TestTrace:
    def test_parse_canonical(self):
        jobs, arrivals = parse_trace(TRACE)
        assert len(jobs) == 120
        assert arrivals == sorted(arrivals)
        assert all(j.scale_factor >= 1 for j in jobs)
        modes = {j.mode for j in jobs}
        assert modes <= {"static", "accordion", "gns"}

    def test_oracle_lookup(self):
        tp = read_throughputs(THROUGHPUTS)
        v = tp["v100"][("ResNet-18 (batch size 16)", 1)]["null"]
        assert v == pytest.approx(57.68, abs=0.5)


class TestAdaptationParity:
    """Cross-check the data-driven schedules against the reference code."""

    CASES = [
        ("ResNet-18", bs, sf, n)
        for bs in (16, 32, 64, 128, 256)
        for sf in (1, 2, 4, 8)
        for n in (5, 12, 40, 80, 200, 400)
    ] + [
        ("ResNet-50", bs, sf, n)
        for bs in (16, 32, 64, 128) for sf in (1, 2, 4) for n in (50, 120, 250)
    ] + [
        ("LM", bs, sf, n)
        for bs in (5, 10, 20, 40, 80) for sf in (1, 2, 4) for n in (10, 35, 90)
    ] + [
        ("Recommendation", bs, 1, n)
        for bs in (512, 1024, 2048, 4096, 8192) for n in (15, 45, 100)
    ] + [("Transformer", 64, 1, 60)]

    def test_gns_matches_reference(self, reference_utils):
        for model, bs, sf, n in self.CASES:
            job_type = f"{model} (batch size {bs})"
            expected = reference_utils.get_gns_bs_pattern(job_type, bs, n, sf)
            got = gns_bs_schedule(model, bs, n, sf)
            assert list(got) == list(expected), (model, bs, sf, n)

    def test_accordion_matches_reference(self, reference_utils):
        for model, bs, sf, n in self.CASES:
            job_type = f"{model} (batch size {bs})"
            expected = reference_utils.get_accordion_bs_pattern(job_type, bs, n, 0)
            got = accordion_bs_schedule(model, bs, n)
            assert got == expected, (model, bs, n)


class TestProfiles:
    def test_profiles_match_reference_generator(self, reference_utils, tmp_path):
        """Exact parity with the reference's Shockwave profile pickles."""
        import pickle as pkl
        import shutil
        trace_copy = tmp_path / "canonical.trace"
        shutil.copy(TRACE, trace_copy)
        reference_utils.generate_pickle_file(str(trace_copy), THROUGHPUTS)
        with open(tmp_path / "canonical.pickle", "rb") as f:
            expected = pkl.load(f)

        jobs, _ = parse_trace(TRACE)
        got = build_profiles(jobs, read_throughputs(THROUGHPUTS))
        assert len(got) == len(expected)
        for g, e in zip(got, expected):
            assert g["model"] == e["model"]
            assert g["num_epochs"] == e["num_epochs"]
            assert g["bs_every_epoch"] == e["bs_every_epoch"]
            assert g["mem_every_epoch"] == e["mem_every_epoch"]
            assert g["util_every_epoch"] == e["util_every_epoch"]
            assert g["duration_every_epoch"] == pytest.approx(e["duration_every_epoch"])
            assert int(g["scale_factor"]) == int(e["scale_factor"])

    def test_build_canonical_profiles(self):
        jobs, _ = parse_trace(TRACE)
        tp = read_throughputs(THROUGHPUTS)
        profiles = build_profiles(jobs, tp)
        assert len(profiles) == 120
        for job, p in zip(jobs, profiles):
            n = p["num_epochs"]
            assert n == num_epochs_for(job.model, job.batch_size, job.total_steps)
            for key in ("bs_every_epoch", "mem_every_epoch", "util_every_epoch",
                        "duration_every_epoch"):
                assert len(p[key]) == n
            assert all(d > 0 for d in p["duration_every_epoch"])


class TestThroughputEstimator:
    """Matrix-completion job-type matching (reference: throughput_estimator.py)."""

    @pytest.fixture(scope="class")
    def oracle(self):
        return read_throughputs(THROUGHPUTS)

    @pytest.fixture(scope="class")
    def job_types(self, oracle):
        return sorted(
            k for k in oracle["v100"]
            if k[1] == 1 and all(oracle[w][k]["null"] > 0
                                 for w in ("v100", "p100")))

    def test_als_recovers_low_rank(self):
        import numpy as np
        from shockwave_tpu.core import als_complete
        rng = np.random.RandomState(1)
        true = rng.rand(20, 3) @ rng.rand(3, 30)  # rank 3
        mask = (rng.rand(20, 30) < 0.8).astype(float)
        recon = als_complete(true * mask, mask, k=3, mu=1e-3,
                             max_iterations=500)
        err = np.abs(recon - true)[mask == 0].mean()
        assert err < 0.05

    def test_fully_profiled_matches_exactly(self, oracle, job_types):
        from shockwave_tpu.core import ThroughputEstimator
        est = ThroughputEstimator(
            oracle, ["v100"], job_types,
            num_reference_job_types=len(job_types),
            profiling_percentage=1.0, seed=0)
        for jt in job_types[:8]:
            assert est.match_job_to_reference_job(jt) == jt

    def test_partial_profiling_returns_reference_type(self):
        # The TACC oracle's packing profiles are near scale-multiples of
        # one another (cosine-indistinguishable), so recovery is tested on
        # a synthetic oracle whose job types have distinct packing shapes.
        import numpy as np
        from shockwave_tpu.core import ThroughputEstimator
        rng = np.random.RandomState(0)
        types = [(f"M{i} (batch size 32)", 1) for i in range(8)]
        oracle = {}
        for w in ("tpu_a", "tpu_b"):
            oracle[w] = {}
            shapes = rng.rand(len(types), len(types)) * 0.8 + 0.1
            for i, t in enumerate(types):
                entry = {"null": 10.0 + i}
                for j, u in enumerate(types):
                    entry[u] = [shapes[i, j] * entry["null"], 0.0]
                oracle[w][t] = entry
        # Non-alphabetical worker-type order: the probe row must follow the
        # constructor order, not sorted() order.
        est = ThroughputEstimator(
            oracle, ["tpu_b", "tpu_a"], types,
            num_reference_job_types=len(types),
            profiling_percentage=0.6, seed=3)
        hits = 0
        for jt in types:
            match = est.match_job_to_reference_job(jt)
            assert match in types
            hits += match == jt
        assert hits >= 6

    def test_reference_throughputs_symmetric(self, oracle, job_types):
        from shockwave_tpu.core import ThroughputEstimator
        est = ThroughputEstimator(
            oracle, ["v100"], job_types,
            num_reference_job_types=6,
            profiling_percentage=1.0, seed=0)
        ref = est.get_reference_throughputs()
        types = est._reference_job_types
        for a in types:
            for b in types:
                fwd, bwd = ref["v100"][a][b], ref["v100"][b][a]
                assert fwd[0] == pytest.approx(bwd[1])
                assert fwd[1] == pytest.approx(bwd[0])
                assert fwd[0] >= 0.0


class TestJobGeneration:
    """Template table + Philly-distribution job/trace generator
    (reference: job_table.py, utils.py:96-275, generate_trace.py)."""

    def test_job_table_families(self):
        from shockwave_tpu.core.job_table import JOB_TABLE
        models = {t.model.split(" ")[0] for t in JOB_TABLE}
        assert models == {"ResNet-18", "ResNet-50", "Transformer", "LM",
                          "Recommendation"}
        assert len(JOB_TABLE) == 4 + 3 + 4 + 5 + 5
        # Transformer capped at 128 to avoid the reference's OOM profile.
        assert all("256" not in t.model for t in JOB_TABLE
                   if t.model.startswith("Transformer"))

    def test_scale_factor_distribution(self):
        import random
        from shockwave_tpu.core.generator import philly_scale_factor
        rng = random.Random(0)
        counts = {1: 0, 2: 0, 4: 0, 8: 0}
        for _ in range(4000):
            counts[philly_scale_factor(rng)] += 1
        assert counts[1] > counts[2] > counts[8]
        assert abs(counts[1] / 4000 - 0.70) < 0.05
        assert abs(counts[4] / 4000 - 0.15) < 0.03

    def test_generate_job_steps_from_oracle(self):
        import random
        from shockwave_tpu.core.generator import generate_job
        tp = read_throughputs(THROUGHPUTS)
        rng = random.Random(1)
        for _ in range(20):
            job = generate_job(tp, rng=rng, fixed_job_duration=3600,
                               generate_multi_gpu_jobs=True)
            key = (job.job_type, job.scale_factor)
            oracle = tp["v100"][key]["null"]
            assert job.total_steps == int(3600 * oracle)
            assert job.total_steps > 0

    def test_generate_trace_deterministic_and_parseable(self, tmp_path):
        from shockwave_tpu.core.generator import generate_trace
        from shockwave_tpu.core.trace import job_to_trace_line
        tp = read_throughputs(THROUGHPUTS)
        jobs1, arr1 = generate_trace(30, tp, lam=300, seed=7,
                                     mode_mix=(0.0, 0.5, 0.5))
        jobs2, arr2 = generate_trace(30, tp, lam=300, seed=7,
                                     mode_mix=(0.0, 0.5, 0.5))
        assert arr1 == arr2
        assert [j.job_type for j in jobs1] == [j.job_type for j in jobs2]
        assert arr1 == sorted(arr1) and arr1[0] == 0.0
        path = tmp_path / "gen.trace"
        with open(path, "w") as f:
            for job, arrival in zip(jobs1, arr1):
                f.write(job_to_trace_line(job, arrival) + "\n")
        jobs3, arr3 = parse_trace(str(path))
        assert len(jobs3) == 30
        assert [j.total_steps for j in jobs3] == [j.total_steps for j in jobs1]

    def test_dynamic_mode_mix(self):
        from shockwave_tpu.core.generator import generate_trace
        tp = read_throughputs(THROUGHPUTS)
        # Long durations so accordion jobs aren't pinned static.
        jobs, _ = generate_trace(60, tp, seed=3, mode_mix=(0.0, 0.5, 0.5),
                                 min_duration_hours=1.0,
                                 max_duration_hours=4.0)
        modes = {j.mode for j in jobs}
        assert "accordion" in modes and "gns" in modes


class TestPackaging:
    def test_version_matches_pyproject(self):
        tomllib = pytest.importorskip("tomllib")  # stdlib from 3.11

        import shockwave_tpu
        root = os.path.join(os.path.dirname(__file__), "..")
        with open(os.path.join(root, "pyproject.toml"), "rb") as f:
            meta = tomllib.load(f)
        assert meta["project"]["version"] == shockwave_tpu.__version__
