"""Shared training scaffold for all workload entry points.

Wires a flax model + synthetic/real pipeline into the cluster runtime:
lease iterator, gang initialization over a dp mesh, checkpoint/resume, and
the dynamic-adaptation monitors (Accordion / GNS). Each workload's main.py
declares its model, data, and loss; everything else lives here.

TPU-first mechanics:
- one jit'd train step; batch sharded over the "dp" mesh axis, params
  replicated; XLA inserts the gradient all-reduce on ICI,
- bf16 compute / fp32 params (models decide), donate_argnums on state so
  buffers are reused in place,
- gradient-norm instrumentation for adaptation rides in the same compiled
  step (no extra device round trips).
"""
from __future__ import annotations

import argparse
import logging
import os
import signal
import sys
import time
from typing import Callable, Optional

import flax.serialization
import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..parallel.mesh import (data_parallel_sharding, make_mesh,
                             maybe_initialize_distributed)
from ..runtime.iterator import LeaseIterator

THROUGHPUT_LOG_INTERVAL = 100


def common_parser(description: str, steps_args=("--num_steps",)) -> argparse.ArgumentParser:
    """Arguments every dispatched workload receives."""
    p = argparse.ArgumentParser(description=description, allow_abbrev=False)
    for name in steps_args:
        p.add_argument(name, dest="num_steps", type=int, default=None)
    p.add_argument("--local_rank", type=int, default=0)
    p.add_argument("--checkpoint_dir", default="/tmp/swtpu_ckpt")
    p.add_argument("--enable_lease_iterator", "--enable_gavel_iterator",
                   dest="enable_lease_iterator", action="store_true")
    p.add_argument("--throughput_estimation_interval", type=int,
                   default=THROUGHPUT_LOG_INTERVAL)
    # Multi-chip gang rendezvous (appended by the scheduler for sf > 1).
    p.add_argument("--coordinator", default=None)
    p.add_argument("--num_processes", type=int, default=None)
    p.add_argument("--process_id", type=int, default=None)
    p.add_argument("--cuda", action="store_true", help="ignored (TPU build)")
    p.add_argument("--synthetic_data", action="store_true", default=True)
    return p


def parse_args(parser: argparse.ArgumentParser):
    """Parse workload CLI args and, for gang members, join the
    jax.distributed cluster BEFORE the caller touches JAX.

    Every workload main must use this instead of parser.parse_args():
    jax.distributed.initialize refuses to run once the XLA backend is
    initialized, and the mains' first act after parsing is model.init —
    a backend-initializing computation. (Found by the first real
    2-process gang run; the stub-worker gang tests never launch a
    training process.)"""
    args = parser.parse_args()
    # The dispatcher kills with SIGTERM-then-SIGKILL; converting SIGTERM
    # to SystemExit lets the mains' finally blocks (checkpoint save,
    # lease-iterator teardown) and atexit (relayed-TPU client disconnect,
    # which otherwise wedges the chip grant) run before exit.
    signal.signal(signal.SIGTERM, lambda *_: sys.exit(143))
    maybe_initialize_distributed(args.coordinator, args.num_processes,
                                 args.process_id)
    return args


def _host_fingerprint() -> str:
    """Short hash of the host's architecture + CPU feature flags.

    XLA:CPU AOT artifacts embed the compile machine's feature set and
    fail to load on a host with different features (cpu_aot_loader
    rejects them, stalling the job until the scheduler's liveness
    watchdog kills it) — so cached executables are segregated per host.
    """
    import hashlib
    import platform

    bits = [platform.system(), platform.machine()]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    bits.append(line.strip())
                    break
    except OSError:
        pass
    return hashlib.sha256("|".join(bits).encode()).hexdigest()[:8]


def enable_compile_cache(path: Optional[str] = None) -> None:
    """Point XLA's persistent compilation cache at a per-host directory.

    Cluster scheduling restarts jobs every few rounds; without this every
    re-dispatch pays the full jit compile inside its lease (the dominant
    startup cost on TPU — the reference's PyTorch workloads have no
    analogue). Executables are keyed by (computation, shapes, mesh), so a
    re-dispatched job at the same batch size restarts in seconds. The
    base dir (or $SWTPU_COMPILE_CACHE) gains a host-fingerprint subdir
    so a cache shared over NFS never serves another machine's AOT code.
    """
    path = path or os.environ.get(
        "SWTPU_COMPILE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "shockwave_tpu",
                     "xla_cache"))
    path = os.path.join(path, _host_fingerprint())
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except Exception as e:  # noqa: BLE001 - cache is an optimization only
        logging.getLogger(__name__).warning("compile cache disabled: %s", e)


def checkpoint_path(checkpoint_dir: str) -> str:
    return os.path.join(checkpoint_dir, "model.ckpt")


# Integrity footer appended to every checkpoint: crc32(payload) + magic.
# A footer-less file is a pre-footer (legacy) checkpoint and is loaded
# unverified rather than rejected.
_CKPT_MAGIC = b"SWCKPT1\n"


def save_checkpoint(path: str, state: dict) -> None:
    """Durable checkpoint write (core/durable_io): CRC-footered payload,
    fsync'd file and directory, previous checkpoint retained as
    `<path>.prev` so a save interrupted by preemption (or a corrupted
    current file) never costs the job ALL of its progress —
    load_checkpoint falls back."""
    from ..core.durable_io import write_durable
    os.makedirs(os.path.dirname(path), exist_ok=True)
    state_dict = flax.serialization.to_state_dict(jax.device_get(state))
    payload = flax.serialization.msgpack_serialize(state_dict)
    write_durable(path, payload, _CKPT_MAGIC)


def save_checkpoint_rank0(path: str, state: dict) -> None:
    """Gang-safe save: members hold replicated state; only rank 0 writes
    (the reference's DDP rank-0 torch.save convention) — two ranks racing
    os.replace on one path lose the .tmp file."""
    if jax.process_index() == 0:
        save_checkpoint(path, state)


def _read_verified_payload(path: str) -> Optional[bytes]:
    """Checkpoint bytes with the integrity footer verified and stripped;
    None if missing or corrupt. Legacy footer-less files pass through
    unverified (msgpack decode is their only check)."""
    from ..core.durable_io import FOOTER_CORRUPT, FOOTER_OK, verify_footer
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    status, payload = verify_footer(blob, _CKPT_MAGIC)
    if status == FOOTER_OK:
        return payload
    if status == FOOTER_CORRUPT:
        logging.getLogger(__name__).warning(
            "checkpoint %s fails CRC; ignoring it", path)
        return None
    return blob or None  # legacy footer-less checkpoint


def load_checkpoint(path: str, template: dict) -> Optional[dict]:
    """Load `path`, falling back to `<path>.prev` — and to a fresh start
    (None) — on corruption instead of crashing the trainer: on
    preemptible capacity a torn checkpoint is a when, not an if."""
    log = logging.getLogger(__name__)
    for candidate in (path, path + ".prev"):
        if not os.path.exists(candidate):
            continue
        payload = _read_verified_payload(candidate)
        if payload is None:
            continue
        try:
            restored = flax.serialization.msgpack_restore(payload)
            result = flax.serialization.from_state_dict(template, restored)
        except Exception as e:  # noqa: BLE001 - any decode failure means
            # the file is unusable; the fallback chain continues.
            log.warning("checkpoint %s unreadable (%s: %s); trying "
                        "fallback", candidate, type(e).__name__, e)
            continue
        if candidate != path:
            log.warning("restored from previous checkpoint %s (current "
                        "was missing or corrupt)", candidate)
        return result
    return None


class AccordionMonitor:
    """Critical-regime detector (Agarwal et al.): compares successive
    epochs' accumulated gradient norms; a large relative swing means the
    gradient is changing fast -> critical regime -> train at the small
    batch size (reference: accordion_workloads/.../main.py:323-429).

    The process only knows the batch size it was launched with; the
    scheduler owns the original/max sizes and applies the actual rescale
    on the next dispatch."""

    def __init__(self, iterator, launch_bs: int, max_bs: int,
                 threshold: float = 0.5):
        self._iterator = iterator
        self._launch_bs = launch_bs
        self._max_bs = max_bs
        self._threshold = threshold
        self._prev_epoch_norm: Optional[float] = None
        self._accum = 0.0
        self._count = 0

    def observe_step(self, grad_norm: float):
        self._accum += float(grad_norm)
        self._count += 1

    def end_epoch(self) -> bool:
        """Returns True if a resize request was issued (job must exit)."""
        if self._count == 0:
            return False
        epoch_norm = self._accum / self._count
        self._accum, self._count = 0.0, 0
        prev, self._prev_epoch_norm = self._prev_epoch_norm, epoch_norm
        if prev is None:
            return False
        ratio = abs(prev - epoch_norm) / max(prev, 1e-12)
        in_critical = ratio > self._threshold
        if in_critical and self._launch_bs >= self._max_bs:
            self._iterator.update_resource_requirement(big_bs=False, small_bs=True)
            return True
        if not in_critical and self._launch_bs < self._max_bs:
            self._iterator.update_resource_requirement(big_bs=True, small_bs=False)
            return True
        return False


class GNSMonitor:
    """Gradient-noise-scale estimator (McCandlish et al.): compares the
    gradient norm at a small (per-chip) batch vs the full global batch to
    estimate the noise scale B_noise = S / |G|^2; when the running noise
    scale clears the current batch size, request a doubling
    (reference: gns_workloads/.../main.py:329-383, 526-555)."""

    def __init__(self, iterator, small_bs: int, big_bs: int, max_bs: int,
                 window: int = 50):
        self._iterator = iterator
        self._b_small = small_bs
        self._b_big = big_bs
        self._max_bs = max_bs
        self._window = window
        self._small_sq: list = []
        self._big_sq: list = []

    def observe_step(self, small_norm_sq: float, big_norm_sq: float):
        self._small_sq.append(float(small_norm_sq))
        self._big_sq.append(float(big_norm_sq))
        if len(self._small_sq) > self._window:
            self._small_sq.pop(0)
            self._big_sq.pop(0)

    def maybe_request_double(self, current_bs: int) -> bool:
        if len(self._small_sq) < self._window or self._b_big == self._b_small:
            return False
        small = float(np.mean(self._small_sq))
        big = float(np.mean(self._big_sq))
        # Unbiased |G|^2 and trace(Sigma) estimates from two batch sizes.
        g2 = (self._b_big * big - self._b_small * small) / (self._b_big - self._b_small)
        s = (small - big) / (1.0 / self._b_small - 1.0 / self._b_big)
        if g2 <= 0:
            return False
        noise_scale = s / g2
        if noise_scale > current_bs and current_bs < self._max_bs:
            self._iterator.update_resource_requirement(big_bs=True, small_bs=False)
            return True
        return False


class Trainer:
    """Drives the standard cluster training loop for one workload."""

    def __init__(self, args, model_apply_loss: Callable, init_state: dict,
                 data_loader, mode: Optional[str] = None,
                 initial_bs: Optional[int] = None, max_bs: Optional[int] = None,
                 learning_rate: float = 1e-2):
        enable_compile_cache()
        maybe_initialize_distributed(args.coordinator, args.num_processes,
                                     args.process_id)
        self.args = args
        self.mode = mode or os.environ.get("SWTPU_MODE", "static")
        self.mesh = make_mesh(batch_size=initial_bs)
        self.batch_sharding, self.repl_sharding = data_parallel_sharding(self.mesh)

        self.tx = optax.sgd(learning_rate, momentum=0.9)
        init_state = dict(init_state)
        init_state.setdefault("opt_state", self.tx.init(init_state["params"]))
        init_state.setdefault("step", jnp.zeros((), jnp.int32))
        self.state = jax.device_put(init_state, self.repl_sharding)
        self._loss_fn = model_apply_loss
        self.data_loader = data_loader
        self.initial_bs = initial_bs
        self.max_bs = max_bs or initial_bs

        track_gns = self.mode == "gns"
        self.train_step = self._build_train_step(track_gns)

    def _build_train_step(self, track_gns: bool):
        tx = self.tx
        loss_fn = self._loss_fn
        mesh = self.mesh

        n_dev = max(1, len(jax.devices()))

        def step_fn(state, *batch):
            def scalar_loss(params):
                return loss_fn(params, state, *batch)
            (loss, aux), grads = jax.value_and_grad(
                scalar_loss, has_aux=True)(state["params"])
            metrics = {"loss": loss}
            gsq = optax.global_norm(grads) ** 2
            metrics["grad_norm_sq"] = gsq
            if track_gns:
                # Small-batch gradient: one chip's slice of the batch. The
                # big/small norm pair feeds the noise-scale estimator.
                small = [b[: max(1, b.shape[0] // n_dev)] for b in batch]

                def small_loss(params):
                    return loss_fn(params, state, *small)
                _, small_grads = jax.value_and_grad(
                    small_loss, has_aux=True)(state["params"])
                metrics["grad_norm_sq_small"] = optax.global_norm(small_grads) ** 2
            updates, new_opt = tx.update(grads, state["opt_state"],
                                         state["params"])
            new_params = optax.apply_updates(state["params"], updates)
            new_state = dict(state, params=new_params, opt_state=new_opt,
                             step=state["step"] + 1)
            if "batch_stats" in aux:
                new_state["batch_stats"] = aux["batch_stats"]
            return new_state, metrics

        return jax.jit(step_fn, donate_argnums=(0,))

    def run(self):
        args = self.args
        use_lease = args.enable_lease_iterator
        if use_lease:
            # Multi-process gangs synchronize lease expiry so the gang
            # checkpoint is consistent (the reference's
            # torch.distributed.barrier() on expiry,
            # gavel_iterator.py:148-149); single-process jobs skip it.
            barrier = None
            gang_allreduce = None
            if args.num_processes and args.num_processes > 1:
                from jax.experimental import multihost_utils

                def barrier():
                    multihost_utils.sync_global_devices("swtpu_lease_exit")

                # Agrees every time-based lease decision across the gang
                # so all members exit at the same step (LeaseIterator
                # docs); allgather returns identical arrays everywhere,
                # so the reduction is deterministic.
                def gang_allreduce(value, op):
                    arr = np.asarray(multihost_utils.process_allgather(
                        np.float32(value)))
                    return float(arr.max() if op == "max" else arr.min())
            iterator = LeaseIterator(
                self.data_loader, args.checkpoint_dir,
                load_checkpoint_func=self._load, save_checkpoint_func=self._save,
                synthetic_data=args.synthetic_data,
                distributed_barrier=barrier,
                gang_allreduce=gang_allreduce)
        else:
            iterator = _PlainIterator(self.data_loader)

        restored = iterator.load_checkpoint(checkpoint_path(args.checkpoint_dir)) \
            if use_lease else self._load(checkpoint_path(args.checkpoint_dir))
        if restored is not None:
            self.state = jax.device_put(restored, self.repl_sharding)
        start_step = int(self.state["step"])
        budget = args.num_steps
        if use_lease and budget is not None and start_step >= budget:
            # Checkpoint is ahead of the scheduler's accounting (previous
            # worker died post-checkpoint, pre-report): reconcile instead
            # of exiting (0, 0) — the micro-task-failure signal — which
            # would burn a failure attempt every round until the job is
            # dropped despite being fully trained.
            iterator.report_checkpoint_ahead()

        monitor = None
        if self.mode == "accordion" and self.initial_bs:
            monitor = AccordionMonitor(iterator, self.initial_bs, self.max_bs)
        elif self.mode == "gns" and self.initial_bs:
            per_chip = max(1, self.initial_bs // len(jax.devices()))
            monitor = GNSMonitor(iterator, per_chip, self.initial_bs,
                                 self.max_bs)

        steps_done = 0
        window_start = time.time()
        window_steps = 0
        loss = None
        # Synthetic pipelines yield the same host batch object every step;
        # re-uploading it would cost a full host->device round trip per
        # step (~70 ms through a relayed chip — measured 30x slowdown).
        # Cache the device-resident copy for the identical host object
        # (kept strongly referenced, so its identity cannot be recycled).
        host_batch_ref, dev_batch = None, None
        try:
            while not iterator.done and (budget is None
                                         or start_step + steps_done < budget):
                epoch_resized = False
                for batch in iterator:
                    if batch is not host_batch_ref:
                        host_batch_ref = batch
                        dev_batch = jax.device_put(batch, self.batch_sharding)
                    batch = dev_batch
                    self.state, metrics = self.train_step(self.state, *batch)
                    loss = metrics["loss"]
                    if use_lease:
                        iterator.set_sync_ref(loss)
                    steps_done += 1
                    window_steps += 1
                    if monitor is not None:
                        gsq = metrics["grad_norm_sq"]
                        if isinstance(monitor, AccordionMonitor):
                            monitor.observe_step(jnp.sqrt(gsq))
                        else:
                            monitor.observe_step(
                                metrics.get("grad_norm_sq_small", gsq), gsq)
                            if monitor.maybe_request_double(self.initial_bs):
                                epoch_resized = True
                                break
                    if window_steps >= args.throughput_estimation_interval:
                        jax.block_until_ready(loss)
                        now = time.time()
                        print(f"[THROUGHPUT_ESTIMATION]\t{now}\t"
                              f"{start_step + steps_done}", flush=True)
                        window_start, window_steps = now, 0
                    if budget is not None and start_step + steps_done >= budget:
                        iterator.complete()
                        break
                if (monitor is not None
                        and isinstance(monitor, AccordionMonitor)
                        and not iterator.done and not epoch_resized):
                    epoch_resized = monitor.end_epoch()
                if epoch_resized:
                    break
                if not use_lease and (budget is None
                                      or start_step + steps_done >= budget):
                    break
        finally:
            if loss is not None:
                jax.block_until_ready(loss)
            if use_lease:
                iterator.save_checkpoint(checkpoint_path(args.checkpoint_dir),
                                         self.state)
            else:
                self._save(checkpoint_path(args.checkpoint_dir), self.state)
        print(f"TRAINED {steps_done} steps (cumulative "
              f"{start_step + steps_done})", flush=True)
        return steps_done

    def _save(self, path, state):
        # The lease iterator's exit barrier has already synchronized the
        # gang by the time save runs, so rank 0's state is the gang's state.
        save_checkpoint_rank0(path, state)

    def _load(self, path):
        return load_checkpoint(path, jax.device_get(self.state))


class _PlainIterator:
    """Lease-free iterator with the same surface (standalone runs)."""

    def __init__(self, loader):
        self._loader = loader
        self.done = False

    def __iter__(self):
        return iter(self._loader)

    def load_checkpoint(self, path):
        return None

    def save_checkpoint(self, path, state):
        return None

    def complete(self):
        self.done = True

    def set_sync_ref(self, v):
        pass

    def update_resource_requirement(self, big_bs, small_bs):
        self.done = True
