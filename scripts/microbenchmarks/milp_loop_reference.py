"""The pre-vectorization MILP loop assemblers, verbatim — the ONE copy.

Two consumers, deliberately sharing this module so they can never
drift: the golden-equivalence suite (tests/test_milp_assembly.py) pins
the vectorized assembler byte-identical to these loops, and
bench_milp_assembly.py's `--assembler loop` arm produces the
EXPERIMENTS.md 'before' numbers from the same certified oracle. Not
part of the shockwave_tpu package: production code must never call the
loop path again.
"""
from __future__ import annotations

import numpy as np
from scipy import sparse


def reference_assemble(L, njobs, future_nrounds, round_duration, ngpus,
                       bases, base_logs, nworkers, durations, dirichlet,
                       progress, epochs, ftf_caps, k, priorities, with_ftf):
    """The historical `assemble` closure from milp.plan_schedule."""
    rows_ub, cols_ub, vals_ub, b_ub = [], [], [], []
    rows_eq, cols_eq, vals_eq, b_eq = [], [], [], []

    def add_ub(entries, rhs):
        r = len(b_ub)
        for col, val in entries:
            rows_ub.append(r); cols_ub.append(col); vals_ub.append(val)
        b_ub.append(rhs)

    def add_eq(entries, rhs):
        r = len(b_eq)
        for col, val in entries:
            rows_eq.append(r); cols_eq.append(col); vals_eq.append(val)
        b_eq.append(rhs)

    for r in range(future_nrounds):
        add_ub([(L.x(j, r), nworkers[j]) for j in range(njobs)], ngpus)
    for j in range(njobs):
        add_ub([(L.p(j), durations[j])]
               + [(L.x(j, r), -round_duration)
                  for r in range(future_nrounds)], 0.0)
        add_eq([(L.w(j, b), bases[b]) for b in range(L.B)]
               + [(L.p(j), -1.0 / epochs[j])], progress[j] / epochs[j])
        add_eq([(L.w(j, b), 1.0) for b in range(L.B)], 1.0)
        for b in range(L.B):
            add_ub([(L.w(j, b), 1.0), (L.z(j, b), -1.0)], 0.0)
        add_ub([(L.z(j, b), 1.0) for b in range(L.B)], 2.0)
        for lo in range(L.B - 2):
            for hi in range(lo + 2, L.B):
                add_ub([(L.z(j, lo), 1.0), (L.z(j, hi), 1.0)], 1.0)
        add_ub([(L.s(j), -1.0), (L.p(j), -durations[j])], -dirichlet[j])
        add_ub([(L.s(j), 1.0), (L.t, -1.0)], 0.0)
        if with_ftf:
            if ftf_caps[j] < 0:
                return None
            add_ub([(L.s(j), 1.0)], ftf_caps[j])
    A_ub = sparse.coo_matrix((vals_ub, (rows_ub, cols_ub)),
                             shape=(len(b_ub), L.n)).tocsr()
    A_eq = sparse.coo_matrix((vals_eq, (rows_eq, cols_eq)),
                             shape=(len(b_eq), L.n)).tocsr()
    c = np.zeros(L.n)
    for j in range(njobs):
        for b in range(L.B):
            c[L.w(j, b)] = -priorities[j] * base_logs[b] / (
                njobs * future_nrounds)
    c[L.t] = k
    integrality = np.zeros(L.n)
    ub = np.full(L.n, np.inf)
    for j in range(njobs):
        for r in range(future_nrounds):
            integrality[L.x(j, r)] = 1
            ub[L.x(j, r)] = 1
        for b in range(L.B):
            integrality[L.z(j, b)] = 1
            ub[L.z(j, b)] = 1
            ub[L.w(j, b)] = 1
    return c, A_ub, np.array(b_ub), A_eq, np.array(b_eq), integrality, ub


def reference_rank_model(x, priorities, nworkers, ngpus):
    """The historical `_rank_in_schedule` model assembly."""
    njobs, nrounds = x.shape
    counts = x.sum(axis=1)
    n = njobs * nrounds
    rows_ub, cols_ub, vals_ub, b_ub = [], [], [], []
    rows_eq, cols_eq, vals_eq, b_eq = [], [], [], []
    for r in range(nrounds):
        row = len(b_ub)
        for j in range(njobs):
            rows_ub.append(row); cols_ub.append(j * nrounds + r)
            vals_ub.append(nworkers[j])
        b_ub.append(ngpus)
    for j in range(njobs):
        row = len(b_eq)
        for r in range(nrounds):
            rows_eq.append(row); cols_eq.append(j * nrounds + r)
            vals_eq.append(1.0)
        b_eq.append(float(counts[j]))
    c = np.zeros(n)
    for j in range(njobs):
        if counts[j] > 0:
            for r in range(nrounds):
                c[j * nrounds + r] = priorities[j] * r / counts[j]
    A_ub = sparse.coo_matrix((vals_ub, (rows_ub, cols_ub)),
                             shape=(len(b_ub), n)).tocsr()
    A_eq = sparse.coo_matrix((vals_eq, (rows_eq, cols_eq)),
                             shape=(len(b_eq), n)).tocsr()
    return c, A_ub, np.array(b_ub), A_eq, np.array(b_eq)
