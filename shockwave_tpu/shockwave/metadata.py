"""Per-job epoch-granular metadata for the Shockwave planner.

Tracks profiled per-epoch durations and batch-size schedules, calibrates
the profile online against measured throughput, and provides the Bayesian
(Dirichlet) remaining-runtime estimate the market solver plans with
(reference: scheduler/JobMetaData.py).

The Dirichlet predictor treats the distinct batch sizes a job has used as
modes of a categorical distribution; observing the realized schedule up to
the current epoch sharpens the posterior over how many future epochs run
at each batch size, and the expected remaining runtime is the posterior-
weighted sum of per-mode epoch durations.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

INFINITY = 1e9


class JobMetadata:
    def __init__(self, job_id: int, profile: dict, overclock: float = 1.0):
        self.jobid = job_id
        self.model = profile["model"]
        self.dataset = profile["dataset"]
        self.jobname = f"ID_{job_id}_{self.model}_{self.dataset}"
        self.nworkers = int(profile.get("scale_factor", 1))
        self.epochs = int(profile["num_epochs"])
        assert self.epochs > 0
        self.epoch_nsamples = profile["num_samples_per_epoch"]
        self.epoch_gpu_req = list(profile["util_every_epoch"])
        self.epoch_gram_req = [round(mb / 1024.0, 1) for mb in profile["mem_every_epoch"]]
        self.epoch_duration = [
            max(1.0, round(d)) / overclock for d in profile["duration_every_epoch"]]
        self.epoch_duration = [max(1.0, d) for d in self.epoch_duration]
        self.epoch_duration_preprofiled = list(self.epoch_duration)
        self.bs_schedule = list(profile["bs_every_epoch"])
        assert len(self.bs_schedule) == self.epochs == len(self.epoch_duration)

        self.bs_modes = sorted(set(self.bs_schedule))
        self.bs_dirichlet_prior = {
            bs: self.epochs / len(self.bs_modes) for bs in self.bs_modes}

        self.epoch_progress = 0
        self.waiting_delay = 0.0
        self.timestamp_submit: Optional[float] = None
        self.timestamp_completion: Optional[float] = None

        self._throughput_measurements: Optional[OrderedDict] = None
        self._round_duration: Optional[float] = None
        # Invalidation state for the calibration/duration-map caches —
        # these run inside every MILP objective build (thousands of
        # calls per simulated trace) but their inputs change at most
        # once per round.
        self._calib_fingerprint = None
        self._duration_version = 0
        self._dmap_cache: Optional[tuple] = None
        # bs_schedule/prior/epochs are fixed after construction, so the
        # posterior is a pure function of (progress, epoch_progress,
        # duration calibration version). Memoized: within one planning
        # pass it runs once per job plus the schedule-construction sort
        # keys, and across rounds most jobs' keys are unchanged.
        self._posterior_cache: Dict[tuple, float] = {}

    # -- lifecycle ---------------------------------------------------------

    def register_submit(self, time: float) -> None:
        if self.timestamp_submit is None:
            self.timestamp_submit = time

    def register_completion(self, time: float) -> None:
        if self.timestamp_completion is None:
            self.timestamp_completion = time

    def set_epoch_progress(self, progress: int) -> None:
        assert 0 <= progress <= self.epochs
        self.epoch_progress = progress

    def add_waiting_delay(self, delay: float) -> None:
        self.waiting_delay += delay

    def reset_waiting_delay(self) -> None:
        self.waiting_delay = 0.0

    def attach_throughput_measurements(self, measurements: OrderedDict,
                                       round_duration: float) -> None:
        """Share the scheduler's per-round (throughput, bs) timeline."""
        self._throughput_measurements = measurements
        self._round_duration = round_duration

    # -- calibration -------------------------------------------------------

    def calibrate_profiled_epoch_duration(self) -> None:
        """Rescale the profiled epoch durations when the measured sample
        rate deviates >40% from the profile (reference: JobMetaData.py:225-288).

        Deliberate divergence from the reference: there, every getter
        re-ran calibration, and because the deficit term reads the
        current (already-rescaled) duration each run refines the last —
        so the planner's input depended on how many times a getter
        happened to run (an unstable x -> c/x feedback that can
        oscillate outright). Here calibration runs exactly once per NEW
        measurement and is cached, making the estimate a deterministic
        function of the measurement sequence; the canonical-trace
        parity suite stays within tolerance for all seven policies.
        """
        if not self._throughput_measurements:
            return
        # The scheduler appends one (tput, bs) entry per round to the
        # shared OrderedDict (and may overwrite the latest round's entry
        # from per-worker callbacks); (len, last item) fingerprints both.
        last = next(reversed(self._throughput_measurements))
        fp = (len(self._throughput_measurements), last,
              self._throughput_measurements[last])
        if fp == self._calib_fingerprint:
            return
        self._calib_fingerprint = fp
        timeline = sorted(self._throughput_measurements.keys())
        prev_round = 0
        measured_nsamples = 0.0
        for cur_round in timeline:
            tput, bs = self._throughput_measurements[cur_round]
            measured_nsamples += bs * tput * self._round_duration * (cur_round - prev_round)
            prev_round = cur_round
        measured_time_range = self._round_duration * max(timeline)

        preprofiled_time = 0.0
        preprofiled_nsamples = 0.0
        iepoch = 0
        for iepoch, duration in enumerate(self.epoch_duration_preprofiled):
            if preprofiled_time + duration > measured_time_range:
                break
            preprofiled_time += duration
            preprofiled_nsamples += self.epoch_nsamples
        deficit = measured_time_range - preprofiled_time
        if deficit > 0:
            # The deficit term reads the CURRENT (possibly rescaled)
            # duration, as in the reference — each new measurement
            # refines the previous calibration rather than restarting
            # from the profile (restarting holds fairness at 5.8%, not
            # the reference's 5%, on the canonical trace).
            preprofiled_nsamples += (
                self.epoch_nsamples * deficit / self.epoch_duration[iepoch])

        if (measured_nsamples <= 0 or preprofiled_nsamples <= 0
                or abs(measured_nsamples - preprofiled_nsamples)
                / preprofiled_nsamples <= 0.4):
            return
        amp = preprofiled_nsamples / measured_nsamples
        self.epoch_duration = [
            d * amp for d in self.epoch_duration_preprofiled]
        self._duration_version += 1

    # -- prediction --------------------------------------------------------

    def bs_epoch_duration_map(self) -> Dict[int, float]:
        self.calibrate_profiled_epoch_duration()
        if (self._dmap_cache is not None
                and self._dmap_cache[0] == self._duration_version):
            # Fresh copy: a caller mutating the result must not corrupt
            # the cached durations for every later planner query.
            return dict(self._dmap_cache[1])
        buckets: Dict[int, List[float]] = {}
        for bs, duration in zip(self.bs_schedule, self.epoch_duration):
            buckets.setdefault(bs, []).append(duration)
        out = {}
        for bs, durations in buckets.items():
            # np.mean (pairwise summation), not sum/len: the MILP's
            # branch decisions are sensitive at the ulp level, and the
            # pinned canonical numbers were produced with this rounding.
            mean = float(np.mean(durations))
            assert 0 < mean < INFINITY
            out[bs] = mean
        self._dmap_cache = (self._duration_version, out)
        return dict(out)

    def dirichlet_posterior_remaining_runtime(self, progress: Optional[int] = None,
                                              oracle: bool = False) -> float:
        if progress is None:
            progress = self.epoch_progress
        assert 0 <= progress <= self.epochs
        if oracle:
            return sum(self.epoch_duration[self.epoch_progress:])

        # Calibration may bump _duration_version; run it before keying.
        self.calibrate_profiled_epoch_duration()
        key = (progress, self.epoch_progress, self._duration_version)
        cached = self._posterior_cache.get(key)
        if cached is not None:
            return cached

        observed = self.bs_schedule[:progress + 1]
        posterior = dict(self.bs_dirichlet_prior)  # flat {int: float}
        for bs in observed:
            posterior[bs] += 1
        total = sum(posterior.values())
        rebased = {bs: self.epochs * c / total for bs, c in posterior.items()}
        for bs in observed:
            if rebased[bs] >= 1:
                rebased[bs] -= 1
        inflated = int(sum(rebased.values()) + 1)
        remaining = self.epochs - self.epoch_progress
        inflated = max(inflated, remaining)
        if not rebased or inflated <= 0 or remaining <= 0:
            runtime = 1.0
        else:
            durations = self.bs_epoch_duration_map()
            # NOTE: for a single-epoch job at progress 0 the rebasing
            # subtracts the whole (observed, in-progress) epoch and
            # this legitimately evaluates to exactly 0 despite
            # remaining > 0 — same algebra as the reference
            # (JobMetaData.py:326-363). The planner's priority ratio
            # guards the resulting zero fair-share averages
            # (milp.py:_relaxation_priorities); flooring the estimate
            # here instead would perturb the pinned canonical replay,
            # which depends on exact-zero estimates for near-done jobs.
            runtime = (sum(rebased[bs] * durations[bs] for bs in rebased)
                       * remaining / inflated)
        self._posterior_cache[key] = runtime
        return runtime

    def interpolated_epoch_duration(self) -> float:
        """Mean profiled duration of the epochs seen so far (+1)."""
        self.calibrate_profiled_epoch_duration()
        return float(np.mean(self.epoch_duration[:self.epoch_progress + 1]))
