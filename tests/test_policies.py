"""Policy layer tests on tiny, hand-checkable clusters."""
import pytest

from shockwave_tpu.core.job import JobIdPair
from shockwave_tpu.solver import get_policy
from shockwave_tpu.solver.max_min_fairness import MaxMinFairnessPolicyWithPacking


def single_type_state(num_jobs, num_workers, tputs=None, sfs=None):
    job_ids = [JobIdPair(i) for i in range(num_jobs)]
    throughputs = {
        j: {"v100": (tputs[i] if tputs else 1.0)} for i, j in enumerate(job_ids)}
    scale_factors = {j: (sfs[i] if sfs else 1) for i, j in enumerate(job_ids)}
    priorities = {j: 1.0 for j in job_ids}
    cluster = {"v100": num_workers}
    return job_ids, throughputs, scale_factors, priorities, cluster


def total_workers_used(alloc, scale_factors):
    return sum(alloc[j][wt] * scale_factors[j] for j in alloc for wt in alloc[j])


class TestIsolated:
    def test_even_split(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(4, 2)
        alloc = get_policy("isolated").get_allocation(tputs, sfs, cluster)
        for j in jobs:
            assert alloc[j]["v100"] == pytest.approx(0.5)

    def test_scale_factor_normalization(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(2, 4, sfs=[1, 4])
        alloc = get_policy("isolated").get_allocation(tputs, sfs, cluster)
        # Each job entitled to 2 workers; the sf=4 job runs 2/4 of the time.
        assert alloc[jobs[0]]["v100"] == pytest.approx(1.0)
        assert alloc[jobs[1]]["v100"] == pytest.approx(0.5)


class TestMaxMinFairness:
    def test_equal_jobs_get_equal_time(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(4, 2)
        alloc = get_policy("max_min_fairness").get_allocation(tputs, sfs, prios, cluster)
        shares = [alloc[j]["v100"] for j in jobs]
        assert shares == pytest.approx([0.5] * 4, abs=1e-4)

    def test_capacity_respected(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(
            5, 4, tputs=[1, 2, 3, 4, 5], sfs=[1, 1, 2, 2, 4])
        alloc = get_policy("max_min_fairness").get_allocation(tputs, sfs, prios, cluster)
        assert total_workers_used(alloc, sfs) <= 4 + 1e-6
        for j in jobs:
            assert -1e-9 <= alloc[j]["v100"] <= 1 + 1e-9

    def test_perf_prefers_fast_worker(self):
        j0, j1 = JobIdPair(0), JobIdPair(1)
        tputs = {j0: {"fast": 10.0, "slow": 1.0}, j1: {"fast": 10.0, "slow": 1.0}}
        sfs = {j0: 1, j1: 1}
        prios = {j0: 1.0, j1: 1.0}
        cluster = {"fast": 1, "slow": 1}
        alloc = get_policy("max_min_fairness_perf").get_allocation(
            tputs, sfs, prios, cluster)
        # Max-min over normalized rates: both jobs split the fast worker.
        rates = {j: 10 * alloc[j]["fast"] + 1 * alloc[j]["slow"] for j in (j0, j1)}
        assert rates[j0] == pytest.approx(rates[j1], rel=1e-3)

    def test_priority_weights_scale_share(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(2, 1)
        prios[jobs[0]] = 3.0
        alloc = get_policy("max_min_fairness").get_allocation(tputs, sfs, prios, cluster)
        assert alloc[jobs[0]]["v100"] == pytest.approx(0.75, abs=1e-3)
        assert alloc[jobs[1]]["v100"] == pytest.approx(0.25, abs=1e-3)


class TestWaterFilling:
    def test_leftover_capacity_is_distributed(self):
        # 3 jobs, 4 workers: plain max-min gives everyone 1.0; water filling
        # must not leave the 4th worker idle either.
        jobs, tputs, sfs, prios, cluster = single_type_state(3, 4)
        alloc = get_policy("max_min_fairness_water_filling").get_allocation(
            tputs, sfs, prios, cluster)
        shares = sorted(alloc[j]["v100"] for j in jobs)
        assert shares == pytest.approx([1.0, 1.0, 1.0], abs=1e-3)

    def test_lexicographic_improvement(self):
        # Job 0 capped by its own time budget (share <= 1); remaining capacity
        # should flow to jobs 1 and 2 rather than being wasted.
        jobs, tputs, sfs, prios, cluster = single_type_state(
            3, 3, tputs=[1.0, 1.0, 1.0])
        alloc = get_policy("max_min_fairness_water_filling").get_allocation(
            tputs, sfs, prios, cluster)
        assert total_workers_used(alloc, sfs) == pytest.approx(3.0, abs=1e-3)


class TestFinishTimeFairness:
    def test_balances_rho(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(
            2, 1, tputs=[1.0, 1.0])
        times = {j: 100.0 for j in jobs}
        steps = {jobs[0]: 1000.0, jobs[1]: 1000.0}
        alloc = get_policy("finish_time_fairness").get_allocation(
            tputs, sfs, prios, times, steps, cluster)
        assert alloc[jobs[0]]["v100"] == pytest.approx(0.5, abs=0.02)

    def test_rho_equalized_across_unequal_jobs(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(2, 1)
        times = {j: 100.0 for j in jobs}
        steps = {jobs[0]: 3000.0, jobs[1]: 1000.0}
        alloc = get_policy("finish_time_fairness").get_allocation(
            tputs, sfs, prios, times, steps, cluster)
        # Isolated share is 0.5 each -> isolated finish times 6000 and 2000.
        rho0 = (times[jobs[0]] + steps[jobs[0]] / alloc[jobs[0]]["v100"]) / 6000.0
        rho1 = (times[jobs[1]] + steps[jobs[1]] / alloc[jobs[1]]["v100"]) / 2000.0
        assert rho0 == pytest.approx(rho1, rel=0.02)
        assert alloc[jobs[0]]["v100"] + alloc[jobs[1]]["v100"] == pytest.approx(1.0, abs=0.02)


class TestMinTotalDuration:
    def test_feasible_makespan(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(
            2, 2, tputs=[10.0, 1.0])
        steps = {jobs[0]: 1000.0, jobs[1]: 500.0}
        alloc = get_policy("min_total_duration").get_allocation(
            tputs, sfs, steps, cluster)
        # Makespan is bottlenecked by job 1 (500 s at full share); the LP only
        # needs to give job 0 enough share to finish within that horizon.
        assert alloc[jobs[1]]["v100"] == pytest.approx(1.0, abs=0.05)
        t_job0 = steps[jobs[0]] / (tputs[jobs[0]]["v100"] * alloc[jobs[0]]["v100"])
        assert t_job0 <= 500.0 * 1.1


class TestMaxSumThroughput:
    def test_prefers_fast_jobs(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(
            3, 1, tputs=[5.0, 1.0, 0.5])
        alloc = get_policy("max_sum_throughput_perf").get_allocation(
            tputs, sfs, cluster)
        assert alloc[jobs[0]]["v100"] == pytest.approx(1.0, abs=1e-3)
        assert alloc[jobs[1]]["v100"] == pytest.approx(0.0, abs=1e-3)

    def test_slo_constraint_forces_share(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(
            2, 1, tputs=[5.0, 1.0])
        policy = get_policy("max_sum_throughput_normalized_by_cost_perf_SLOs")
        alloc = policy.get_allocation(
            tputs, sfs, cluster, SLOs={jobs[1]: 1000.0},
            num_steps_remaining={jobs[0]: 1e6, jobs[1]: 500.0})
        assert alloc[jobs[1]]["v100"] >= 0.5 - 1e-3


class TestFIFO:
    def test_queue_order(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(3, 2)
        alloc = get_policy("fifo", seed=0).get_allocation(tputs, sfs, cluster)
        assert alloc[jobs[0]]["v100"] == 1.0
        assert alloc[jobs[1]]["v100"] == 1.0
        assert alloc[jobs[2]]["v100"] == 0.0

    def test_backfills_after_completion(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(3, 2)
        policy = get_policy("fifo", seed=0)
        policy.get_allocation(tputs, sfs, cluster)
        del tputs[jobs[0]]  # job 0 completes
        alloc = policy.get_allocation(tputs, sfs, cluster)
        assert alloc[jobs[2]]["v100"] == 1.0

    def test_perf_picks_fast_type(self):
        j0 = JobIdPair(0)
        tputs = {j0: {"fast": 5.0, "slow": 1.0}}
        alloc = get_policy("fifo_perf").get_allocation(
            tputs, {j0: 1}, {"fast": 1, "slow": 1})
        assert alloc[j0]["fast"] == 1.0


class TestAllox:
    def test_single_job_gets_worker(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(1, 1, tputs=[2.0])
        alloc = get_policy("allox").get_allocation(
            tputs, sfs, {jobs[0]: 0.0}, {jobs[0]: 100.0}, [], cluster)
        assert alloc[jobs[0]]["v100"] == 1.0

    def test_non_preemptive(self):
        jobs, tputs, sfs, prios, cluster = single_type_state(2, 1)
        policy = get_policy("allox_alpha=1.0")
        times = {j: 10.0 for j in jobs}
        steps = {j: 100.0 for j in jobs}
        a1 = policy.get_allocation(tputs, sfs, times, steps, [], cluster)
        placed = [j for j in jobs if a1[j]["v100"] == 1.0]
        assert len(placed) == 1
        a2 = policy.get_allocation(tputs, sfs, times, steps, [], cluster)
        assert a2[placed[0]]["v100"] == 1.0


class TestGandiva:
    def test_no_packing_when_fits(self):
        j0, j1 = JobIdPair(0), JobIdPair(1)
        tputs = {j0: {"v100": 1.0}, j1: {"v100": 1.0},
                 JobIdPair(0, 1): {"v100": [0.5, 0.5]}}
        alloc = get_policy("gandiva", seed=0).get_allocation(
            tputs, {j0: 1, j1: 1}, {"v100": 2})
        assert alloc[j0]["v100"] == pytest.approx(1.0)
        assert alloc[JobIdPair(0, 1)]["v100"] == pytest.approx(0.0)

    def test_packs_under_contention(self):
        singles = [JobIdPair(i) for i in range(4)]
        tputs = {s: {"v100": 1.0} for s in singles}
        for i in range(4):
            for j in range(i + 1, 4):
                tputs[JobIdPair(i, j)] = {"v100": [0.8, 0.8]}
        alloc = get_policy("gandiva", seed=0).get_allocation(
            tputs, {s: 1 for s in singles}, {"v100": 2})
        packed_share = sum(alloc[k]["v100"] for k in alloc if k.is_pair())
        assert packed_share > 0


class TestPackedMaxMin:
    def test_packing_lp_runs(self):
        singles = [JobIdPair(i) for i in range(3)]
        tputs = {s: {"v100": 2.0} for s in singles}
        for i in range(3):
            for j in range(i + 1, 3):
                tputs[JobIdPair(i, j)] = {"v100": [1.5, 1.5]}
        sfs = {s: 1 for s in singles}
        prios = {s: 1.0 for s in singles}
        alloc = MaxMinFairnessPolicyWithPacking().get_allocation(
            tputs, sfs, prios, {"v100": 2})
        assert alloc is not None
        # Per-single-job total time share <= 1.
        for s in singles:
            used = sum(alloc[k]["v100"] for k in alloc
                       if k == s or (k.is_pair() and s.overlaps_with(k)))
            assert used <= 1 + 1e-4


class TestPackedMakespanAndThemis:
    def _packed_state(self):
        singles = [JobIdPair(i) for i in range(3)]
        tputs = {s: {"v100": 2.0} for s in singles}
        for i in range(3):
            for j in range(i + 1, 3):
                tputs[JobIdPair(i, j)] = {"v100": [1.5, 1.5]}
        sfs = {s: 1 for s in singles}
        return singles, tputs, sfs

    def test_min_total_duration_packed_beats_unpacked(self):
        from shockwave_tpu.solver.min_total_duration import (
            MinTotalDurationPolicyWithPacking)
        singles, tputs, sfs = self._packed_state()
        remaining = {s: 1000 for s in singles}
        alloc = MinTotalDurationPolicyWithPacking().get_allocation(
            tputs, sfs, remaining, {"v100": 2})
        assert alloc is not None
        # 3 jobs on 2 workers: packing lets every job exceed the 2/3
        # time-share it would get unpacked, so effective tput > 2*2/3.
        for s in singles:
            eff = alloc[s]["v100"] * 2.0 + sum(
                alloc[k]["v100"] * 1.5 for k in alloc
                if k.is_pair() and s.overlaps_with(k))
            assert eff > 2.0 * 2 / 3 - 1e-3
        # Capacity respected over combinations.
        used = sum(alloc[k]["v100"] for k in alloc)
        assert used <= 2 + 1e-4

    def test_finish_time_fairness_packed_runs(self):
        from shockwave_tpu.solver.finish_time_fairness import (
            FinishTimeFairnessPolicyWithPacking)
        singles, tputs, sfs = self._packed_state()
        prios = {s: 1.0 for s in singles}
        elapsed = {s: 0.0 for s in singles}
        remaining = {s: 1000 for s in singles}
        alloc = FinishTimeFairnessPolicyWithPacking().get_allocation(
            tputs, sfs, prios, elapsed, remaining, {"v100": 2})
        assert alloc is not None
        for s in singles:
            used = sum(alloc[k]["v100"] for k in alloc
                       if k == s or (k.is_pair() and s.overlaps_with(k)))
            assert used <= 1 + 1e-4
        used = sum(alloc[k]["v100"] for k in alloc)
        assert used <= 2 + 1e-4

    def test_water_filling_packed_beats_unpacked(self):
        from shockwave_tpu.solver.water_filling import (
            MaxMinFairnessWaterFillingPolicyWithPacking)
        singles, tputs, sfs = self._packed_state()
        prios = {s: 1.0 for s in singles}
        alloc = MaxMinFairnessWaterFillingPolicyWithPacking().get_allocation(
            tputs, sfs, prios, {"v100": 2})
        assert alloc is not None
        # Proportional share = 2/3 worker each -> normalized tput 1 would
        # need 2/3 time at tput 2.0; packing (1.5 each, both run) lets all
        # three exceed their proportional effective throughput.
        for s in singles:
            eff = alloc[s]["v100"] * 2.0 + sum(
                alloc[k]["v100"] * 1.5 for k in alloc
                if k.is_pair() and s.overlaps_with(k))
            assert eff > 2.0 * 2 / 3 - 1e-3
            used = sum(alloc[k]["v100"] for k in alloc
                       if k == s or (k.is_pair() and s.overlaps_with(k)))
            assert used <= 1 + 1e-4
        used = sum(alloc[k]["v100"] for k in alloc)
        assert used <= 2 + 1e-4

    def test_water_filling_packed_matches_perf_without_pairs(self):
        from shockwave_tpu.solver.water_filling import (
            MaxMinFairnessWaterFillingPolicyWithPacking,
            MaxMinFairnessWaterFillingPolicyWithPerf)
        singles = [JobIdPair(i) for i in range(3)]
        tputs = {s: {"v100": float(i + 1)} for i, s in enumerate(singles)}
        sfs = {s: 1 for s in singles}
        prios = {s: 1.0 for s in singles}
        packed = MaxMinFairnessWaterFillingPolicyWithPacking().get_allocation(
            tputs, sfs, prios, {"v100": 2})
        perf = MaxMinFairnessWaterFillingPolicyWithPerf().get_allocation(
            tputs, sfs, prios, {"v100": 2})
        for s in singles:
            assert packed[s]["v100"] == pytest.approx(perf[s]["v100"], abs=1e-3)


class TestRegistry:
    def test_all_names_construct(self):
        names = ["fifo", "fifo_perf", "fifo_packed", "finish_time_fairness",
                 "finish_time_fairness_perf", "gandiva", "gandiva_fair",
                 "isolated", "isolated_plus", "max_min_fairness",
                 "max_min_fairness_perf", "max_min_fairness_packed",
                 "max_min_fairness_strategy_proof",
                 "max_min_fairness_water_filling",
                 "max_min_fairness_water_filling_perf",
                 "max_min_fairness_water_filling_packed",
                 "max_sum_throughput_perf", "min_total_duration",
                 "min_total_duration_perf", "min_total_duration_packed",
                 "finish_time_fairness_packed", "allox", "allox_alpha=0.5",
                 "proportional", "shockwave"]
        for name in names:
            assert get_policy(name, seed=0) is not None

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            get_policy("nope")
