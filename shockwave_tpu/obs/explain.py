"""Job-timeline explainer: where did this job's time go?

    python -m shockwave_tpu.obs.explain <job_id> --state_dir <dir> \
        [--trace merged_trace.json] [--wall]

Fuses the scheduler's journal (the authoritative, crash-durable record
of admission, round schedules, micro-task completions, failures,
quarantines) with the merged fleet trace (optional: sub-round span
detail) into one per-job lifecycle timeline, attributing every round of
the job's JCT to a named phase:

- ``run``              scheduled and progressing (extended leases too)
- ``restart``          scheduled but the micro-task failed (worker
                       death, kill, rejected dispatch) — the round was
                       consumed by restart overhead
- ``quarantine_migration``  a failed round whose workers were
                       quarantined mid-round (gray-failure migration)
- ``preempted_wait``   queued immediately after losing its chips
- ``queue_wait``       queued (admission wait and ordinary rounds off
                       the schedule)

The DEFAULT output is **round-quantized and byte-stable**: two
identical drives produce identical bytes (CI diffs them), because every
number derives from journal event ORDER and recorded round indices,
never from wall clocks. ``--wall`` adds wall-second attribution (from
journal record stamps) and, with ``--trace``, per-process span detail —
informative, not reproducible.

Phase rounds always sum to the journal-derived JCT (coverage 100%); the
acceptance gate asserts >= 99%.
"""
from __future__ import annotations

import argparse
import json
import sys
from collections import OrderedDict
from typing import Dict, List, Optional


# -- journal loading ----------------------------------------------------

def read_all_events(state_dir: str) -> List[dict]:
    """Every surviving journal record in `state_dir`, seq-ordered and
    epoch-fenced (compacted-away history is simply absent)."""
    from ..sched.journal import (filter_epoch_chain, list_segments,
                                 read_journal)
    events: List[dict] = []
    for path in list_segments(state_dir):
        records, _ = read_journal(path)
        events.extend(records)
    events.sort(key=lambda r: int(r.get("seq", 0)))
    kept, _ = filter_epoch_chain(events)
    return kept


def _members(key) -> List[int]:
    """Decode a journaled job key ([lo, hi] pair or bare int)."""
    if isinstance(key, (list, tuple)):
        return [int(k) for k in key]
    return [int(key)]


# -- timeline model -----------------------------------------------------

PHASE_RUN = "run"
PHASE_RESTART = "restart"
PHASE_QUARANTINE = "quarantine_migration"
PHASE_PREEMPTED = "preempted_wait"
PHASE_QUEUE = "queue_wait"
PHASE_ORDER = (PHASE_RUN, PHASE_RESTART, PHASE_QUARANTINE,
               PHASE_PREEMPTED, PHASE_QUEUE)


class JobTimeline:
    """Everything explain derives for one job from the journal."""

    def __init__(self, int_id: int):
        self.int_id = int_id
        self.admitted: Optional[dict] = None      # job_added data
        self.admitted_seq_t: Optional[float] = None
        self.admission_round: Optional[int] = None
        self.removed_round: Optional[int] = None
        self.removed_t: Optional[float] = None
        self.deferred = False
        self.scheduled: "OrderedDict[int, list]" = OrderedDict()
        # round -> {"failed": bool, "steps": int, "quarantined": bool}
        self.microtasks: Dict[int, dict] = {}
        self.failure_comps = 0
        self.round_wall: Dict[int, float] = {}     # round -> end stamp

    # -- derivation -----------------------------------------------------

    @property
    def completion_round(self) -> Optional[int]:
        if self.removed_round is None:
            return None
        last_sched = max(self.scheduled, default=self.removed_round)
        return max(self.removed_round, last_sched)

    def phases(self) -> "OrderedDict[int, str]":
        """round index -> phase name over [admission, completion]."""
        out: "OrderedDict[int, str]" = OrderedDict()
        if self.admission_round is None or self.completion_round is None:
            return out
        prev_scheduled = False
        for rnd in range(self.admission_round,
                         self.completion_round + 1):
            if rnd in self.scheduled:
                micro = self.microtasks.get(rnd)
                if micro is None or not micro["failed"]:
                    phase = PHASE_RUN
                elif micro.get("quarantined"):
                    phase = PHASE_QUARANTINE
                else:
                    phase = PHASE_RESTART
                prev_scheduled = True
            else:
                phase = PHASE_PREEMPTED if prev_scheduled else PHASE_QUEUE
                prev_scheduled = False
            out[rnd] = phase
        return out

    def phase_totals(self) -> "OrderedDict[str, int]":
        totals: "OrderedDict[str, int]" = OrderedDict(
            (p, 0) for p in PHASE_ORDER)
        for phase in self.phases().values():
            totals[phase] += 1
        return totals


def build_timeline(events: List[dict], int_id: int) -> JobTimeline:
    tl = JobTimeline(int_id)
    rounds_ended = 0          # rounds completed so far (anchor)
    next_record_idx = 0       # see round-index rule below
    quarantined_this_round: set = set()
    for rec in events:
        etype = rec.get("type", "?")
        data = rec.get("data", {}) or {}
        if etype == "round_recorded":
            # A recorded round's index: the stamped value when present
            # (emitted since this module landed), kept monotonic — the
            # physical mid-round records NEXT round under the current
            # round's counter, and a crash re-records an abandoned
            # round; max(stamp, next expected) resolves both.
            stamp = int(data.get("round", next_record_idx))
            idx = max(stamp, next_record_idx)
            next_record_idx = idx + 1
            for key, ids in data.get("assignments", []):
                if int_id in _members(key):
                    tl.scheduled[idx] = [int(i) for i in ids]
            quarantined_this_round = set()
        elif etype == "round_ended":
            rounds_ended = int(data.get("round", rounds_ended + 1))
            next_record_idx = max(next_record_idx, rounds_ended)
            tl.round_wall[rounds_ended] = float(rec.get("t", 0.0))
            quarantined_this_round = set()
        elif etype == "job_added" and int(data.get("int_id", -1)) == int_id:
            tl.admitted = data
            tl.admitted_seq_t = float(rec.get("t", 0.0))
            tl.admission_round = rounds_ended
            tl.deferred = "trace_position" in (data.get("job") or {}) or \
                "trace_position" in data
        elif etype == "job_removed" and int(data.get("int_id", -1)) == int_id:
            tl.removed_round = rounds_ended
            tl.removed_t = float(data.get("ts", rec.get("t", 0.0)))
        elif etype == "microtask_done":
            members = _members(data.get("key", []))
            if int_id not in members:
                continue
            j = members.index(int_id)
            failed = False
            steps = 0
            for update in data.get("updates", []):
                _, num_steps, times = update
                if j < len(num_steps):
                    steps += int(num_steps[j])
                    if num_steps[j] <= 0 and times[j] <= 0:
                        failed = True
            executing = rounds_ended
            micro = tl.microtasks.setdefault(
                executing, {"failed": False, "steps": 0,
                            "quarantined": False})
            micro["failed"] = micro["failed"] or failed
            micro["steps"] += steps
            if failed and (set(tl.scheduled.get(executing, []))
                           & quarantined_this_round):
                micro["quarantined"] = True
        elif etype == "failure_comp" and int(
                data.get("int_id", -1)) == int_id:
            tl.failure_comps += 1
        elif etype == "worker_quarantined":
            quarantined_this_round.update(
                int(i) for i in data.get("worker_ids", []))
    return tl


# -- rendering ----------------------------------------------------------

def render(tl: JobTimeline, wall: bool = False,
           trace_path: Optional[str] = None) -> str:
    if tl.admitted is None:
        return (f"job {tl.int_id}: no job_added event in the journal "
                "(wrong id, or its history was compacted away)")
    lines: List[str] = []
    phases = tl.phases()
    totals = tl.phase_totals()
    jct_rounds = len(phases)
    completion = ("incomplete (no job_removed event)"
                  if tl.removed_round is None
                  else f"completed round {tl.completion_round}")
    job_meta = tl.admitted.get("job") or {}
    lines.append(
        f"job {tl.int_id} · {job_meta.get('job_type', '?')} "
        f"sf={job_meta.get('scale_factor', '?')} · admitted round "
        f"{tl.admission_round}"
        + (" (admission deferred/reordered)" if tl.deferred else "")
        + f" · {completion} · jct {jct_rounds} rounds")
    attributed = sum(totals.values())
    lines.append("")
    lines.append(f"{'phase':<22}{'rounds':>8}{'share':>9}")
    for phase in PHASE_ORDER:
        count = totals[phase]
        share = 100.0 * count / jct_rounds if jct_rounds else 0.0
        lines.append(f"{phase:<22}{count:>8}{share:>8.1f}%")
    coverage = 100.0 * attributed / jct_rounds if jct_rounds else 0.0
    lines.append(f"{'total':<22}{attributed:>8}{coverage:>8.1f}%"
                 f"  (coverage of journal-derived JCT)")
    lines.append("")
    lines.append("timeline:")
    for rnd, phase in phases.items():
        detail = ""
        if rnd in tl.scheduled:
            detail = f"  workers={tl.scheduled[rnd]}"
            micro = tl.microtasks.get(rnd)
            if micro is not None:
                detail += f" steps={micro['steps']}"
                if micro["failed"]:
                    detail += " FAILED"
        lines.append(f"  round {rnd:<5} {phase:<20}{detail}")
    lines.append("")
    restarts = sum(1 for m in tl.microtasks.values() if m["failed"])
    lines.append(
        f"events: requeues={restarts} "
        f"failure_compensations={tl.failure_comps} "
        f"quarantine_migrations={totals[PHASE_QUARANTINE]}")
    if wall:
        lines.extend(_render_wall(tl))
    if wall and trace_path:
        lines.extend(_render_trace_detail(tl, trace_path))
    return "\n".join(lines)


def _render_wall(tl: JobTimeline) -> List[str]:
    """Wall-second attribution from journal record stamps (NOT
    byte-stable across drives — excluded from the default output)."""
    if tl.removed_t is None or tl.admitted_seq_t is None:
        return ["", "wall: job incomplete; no wall attribution"]
    jct_s = max(tl.removed_t - tl.admitted_seq_t, 0.0)
    phases = tl.phases()
    seconds: Dict[str, float] = {p: 0.0 for p in PHASE_ORDER}
    for rnd, phase in phases.items():
        start = tl.round_wall.get(rnd)
        end = tl.round_wall.get(rnd + 1)
        if start is None:
            start = tl.admitted_seq_t
        if end is None:
            end = tl.removed_t
        lo = max(start, tl.admitted_seq_t)
        hi = min(end, tl.removed_t)
        seconds[phase] += max(hi - lo, 0.0)
    attributed = sum(seconds.values())
    out = ["", f"wall: jct {jct_s:.1f}s, attributed "
               f"{attributed:.1f}s "
               f"({100.0 * attributed / jct_s if jct_s else 0.0:.1f}%)"]
    for phase in PHASE_ORDER:
        if seconds[phase] > 0:
            out.append(f"  {phase:<22}{seconds[phase]:>10.1f}s")
    return out


def _render_trace_detail(tl: JobTimeline, trace_path: str) -> List[str]:
    """Sub-round span detail for this job from a merged fleet trace."""
    try:
        with open(trace_path, encoding="utf-8") as f:
            trace = json.load(f)
    except (OSError, ValueError) as e:
        return ["", f"trace: unreadable ({e})"]
    events = (trace.get("traceEvents", trace)
              if isinstance(trace, dict) else trace)
    by_name: Dict[str, List[float]] = {}
    for e in events:
        if e.get("ph", "X") != "X":
            continue
        args = e.get("args") or {}
        if args.get("job") != tl.int_id:
            continue
        by_name.setdefault(e.get("name", "?"), []).append(
            float(e.get("dur", 0.0)) / 1e6)
    if not by_name:
        return ["", "trace: no spans tagged with this job id"]
    out = ["", "trace spans (merged fleet trace):"]
    for name in sorted(by_name):
        durs = by_name[name]
        out.append(f"  {name:<22}n={len(durs):<5} "
                   f"total={sum(durs):.3f}s mean={sum(durs)/len(durs):.4f}s")
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m shockwave_tpu.obs.explain",
        description=__doc__.splitlines()[0])
    p.add_argument("job_id", type=int, help="integer job id")
    p.add_argument("--state_dir", required=True,
                   help="scheduler state dir (write-ahead journal)")
    p.add_argument("--trace", default=None,
                   help="merged fleet trace (obs.merge output) for "
                        "span detail (implies nothing without --wall)")
    p.add_argument("--wall", action="store_true",
                   help="add wall-second attribution and span detail "
                        "(not byte-stable across drives)")
    args = p.parse_args(argv)
    events = read_all_events(args.state_dir)
    if not events:
        print(f"{args.state_dir}: no journal events", file=sys.stderr)
        return 1
    tl = build_timeline(events, args.job_id)
    out = render(tl, wall=args.wall, trace_path=args.trace)
    print(out)
    return 0 if tl.admitted is not None else 1


if __name__ == "__main__":
    sys.exit(main())
