#!/usr/bin/env python3
"""Serving replica workload (trace: "Serving (batch size N)").

One autoregressive token-serving replica: a small decoder-only LM
(models/decoder.py, KV-cached decode on the transformer/flash stack)
greedily generating ``tokens_per_request`` tokens for a batch of
``batch_size`` synthetic requests per step. The replica flows through
the standard cluster runtime unchanged — the LeaseIterator accounts one
step (= one served request batch) against a scheduler-granted lease and
exits cooperatively at expiry — so "progress" reported to the scheduler
is requests served, the serving tier's unit of work.

Dispatched with the trace's `serving_command` (core/trace.py) plus the
scheduler's --replica_of/--replica_index markers; load-curve flags are
accepted (they parameterize the simulator's analytic twin) but only the
decode-shape flags matter here.
"""
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 3))

import jax
import jax.numpy as jnp

from shockwave_tpu.models.decoder import DecoderLM
from shockwave_tpu.models.train_common import (common_parser,
                                               enable_compile_cache,
                                               parse_args)
from shockwave_tpu.runtime.iterator import LeaseIterator

THROUGHPUT_LOG_INTERVAL = 50


def build_parser():
    p = common_parser("Autoregressive serving replica")
    p.add_argument("--batch_size", type=int, default=1)
    p.add_argument("--tokens_per_request", type=int, default=64)
    # Load-curve parameters: carried by the trace command so one line
    # parameterizes both the simulator's analytic model and this
    # process; the replica itself serves as fast as the chip allows.
    p.add_argument("--base_rps", type=float, default=0.0)
    p.add_argument("--peak_rps", type=float, default=0.0)
    p.add_argument("--period_s", type=float, default=0.0)
    p.add_argument("--phase_s", type=float, default=0.0)
    p.add_argument("--decode_tokens_per_s", type=float, default=0.0)
    p.add_argument("--max_replicas", type=int, default=8)
    p.add_argument("--spike_at", action="append", default=[])
    p.add_argument("--spike_seed", type=int, default=None)
    p.add_argument("--num_spikes", type=int, default=0)
    p.add_argument("--spike_mult", type=float, default=10.0)
    p.add_argument("--spike_duration_s", type=float, default=1800.0)
    p.add_argument("--replica_of", type=int, default=None)
    p.add_argument("--replica_index", type=int, default=0)
    # Decode model shape (defaults sized for a single chip).
    p.add_argument("--model_dim", type=int, default=128)
    p.add_argument("--model_layers", type=int, default=2)
    p.add_argument("--model_heads", type=int, default=4)
    p.add_argument("--prompt_len", type=int, default=8)
    return p


def main():
    args = parse_args(build_parser())
    enable_compile_cache()

    max_len = args.prompt_len + args.tokens_per_request + 1
    model = DecoderLM(dim=args.model_dim, num_layers=args.model_layers,
                      num_heads=args.model_heads,
                      mlp_dim=2 * args.model_dim, max_len=max_len)
    rng = jax.random.PRNGKey(args.replica_index or 0)
    prompt = jax.random.randint(
        rng, (args.batch_size, args.prompt_len), 0, model.vocab_size,
        dtype=jnp.int32)
    params = model.init(rng, prompt)

    @jax.jit
    def serve_request_batch(params, prompt):
        """Greedy-decode tokens_per_request tokens for one batch of
        requests through the KV cache; returns the last generated
        token ids (the sync ref)."""
        caches = model.init_cache(args.batch_size)

        def step(carry, token_in):
            caches, pos = carry
            logits, caches = model.apply(params, token_in, caches, pos,
                                         method=DecoderLM.decode_step)
            next_tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            return (caches, pos + 1), next_tok[:, None]

        carry = (caches, jnp.int32(0))
        token = prompt[:, :1]
        for i in range(args.prompt_len):
            carry, token = step(carry, prompt[:, i:i + 1])
        def body(i, state):
            carry, token = state
            carry, token = step(carry, token)
            return (carry, token)
        carry, token = jax.lax.fori_loop(
            0, args.tokens_per_request, body, (carry, token))
        return token

    # Synthetic request stream: a small ring of the same cached prompt
    # batch. The LEASE bounds how long we serve, not the loader length
    # — the loop below re-enters the iterator at each synthetic "epoch"
    # boundary (a huge literal list here would cost gigabytes of
    # pointer storage per replica before the first request).
    request_ring = [prompt] * 1024
    if args.enable_lease_iterator:
        iterator = LeaseIterator(
            data_loader=request_ring,
            checkpoint_dir=args.checkpoint_dir,
            # Replicas are stateless (weights re-init from the replica
            # seed); there is no training state to checkpoint.
            load_checkpoint_func=lambda path: None,
            save_checkpoint_func=lambda path, state: None,
            synthetic_data=True)
    else:
        iterator = None

    served = 0
    window_start = time.time()
    window_steps = 0
    last = None
    budget = args.num_steps

    def serve_one(batch):
        nonlocal last, served, window_steps, window_start
        last = serve_request_batch(params, batch)
        if iterator is not None:
            iterator.set_sync_ref(last)
        served += 1
        window_steps += 1
        if window_steps >= THROUGHPUT_LOG_INTERVAL:
            jax.block_until_ready(last)
            print(f"[THROUGHPUT_ESTIMATION]\t{time.time()}\t{served}",
                  flush=True)
            window_start, window_steps = time.time(), 0

    try:
        if iterator is not None:
            while not iterator.done and (budget is None or served < budget):
                try:
                    for batch in iterator:
                        serve_one(batch)
                        if budget is not None and served >= budget:
                            iterator.complete()
                            break
                except StopIteration:
                    pass  # lease expiry or epoch boundary; `done` decides
        else:
            for _ in range(budget or 100):
                serve_one(prompt)
    finally:
        if last is not None:
            jax.block_until_ready(last)
    print(f"SERVED {served} request batches "
          f"(x{args.batch_size} requests, {args.tokens_per_request} "
          f"tokens each)", flush=True)
    return served


if __name__ == "__main__":
    main()
