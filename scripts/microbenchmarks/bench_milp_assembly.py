#!/usr/bin/env python3
"""Shockwave MILP assembly-vs-solve split microbenchmark.

Times, at each job count, (a) assembling the EG model — both fallback
arms, the work one `plan_schedule` call pays before HiGHS ever runs —
and (b) one bounded relaxed solve, through the same obs histograms the
planner reports (`swtpu_milp_assembly_seconds` /
`swtpu_milp_solve_seconds`, dumpable with --metrics_out). Prints one
JSON line per job count.

`--assembler loop` times the historical pure-python loop assembler —
the SAME single copy (milp_loop_reference.py, next to this script) the
golden-equivalence suite in tests/test_milp_assembly.py certifies
byte-identical to the vectorized path — so the before/after table in
EXPERIMENTS.md is reproducible against the tested oracle.

`--smoke` asserts the assembly wall stays under the instance's solve-
budget floor (opts.timeout x njobs/120) — the CI guard that model
assembly never again grows into round-budget territory.

Example:
    python scripts/microbenchmarks/bench_milp_assembly.py \
        --num_jobs 120 220 460 900 --metrics_out assembly.prom
"""
import argparse
import json
import math
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from milp_loop_reference import reference_assemble
from shockwave_tpu.obs import Observability
from shockwave_tpu.obs import names as obs_names
from shockwave_tpu.obs.clock import perf_clock
from shockwave_tpu.shockwave import milp as milp_mod
from shockwave_tpu.shockwave.milp import MilpOptions


def synth_instance(njobs, seed, future_nrounds=20, ngpus=None):
    """Deterministic synthetic solve inputs shaped like the scale
    traces: mostly single-chip jobs, wide duration/remaining spreads."""
    rng = np.random.RandomState(seed)
    ngpus = ngpus or max(32, njobs // 4)
    data = dict(
        nworkers=[int(rng.choice([1, 1, 1, 2, 4])) for _ in range(njobs)],
        durations=[float(rng.uniform(20, 400)) for _ in range(njobs)],
        dirichlet=[float(rng.uniform(100, 9000)) for _ in range(njobs)],
        epochs=[int(rng.randint(2, 60)) for _ in range(njobs)],
        ftf_caps=[float(rng.uniform(10, 9000)) for _ in range(njobs)],
        round_duration=120.0, ngpus=ngpus,
        future_nrounds=future_nrounds)
    data["progress"] = [int(rng.randint(0, e)) for e in data["epochs"]]
    return data


def loop_assemble(data, bases, base_logs, priorities, with_ftf, k):
    """One arm of the shared loop oracle, adapted to the synth dict."""
    njobs = len(data["nworkers"])
    R = data["future_nrounds"]
    return reference_assemble(
        milp_mod._Layout(njobs, R, len(bases)), njobs, R,
        data["round_duration"], data["ngpus"], bases, base_logs,
        data["nworkers"], data["durations"], data["dirichlet"],
        data["progress"], data["epochs"], data["ftf_caps"], k,
        priorities, with_ftf)


def time_assembly(obs, assembler, data, opts, trials):
    """Both fallback arms per trial (what one plan_schedule pays),
    through the assembly histogram. Returns (best_s, mean_s, model)."""
    bases = list(opts.logapx_bases)
    base_logs = [math.log(opts.logapx_origin)] + [
        math.log(b) for b in bases[1:]]
    ones = [1.0] * len(data["nworkers"])
    model = None
    events_before = len(obs.tracer.events())
    for t in range(trials):
        with obs.span(obs_names.SPAN_PLANNER_SOLVE,
                      phase="assembly", assembler=assembler, trial=t), \
                obs.timed(obs_names.MILP_ASSEMBLY_SECONDS, path="ftf"):
            if assembler == "loop":
                loop_assemble(data, bases, base_logs, ones, True, opts.k)
                model = loop_assemble(data, bases, base_logs, ones, False,
                                      opts.k)
            else:
                inst = milp_mod._InstanceAssembler(
                    milp_mod._structure_for(len(ones),
                                            data["future_nrounds"],
                                            len(bases)),
                    bases, base_logs, data["nworkers"], data["durations"],
                    data["dirichlet"], data["progress"], data["epochs"],
                    data["ftf_caps"], data["round_duration"],
                    data["ngpus"], opts.k)
                inst.model(ones, True)
                model = inst.model(ones, False)
    times = [e["dur"] for e in obs.tracer.events()[events_before:]
             if e["name"] == obs_names.SPAN_PLANNER_SOLVE]
    return min(times), sum(times) / len(times), model


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num_jobs", nargs="*", type=int,
                   default=[120, 220, 460, 900])
    p.add_argument("--assembler", choices=["vectorized", "loop"],
                   default="vectorized")
    p.add_argument("--trials", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--solve_timeout", type=float, default=5.0,
                   help="bounded relaxed-solve budget per size (seconds); "
                        "keeps the solve leg of the split cheap")
    p.add_argument("--skip_solve", action="store_true")
    p.add_argument("--smoke", action="store_true",
                   help="exit 1 unless assembly stays under the solve-"
                        "budget floor (opts.timeout x njobs/120)")
    p.add_argument("--output", default=None, help="JSON results path")
    p.add_argument("--metrics_out", default=None, metavar="PROM_TXT",
                   help="dump the assembly/solve histograms as "
                        "Prometheus text")
    args = p.parse_args()

    # Force-enabled local bundle on the perf clock: a benchmark must
    # measure even when the ambient SWTPU_OBS=0 disables production
    # telemetry.
    obs = Observability(clock=perf_clock, enabled=True)
    opts = MilpOptions()
    results, smoke_ok = [], True
    for n in args.num_jobs:
        data = synth_instance(n, args.seed)
        best, mean, model = time_assembly(obs, args.assembler, data, opts,
                                          args.trials)
        row = {"njobs": n, "assembler": args.assembler,
               "assembly_best_s": round(best, 4),
               "assembly_mean_s": round(mean, 4)}
        if not args.skip_solve and model is not None:
            solve_opts = MilpOptions(timeout=args.solve_timeout)
            t0 = perf_clock()
            with obs.timed(obs_names.MILP_SOLVE_SECONDS, path="relaxed"):
                res = milp_mod._solve(*model, solve_opts)
            row["solve_s"] = round(perf_clock() - t0, 4)
            row["solve_status"] = getattr(res, "status", None)
        floor = opts.timeout * max(1.0, n / 120.0)
        row["solve_budget_floor_s"] = round(floor, 1)
        if args.smoke and best >= floor:
            row["smoke"] = "FAIL"
            smoke_ok = False
        results.append(row)
        print(json.dumps(row), flush=True)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(results, f, indent=1)
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            f.write(obs.registry.render_prometheus())
    if not smoke_ok:
        print("SMOKE FAIL: assembly wall reached the solve-budget floor",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
