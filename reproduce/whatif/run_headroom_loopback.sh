#!/usr/bin/env bash
# Physical-loopback what-if knob-tuning drive: the REAL round pipeline
# (run_physical.py + two stub worker daemons) with the serving
# autoscaler deliberately over-provisioned (headroom 3.0 — both chips
# reserved for a 10 req/s service a single 25 req/s replica covers).
# The what-if plane must sweep the headroom knob on digital-twin
# rollouts, commit 1.15, and journal the decision. Produces the
# committed evidence artifact headroom_tuning_loopback.json (knob sweep
# log + the journaled whatif_knob event).
#
#   bash reproduce/whatif/run_headroom_loopback.sh
set -euo pipefail
cd "$(dirname "$0")/../.."
export JAX_PLATFORMS=cpu
WORK=$(mktemp -d)
PIDS=""
# Kill only OUR children — `kill 0` would take the caller's process
# group (CI runner included) down with the loopback.
trap 'kill $PIDS 2>/dev/null || true; rm -rf "$WORK"' EXIT
PORT=${PORT:-$((20000 + RANDOM % 20000))}

python scripts/drivers/run_physical.py \
  --trace reproduce/whatif/headroom_loopback.trace \
  --policy max_min_fairness \
  --throughputs data/tacc_throughputs.json \
  --expected_num_workers 2 --round_duration 2 --port "$PORT" \
  --state_dir "$WORK/state" --snapshot_interval 50 \
  --heartbeat_interval 0.5 --worker_timeout 5 --first_init_grace 0 \
  --config reproduce/whatif/headroom_loopback_config.json \
  --output "$WORK/metrics.pkl" --timeout 150 &
SCHED=$!
PIDS="$SCHED"
sleep 3
for w in 0 1; do
  python tests/fault_stub_worker.py --sched_port "$PORT" \
    --worker_port $((PORT + 1 + w)) --num_chips 1 \
    --state_file "$WORK/w$w.json" &
  PIDS="$PIDS $!"
done
wait "$SCHED"

python - "$WORK" <<'PY'
import json
import pickle
import sys

from shockwave_tpu.sched import journal

work = sys.argv[1]
with open(f"{work}/metrics.pkl", "rb") as f:
    metrics = pickle.load(f)
whatif = metrics["whatif"]
recovered = journal.load_state(f"{work}/state")
knob_events = [
    {"seq": e["seq"], "type": e["type"], "data": e["data"]}
    for e in recovered.events if e.get("type") == "whatif_knob"]
committed = [r for r in whatif["knob_log"] if r["changed"]]
assert committed, f"headroom never retuned: {whatif['knob_log']}"
assert committed[-1]["chosen"] < committed[-1]["previous"], committed
evidence = {
    "drive": "reproduce/whatif/run_headroom_loopback.sh",
    "knob": "autoscaler_headroom",
    "initial_headroom": 3.0,
    "committed": committed[-1],
    "knob_log": whatif["knob_log"],
    "journaled_whatif_knob_events": knob_events,
    "fork_status": whatif["status"],
    "all_jobs_completed": metrics["all_jobs_completed"],
    "serving": metrics.get("serving"),
}
out = "reproduce/whatif/headroom_tuning_loopback.json"
with open(out, "w") as f:
    json.dump(evidence, f, indent=1, sort_keys=True)
    f.write("\n")
print("evidence written:", out)
print("committed:", committed[-1]["previous"], "->",
      committed[-1]["chosen"], "at round", committed[-1]["round"])
PY
