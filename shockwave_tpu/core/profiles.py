"""Per-epoch job profiles: the Shockwave solver's input.

For each job we derive a per-epoch batch-size schedule (via the adaptation
oracles) and attach per-epoch duration / memory / accelerator-utilization
estimates. Durations come from the isolated throughput oracle; memory and
utilization from profiled tables (reference: scheduler/utils.py:706-738,
1331-1443). Profiles are plain dicts so they pickle/json cleanly.
"""
from __future__ import annotations

import pickle
from typing import List, Sequence

from .adaptation import bs_schedule_for_mode
from .constants import (MODEL_DATASET, dataset_size, num_epochs_for,
                        oracle_job_type)
from .job import Job

# Profiled per-(model, batch size) device memory footprint in MB.
MEM_MB = {
    "ResNet-18": {16: 1771, 32: 1857, 64: 2925, 128: 4137, 256: 3581},
    "ResNet-50": {16: 3279, 32: 4597, 64: 4949, 128: 10289},
    "Transformer": {16: 3145, 32: 4219, 64: 7199, 128: 12197},
    "LM": {5: 1687, 10: 1789, 20: 1983, 40: 2415, 80: 3337},
    "Recommendation": {512: 1751, 1024: 2373, 2048: 3559, 4096: 6565, 8192: 7699},
    "CycleGAN": {1: 7901, 2: 8435, 4: 12291},
    "A3C": {4: 5880},
}

# Profiled per-(model, batch size) accelerator utilization percentage.
UTIL_PCT = {
    "ResNet-18": {16: 76.8, 32: 87.6, 64: 95.5, 128: 98.0, 256: 98.8},
    "ResNet-50": {16: 96.0, 32: 96.4, 64: 98.8, 128: 99.2},
    "Transformer": {16: 76.7, 32: 82.0, 64: 88.8, 128: 93.8},
    "LM": {5: 71.5, 10: 67.6, 20: 60.8, 40: 58.9, 80: 60.0},
    "Recommendation": {512: 12.3, 1024: 8.9, 2048: 12.2, 4096: 10.9, 8192: 15.3},
    "CycleGAN": {1: 96.0, 2: 98.0, 4: 98.0},
    "A3C": {4: 88.0},
}


def epoch_duration(model: str, batch_size: int, scale_factor: int,
                   throughputs: dict, worker_type: str = "v100") -> float:
    """Seconds per epoch from the isolated oracle throughput.

    Uses fractional steps-per-epoch (dataset_size / batch_size without
    rounding) to match the reference profiler (utils.py:700-704).
    """
    job_type = oracle_job_type(model, batch_size)
    tput = throughputs[worker_type][(job_type, scale_factor)]["null"]
    return (dataset_size(model) / batch_size) / tput


def build_job_profile(job: Job, throughputs: dict, worker_type: str = "v100") -> dict:
    """Profile one job: per-epoch bs/duration/mem/util lists plus metadata."""
    model = job.model
    bs0 = job.batch_size
    n_epochs = num_epochs_for(model, bs0, job.total_steps)
    bs_every_epoch = bs_schedule_for_mode(job.mode, model, bs0, n_epochs, job.scale_factor)

    def safe_epoch_duration(bs: int) -> float:
        # Families outside the profiled table (or with a zeroed oracle
        # entry) fall back to the trace's expected duration spread
        # uniformly over epochs.
        try:
            return epoch_duration(model, bs, job.scale_factor, throughputs,
                                  worker_type)
        except (KeyError, ZeroDivisionError):
            return float(job.duration) / n_epochs

    return {
        "model": model,
        "dataset": MODEL_DATASET[model],
        "num_epochs": n_epochs,
        "num_samples_per_epoch": dataset_size(model),
        "bs_every_epoch": bs_every_epoch,
        "mem_every_epoch": [MEM_MB[model][bs] for bs in bs_every_epoch],
        "util_every_epoch": [UTIL_PCT[model][bs] for bs in bs_every_epoch],
        "duration_every_epoch": [
            safe_epoch_duration(bs) for bs in bs_every_epoch
        ],
        "scale_factor": job.scale_factor,
        "duration": job.duration,
    }


def build_profiles(jobs: Sequence[Job], throughputs: dict,
                   worker_type: str = "v100") -> List[dict]:
    """Profiles positionally aligned with the trace's job ids. Serving
    jobs (mode ``serving``) have no epoch structure — their slot is None
    (the scheduler never reads a profile for them)."""
    from .trace import is_serving_job
    return [None if is_serving_job(job)
            else build_job_profile(job, throughputs, worker_type)
            for job in jobs]


def save_profiles(profiles: List[dict], path: str) -> None:
    with open(path, "wb") as f:
        pickle.dump(profiles, f)


def load_profiles(path: str) -> List[dict]:
    with open(path, "rb") as f:
        return pickle.load(f)
