#!/bin/bash
# Schedule-replay decomposition of a committed fidelity artifact
# (VERDICT r4 #2): separates the simulator's pure timing-model error
# from scheduling-decision divergence.
#
#   leg 1  replay          physical schedule + oracle rates
#          -> physical-vs-replay delta = timing model only
#   leg 2  replay+measured physical schedule + this run's measured rates
#          -> residual when the rate model is removed too
#   leg 3  free+measured   live policy + measured rates
#          -> does feeding the planner the physically-experienced rates
#             close the free-run gap? (it does not: divergence is
#             intrinsic to the planner's feedback loop, not rate input)
#
# Usage: reproduce/fidelity/run_replay_analysis.sh ARTIFACT_DIR POLICY
# e.g.   reproduce/fidelity/run_replay_analysis.sh \
#            reproduce/fidelity/cpu_loopback_12job_shockwave shockwave
set -eu -o pipefail
cd "$(dirname "$0")/../.."
DIR=${1:?artifact dir}
POLICY=${2:?policy}
TRACE=${TRACE:-reproduce/fidelity/fidelity_cpu_12job.trace}
ORACLE=${ORACLE:-reproduce/fidelity/cpu_throughputs.json}
ROUND=${ROUND:-120}
OUT="$DIR/replay"
mkdir -p "$OUT"

run_sim() {  # extra-args... output
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
      python scripts/drivers/simulate.py \
        --trace "$TRACE" --policy "$POLICY" --throughputs "$ORACLE" \
        --cluster_spec cpu:1 --round_duration "$ROUND" "$@"
}

run_sim --replay_schedule "$DIR/physical_cpu.pkl" \
        --output "$OUT/replay_oracle_rates.pkl"
python reproduce/analyze_fidelity.py "$DIR/physical_cpu.pkl" \
    "$OUT/replay_oracle_rates.pkl" --tolerance 0.1 \
    | tee "$OUT/replay_report.txt" || true

run_sim --replay_schedule "$DIR/physical_cpu.pkl" \
        --measured_rates "$DIR/physical_cpu.pkl" \
        --output "$OUT/replay_measured_rates.pkl"
python reproduce/analyze_fidelity.py "$DIR/physical_cpu.pkl" \
    "$OUT/replay_measured_rates.pkl" --tolerance 0.1 \
    | tee "$OUT/replay_measured_report.txt" || true

run_sim --measured_rates "$DIR/physical_cpu.pkl" \
        --output "$OUT/free_measured_rates.pkl"
python reproduce/analyze_fidelity.py "$DIR/physical_cpu.pkl" \
    "$OUT/free_measured_rates.pkl" --tolerance 0.1 \
    | tee "$OUT/free_measured_report.txt" || true

# Per-job completion deltas for each leg (the quantification the
# aggregate deltas hide).
python - "$DIR" "$OUT" <<'EOF' | tee "$OUT/per_job_deltas.txt"
import pickle, statistics, sys
d, out = sys.argv[1], sys.argv[2]
phys = pickle.load(open(f"{d}/physical_cpu.pkl", "rb"))
legs = [("free", f"{d}/simulated_cpu.pkl"),
        ("replay", f"{out}/replay_oracle_rates.pkl"),
        ("replay+measured", f"{out}/replay_measured_rates.pkl"),
        ("free+measured", f"{out}/free_measured_rates.pkl")]
for name, path in legs:
    s = pickle.load(open(path, "rb"))
    deltas = [sj - pj for sj, pj in zip(s["jct_list"], phys["jct_list"])]
    med = statistics.median(abs(x) for x in deltas)
    print(f"{name:16s} median|dJCT|={med:7.1f}s "
          f"per-job={[round(x) for x in deltas]}")
EOF
