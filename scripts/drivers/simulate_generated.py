#!/usr/bin/env python3
"""Simulation driver over generated jobs with Poisson arrivals.

Instead of replaying a fixed trace, samples `--num_jobs` jobs from the
template table (Philly scale-factor/duration mixes) with exponential
interarrival gaps, then runs the same simulator loop as simulate.py
(reference: scheduler/scripts/drivers/simulate_scheduler_with_generated_jobs.py).
Trace loading, scheduler construction and metric collection are shared
with simulate.py via driver_common.

Example:
    python scripts/drivers/simulate_generated.py \
        --num_jobs 64 --lam 600 --policy max_min_fairness \
        --throughputs data/tacc_throughputs.json --cluster_spec v100:16
"""
import argparse
import json
import os
import pickle
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import driver_common  # noqa: E402
from shockwave_tpu.core.generator import generate_trace  # noqa: E402
from shockwave_tpu.core.metrics import parse_cluster_spec  # noqa: E402
from shockwave_tpu.core.oracle import read_throughputs  # noqa: E402
from shockwave_tpu.core.profiles import build_profiles  # noqa: E402
from shockwave_tpu.obs.logconfig import setup_logging  # noqa: E402


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--num_jobs", type=int, default=64)
    p.add_argument("--lam", type=float, default=0.0,
                   help="mean interarrival seconds (0 = all arrive at t=0)")
    p.add_argument("--policy", default="max_min_fairness")
    p.add_argument("--throughputs", required=True)
    p.add_argument("--cluster_spec", default="v100:32")
    p.add_argument("--round_duration", type=float, default=360.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max_rounds", type=int, default=None)
    p.add_argument("--multi_gpu", action="store_true", default=True)
    p.add_argument("--no_multi_gpu", dest="multi_gpu", action="store_false")
    p.add_argument("--dynamic", action="store_true", default=True,
                   help="include accordion/gns jobs")
    p.add_argument("--static_only", dest="dynamic", action="store_false")
    p.add_argument("--min_duration_hours", type=float, default=0.2)
    p.add_argument("--max_duration_hours", type=float, default=5.0)
    p.add_argument("--reference_worker_type", default=None,
                   help="oracle worker type that anchors duration->steps "
                        "(default: v100 when present, else the first "
                        "cluster_spec type — e.g. v5e for a TPU oracle)")
    p.add_argument("--config", default=None,
                   help="JSON file of shockwave hyperparameters")
    p.add_argument("--output", default=None, help="metrics pickle path")
    p.add_argument("--scalar_sim", action="store_true",
                   help="run the retained scalar sim core (reference "
                        "oracle) instead of the vectorized passes")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()

    setup_logging("info" if args.verbose else "warning")

    throughputs = read_throughputs(args.throughputs)
    cluster_spec = parse_cluster_spec(args.cluster_spec)
    reference_worker_type = (
        args.reference_worker_type
        or ("v100" if "v100" in throughputs else next(iter(cluster_spec))))
    jobs, arrival_times = generate_trace(
        args.num_jobs, throughputs, lam=args.lam, seed=args.seed,
        generate_multi_gpu_jobs=args.multi_gpu,
        generate_dynamic_jobs=args.dynamic,
        min_duration_hours=args.min_duration_hours,
        max_duration_hours=args.max_duration_hours,
        reference_worker_type=reference_worker_type)
    profiles = build_profiles(jobs, throughputs,
                              worker_type=reference_worker_type)

    shockwave_config, serving_config, whatif_config, oracle_config = (
        driver_common.load_configs(args.config, args.policy, cluster_spec,
                                   args.round_duration))

    sched = driver_common.build_scheduler(
        args.policy, args.throughputs, profiles,
        round_duration=args.round_duration, seed=args.seed,
        max_rounds=args.max_rounds, shockwave_config=shockwave_config,
        serving_config=serving_config, whatif_config=whatif_config,
        oracle_config=oracle_config, vectorized=not args.scalar_sim)

    makespan = sched.simulate(cluster_spec, arrival_times, jobs)

    metrics = {"num_jobs": args.num_jobs, "lam": args.lam,
               "seed": args.seed,
               **driver_common.collect_metrics(sched, makespan,
                                               args.round_duration,
                                               args.policy)}
    if args.output:
        with open(args.output, "wb") as f:
            pickle.dump(metrics, f)

    summary = driver_common.summary_core(metrics, sched)
    summary["num_jobs"] = args.num_jobs
    summary["lam"] = args.lam
    summary.update(driver_common.milp_summary(metrics["milp_solve_stats"]))
    print(json.dumps(summary))


if __name__ == "__main__":
    main()
