"""Shared reporting helpers used by drivers and reproduce tooling."""
from __future__ import annotations

from typing import Dict, Sequence

# A job is "unfair" when its finish-time-fairness rho exceeds this; the
# paper's reporting threshold (reference: reproduce/analyze_fidelity.py).
UNFAIR_RHO_THRESHOLD = 1.1


def unfair_fraction(ftf_list: Sequence[float],
                    threshold: float = UNFAIR_RHO_THRESHOLD) -> float:
    """Fraction of jobs whose rho exceeds the unfairness threshold."""
    if not ftf_list:
        return 0.0
    return sum(1 for r in ftf_list if r > threshold) / len(ftf_list)


def parse_cluster_spec(spec: str) -> Dict[str, int]:
    """Parse "worker_type:count[,worker_type:count...]" CLI specs."""
    cluster: Dict[str, int] = {}
    for part in spec.split(","):
        worker_type, count = part.split(":")
        cluster[worker_type] = int(count)
    return cluster
