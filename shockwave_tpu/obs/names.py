"""Central catalog of every metric and span name in the tree.

Instrumentation call sites reference these as attributes
(``names.DISPATCHES_TOTAL``), never as inline string literals — enforced
by the `obs-discipline` swtpu-check pass — so the catalog below IS the
complete instrumentation surface: grep-able, documentable (README's
"Observability" table is generated from it by
``python -m shockwave_tpu.obs.catalog``), and safe to rename in one
place.

Conventions: counters end in ``_total``; durations are seconds in
histograms named ``*_seconds``; label sets are small and bounded (no
job ids — per-job detail lives in spans and job timelines, not in
metric cardinality).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple


@dataclass(frozen=True)
class MetricSpec:
    """Declaration of one metric: pure data, no behavior. The registry
    instantiates storage from it on first use."""
    name: str
    kind: str                      # "counter" | "gauge" | "histogram"
    help: str
    labels: Tuple[str, ...] = ()
    buckets: Tuple[float, ...] = ()   # histograms only

    def __post_init__(self):
        if self.kind not in ("counter", "gauge", "histogram"):
            raise ValueError(f"unknown metric kind {self.kind!r}")
        if self.kind == "histogram" and not self.buckets:
            raise ValueError(f"{self.name}: histogram needs buckets")


#: Default latency buckets: sub-millisecond RPCs through multi-minute
#: MILP solves.
LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                   0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
                   120.0, 300.0)


def _counter(name, help, labels=()):
    return MetricSpec(name, "counter", help, tuple(labels))


def _gauge(name, help, labels=()):
    return MetricSpec(name, "gauge", help, tuple(labels))


def _histogram(name, help, labels=(), buckets=LATENCY_BUCKETS):
    return MetricSpec(name, "histogram", help, tuple(labels),
                      tuple(buckets))


# ----------------------------------------------------------------------
# Scheduling core (shared by the simulator and the physical scheduler;
# in simulation these run on the virtual clock)
# ----------------------------------------------------------------------

MICROTASKS_TOTAL = _counter(
    "swtpu_microtasks_total",
    "Round micro-task aggregates completed, by outcome", ("outcome",))
JOBS_SUBMITTED_TOTAL = _counter(
    "swtpu_jobs_submitted_total", "Jobs admitted into the scheduler")
JOBS_COMPLETED_TOTAL = _counter(
    "swtpu_jobs_completed_total", "Jobs completed (or dropped at the "
    "failure cap) and removed from the active set")
ALLOCATION_SOLVE_SECONDS = _histogram(
    "swtpu_allocation_solve_seconds",
    "Policy allocation solve time (LP policies; virtual-clock zero in "
    "simulation)", ("policy",))
CURRENT_ROUND = _gauge(
    "swtpu_current_round", "Completed scheduling rounds")
ACTIVE_JOBS = _gauge(
    "swtpu_active_jobs", "Jobs currently in the active set")
LIVE_WORKERS = _gauge(
    "swtpu_live_workers", "Schedulable (non-dead) worker chips")

# ----------------------------------------------------------------------
# Physical round pipeline (sched/physical.py)
# ----------------------------------------------------------------------

ROUND_PHASE_SECONDS = _histogram(
    "swtpu_round_phase_seconds",
    "Wall time of each round-pipeline phase (also exported as trace "
    "spans)", ("phase",))
DISPATCH_LATENCY_SECONDS = _histogram(
    "swtpu_dispatch_latency_seconds",
    "RunJob dispatch RPC latency to a worker daemon")
DISPATCHES_TOTAL = _counter(
    "swtpu_dispatches_total",
    "RunJob dispatch RPCs, by outcome (ok / unavailable / rejected)",
    ("outcome",))
JOBS_REQUEUED_TOTAL = _counter(
    "swtpu_jobs_requeued_total",
    "Jobs failed-in-round and requeued, by reason (worker_dead / "
    "dispatch_rejected / recovery)", ("reason",))
JOB_KILLS_TOTAL = _counter(
    "swtpu_job_kills_total", "Unresponsive-job kills issued by the "
    "round-end watchdog")
WORKER_RETIREMENTS_TOTAL = _counter(
    "swtpu_worker_retirements_total",
    "Worker hosts declared dead and retired from capacity")
WORKER_REVIVALS_TOTAL = _counter(
    "swtpu_worker_revivals_total",
    "Worker hosts revived (rejoin or partition heal)")
WORKER_HEARTBEAT_AGE_SECONDS = _gauge(
    "swtpu_worker_heartbeat_age_seconds",
    "Seconds since each live worker host was last heard from "
    "(refreshed by the liveness monitor; series dropped when the host "
    "is retired or quarantined)", ("host",))
WORKER_BREAKER_STATE = _gauge(
    "swtpu_worker_breaker_state",
    "Circuit-breaker state of each live worker host's channel "
    "(0=closed, 1=half-open, 2=open; series dropped when the host is "
    "retired or quarantined)", ("host",))

# ----------------------------------------------------------------------
# Gray-failure resilience: per-host health scoring + worker quarantine
# (runtime/resilience.py HostHealth, sched/physical.py)
# ----------------------------------------------------------------------

WORKER_HEALTH_SCORE = _gauge(
    "swtpu_worker_health_score",
    "EWMA gray-failure health score of each worker host in [0, 1] "
    "(1 = nominal; fed by observed steps/s vs the fleet reference, "
    "dispatch latency, and working-host heartbeat age; kept live for "
    "quarantined hosts — it is their recovery signal)", ("host",))
WORKER_HEALTH_TRANSITIONS_TOTAL = _counter(
    "swtpu_worker_health_transitions_total",
    "Host health-state transitions, by destination state "
    "(healthy / suspect / degraded)", ("to",))
QUARANTINE_EVENTS_TOTAL = _counter(
    "swtpu_quarantine_events_total",
    "Worker-host quarantine lifecycle events, by action (quarantine / "
    "release / dead / reregistered — dead: a quarantined host stopped "
    "answering probes and converts to a plain retirement; "
    "reregistered: its daemon restarted, which clears the quarantine)",
    ("action",))
QUARANTINED_CHIPS = _gauge(
    "swtpu_quarantined_chips",
    "Chips currently held out of capacity by the gray-failure "
    "quarantine (alive but degraded)")

# ----------------------------------------------------------------------
# Solver / shockwave planner
# ----------------------------------------------------------------------

MILP_SOLVE_SECONDS = _histogram(
    "swtpu_milp_solve_seconds",
    "Shockwave EG-MILP plan_schedule wall time, by fallback path",
    ("path",))
MILP_ASSEMBLY_SECONDS = _histogram(
    "swtpu_milp_assembly_seconds",
    "Sparse-model assembly share of each plan_schedule wall "
    "(structure splice + COO->CSR; included in the solve wall)",
    ("path",))
SOLVER_FALLBACKS_TOTAL = _counter(
    "swtpu_solver_fallbacks_total",
    "MILP solves that fell off the primary (ftf) arm, by landing path "
    "(relaxed / relaxed_retry / greedy)", ("path",))
PIPELINED_SOLVES_TOTAL = _counter(
    "swtpu_pipelined_solves_total",
    "Physical pipelined-planning outcomes: hit (background solve "
    "committed before its re-solve round), late (committed after — its "
    "round already ran on the fallback), miss (one planner query "
    "served by the deadline fallback: cached schedule / backfill), "
    "inline (startup solve on the round loop)", ("outcome",))

# ----------------------------------------------------------------------
# Durability (sched/journal.py)
# ----------------------------------------------------------------------

JOURNAL_APPEND_SECONDS = _histogram(
    "swtpu_journal_append_seconds",
    "Write-ahead journal append latency (sync=true includes the fsync "
    "barrier)", ("sync",))
JOURNAL_RECORDS_TOTAL = _counter(
    "swtpu_journal_records_total", "Journal records appended", ("sync",))
JOURNAL_BYTES_TOTAL = _counter(
    "swtpu_journal_bytes_total", "Framed journal bytes written")
JOURNAL_COMPACTIONS_TOTAL = _counter(
    "swtpu_journal_compactions_total",
    "Compacting snapshots written (journal segments rotated)")
SNAPSHOT_WRITE_SECONDS = _histogram(
    "swtpu_snapshot_write_seconds",
    "Durable snapshot write time (pickle + fsync + rename)")
JOURNAL_LAG_EVENTS = _gauge(
    "swtpu_journal_lag_events",
    "Journal events appended since the last compacting snapshot")

# ----------------------------------------------------------------------
# Control-plane HA (sched/ha.py: journal-shipping hot standby, fenced
# automatic failover)
# ----------------------------------------------------------------------

HA_ROLE = _gauge(
    "swtpu_ha_role",
    "This process's control-plane role (0=standby, 1=leader, 2=fenced "
    "ex-leader)")
HA_LEADER_EPOCH = _gauge(
    "swtpu_ha_leader_epoch",
    "Fenced leader epoch this process claimed (leaders only; every "
    "journal record and scheduler->worker RPC carries it)")
HA_LEASE_RENEWALS_TOTAL = _counter(
    "swtpu_ha_lease_renewals_total",
    "Leader liveness-lease rewrites (one per lease_interval_s while "
    "healthy)")
HA_FAILOVERS_TOTAL = _counter(
    "swtpu_ha_failovers_total",
    "Promotions this process won (standby -> leader transitions)")
HA_PROMOTION_SECONDS = _histogram(
    "swtpu_ha_promotion_seconds",
    "Wall time from lease-lapse detection to the promotion claim being "
    "durable (scheduler reconstruction adds its recovery time on top)")
HA_FENCED_RPCS_TOTAL = _counter(
    "swtpu_ha_fenced_rpcs_total",
    "RPCs rejected by epoch fencing, by side (worker: a stale leader's "
    "dispatch refused; scheduler: a fenced ex-leader refusing reports "
    "so workers re-resolve)", ("side",))
HA_REPLICATION_APPLIED_SEQ = _gauge(
    "swtpu_ha_replication_applied_seq",
    "Highest journal sequence the standby's warm twin has applied")
HA_REPLICATION_RECORDS_TOTAL = _counter(
    "swtpu_ha_replication_records_total",
    "Journal records shipped into the standby's warm twin")
HA_REPLICATION_LAG_SECONDS = _gauge(
    "swtpu_ha_replication_lag_seconds",
    "Standby replication lag: now minus the wall stamp of the last "
    "journal record applied to the warm twin")

# ----------------------------------------------------------------------
# RPC resilience (runtime/resilience.py)
# ----------------------------------------------------------------------

RPC_RETRIES_TOTAL = _counter(
    "swtpu_rpc_retries_total",
    "Transport-level RPC attempt failures that were retried, by method",
    ("method",))
RPC_UNAVAILABLE_TOTAL = _counter(
    "swtpu_rpc_unavailable_total",
    "RPCs that exhausted their whole retry budget, by method",
    ("method",))
BREAKER_TRANSITIONS_TOTAL = _counter(
    "swtpu_breaker_transitions_total",
    "Circuit-breaker state transitions, by destination state "
    "(open / half_open / closed)", ("to",))

# ----------------------------------------------------------------------
# Worker daemon (runtime/worker.py)
# ----------------------------------------------------------------------

WORKER_JOBS_DISPATCHED_TOTAL = _counter(
    "swtpu_worker_jobs_dispatched_total",
    "RunJob dispatches received by this worker daemon")
WORKER_LAST_DISPATCH_TIMESTAMP = _gauge(
    "swtpu_worker_last_dispatch_timestamp_seconds",
    "Wall-clock time of the last RunJob this daemon received")

# ----------------------------------------------------------------------
# Serving tier (shockwave_tpu/serving/; virtual-clock in simulation)
# ----------------------------------------------------------------------

SERVING_SERVICES = _gauge(
    "swtpu_serving_services", "Live (non-retired) serving services")
SERVING_REPLICAS = _gauge(
    "swtpu_serving_replicas",
    "Replica chips assigned to each service this round", ("service",))
SERVING_TARGET_REPLICAS = _gauge(
    "swtpu_serving_target_replicas",
    "Autoscaler replica target for each service this round", ("service",))
SERVING_P99_SECONDS = _gauge(
    "swtpu_serving_p99_seconds",
    "Worst modeled p99 request latency across the round's load window "
    "(M/M/c analytic; omitted while saturated)", ("service",))
SERVING_SLO_ATTAINMENT = _gauge(
    "swtpu_serving_slo_attainment",
    "Cumulative requests-weighted fraction of each service's load "
    "served within its p99 SLO", ("service",))
SERVING_REQUESTS_TOTAL = _counter(
    "swtpu_serving_requests_total",
    "Modeled requests offered to each service, split by whether the "
    "round's p99 met the SLO (slo=ok|violated)", ("service", "slo"))
SERVING_RESERVED_CHIPS = _gauge(
    "swtpu_serving_reserved_chips",
    "Chips reserved for serving replicas ahead of the training "
    "planner this round")
SERVING_SCALE_EVENTS_TOTAL = _counter(
    "swtpu_serving_scale_events_total",
    "Replica scale events, by direction (up / down); each unit is one "
    "replica spawned or drained", ("direction",))
SERVING_SATURATED = _gauge(
    "swtpu_serving_saturated",
    "Whether the analytic model says each service's replica pool is "
    "saturated this round (1 = offered load >= pool capacity; the p99 "
    "gauge is dropped while saturated instead of freezing at its last "
    "healthy value)", ("service",))

# Measured serving path (serving/measured.py + obs/quantiles.py):
# per-request telemetry from the physical replicas, merged per service.
# Absent in simulation — the analytic gauges above are the sim story.
SERVING_MEASURED_P50_SECONDS = _gauge(
    "swtpu_serving_measured_p50_seconds",
    "Measured p50 admission->last-token request latency over the "
    "round's merged replica sketches (quantile-sketch upper edge; only "
    "exported when the round saw measured samples)", ("service",))
SERVING_MEASURED_P99_SECONDS = _gauge(
    "swtpu_serving_measured_p99_seconds",
    "Measured p99 admission->last-token request latency over the "
    "round's merged replica sketches — the autoscaler's preferred "
    "signal when samples exist", ("service",))
SERVING_TOKENS_PER_S = _gauge(
    "swtpu_serving_tokens_per_s",
    "Measured decode throughput of each service's replica pool over "
    "the round (tokens served / round seconds)", ("service",))
SERVING_MEASURED_VS_ANALYTIC_P99 = _gauge(
    "swtpu_serving_measured_vs_analytic_p99",
    "Calibration error of the analytic latency model: measured p99 / "
    "analytic p99 for the same round (1.0 = perfectly calibrated; "
    "omitted while the analytic model reports saturation)", ("service",))
SERVING_MEASURED_SAMPLES_TOTAL = _counter(
    "swtpu_serving_measured_samples_total",
    "Measured request-latency samples merged into each service's "
    "sketches (the measured-path coverage gate in CI)", ("service",))
SERVING_MU_ESTIMATE = _gauge(
    "swtpu_serving_mu_estimate",
    "Online per-replica service-rate estimate mu (requests/s): "
    "measured tokens/s / tokens_per_request blended with the analytic "
    "prior by sample count; equals the analytic value until samples "
    "arrive", ("service",))

# ----------------------------------------------------------------------
# Fleet-scale simulation (vectorized sim core + Monte Carlo sweep:
# sched/simcore.py, scripts/drivers/sweep_scenarios.py,
# scripts/microbenchmarks/bench_sim_round.py)
# ----------------------------------------------------------------------

SIM_FAULT_EVENTS_TOTAL = _counter(
    "swtpu_sim_fault_events_total",
    "Injected chip-fault events applied by the simulator, by action "
    "(kill / revive / degrade / restore) — sweep and chaos scenarios "
    "only, zero on canonical replays", ("action",))
SIM_ROUND_CORE_SECONDS = _histogram(
    "swtpu_sim_round_core_seconds",
    "bench_sim_round: wall time of one round of scheduling bookkeeping "
    "(priorities + selection + assignment + round record), by sim-core "
    "path (scalar / vectorized)", ("path",))
SWEEP_SCENARIOS_TOTAL = _counter(
    "swtpu_sweep_scenarios_total",
    "Monte Carlo sweep scenarios, by outcome (ok / failed / "
    "skipped_existing)", ("outcome",))
SWEEP_SCENARIO_WALL_SECONDS = _histogram(
    "swtpu_sweep_scenario_wall_seconds",
    "Per-scenario simulation wall time inside the sweep's process pool")

# ----------------------------------------------------------------------
# Online what-if control plane (shockwave_tpu/whatif/): digital-twin
# forks of the live scheduler rolled forward in-memory every round
# ----------------------------------------------------------------------

WHATIF_FORK_SECONDS = _histogram(
    "swtpu_whatif_fork_seconds",
    "Digital-twin state-fork copy time (the pickle of the journal "
    "snapshot; runs under the scheduler lock in physical mode, so this "
    "IS the round pipeline's fork hold-time)")
WHATIF_ROLLOUTS_TOTAL = _counter(
    "swtpu_whatif_rollouts_total",
    "Twin rollouts completed, by purpose (admission / tune / forecast "
    "/ shadow_chaos)", ("purpose",))
WHATIF_ADMISSION_DECISIONS_TOTAL = _counter(
    "swtpu_whatif_admission_decisions_total",
    "Monte-Carlo admission-control verdicts, by decision (admit / "
    "defer / fast_path / would_defer — fast_path: the cluster-load "
    "guard admitted without rolling a twin; would_defer: a physical "
    "ADVISORY verdict, the job was admitted anyway)", ("decision",))
WHATIF_KNOB_VALUE = _gauge(
    "swtpu_whatif_knob_value",
    "Current value of each auto-tuned knob (set at every committed "
    "sweep)", ("knob",))
WHATIF_KNOB_COMMITS_TOTAL = _counter(
    "swtpu_whatif_knob_commits_total",
    "Knob auto-tuning sweeps that committed a CHANGED value, by knob",
    ("knob",))
WHATIF_FORECAST_MAKESPAN_SECONDS = _gauge(
    "swtpu_whatif_forecast_makespan_seconds",
    "Forecast projected drain time of the active workload from seeded "
    "twin rollouts, by quantile (p50 / p99)", ("quantile",))
WHATIF_FORECAST_ATTAINMENT = _gauge(
    "swtpu_whatif_forecast_attainment",
    "Forecast serving SLO attainment over the rollout horizon, by "
    "quantile (p50 / p99; 1.0 with no serving load)", ("quantile",))
WHATIF_SHADOW_CHAOS_TOTAL = _counter(
    "swtpu_whatif_shadow_chaos_total",
    "Low-rate shadow chaos probes run against the digital twin, by "
    "outcome (ok / violation — violation: the injected fault added "
    "failure charges or crashed the twin rollout)", ("outcome",))

# ----------------------------------------------------------------------
# Fleet-wide tracing (obs/propagation.py, obs/shard.py, obs/merge.py)
# and telemetry history (obs/history.py)
# ----------------------------------------------------------------------

TRACE_SHARD_SPANS = _gauge(
    "swtpu_trace_shard_spans",
    "Spans currently buffered in this process's bounded span-shard "
    "ring (worker daemons and trainers write shards into the trace "
    "dir; python -m shockwave_tpu.obs.merge fuses them)")
TRACE_SHARD_FLUSHES_TOTAL = _counter(
    "swtpu_trace_shard_flushes_total",
    "Atomic span-shard file rewrites by this process")
TRACE_MERGE_SHARDS_TOTAL = _counter(
    "swtpu_trace_merge_shards_total",
    "Per-process span shards folded into the merged fleet trace, by "
    "shard role (scheduler / worker / trainer)", ("role",))
TRACE_MERGE_SPANS_TOTAL = _counter(
    "swtpu_trace_merge_spans_total",
    "Spans emitted into the merged fleet trace")
TRACE_MERGE_CLOCK_OFFSET_SECONDS = _gauge(
    "swtpu_trace_merge_clock_offset_seconds",
    "Per-host clock offset the merge subtracted, estimated from RPC "
    "send/recv timestamp pairs (scheduler host is the reference)",
    ("host",))
HISTORY_SAMPLES_TOTAL = _counter(
    "swtpu_history_samples_total",
    "Telemetry-history ring appends, by kind (round: one full metric "
    "snapshot per round; observation: one per-microtask observed "
    "steps/s point keyed by (job_type, bs, sf, worker_type); serving: "
    "one measured-serving row per (service, round) with samples)",
    ("kind",))
HISTORY_FLUSHES_TOTAL = _counter(
    "swtpu_history_flushes_total",
    "Crash-safe telemetry-history ring flushes to disk "
    "(core/durable_io atomic rewrite)")
ALERT = _gauge(
    "swtpu_alert",
    "Burn-rate / regression check verdicts over the telemetry history "
    "(1 = firing), by check (round_overrun / dispatch_failure_burn / "
    "throughput_regression); readable by the health scorer and the "
    "what-if forecasts", ("check",))

# ----------------------------------------------------------------------
# Learned throughput oracle (shockwave_tpu/oracle +
# core/throughput_estimator.OracleThroughputChain)
# ----------------------------------------------------------------------

ORACLE_PREDICTIONS_TOTAL = _counter(
    "swtpu_oracle_predictions_total",
    "Throughput predictions served by the oracle fallback chain, by "
    "provenance (profiled: offline table hit; learned: model "
    "prediction above the confidence gate; prior: conservative "
    "default)", ("provenance",))
ORACLE_ONLINE_UPDATES_TOTAL = _counter(
    "swtpu_oracle_online_updates_total",
    "Observed micro-task rates folded back into the learned model's "
    "online residual corrections")
ORACLE_PREDICTION_REL_ERROR = _histogram(
    "swtpu_oracle_prediction_rel_error",
    "Relative error |observed - predicted| / observed of the oracle's "
    "current estimate at each online update (converges toward 0 as "
    "corrections accumulate)",
    buckets=(0.01, 0.025, 0.05, 0.1, 0.2, 0.4, 0.8, 1.6, 3.2, 10.0))

# ----------------------------------------------------------------------
# Offline harnesses (scripts/microbenchmarks, scripts/profiling)
# ----------------------------------------------------------------------

POLICY_SOLVE_SECONDS = _histogram(
    "swtpu_policy_solve_seconds",
    "Microbenchmark get_allocation wall time", ("policy",))
PROFILE_MEASURE_SECONDS = _histogram(
    "swtpu_profile_measure_seconds",
    "Throughput-profiler measurement wall time per oracle row "
    "(device timing itself stays core/timing.marginal_step_time)",
    ("family",))

# ----------------------------------------------------------------------
# Span names (tracer). The round-pipeline phases are the rows of
# `python -m shockwave_tpu.obs.report`.
# ----------------------------------------------------------------------

SPAN_BEGIN_ROUND = "begin_round"
SPAN_SOLVE = "solve"
SPAN_DISPATCH = "dispatch"
SPAN_WAIT = "wait"
SPAN_END_ROUND = "end_round"
SPAN_JOURNAL_FSYNC = "journal-fsync"
SPAN_SNAPSHOT = "snapshot"
SPAN_ESTIMATE_REFRESH = "estimate-refresh"
SPAN_SERVING_PLAN = "serving-plan"
#: The fork's state copy — a round-pipeline phase (it runs under the
#: scheduler lock in physical mode), so it lands in the phase
#: histogram AND the trace timeline like solve/dispatch/wait do.
SPAN_WHATIF_FORK = "whatif_fork"
SPAN_WHATIF_ROLLOUT = "whatif-rollout"
SPAN_PLANNER_SOLVE = "planner-solve"
SPAN_POLICY_SOLVE = "policy-solve"
SPAN_PROFILE_MEASURE = "profile-measure"
SPAN_TRACING_BENCH = "tracing-bench"  # bench_tracing.py synthetic span
#: Fleet-trace spans (obs/propagation.py). One round's
#: solve -> dispatch -> launch -> trainer -> done chain shares one
#: trace id across the scheduler, worker-daemon and trainer processes.
SPAN_ROUND = "round"                  # scheduler: whole-round root span
SPAN_RUNJOB_RPC = "runjob-rpc"        # scheduler: one RunJob dispatch RPC
SPAN_RUNJOB = "runjob"                # worker daemon: RunJob handling
SPAN_LAUNCH = "launch"                # worker daemon: trainer process life
SPAN_DONE_REPORT = "done-report"      # worker daemon: Done RPC back
SPAN_TRAINER = "trainer"              # trainer: lease window (init->exit)
SPAN_CKPT_LOAD = "ckpt-load"          # trainer: checkpoint restore
SPAN_CKPT_SAVE = "ckpt-save"          # trainer: checkpoint save

#: Default phase columns of the report table, in pipeline order.
REPORT_PHASES = (SPAN_SOLVE, SPAN_DISPATCH, SPAN_WAIT, SPAN_END_ROUND,
                 SPAN_JOURNAL_FSYNC)

# ----------------------------------------------------------------------
# Span-context propagation keys and shard filenames. Declared ONLY here
# (enforced by the obs-discipline pass: these literals may not appear
# anywhere else in the tree) so the cross-process contract between the
# scheduler, the worker daemon, the dispatcher and the trainer-side
# LeaseIterator cannot fork silently.
# ----------------------------------------------------------------------

#: gRPC metadata key carrying the traceparent of the sender's active
#: span on scheduler->worker RPCs (must be lowercase per gRPC).
TRACEPARENT_METADATA_KEY = "swtpu-traceparent"
#: gRPC metadata key carrying the sender's wall-clock send timestamp;
#: paired with the receiver's recv stamp by obs/merge.py to align
#: per-host clock offsets.
TRACE_SENDTS_METADATA_KEY = "swtpu-trace-sendts"
#: Environment variable the dispatcher exports into trainer processes
#: (the SWTPU_DEGRADE_FACTOR / GAVEL_* pattern): the launch span's
#: traceparent, consumed by the job-side LeaseIterator.
TRACEPARENT_ENV = "SWTPU_TRACEPARENT"
#: Environment variable naming the directory every process writes its
#: bounded span shard into (run_dir of the drive).
SHARD_DIR_ENV = "SWTPU_SPAN_SHARD_DIR"
#: Span-shard filename pattern: spans-<role>-<pid>.json.
SHARD_FILE_PREFIX = "spans-"
SHARD_FILE_SUFFIX = ".json"
#: Default filename of the merged fleet trace next to the shards.
MERGED_TRACE_NAME = "merged_trace.json"
#: Default filename of the crash-safe telemetry-history ring.
HISTORY_FILE_NAME = "history.json"


def shard_filename(role: str, pid: int) -> str:
    """Canonical shard filename for one process's span shard."""
    return f"{SHARD_FILE_PREFIX}{role}-{int(pid)}{SHARD_FILE_SUFFIX}"


def all_metric_specs():
    """Every MetricSpec declared in this module, in declaration order."""
    return [v for v in globals().values() if isinstance(v, MetricSpec)]
