"""Seeded interleaving explorer: perturb-many, not observe-one.

The sanitizer (analysis/sanitizer.py) validates whatever single
interleaving a test happens to execute. This module upgrades it: under
``SWTPU_SANITIZE_EXPLORE=<seed>`` every ``SanitizedLock`` injects a
*seeded* scheduling perturbation at its acquire/release boundaries —
nothing, a bare scheduler yield (``sleep(0)``), or a short seeded
sleep — so N seeds drive N different interleavings of the same
critical sections, with the lock-order-cycle, ownership and hold-time
checks evaluated on every schedule.

Determinism contract (asserted by tests/test_explorer.py): the
decision at a thread's k-th lock event is a pure function of
``(seed, thread name, k)`` — it does NOT depend on what other threads
do. Two runs of the same seeded workload therefore produce identical
per-thread decision traces even though the OS schedules them
differently, and the trace IS the reproduction recipe: replaying the
seed replays the perturbation schedule exactly.

Yield points fire only when BOTH the sanitizer and the explorer are
enabled; production locks are never wrapped, so this module is inert
outside explicitly-marked tests and the CI explorer smoke.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

ENV_VAR = "SWTPU_SANITIZE_EXPLORE"

_M64 = (1 << 64) - 1

#: Decision space: cumulative thresholds over the 64-bit hash.
#: ~45% no perturbation, ~35% bare yield, ~20% short seeded sleep.
_YIELD_AT = int(0.45 * _M64)
_SLEEP_AT = int(0.80 * _M64)
#: Seeded sleep range (seconds): long enough to genuinely reorder
#: threads, short enough that a 20-seed smoke stays in tier-1 budget.
_SLEEP_MIN_S = 0.00005
_SLEEP_MAX_S = 0.0008

ACTION_NONE = "-"
ACTION_YIELD = "yield"
ACTION_SLEEP = "sleep"


def _fnv64(data: bytes) -> int:
    h = 0xCBF29CE484222325
    for b in data:
        h = ((h ^ b) * 0x100000001B3) & _M64
    return h


def _mix(seed: int, thread_hash: int, counter: int) -> int:
    """splitmix64-style avalanche over (seed, thread, event counter)."""
    x = (seed * 0x9E3779B97F4A7C15 + thread_hash * 0xBF58476D1CE4E5B9
         + counter * 0x94D049BB133111EB) & _M64
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & _M64
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & _M64
    x ^= x >> 31
    return x


class InterleavingExplorer:
    """One seeded exploration run (normally installed via `install`)."""

    def __init__(self, seed: int):
        self.seed = int(seed)
        self._tls = threading.local()
        self._mu = threading.Lock()
        #: thread name -> [(counter, point, lock_name, action)]
        self._traces: Dict[str, List[Tuple[int, str, str, str]]] = {}
        self._events = 0
        self._perturbations = 0

    # -- decision core -------------------------------------------------

    def _thread_state(self):
        state = getattr(self._tls, "state", None)
        if state is None:
            name = threading.current_thread().name
            state = self._tls.state = {
                "name": name,
                "hash": _fnv64(name.encode()),
                "counter": 0,
                "trace": [],
            }
            with self._mu:
                self._traces[name] = state["trace"]
        return state

    def decide(self, point: str, lock_name: str) -> Tuple[str, float]:
        """The (action, sleep_s) for this thread's next lock event —
        pure in (seed, thread name, per-thread counter)."""
        state = self._thread_state()
        counter = state["counter"]
        state["counter"] = counter + 1
        h = _mix(self.seed, state["hash"], counter)
        if h < _YIELD_AT:
            action, sleep_s = ACTION_NONE, 0.0
        elif h < _SLEEP_AT:
            action, sleep_s = ACTION_YIELD, 0.0
        else:
            frac = (h & 0xFFFF) / 0xFFFF
            action = ACTION_SLEEP
            sleep_s = _SLEEP_MIN_S + frac * (_SLEEP_MAX_S - _SLEEP_MIN_S)
        state["trace"].append((counter, point, lock_name, action))
        return action, sleep_s

    def perturb(self, point: str, lock_name: str) -> None:
        """Called by SanitizedLock at an acquire/release boundary."""
        action, sleep_s = self.decide(point, lock_name)
        with self._mu:
            self._events += 1
            if action != ACTION_NONE:
                self._perturbations += 1
        if action == ACTION_YIELD:
            time.sleep(0)
        elif action == ACTION_SLEEP:
            time.sleep(sleep_s)

    # -- reporting -----------------------------------------------------

    def trace(self) -> Dict[str, List[Tuple[int, str, str, str]]]:
        """Per-thread decision traces (copies)."""
        with self._mu:
            return {name: list(t) for name, t in self._traces.items()}

    def stats(self) -> dict:
        with self._mu:
            return {"seed": self.seed, "events": self._events,
                    "perturbations": self._perturbations,
                    "threads": len(self._traces)}


_active: Optional[InterleavingExplorer] = None
_env_checked = False
#: Serializes env installation so exactly ONE explorer instance ever
#: results from a given environment (two bring-up threads racing
#: install_from_env must not each build one — the loser's per-thread
#: counters would reset mid-run and fork the schedule).
_install_mu = threading.Lock()


def install(seed: int) -> InterleavingExplorer:
    """Activate exploration with `seed` (tests drive this directly;
    the env var is the subprocess interface). Returns the explorer."""
    global _active, _env_checked
    _active = InterleavingExplorer(seed)
    _env_checked = True
    return _active


def uninstall() -> None:
    global _active, _env_checked
    _active = None
    _env_checked = True


def active() -> Optional[InterleavingExplorer]:
    return _active


def install_from_env() -> Optional[InterleavingExplorer]:
    """Install from ``SWTPU_SANITIZE_EXPLORE`` (once; later lock
    creations reuse the installed explorer). A garbage value logs and
    stays off rather than crashing every instrumented process.

    Ordering matters: ``_env_checked`` flips True only AFTER
    ``_active`` is assigned (install() does both in that order), so a
    concurrently-starting thread either performs the (idempotent)
    installation itself or observes the fully-installed explorer —
    never a half-open window where its lock events are skipped without
    consuming counter ticks, which would break seed replay."""
    global _env_checked
    if _env_checked:
        return _active
    with _install_mu:
        if _env_checked:
            return _active
        raw = os.environ.get(ENV_VAR)
        if raw is None or raw == "":
            _env_checked = True
            return None
        try:
            seed = int(raw)
        except ValueError:
            import logging
            logging.getLogger("shockwave_tpu.analysis").warning(
                "%s=%r is not an integer seed; interleaving exploration "
                "stays off", ENV_VAR, raw)
            _env_checked = True
            return None
        return install(seed)


def on_lock_event(point: str, lock_name: str) -> None:
    """SanitizedLock hook: perturb if an explorer is active (either
    installed programmatically or via the environment)."""
    explorer = _active if _env_checked else install_from_env()
    if explorer is not None:
        explorer.perturb(point, lock_name)
