"""Control-plane HA: epoch fencing, journal following, hot-standby
promotion — units plus the sanitizer-clean loopback failover
(acceptance criterion)."""
import json
import os
import pickle
import signal
import socket
import subprocess
import sys
import time

import pytest

from shockwave_tpu.core.job import Job, JobIdPair
from shockwave_tpu.runtime.resilience import (EPOCH_ADVANCED, EPOCH_OK,
                                              EPOCH_STALE, CircuitBreaker,
                                              EpochFence)
from shockwave_tpu.sched import journal
from shockwave_tpu.sched import ha

TESTS_DIR = os.path.dirname(__file__)
REPO = os.path.abspath(os.path.join(TESTS_DIR, ".."))
DATA = os.path.join(REPO, "data")
RUN_PHYSICAL = os.path.join(REPO, "scripts", "drivers", "run_physical.py")
FSCK = os.path.join(REPO, "scripts", "utils", "fsck_journal.py")
THROUGHPUTS = os.path.join(DATA, "tacc_throughputs.json")


def free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


# ----------------------------------------------------------------------
# Epoch chain (journal supersede rule)
# ----------------------------------------------------------------------

def _rec(seq, epoch=None, etype="x", t=1.0):
    rec = {"seq": seq, "type": etype, "t": t, "data": {}}
    if epoch is not None:
        rec["epoch"] = epoch
    return rec


class TestEpochChain:
    def test_untagged_records_pass_through(self):
        events = [_rec(1), _rec(2), _rec(3)]
        kept, orphans = journal.filter_epoch_chain(events)
        assert kept == events and orphans == []

    def test_duplicate_seq_higher_epoch_wins(self):
        stale, fresh = _rec(5, epoch=1), _rec(5, epoch=2)
        kept, orphans = journal.filter_epoch_chain([stale, fresh])
        assert kept == [fresh] and orphans == [stale]

    def test_stale_writer_tail_is_dropped(self):
        # Epoch-1 zombie kept appending seqs 4-5 after epoch 2 wrote 4+.
        events = [_rec(1, 1), _rec(2, 1), _rec(3, 1),
                  _rec(4, 2), _rec(4, 1), _rec(5, 1), _rec(5, 2),
                  _rec(6, 2)]
        events.sort(key=lambda r: r["seq"])
        kept, orphans = journal.filter_epoch_chain(events)
        assert [(r["seq"], r["epoch"]) for r in kept] == [
            (1, 1), (2, 1), (3, 1), (4, 2), (5, 2), (6, 2)]
        assert {(r["seq"], r["epoch"]) for r in orphans} == {(4, 1), (5, 1)}

    def test_epoch_never_decreases_along_chain(self):
        events = [_rec(1, 2), _rec(2, 1), _rec(3, 2)]
        kept, orphans = journal.filter_epoch_chain(events)
        assert [r["seq"] for r in kept] == [1, 3]
        assert [r["seq"] for r in orphans] == [2]

    def test_load_state_discards_stale_writer(self, tmp_path):
        d = str(tmp_path)
        # Epoch-1 incarnation writes 3 events and "freezes" (keeps its
        # layer open); epoch-2 recovers and writes its own.
        a = journal.DurabilityLayer(d, epoch=1, rotate_on_open=True)
        for i in range(3):
            a.record("job_added", {"i": i})
        b = journal.DurabilityLayer(d, epoch=2, rotate_on_open=True)
        b.record("round_ended", {"round": 1})
        # The zombie wakes and appends to ITS OWN segment (rotate-on-
        # open confined it there) with already-claimed seqs.
        a.record("job_added", {"i": 99})
        recovered = journal.load_state(d)
        assert [(int(e["seq"]), e["epoch"]) for e in recovered.events] \
            == [(1, 1), (2, 1), (3, 1), (4, 2)]
        assert len(recovered.stale_orphans) == 1
        assert recovered.stale_orphans[0]["data"] == {"i": 99}
        a.close()
        b.close()

    def test_rotate_on_open_never_shares_a_segment(self, tmp_path):
        d = str(tmp_path)
        a = journal.DurabilityLayer(d, epoch=1, rotate_on_open=True)
        a.record("job_added", {})
        seg_a = a._writer.path
        b = journal.DurabilityLayer(d, epoch=2, rotate_on_open=True)
        assert b._writer.path != seg_a
        a.close()
        b.close()


# ----------------------------------------------------------------------
# Streaming follower
# ----------------------------------------------------------------------

class TestJournalFollower:
    def test_incremental_tail(self, tmp_path):
        d = str(tmp_path)
        layer = journal.DurabilityLayer(d)
        follower = journal.JournalFollower(d)
        layer.record("a", {"n": 1})
        events, status = follower.poll()
        assert [e["type"] for e in events] == ["a"]
        assert status == journal.TAIL_CLEAN
        layer.record("b", {})
        layer.record("c", {})
        events, status = follower.poll()
        assert [e["type"] for e in events] == ["b", "c"]
        assert follower.last_seq == 3
        events, _ = follower.poll()
        assert events == []
        layer.close()

    def test_torn_tail_is_wait_not_corruption(self, tmp_path):
        d = str(tmp_path)
        layer = journal.DurabilityLayer(d)
        layer.record("a", {})
        path = layer._writer.path
        follower = journal.JournalFollower(d)
        follower.poll()
        # Simulate a mid-append crash: half a frame at the tail.
        with open(path, "ab") as f:
            f.write(b"\x40\x00\x00\x00\x12")
        events, status = follower.poll()
        assert events == [] and status == journal.FOLLOW_WAIT
        # The restart truncates the torn tail and appends a real
        # record; the follower re-reads from its valid offset.
        layer.close()
        layer2 = journal.DurabilityLayer(d)
        layer2.record("b", {})
        events, status = follower.poll()
        assert [e["type"] for e in events] == ["b"]
        assert status == journal.TAIL_CLEAN
        layer2.close()

    def test_follower_spans_segment_rotation(self, tmp_path):
        d = str(tmp_path)
        layer = journal.DurabilityLayer(d, snapshot_interval_rounds=1)
        follower = journal.JournalFollower(d)
        layer.record("a", {})
        assert len(follower.poll()[0]) == 1
        layer.snapshot({"state": {}})  # rotates to a new segment
        layer.record("b", {})
        events, status = follower.poll()
        assert [e["type"] for e in events] == ["b"]
        assert status == journal.TAIL_CLEAN
        layer.close()

    def test_behind_compaction_detected(self, tmp_path):
        d = str(tmp_path)
        layer = journal.DurabilityLayer(d)
        layer.record("a", {})
        layer.record("b", {})
        # Two snapshots delete the covered segments (retention keeps
        # only the .prev horizon's tail) while the follower never read.
        layer.snapshot({"state": {}})
        layer.record("c", {})
        layer.snapshot({"state": {}})
        follower = journal.JournalFollower(d)
        events, status = follower.poll()
        assert status == journal.FOLLOW_BEHIND
        layer.close()

    def test_superseded_writers_torn_tail_is_ignorable(self, tmp_path):
        """A SIGKILLed HA leader's torn tail is permanent debris (each
        incarnation rotates to a fresh segment, so nothing ever
        truncates it): once a higher epoch exists, the follower must
        report a CLEAN tail and fsck must exit 0 — only the CURRENT
        writer chain's torn tail is damage."""
        d = str(tmp_path)
        a = journal.DurabilityLayer(d, epoch=1, rotate_on_open=True)
        a.record("a", {})
        with open(a._writer.path, "ab") as f:
            f.write(b"\x40\x00\x00\x00\x12")  # SIGKILL mid-append
        a.close()
        b = journal.DurabilityLayer(d, epoch=2, rotate_on_open=True)
        b.record("b", {})
        follower = journal.JournalFollower(d)
        events, status = follower.poll()
        assert [e["epoch"] for e in events] == [1, 2]
        assert status == journal.TAIL_CLEAN
        fsck = subprocess.run(
            [sys.executable, FSCK, d], capture_output=True, text=True,
            env=dict(os.environ,
                     PYTHONPATH=REPO + os.pathsep
                     + os.environ.get("PYTHONPATH", "")),
            timeout=60)
        assert fsck.returncode == 0, fsck.stdout + fsck.stderr
        assert "ignorable" in fsck.stdout
        # Without a successor epoch the same torn tail IS recoverable
        # damage (exit 1) — single-writer semantics unchanged.
        d2 = str(tmp_path / "solo")
        c = journal.DurabilityLayer(d2, epoch=1, rotate_on_open=True)
        c.record("a", {})
        with open(c._writer.path, "ab") as f:
            f.write(b"\x40\x00\x00\x00\x12")
        c.close()
        fsck = subprocess.run(
            [sys.executable, FSCK, d2], capture_output=True, text=True,
            env=dict(os.environ,
                     PYTHONPATH=REPO + os.pathsep
                     + os.environ.get("PYTHONPATH", "")),
            timeout=60)
        assert fsck.returncode == 1, fsck.stdout + fsck.stderr
        b.close()

    def test_lease_advertises_failover_budget(self, tmp_path):
        """HAConfig.failover_budget_s reaches worker clients through
        the lease file (the --ha block's worker-side delivery channel),
        not the environment."""
        from shockwave_tpu.runtime.clients import WorkerToSchedulerClient
        d = str(tmp_path)
        ctl = ha.HAController(d, ha.HAConfig(failover_budget_s=77.0),
                              port=1234)
        assert ctl._renew_once() is True
        client = WorkerToSchedulerClient(
            "127.0.0.1", 1234, endpoint_file=ha.lease_path(d))
        assert client.failover_budget_s() == 77.0
        # Explicit constructor arg wins over the lease.
        pinned = WorkerToSchedulerClient(
            "127.0.0.1", 1234, endpoint_file=ha.lease_path(d),
            failover_budget_s=5.0)
        assert pinned.failover_budget_s() == 5.0
        ctl.stop()

    def test_follower_fences_stale_writer_across_polls(self, tmp_path):
        d = str(tmp_path)
        a = journal.DurabilityLayer(d, epoch=1, rotate_on_open=True)
        a.record("a", {})
        follower = journal.JournalFollower(d)
        assert len(follower.poll()[0]) == 1
        b = journal.DurabilityLayer(d, epoch=2, rotate_on_open=True)
        b.record("b", {})
        events, _ = follower.poll()
        assert [e["epoch"] for e in events] == [2]
        # Zombie appends with stale epoch + stale seqs: never delivered.
        a.record("z", {})
        events, _ = follower.poll()
        assert events == []
        assert follower.stale_dropped >= 1
        a.close()
        b.close()


# ----------------------------------------------------------------------
# Lease + claims + fence
# ----------------------------------------------------------------------

class TestLeaseAndClaims:
    def test_lease_roundtrip(self, tmp_path):
        d = str(tmp_path)
        ha.write_lease(d, epoch=3, addr="10.0.0.9", port=5007)
        lease = ha.read_lease(d)
        assert lease["epoch"] == 3
        assert (lease["addr"], lease["port"]) == ("10.0.0.9", 5007)
        assert ha.read_lease(str(tmp_path / "nope")) is None

    def test_epoch_claim_is_exclusive(self, tmp_path):
        d = str(tmp_path)
        assert ha.try_claim_epoch(d, 1, role="leader")
        assert not ha.try_claim_epoch(d, 1, role="standby")
        assert ha.max_claimed_epoch(d) == 1
        assert ha.claim_next_epoch(d, role="standby") == 2
        assert ha.max_claimed_epoch(d) == 2

    def test_controller_claims_and_fences(self, tmp_path):
        d = str(tmp_path)
        fenced = []
        ctl = ha.HAController(d, ha.HAConfig(), port=1234,
                              on_fenced=fenced.append)
        assert ctl.epoch == 1
        assert ctl._renew_once() is True
        lease = ha.read_lease(d)
        assert lease["epoch"] == 1 and lease["port"] == 1234
        # A standby claims over us: the next deadman tick self-fences.
        assert ha.try_claim_epoch(d, 2, role="standby")
        assert ctl._renew_once() is False
        assert ctl.fenced and fenced == [2]
        # Fencing is once-only.
        assert ctl._renew_once() is False
        assert fenced == [2]
        ctl.stop()

    def test_epoch_fence_verdicts(self):
        fence = EpochFence()
        assert fence.observe(1) == EPOCH_ADVANCED
        assert fence.observe(1) == EPOCH_OK
        assert fence.observe(3) == EPOCH_ADVANCED
        assert fence.observe(2) == EPOCH_STALE
        assert fence.epoch == 3

    def test_breaker_reset_closes_open_circuit(self):
        breaker = CircuitBreaker(failure_threshold=2, reset_timeout_s=60)
        breaker.record_failure()
        breaker.record_failure()
        assert breaker.state == "open"
        assert not breaker.allow()
        breaker.reset()
        assert breaker.state == "closed"
        assert breaker.allow()

    def test_worker_client_refreshes_endpoint(self, tmp_path):
        from shockwave_tpu.runtime.clients import WorkerToSchedulerClient
        d = str(tmp_path)
        ha.write_lease(d, epoch=1, addr="127.0.0.1", port=1111)
        client = WorkerToSchedulerClient(
            "127.0.0.1", 1111, endpoint_file=ha.lease_path(d))
        assert client.breaker is not None
        assert client.refresh_endpoint() is False  # unchanged
        # The breaker opened against the dead leader...
        client.breaker.record_failure()
        client.breaker.record_failure()
        client.breaker.record_failure()
        assert client.breaker.state == "open"
        # ...and a promoted leader's lease resets channel + breaker.
        ha.write_lease(d, epoch=2, addr="127.0.0.1", port=2222)
        assert client.refresh_endpoint() is True
        assert client._sched_port == 2222
        assert client.breaker.state == "closed"


# ----------------------------------------------------------------------
# Hot standby: warm twin + in-process promotion
# ----------------------------------------------------------------------

def _job(total_steps=300):
    return Job(None, "ResNet-18 (batch size 32)",
               "python3 main.py --batch_size 32",
               "image_classification/cifar10", "--num_steps",
               total_steps=total_steps, duration=10000)


@pytest.mark.recovery
@pytest.mark.timeout(120)
class TestHotStandbyPromotion:
    def _leader(self, state_dir, ha_cfg, resume=False, port=None):
        from shockwave_tpu.sched.physical import PhysicalScheduler
        from shockwave_tpu.sched.scheduler import SchedulerConfig
        from shockwave_tpu.solver import get_policy
        return PhysicalScheduler(
            get_policy("max_min_fairness"), throughputs_file=THROUGHPUTS,
            config=SchedulerConfig(
                time_per_iteration=2.0, heartbeat_interval_s=0.0,
                state_dir=str(state_dir), resume=resume,
                snapshot_interval_rounds=2, ha=ha_cfg),
            port=port or free_port())

    def _twin_factory(self):
        from shockwave_tpu.sched.scheduler import (Scheduler,
                                                   SchedulerConfig)
        from shockwave_tpu.solver import get_policy
        from shockwave_tpu.whatif.fork import twin_config

        def factory():
            return Scheduler(get_policy("max_min_fairness"),
                             simulate=True,
                             throughputs_file=THROUGHPUTS,
                             config=twin_config(SchedulerConfig(
                                 time_per_iteration=2.0)))
        return factory

    def test_warm_twin_and_promotion(self, tmp_path):
        d = tmp_path / "state"
        ha_cfg = {"lease_interval_s": 0.1, "lease_ttl_s": 0.6,
                  "standby_poll_interval_s": 0.05}
        leader = self._leader(d, ha_cfg)
        try:
            assert leader._ha.epoch == 1
            ids, _ = leader._register_worker_rpc("v5e", 2, "127.0.0.1",
                                                 free_port())
            j0 = leader.add_job(_job(300))
            leader.add_job(_job(300))
            with leader._cv:
                leader.rounds.current_assignments[j0] = (ids[0],)
                leader._running_jobs.add(j0)
                leader._dispatch_seq += 1
                leader._dispatch_stamp[(j0, ids[0])] = leader._dispatch_seq
            leader.done_callback(j0, ids[0], [120], [1.0])

            standby = ha.HotStandby(str(d),
                                    ha.HAConfig.from_dict(ha_cfg),
                                    twin_factory=self._twin_factory())
            standby.poll_once()
            # The warm twin tracked the leader's live state.
            assert set(standby.twin.acct.jobs) == {j0, JobIdPair(1)}
            assert standby.twin.acct.total_steps_run[j0] == 120
            assert standby.twin.workers.cluster_spec == {"v5e": 2}
            # Leader alive: no promotion.
            assert not standby.leader_lapsed()
        finally:
            leader.shutdown()

        # Leader gone: the lease lapses and the standby wins the CAS.
        deadline = time.time() + 10
        while time.time() < deadline and not standby.leader_lapsed():
            time.sleep(0.05)
        assert standby.leader_lapsed()
        standby._promote_port = 4321
        record = standby.try_promote()
        assert record is not None and record.epoch == 2
        assert record.applied_seq == standby.follower.last_seq
        lease = ha.read_lease(str(d))
        assert lease["epoch"] == 2 and lease["port"] == 4321

        # The promoted incarnation re-enters via the conservative
        # recovery path with the claimed epoch.
        promoted_cfg = dict(ha_cfg, claimed_epoch=record.epoch)
        new = self._leader(d, promoted_cfg, resume=True)
        try:
            assert new._ha.epoch == 2
            assert new._durability.epoch == 2
            assert set(new.acct.jobs) == {j0, JobIdPair(1)}
            assert new.acct.total_steps_run[j0] == 120
            assert not new.rounds.current_assignments  # requeued
            assert new.acct.failures[j0] == 0
        finally:
            new.shutdown()

    def test_promotion_race_single_winner(self, tmp_path):
        d = str(tmp_path)
        assert ha.try_claim_epoch(d, 1, role="leader")
        ha.write_lease(d, epoch=1, addr="127.0.0.1", port=1, stamp=0.0)
        cfg = ha.HAConfig(lease_ttl_s=0.1)
        a = ha.HotStandby(d, cfg)
        b = ha.HotStandby(d, cfg)
        assert a.leader_lapsed() and b.leader_lapsed()
        a._promote_port = b._promote_port = 1
        rec_a = a.try_promote()
        rec_b = b.try_promote()
        assert rec_a is not None and rec_a.epoch == 2
        # b saw a's claim (max+1 = 3 now), so b either loses epoch 2 or
        # claims 3; with the sequential calls here b claims 3 — what
        # matters is the CAS: epoch 2 has exactly one owner.
        assert rec_b is None or rec_b.epoch != 2

    def test_fenced_leader_rejects_dispatch_metadata(self, tmp_path):
        """Worker-side fencing end to end over real gRPC: a stale
        epoch's RunJob is refused, the advanced epoch is adopted."""
        from shockwave_tpu.runtime.clients import SchedulerToWorkerClient
        from shockwave_tpu.runtime.servers import serve_worker
        fence = EpochFence()
        advances = []
        seen = []
        port = free_port()
        server = serve_worker(port, {
            "RunJob": lambda jobs, wid, rid: seen.append(rid),
            "KillJob": lambda j: None, "Reset": lambda: None,
            "Shutdown": lambda: None,
        }, fence=fence, on_epoch_advance=advances.append)
        try:
            new = SchedulerToWorkerClient("127.0.0.1", port,
                                          epoch_source=lambda: 2)
            old = SchedulerToWorkerClient("127.0.0.1", port,
                                          epoch_source=lambda: 1)
            unfenced = SchedulerToWorkerClient("127.0.0.1", port)
            new.run_job([], worker_id=0, round_id=7)
            assert seen == [7] and advances == [2]
            import grpc
            with pytest.raises(grpc.RpcError) as err:
                old.run_job([], worker_id=0, round_id=8)
            assert err.value.code() == grpc.StatusCode.FAILED_PRECONDITION
            assert "stale leader epoch" in err.value.details()
            assert seen == [7]
            # Epoch-less clients (HA disabled) pass unfenced.
            unfenced.run_job([], worker_id=0, round_id=9)
            assert seen == [7, 9]
            for c in (new, old, unfenced):
                c.close()
        finally:
            server.stop(grace=0)


# ----------------------------------------------------------------------
# Loopback failover (subprocess; sanitizer-clean; tier-1)
# ----------------------------------------------------------------------

def _wait_for_port(port, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with socket.socket() as s:
            s.settimeout(0.2)
            try:
                s.connect(("127.0.0.1", port))
                return True
            except OSError:
                time.sleep(0.1)
    return False


HA_JSON = json.dumps({"lease_interval_s": 0.15, "lease_ttl_s": 0.8,
                      "standby_poll_interval_s": 0.1,
                      "failover_budget_s": 20.0})


def _spawn(cmd, log_path, env):
    log = open(log_path, "w")
    return subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                            env=env), log


@pytest.mark.recovery
@pytest.mark.faults
@pytest.mark.timeout(180)
class TestLoopbackFailover:
    """SIGKILL the HA leader mid-run; the hot standby must promote
    automatically (no operator --resume) and every job completes with
    exact journal accounting — under SWTPU_SANITIZE=1."""

    def test_leader_kill_standby_completes(self, tmp_path):
        state_dir = tmp_path / "state"
        trace = tmp_path / "ha.trace"
        line = ("ResNet-18 (batch size 32)\tpython3 main.py "
                "--batch_size 32\timage_classification/cifar10\t"
                "--num_steps\t0\t300\t1\tstatic\t1\t-1.000000\t10000\t0")
        trace.write_text(line + "\n" + line + "\n")
        p1, p2 = free_port(), free_port()
        out2 = tmp_path / "m2.pkl"

        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"
        env["SWTPU_SANITIZE"] = "1"
        env["SWTPU_HA_ENDPOINT_FILE"] = str(state_dir / "leader.lease")
        env["SWTPU_RPC_JITTER_SEED"] = "0"
        # The dead-leader window must fail fast for the stub's reports:
        # keep the per-attempt deadline short (failover retry loops own
        # the patience).
        env["SWTPU_RPC_DEADLINE_S"] = "5"
        env["SWTPU_RPC_BUDGET_S"] = "8"

        def sched_cmd(port, out, standby=False):
            cmd = [sys.executable, RUN_PHYSICAL, "--trace", str(trace),
                   "--policy", "max_min_fairness",
                   "--throughputs", THROUGHPUTS,
                   "--expected_num_workers", "1",
                   "--round_duration", "2", "--port", str(port),
                   "--state_dir", str(state_dir),
                   "--snapshot_interval", "2",
                   "--output", str(out), "--ha", HA_JSON,
                   "--heartbeat_interval", "0.2",
                   "--worker_timeout", "1.0",
                   "--probe_failures", "2", "--kill_wait", "0.5",
                   "--completion_buffer", "5", "--first_init_grace", "0",
                   "--verbose"]
            if standby:
                cmd.append("--ha_standby")
            return cmd

        leader, llog = _spawn(sched_cmd(p1, tmp_path / "m1.pkl"),
                              tmp_path / "leader.log", env)
        assert _wait_for_port(p1), "leader never bound"
        standby, slog = _spawn(sched_cmd(p2, out2, standby=True),
                               tmp_path / "standby.log", env)
        worker, wlog = _spawn(
            [sys.executable, os.path.join(TESTS_DIR,
                                          "fault_stub_worker.py"),
             "--sched_port", str(p1), "--worker_port", str(free_port()),
             "--num_chips", "1",
             "--state_file", str(tmp_path / "w.json")],
            tmp_path / "worker.log", env)
        try:
            # Wait for journaled progress, then SIGKILL the leader.
            deadline = time.time() + 60
            while time.time() < deadline:
                if leader.poll() is not None:
                    pytest.fail("leader exited prematurely: "
                                + (tmp_path / "leader.log").read_text())
                try:
                    rec = journal.load_state(str(state_dir))
                    done = sum(e["type"] == "microtask_done"
                               for e in rec.events)
                    removed = sum(e["type"] == "job_removed"
                                  for e in rec.events)
                    if (rec.snapshot is not None or done >= 1) \
                            and removed < 2:
                        break
                except journal.JournalError:
                    pass
                time.sleep(0.05)
            else:
                pytest.fail("no journaled progress within 60s: "
                            + (tmp_path / "leader.log").read_text())
            os.kill(leader.pid, signal.SIGKILL)
            leader.wait(timeout=10)

            # No operator intervention from here: the standby must
            # detect, promote, re-adopt the worker, finish the trace.
            rc = standby.wait(timeout=120)
            assert rc == 0, (tmp_path / "standby.log").read_text()
            with open(out2, "rb") as f:
                metrics = pickle.load(f)
            assert metrics["all_jobs_completed"] is True

            # Promotion was recorded with a bounded failover latency.
            with open(state_dir / "promotion.json") as f:
                promo = json.load(f)
            assert promo["epoch"] == 2
            assert promo["from_lease_expiry_s"] <= 2.0  # <= 1 round

            # Exact step accounting from the durable record, through
            # the epoch filter.
            from shockwave_tpu.sched.scheduler import Scheduler
            from shockwave_tpu.solver import get_policy
            final = Scheduler(get_policy("max_min_fairness"),
                              throughputs_file=THROUGHPUTS)
            final.restore_from_durable_state(
                journal.load_state(str(state_dir)))
            assert final._completed_jobs == {JobIdPair(0), JobIdPair(1)}
            for int_id in (0, 1):
                jid = JobIdPair(int_id)
                assert final.acct.total_steps_run[jid] == 300
                assert final.acct.failures.get(jid, 0) == 0

            # fsck agrees (exit 0: torn tails were handled, the epoch
            # chain has exactly one writer per epoch).
            fsck = subprocess.run(
                [sys.executable, FSCK, str(state_dir)], env=env,
                capture_output=True, text=True, timeout=60)
            assert fsck.returncode == 0, fsck.stdout + fsck.stderr

            # And the streaming validator sees a clean, idle tail.
            follow = subprocess.run(
                [sys.executable, FSCK, str(state_dir), "--follow",
                 "--max_wait_s", "1", "--poll_interval_s", "0.2"],
                env=env, capture_output=True, text=True, timeout=60)
            assert follow.returncode == 0, follow.stdout + follow.stderr
        finally:
            for proc in (leader, standby, worker):
                if proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=10)
            for log in (llog, slog, wlog):
                log.close()
