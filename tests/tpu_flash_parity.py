"""Hardware parity check for the fused flash-attention kernel.

Standalone script (run via `tests/test_ops.py::TestFlashTPU` in a clean
subprocess, outside conftest's forced-CPU env): compares the Pallas
kernel's forward and gradients against the einsum attention path on the
REAL TPU backend. Tolerances reflect MXU default precision (bf16 passes
for f32 operands): measured on v5e, flash is *closer* to an f64 host
reference than the einsum path (4.7e-3 vs 6.1e-3 max-abs), so parity
within 2e-2 (f32) / 6e-2 (bf16) is the hardware noise floor, not slack.

Exit codes: 0 = parity OK, 75 = no TPU backend available (callers skip).
The reference implementation has no attention kernel at all (vanilla
torch softmax attention, workloads/pytorch/translation/transformer/
SubLayers.py) — the parity target is the einsum path itself.
"""
import os
import subprocess
import sys

# Probe backend init in a disposable child first: a wedged relay makes
# jax.devices() hang indefinitely, and a hang must read as a skip (75),
# not a test failure.
try:
    probe = subprocess.run(
        [sys.executable, "-c", "import jax; jax.devices()"],
        capture_output=True, timeout=90)
except subprocess.TimeoutExpired:
    print("SKIP: backend init timed out (wedged tunnel?)")
    sys.exit(75)
if probe.returncode != 0:
    print("SKIP: backend init failed")
    sys.exit(75)

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from shockwave_tpu.ops import flash_attention

if jax.default_backend() != "tpu":
    print(f"SKIP: backend={jax.default_backend()}")
    sys.exit(75)


def ref_attn(q, k, v, causal=False, mask=None):
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) / jnp.sqrt(d)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        s = jnp.where(jnp.tril(jnp.ones((tq, tk), bool))[None, None],
                      s, -1e30)
    if mask is not None:
        s = jnp.where(mask[:, None, None, :], s, -1e30)
    w = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w,
                      v.astype(jnp.float32)).astype(q.dtype)


def main():
    key = jax.random.PRNGKey(0)
    cases = [
        (2, 64, 4, 32, False, False, jnp.float32),
        (2, 64, 4, 32, True, False, jnp.float32),
        (2, 256, 4, 64, True, True, jnp.float32),
        (2, 256, 8, 64, False, True, jnp.bfloat16),
    ]
    records = []
    for (b, t, h, d, causal, masked, dtype) in cases:
        ks = jax.random.split(key, 3)
        q = jax.random.normal(ks[0], (b, t, h, d), dtype)
        k = jax.random.normal(ks[1], (b, t, h, d), dtype)
        v = jax.random.normal(ks[2], (b, t, h, d), dtype)
        mask = None
        if masked:
            mask = jnp.arange(t)[None, :] < jnp.array([t, t // 2])[:, None]

        flash = jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=causal, key_padding_mask=mask))
        ref = jax.jit(lambda q, k, v: ref_attn(
            q, k, v, causal=causal, mask=mask))
        fwd_tol = 6e-2 if dtype == jnp.bfloat16 else 2e-2
        err = float(jnp.max(jnp.abs(
            flash(q, k, v).astype(jnp.float32)
            - ref(q, k, v).astype(jnp.float32))))
        assert err < fwd_tol, ("fwd", b, t, h, d, causal, masked, dtype, err)

        gflash = jax.jit(jax.grad(
            lambda q, k, v: (flash_attention(
                q, k, v, causal=causal,
                key_padding_mask=mask) ** 2).sum(), argnums=(0, 1, 2)))
        gref = jax.jit(jax.grad(
            lambda q, k, v: (ref_attn(
                q, k, v, causal=causal, mask=mask) ** 2).sum(),
            argnums=(0, 1, 2)))
        grad_tol = 1e-1 if dtype == jnp.bfloat16 else 5e-2
        grad_rels = {}
        for name, a, r in zip("qkv", gflash(q, k, v), gref(q, k, v)):
            gerr = float(jnp.max(jnp.abs(
                a.astype(jnp.float32) - r.astype(jnp.float32))))
            rel = gerr / (float(jnp.max(jnp.abs(
                r.astype(jnp.float32)))) + 1e-9)
            grad_rels[name] = rel
            assert rel < grad_tol, ("grad", name, b, t, h, d, causal,
                                    masked, dtype, gerr, rel)
        records.append({
            "shape": [b, t, h, d], "causal": causal, "masked": masked,
            "dtype": dtype.__name__, "fwd_max_abs_err": err,
            "fwd_tol": fwd_tol,
            "grad_max_rel_err": {k: round(v, 6)
                                 for k, v in grad_rels.items()},
            "grad_tol": grad_tol})
        print(f"ok b={b} t={t} h={h} d={d} causal={causal} "
              f"masked={masked} {dtype.__name__} fwd_err={err:.2e}")
    # Persist the raw per-case errors as a timestamped artifact so the
    # hardware parity claim stays checkable after the chip goes away.
    from shockwave_tpu.core.artifacts import save_measurement
    out_dir = os.environ.get(
        "SWTPU_PARITY_DIR",
        os.path.join(os.path.dirname(__file__), "..", "reproduce", "tpu"))
    path, _ = save_measurement(out_dir, "flash_parity", {"cases": records})
    print(f"saved {path}")
    print("ALL OK")


if __name__ == "__main__":
    main()
