"""JAX/Flax workload model families.

The five active families of the reference's job table
(reference: scheduler/job_table.py:110-130), redesigned for the MXU:
bf16 compute, channels-last convs, static shapes, jit-compiled train
steps sharded over a dp mesh.

| Family         | Model                      | Dataset (synthetic fallback) |
|----------------|----------------------------|------------------------------|
| ResNet-18      | resnet.ResNet18            | CIFAR-10 32x32x3, 10 cls     |
| ResNet-50      | resnet.ResNet50            | ImageNet 224x224x3, 1000 cls |
| Transformer    | transformer.Seq2SeqTransformer | Multi30k-like token pairs |
| LM             | lm.LSTMLanguageModel       | Wikitext-2-like tokens       |
| Recommendation | recommendation.AutoEncoder | ML-20M-like interaction rows |
"""
