#!/usr/bin/env python3
"""ResNet-18 / CIFAR-10 workload (trace: "ResNet-18 (batch size N)").

CLI parity with the reference's cifar10 main.py — the trace command is
`python3 main.py --data_dir=%s/cifar10 --batch_size N` with `--num_steps`
appended by the dispatcher.
"""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), *[".."] * 4))

import jax
import jax.numpy as jnp
import optax

from shockwave_tpu.models import data
from shockwave_tpu.models.resnet import ResNet18
from shockwave_tpu.models.train_common import Trainer, common_parser, parse_args


def main():
    p = common_parser("ResNet-18 on CIFAR-10", steps_args=("--num_steps",))
    p.add_argument("--data_dir", default=None)
    p.add_argument("--batch_size", type=int, default=128)
    args = parse_args(p)

    model = ResNet18()
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((1, 32, 32, 3), jnp.float32)
    variables = model.init(rng, sample, train=True)
    init_state = {"params": variables["params"],
                  "batch_stats": variables["batch_stats"]}

    def loss_fn(params, state, images, labels):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": state["batch_stats"]},
            images, train=True, mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, {"batch_stats": mutated["batch_stats"]}

    trainer = Trainer(
        args, loss_fn, init_state,
        data.cifar10(args.batch_size, data_dir=args.data_dir),
        initial_bs=args.batch_size, max_bs=256, learning_rate=0.1)
    trainer.run()


if __name__ == "__main__":
    main()
