"""Batch-size adaptation oracles for Accordion and GNS workloads.

These produce, for a job, the per-epoch batch-size schedule the adaptive
training algorithm would emit, used both by the simulator and by the
Shockwave profile generator. Semantics match the reference's measured
tables (reference: scheduler/utils.py:741-1328) but are expressed as data
rather than branching code.

Accordion (Agarwal et al.): trains at the small batch size inside
"critical regimes" (high gradient-norm phases) and at the family's max
batch size outside them; the first 30% of training is forced critical.

GNS (McCandlish et al., gradient noise scale): batch size doubles at
measured epochs; the doubling points were profiled per (model, bs,
scale_factor) and are captured in `_GNS_SEGMENTS`.
"""
from __future__ import annotations

from functools import lru_cache
from typing import Dict, List, Optional, Sequence, Tuple

from .constants import MAX_BS

# Models whose adaptive variants never rescale.
_NON_ADAPTIVE = ("Transformer", "CycleGAN", "A3C")


def _critical_regime(model: str, initial_bs: int) -> Optional[set]:
    """Epochs inside the gradient-critical regime, or None if no adaptation."""
    if model == "ResNet-18":
        head = 20 if initial_bs == 256 else 10
        return set(range(head)) | set(range(150, 160)) | set(range(250, 260))
    if model == "ResNet-50":
        return {e for e in range(600) if e % 30 < 10}
    if model == "LM":
        return set(range(10))
    if model == "Recommendation":
        head = {512: 30, 1024: 30, 2048: 40, 4096: 10, 8192: 10}[initial_bs]
        return set(range(head)) | set(range(60, 70)) | set(range(80, 90))
    return None


def accordion_bs_schedule(model: str, initial_bs: int, num_epochs: int) -> List[int]:
    """Per-epoch batch sizes under Accordion adaptation."""
    schedule = [initial_bs] * num_epochs
    if model in _NON_ADAPTIVE:
        return schedule
    critical = _critical_regime(model, initial_bs)
    if critical is None:
        return schedule
    big = MAX_BS.get(model, initial_bs)
    warmup = num_epochs * 0.3  # first 30% forced critical to preserve accuracy
    for epoch in range(num_epochs):
        if epoch not in critical and epoch > warmup:
            schedule[epoch] = big
    return schedule


# (model, initial_bs, scale_factor) -> (min_epochs_to_adapt, segments).
# Each segment (start, end, multiplier) multiplies epochs in [start, end);
# end None means "to the last epoch". The profiled doubling points below
# correspond to the reference's measured GNS runs (utils.py:801-1328).
_Seg = Tuple[int, Optional[int], int]
_GNS_SEGMENTS: Dict[Tuple[str, int, int], Tuple[int, List[_Seg]]] = {
    ("ResNet-18", 16, 1): (31, [(31, 41, 2), (41, 51, 4), (51, 71, 8), (71, None, 16)]),
    ("ResNet-18", 32, 1): (21, [(21, 31, 2), (31, 51, 4), (51, None, 8)]),
    ("ResNet-18", 64, 1): (11, [(11, 31, 2), (31, None, 4)]),
    ("ResNet-18", 128, 1): (11, [(11, None, 2)]),
    ("ResNet-18", 16, 2): (21, [(21, 31, 2), (31, 91, 4), (91, 111, 8), (111, None, 16)]),
    ("ResNet-18", 32, 2): (11, [(11, 21, 2), (21, 41, 4), (41, None, 8)]),
    ("ResNet-18", 64, 2): (21, [(21, 41, 2), (41, None, 4)]),
    ("ResNet-18", 128, 2): (41, [(41, None, 2)]),
    ("ResNet-18", 16, 4): (11, [(11, 21, 2), (21, 81, 4), (81, 91, 8), (91, None, 16)]),
    ("ResNet-18", 32, 4): (21, [(21, 31, 2), (31, 61, 4), (61, None, 8)]),
    ("ResNet-18", 64, 4): (11, [(11, 61, 2), (61, None, 4)]),
    ("ResNet-18", 128, 4): (11, [(11, None, 2)]),
    ("ResNet-50", 64, 1): (101, [(101, None, 2)]),
    ("ResNet-50", 32, 2): (101, [(101, 111, 2), (111, None, 4)]),
    ("ResNet-50", 64, 2): (81, [(81, None, 2)]),
    ("ResNet-50", 32, 4): (131, [(131, 221, 2), (221, None, 4)]),
    ("ResNet-50", 64, 4): (191, [(191, None, 2)]),
    ("LM", 5, 1): (31, [(31, 41, 2), (41, 61, 4), (61, 71, 8), (71, None, 16)]),
    ("LM", 10, 1): (11, [(11, 21, 2), (21, 41, 4), (41, None, 8)]),
    ("LM", 20, 1): (11, [(11, 41, 2), (41, None, 4)]),
    ("LM", 40, 1): (11, [(11, None, 2)]),
    ("LM", 5, 2): (31, [(31, 51, 2), (51, 61, 4), (61, 71, 8), (71, None, 16)]),
    ("LM", 10, 2): (11, [(11, 31, 2), (31, 41, 4), (41, None, 8)]),
    ("LM", 20, 2): (31, [(31, 41, 2), (41, None, 4)]),
    ("LM", 40, 2): (11, [(11, None, 2)]),
    ("LM", 5, 4): (11, [(11, 31, 2), (31, 71, 4), (71, 91, 8), (91, None, 16)]),
    ("LM", 10, 4): (11, [(11, 31, 2), (31, 61, 4), (61, None, 8)]),
    ("LM", 20, 4): (11, [(11, 61, 2), (61, None, 4)]),
    ("LM", 40, 4): (61, [(61, None, 2)]),
    ("Recommendation", 512, 1): (21, [(21, 41, 2), (41, 71, 4), (71, 91, 8), (91, None, 16)]),
    ("Recommendation", 1024, 1): (21, [(21, 51, 2), (51, 91, 4), (91, None, 8)]),
    ("Recommendation", 2048, 1): (21, [(21, 41, 2), (41, None, 4)]),
    ("Recommendation", 4096, 1): (41, [(41, None, 2)]),
}


def gns_bs_schedule(model: str, initial_bs: int, num_epochs: int,
                    scale_factor: int) -> Sequence[int]:
    """Per-epoch batch sizes under GNS adaptation.

    The simulator's GNS oracle rebuilds this schedule every round
    (sched/scheduler.py:_simulate_gns), so the pure computation is
    memoized. Returns a read-only tuple; all callers only index or
    iterate it.
    """
    return _gns_bs_schedule(model, initial_bs, num_epochs, scale_factor)


@lru_cache(maxsize=4096)
def _gns_bs_schedule(model: str, initial_bs: int, num_epochs: int,
                     scale_factor: int) -> tuple:
    schedule = [initial_bs] * num_epochs
    if model in _NON_ADAPTIVE:
        return tuple(schedule)
    entry = _GNS_SEGMENTS.get((model, initial_bs, scale_factor))
    if entry is not None:
        min_epochs, segments = entry
        if num_epochs > min_epochs:
            for i, (start, end, mult) in enumerate(segments):
                # The final epoch of the run is only rescaled when it falls in
                # the first segment (matches the reference loop structure).
                stop = num_epochs if i == 0 else num_epochs - 1
                if end is not None:
                    stop = min(stop, end)
                for epoch in range(start, stop):
                    schedule[epoch] *= mult
    cap = MAX_BS[model]
    return tuple(min(bs, cap) for bs in schedule)


def gns_bs_at(model: str, initial_bs: int, num_epochs: int,
              scale_factor: int, epoch: int) -> int:
    """``gns_bs_schedule(...)[epoch]`` without building the schedule.

    The simulator's GNS oracle queries exactly two epochs per job per
    round with ``num_epochs = max(760, epoch + 2)`` — once the run
    passes 760 epochs every query carries a fresh ``num_epochs`` and
    the memoized full-schedule path rebuilds an O(num_epochs) tuple per
    call. This point query replays the same segment arithmetic (same
    multiplication order, same first-segment-only final-epoch rule,
    same MAX_BS cap) for one epoch in O(#segments); equivalence with
    the full schedule is pinned by tests/test_sim_vectorized.py.
    """
    if model in _NON_ADAPTIVE:
        return initial_bs
    bs = initial_bs
    entry = _GNS_SEGMENTS.get((model, initial_bs, scale_factor))
    if entry is not None:
        min_epochs, segments = entry
        if num_epochs > min_epochs:
            for i, (start, end, mult) in enumerate(segments):
                stop = num_epochs if i == 0 else num_epochs - 1
                if end is not None:
                    stop = min(stop, end)
                if start <= epoch < stop:
                    bs *= mult
    return min(bs, MAX_BS[model])


def bs_schedule_for_mode(mode: str, model: str, initial_bs: int, num_epochs: int,
                         scale_factor: int) -> List[int]:
    if mode == "accordion":
        return accordion_bs_schedule(model, initial_bs, num_epochs)
    if mode == "gns":
        # Profiles (and their JSON/pickle round trips) carry lists; only
        # the simulator's per-round GNS oracle consumes the raw tuple.
        return list(gns_bs_schedule(model, initial_bs, num_epochs, scale_factor))
    return [initial_bs] * num_epochs
