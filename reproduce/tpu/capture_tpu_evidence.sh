#!/bin/bash
# Capture the full TPU hardware evidence set in one pass (run whenever
# the chip is reachable). Produces timestamped raw artifacts under
# reproduce/tpu/ — the committed-measurement pattern the reference uses
# for its oracle JSONs — which bench.py merges (provenance-marked) when
# the chip is later unreachable.
#
#   1. bench_tpu.py        — flagship train step steps/s + MFU at the
#                            trace-parity config AND the compute-bound
#                            long-seq config; flash-vs-einsum latency.
#   2. tpu_flash_parity.py — per-case fwd/grad kernel parity errors.
#   3. run_fidelity.sh     — physical-vs-sim on the attached chip
#                            (skipped with SKIP_FIDELITY=1; ~15 min).
#
# Commit the resulting reproduce/tpu/*.json (and tpu_loopback/) files.
set -eu -o pipefail
cd "$(dirname "$0")/../.."

# Bounded-retry, subprocess-isolated liveness probe FIRST: a wedged
# accelerator tunnel otherwise hangs step 1 forever inside backend
# init. On a dead/wedged backend we exit 0 deliberately — the committed
# last-good evidence files under reproduce/tpu/ remain the record
# (bench.py merges them provenance-marked), which beats a half-written
# capture or a poisoned bench row.
echo "== 0/4 backend liveness probe =="
if ! python reproduce/tpu/liveness_probe.py; then
    echo "backend unreachable; keeping last-good evidence files" >&2
    exit 0
fi

echo "== 1/4 bench_tpu =="
python scripts/profiling/bench_tpu.py

echo "== 2/4 flash parity =="
python tests/tpu_flash_parity.py

echo "== 3/4 v5e dispatch-overhead calibration =="
python scripts/profiling/measure_startup.py --worker_type v5e \
    --oracle data/v5e_throughputs.json \
    --families "ResNet-18 (batch size 32)" "LM (batch size 20)" \
               "Recommendation (batch size 512)"

if [ "${SKIP_FIDELITY:-0}" != "1" ]; then
    echo "== 4/4 TPU-physical fidelity =="
    TOL=${TOL:-0.10} ROUND=${ROUND:-120} \
        bash reproduce/fidelity/run_fidelity.sh reproduce/fidelity/tpu_loopback
fi
echo "done; review and commit reproduce/tpu/, data/v5e_throughputs.json,"
echo "and reproduce/fidelity/tpu_loopback/"
