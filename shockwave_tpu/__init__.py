"""shockwave_tpu: a TPU-native cluster scheduler for dynamic-adaptation ML training.

A ground-up reimplementation of the capabilities of uw-mad-dash/shockwave
(NSDI '23; itself a fork of Gavel, OSDI '20) targeting TPU pods:

- workers register TPU chips instead of CUDA devices,
- training workloads are JAX/Flax programs jit-compiled for the MXU,
- multi-chip jobs shard over a `jax.sharding.Mesh` with XLA collectives on
  ICI (replacing the reference's PyTorch DDP/NCCL data plane),
- the market solver (dynamic Eisenberg-Gale MILP) runs on scipy's HiGHS
  instead of cvxpy/Gurobi, with the same model and fallback chain.

Layer map (mirrors SURVEY.md §1):
  core/      Job model, traces, throughput oracles, adaptation oracles
  solver/    Gavel policy suite (LP/MILP over scipy HiGHS)
  shockwave/ JobMetaData + dynamic EG MILP planner
  sched/     round-based scheduler core + discrete-event simulator
  runtime/   gRPC control plane, worker daemon, dispatcher, lease iterator
  models/    JAX/Flax workload suite (static / accordion / GNS variants)
  parallel/  mesh + sharding helpers, DP/TP/SP train steps, ring attention
"""

__version__ = "0.3.0"
