"""Seeded obs-discipline violations.

Two halves, mirroring the pass: an inline metric-name literal at an
instrument call site (the catalog in obs/names.py is the only place
names may be spelled), and a wall-clock read inside what the test
treats as the obs package (``obs_globs=("bad_obs.py",)``) — the clock
must arrive by injection through obs/clock.py.
"""
import time


class _Registry:
    """Stand-in with the real instrument method names."""

    def inc(self, spec, amount=1.0, **labels):
        return (spec, amount, labels)

    def observe(self, spec, value, **labels):
        return (spec, value, labels)


REGISTRY = _Registry()

GOOD_SPEC = object()


def emit_adhoc():
    REGISTRY.inc("swtpu_adhoc_total")  # SEEDED


def observe_adhoc():
    REGISTRY.observe("swtpu_adhoc_seconds", 0.25)  # SEEDED


def emit_declared():
    # Attribute/spec references are the sanctioned form — not flagged.
    REGISTRY.inc(GOOD_SPEC)


def read_clock():
    return time.time()  # SEEDED


def read_perf_clock():
    return time.perf_counter()  # SEEDED
