"""Encoder-decoder Transformer for translation (Multi30k-class workloads).

Standard pre-LN Transformer with tied output projection (the reference
trains "Attention is All You Need" on multi30k with -proj_share_weight;
workloads/pytorch/translation/train.py). TPU-native choices: bf16
activations, static sequence lengths, einsum attention that XLA maps to
the MXU, and an optional ring-attention path (parallel/ring_attention.py)
for sequence-parallel long-context runs.
"""
from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax.numpy as jnp
import numpy as np


def sinusoidal_positions(length: int, dim: int) -> np.ndarray:
    pos = np.arange(length)[:, None]
    div = np.exp(np.arange(0, dim, 2) / dim * -np.log(10000.0))
    table = np.zeros((length, dim), dtype=np.float32)
    table[:, 0::2] = np.sin(pos * div)
    table[:, 1::2] = np.cos(pos * div)
    return table


class MultiHeadAttention(nn.Module):
    """Attention expressed as (causal, key_padding_mask) so it can lower
    to the fused Pallas flash-attention kernel (ops/flash_attention.py)
    when `use_flash`; otherwise einsum attention that XLA maps to the MXU.
    """
    num_heads: int
    dim: int
    dtype: Any = jnp.bfloat16
    use_flash: bool = False

    @nn.compact
    def __call__(self, q_in, kv_in, causal: bool = False,
                 key_padding_mask: Optional[jnp.ndarray] = None):
        head_dim = self.dim // self.num_heads
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (self.num_heads, head_dim), axis=-1, dtype=self.dtype, name=name)
        q = dense("query")(q_in)
        k = dense("key")(kv_in)
        v = dense("value")(kv_in)
        tq, tk = q.shape[1], k.shape[1]
        # flash_attention blocks at min(1024, T): T > 1024 must divide
        # into 1024-blocks; shorter lengths are their own block and only
        # need the second-minor dim on the sublane tile (16 for bf16,
        # 8 for f32). Anything unaligned falls back to einsum.
        align = 16 if self.dtype == jnp.bfloat16 else 8

        def blockable(t):
            return t % 1024 == 0 if t > 1024 else t % align == 0

        flash_ok = (self.use_flash and not (causal and tq != tk)
                    and blockable(tq) and blockable(tk))
        if flash_ok:
            from ..ops import flash_attention
            out = flash_attention(q, k, v, causal=causal,
                                  key_padding_mask=key_padding_mask)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(head_dim)
            if causal:
                cmask = jnp.tril(jnp.ones((tq, tk), bool))[None, None]
                scores = jnp.where(cmask, scores,
                                   jnp.finfo(jnp.float32).min)
            if key_padding_mask is not None:
                kmask = key_padding_mask[:, None, None, :]
                scores = jnp.where(kmask, scores,
                                   jnp.finfo(jnp.float32).min)
            weights = nn.softmax(scores.astype(jnp.float32)).astype(self.dtype)
            out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
        return nn.DenseGeneral(self.dim, axis=(-2, -1), dtype=self.dtype,
                               name="out")(out)


class TransformerLayer(nn.Module):
    num_heads: int
    dim: int
    mlp_dim: int
    decoder: bool = False
    dtype: Any = jnp.bfloat16
    use_flash: bool = False

    @nn.compact
    def __call__(self, x, enc_out=None, self_padding=None,
                 cross_padding=None):
        attn = lambda name: MultiHeadAttention(  # noqa: E731
            self.num_heads, self.dim, self.dtype, self.use_flash, name=name)
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        x = x + attn("self_attn")(y, y, causal=self.decoder,
                                  key_padding_mask=self_padding)
        if self.decoder:
            y = nn.LayerNorm(dtype=jnp.float32)(x)
            x = x + attn("cross_attn")(y, enc_out,
                                       key_padding_mask=cross_padding)
        y = nn.LayerNorm(dtype=jnp.float32)(x)
        y = nn.Dense(self.mlp_dim, dtype=self.dtype)(y)
        y = nn.gelu(y)
        y = nn.Dense(self.dim, dtype=self.dtype)(y)
        return x + y


class Seq2SeqTransformer(nn.Module):
    vocab_size: int = 9521  # multi30k shared vocab size ballpark
    dim: int = 512
    num_heads: int = 8
    num_layers: int = 6
    mlp_dim: int = 2048
    max_len: int = 64
    dtype: Any = jnp.bfloat16
    use_flash: bool = False

    @nn.compact
    def __call__(self, src_tokens, tgt_tokens):
        embed = nn.Embed(self.vocab_size, self.dim,
                         embedding_init=nn.initializers.normal(0.02),
                         name="shared_embedding")
        positions = jnp.asarray(sinusoidal_positions(self.max_len, self.dim))

        src = embed(src_tokens).astype(self.dtype)
        src = src + positions[: src_tokens.shape[1]]
        src_padding = src_tokens != 0
        for i in range(self.num_layers):
            src = TransformerLayer(self.num_heads, self.dim, self.mlp_dim,
                                   dtype=self.dtype,
                                   use_flash=self.use_flash,
                                   name=f"enc_{i}")(
                src, self_padding=src_padding)
        src = nn.LayerNorm(dtype=jnp.float32, name="enc_norm")(src)

        tgt = embed(tgt_tokens).astype(self.dtype)
        tgt = tgt + positions[: tgt_tokens.shape[1]]
        tgt_padding = tgt_tokens != 0
        for i in range(self.num_layers):
            tgt = TransformerLayer(self.num_heads, self.dim, self.mlp_dim,
                                   decoder=True, dtype=self.dtype,
                                   use_flash=self.use_flash,
                                   name=f"dec_{i}")(
                tgt, enc_out=src, self_padding=tgt_padding,
                cross_padding=src_padding)
        tgt = nn.LayerNorm(dtype=jnp.float32, name="dec_norm")(tgt)
        # Tied output projection (-proj_share_weight).
        logits = jnp.einsum("bld,vd->blv", tgt.astype(jnp.float32),
                            embed.embedding.astype(jnp.float32))
        return logits
