"""Serving tier: co-schedules latency-SLO inference services with training.

One `ServingTier` hangs off a scheduler (simulated or physical) and owns
every serving *service* in the trace. A service (the trace line, mode
``serving``) is a descriptor — load curve, SLO, per-replica service
rate, lifetime — that the tier expands into autoscaled *replica jobs*:
gang-of-1 jobs (``mode="serving"``) that flow through the existing
round-lease / dispatch / cooperative-preemption machinery unchanged,
their "progress" being requests served.

Integration contract (see Scheduler._schedule_jobs_on_workers):

- `plan_round()` runs at every round-scheduling point, BEFORE training
  selection: it retires expired services, reconciles replica counts to
  the autoscaler's target, assigns chips to replicas (sticky where the
  previous chip is alive), and returns the serving assignments. The
  chips it reserves are subtracted from the capacity the training
  selector AND the Shockwave MILP see — serving preempts training under
  spikes and hands the chips back at troughs, by construction rather
  than by priority fighting.
- SLO attainment is accounted analytically per round from the same
  deterministic load curve and M/M/c model the autoscaler planned with,
  so the simulator evaluates serving quality bit-identically across
  replays.
- When a trace has no serving jobs the tier is never constructed and
  every hook is a no-op — the canonical training-only replay is
  untouched.

Pickles with scheduler snapshots (the scheduler reference is dropped and
re-bound on restore); replica add/remove rides the existing job journal
events, service registration/retirement adds two small event types.
"""
from __future__ import annotations

import collections
import logging
from typing import Dict, List, Optional, Tuple

from ..core.job import Job, JobIdPair
from ..core.trace import parse_serving_command, serving_service_rate
from ..obs import names as obs_names
from .autoscaler import Autoscaler, AutoscalerConfig
from .latency_model import p99_latency
from .load import DiurnalLoad, Spike, seeded_spikes
from .measured import ServiceMeasuredState

logger = logging.getLogger("shockwave_tpu.serving")

#: Samples per round window for load evaluation and SLO accounting.
WINDOW_SAMPLES = 8
#: Per-service round-history entries retained (physical services can
#: run indefinitely; the full series lives in obs, not here).
HISTORY_LIMIT = 10000


def _load_from_params(params: dict, lifetime_s: float) -> DiurnalLoad:
    spikes: Tuple[Spike, ...] = tuple(
        Spike(s, d, m) for s, d, m in params.get("spikes", ()))
    seed = params.get("spike_seed")
    if seed is not None and params.get("num_spikes", 0) > 0:
        spikes = spikes + seeded_spikes(
            int(seed), lifetime_s, int(params["num_spikes"]),
            float(params.get("spike_mult", 10.0)),
            float(params.get("spike_duration_s", 1800.0)))
    return DiurnalLoad(
        base_rps=float(params.get("base_rps", 0.0)),
        peak_rps=float(params.get("peak_rps", params.get("base_rps", 0.0))),
        period_s=float(params.get("period_s", 0.0)),
        phase_s=float(params.get("phase_s", 0.0)),
        spikes=spikes)


class ServingService:
    """One registered serving service and its autoscaling state."""

    def __init__(self, int_id: int, job: Job, params: dict,
                 arrival_ts: float, autoscaler_config: AutoscalerConfig,
                 mu_prior: Optional[float] = None):
        self.int_id = int_id
        self.job = job                      # anchor (never in acct.jobs)
        self.params = dict(params)
        self.arrival_ts = float(arrival_ts)
        self.lifetime_s = float(job._duration)
        self.slo_p99_s = float(job.SLO) if job.SLO is not None else 1.0
        #: Declared (trace) per-replica service rate — the analytic
        #: prior. `mu` is the live effective value: it starts from the
        #: learned oracle's decode-rate prediction when one exists
        #: (`mu_prior`, scheduler.oracle_serving_mu) and from the
        #: declared rate otherwise (None — the zero-sample fallback
        #: that keeps canonical replays bit-identical); measured
        #: samples then refine it (never in sim).
        self.mu_analytic = serving_service_rate(job.command)
        self.mu_oracle_prior = mu_prior
        self.mu = mu_prior if mu_prior is not None else self.mu_analytic
        self.tokens_per_request = int(params.get("tokens_per_request", 1)
                                      or 1)
        # The online mu re-estimator blends measured rates against this
        # same prior with mu_prior_weight pseudo-samples, so an
        # oracle-seeded service converges from the oracle's estimate
        # rather than snapping back to the declared one.
        self.measured = ServiceMeasuredState(
            self.mu, self.tokens_per_request,
            mu_prior_weight=autoscaler_config.mu_prior_weight)
        #: Per-replica (round, seq) high-water of ingested deltas:
        #: reports ride BOTH the renewal heartbeat and the Done log
        #: (exit flush), and renewals retry on transport failure — the
        #: seq stamp makes double delivery harmless.
        self.measured_seen: Dict[int, Tuple[int, int]] = {}
        #: Last accounted round's measured window (take_window output),
        #: consumed by the NEXT round's scaling decision.
        self.last_measured_window: Optional[dict] = None
        self.max_replicas = int(params.get("max_replicas", 8))
        self.load = _load_from_params(params, self.lifetime_s)
        self.autoscaler = Autoscaler(autoscaler_config)
        #: Active replicas: JobIdPair -> replica index.
        self.replicas: "collections.OrderedDict[JobIdPair, int]" = (
            collections.OrderedDict())
        #: Replicas draining out (excluded from assignment; removed from
        #: the scheduler once their in-flight round has completed).
        self.draining: "collections.OrderedDict[JobIdPair, int]" = (
            collections.OrderedDict())
        self.next_replica_index = 0
        self.retired = False
        self.retired_ts: Optional[float] = None
        # -- round accounting (requests-weighted SLO attainment) --------
        self.target = 0
        self.requests_offered = 0.0
        self.requests_ok = 0.0
        self.rounds_total = 0
        self.rounds_at_zero = 0
        self.rounds_violated = 0
        self.peak_assigned = 0
        self.history: List[dict] = []

    @property
    def label(self) -> str:
        return str(self.int_id)

    def attainment(self) -> float:
        if self.requests_offered <= 0.0:
            return 1.0
        return self.requests_ok / self.requests_offered

    def measured_p99_for_scaling(self,
                                 min_samples: int) -> Optional[float]:
        """The previous round's measured p99 when it carried enough
        samples to act on, else None (analytic-only scaling)."""
        window = self.last_measured_window
        if window is None or window["requests"] < min_samples:
            return None
        return window["p99_s"]

    def summary(self) -> dict:
        return {
            "service": self.int_id,
            "slo_p99_s": self.slo_p99_s,
            "mu_requests_per_s": self.mu,
            "mu_analytic_requests_per_s": self.mu_analytic,
            "measured_requests": self.measured.requests_total,
            "measured_p99_s": self.measured.sketch_total.quantile(0.99),
            "requests_offered": round(self.requests_offered, 2),
            "requests_within_slo": round(self.requests_ok, 2),
            "slo_attainment": round(self.attainment(), 6),
            "rounds": self.rounds_total,
            "rounds_at_zero_replicas": self.rounds_at_zero,
            "rounds_with_violation": self.rounds_violated,
            "peak_replicas": self.peak_assigned,
            "retired": self.retired,
        }


class ServingTier:
    """Coordinator for all serving services of one scheduler."""

    #: Tier state is mutated from the locked round pipeline
    #: (`plan_round`) and the scheduler's job-lifecycle hooks (add_job
    #: / replica removal, gRPC handler paths) — all call sites hold the
    #: owning scheduler's lock, which a per-class static lockset cannot
    #: see; in simulation the tier is single-threaded. Documented here
    #: for the race detector; the sanitizer + explorer check the claim
    #: dynamically. `_sched` is rebound once by `bind()` on restore.
    _EXTERNALLY_SYNCHRONIZED = frozenset({
        "services", "_replica_service", "_retired_unreaped",
        "last_reserved", "_sched", "_measured_rows",
    })

    def __init__(self, sched, config: Optional[dict] = None):
        self._sched = sched
        self.autoscaler_config = AutoscalerConfig.from_dict(config or {})
        self.services: "collections.OrderedDict[int, ServingService]" = (
            collections.OrderedDict())
        #: int replica job id -> service int id (reverse index).
        self._replica_service: Dict[int, int] = {}
        self._retired_unreaped = 0
        #: worker_type -> chips reserved by the LAST plan_round (what
        #: _allocation_state subtracts from the cluster the LP sees).
        self.last_reserved: Dict[str, int] = {}
        #: Measured per-round rows awaiting the telemetry history
        #: (drained by take_measured_rows in the physical round loop).
        self._measured_rows: List[dict] = []

    # The scheduler reference must not ride into snapshots/checkpoints
    # (it would drag a ghost scheduler copy along); restore re-binds.
    def __getstate__(self):
        state = dict(self.__dict__)
        state["_sched"] = None
        return state

    def bind(self, sched) -> None:
        self._sched = sched

    # ------------------------------------------------------------------
    # Registration / lifecycle hooks (called from Scheduler.add_job etc.)
    # ------------------------------------------------------------------

    def register_service(self, int_id: int, job: Job, params: dict,
                         arrival_ts: float) -> ServingService:
        # Oracle mu prior (scheduler.oracle_serving_mu): None unless
        # the learned chain is configured AND has samples for this
        # family — the exact-config fallback is the common case.
        mu_prior = None
        hook = getattr(self._sched, "oracle_serving_mu", None)
        if hook is not None:
            mu_prior = hook(job)
        svc = ServingService(int_id, job, params, arrival_ts,
                             self.autoscaler_config, mu_prior=mu_prior)
        self.services[int_id] = svc
        self._obs().set_gauge(obs_names.SERVING_SERVICES,
                              len(self._live_services()))
        logger.info("[Serving] service %d registered: slo_p99=%.3fs "
                    "mu=%.2f req/s max_replicas=%d lifetime=%.0fs",
                    int_id, svc.slo_p99_s, svc.mu, svc.max_replicas,
                    svc.lifetime_s)
        return svc

    def adopt_replica(self, job_id: JobIdPair, job: Job,
                      params: Optional[dict] = None) -> None:
        """Attach a replica job (just admitted through add_job — live
        spawn or journal replay) to its service."""
        params = params or parse_serving_command(job.command)
        service_id = int(params["replica_of"])
        index = int(params.get("replica_index", 0))
        svc = self.services.get(service_id)
        if svc is None:
            logger.warning("replica %s names unknown service %d; dropping "
                           "it from the serving books", job_id, service_id)
            return
        svc.replicas[job_id] = index
        svc.next_replica_index = max(svc.next_replica_index, index + 1)
        self._replica_service[job_id.integer_job_id()] = service_id

    def on_replica_removed(self, job_id: JobIdPair) -> None:
        """Scheduler hook: a replica job left the active set (drain
        completed, journal replay, or deadline enforcement)."""
        service_id = self._replica_service.pop(job_id.integer_job_id(), None)
        if service_id is None:
            return
        svc = self.services.get(service_id)
        if svc is not None:
            svc.replicas.pop(job_id, None)
            svc.draining.pop(job_id, None)

    def ingest_measured(self, job_id: JobIdPair, delta: dict) -> None:
        """Fold one replica's measured-telemetry delta (shipped on its
        Done heartbeat, serving/measured.py wire format) into its
        service: merge the latency sketch, advance the token/request
        counters, and refine the live `mu` estimate (analytic prior,
        measurement takes over with evidence). Called under the
        scheduler lock from the Done fold; never in simulation."""
        service_id = self._replica_service.get(job_id.integer_job_id())
        if service_id is None:
            return
        svc = self.services.get(service_id)
        if svc is None:
            return
        stamp = (int(delta.get("round", -1)), int(delta.get("seq", -1)))
        if stamp != (-1, -1):
            last = svc.measured_seen.get(job_id.integer_job_id())
            if last is not None and stamp <= last:
                return      # duplicate delivery (renewal retry / Done replay)
            svc.measured_seen[job_id.integer_job_id()] = stamp
        try:
            svc.measured.ingest(delta)
        except (KeyError, ValueError, TypeError) as e:
            logger.warning("dropping malformed measured delta from "
                           "replica %s of service %d: %s", job_id,
                           service_id, e)
            return
        svc.mu = svc.measured.mu_estimate()
        requests = int(delta.get("requests", 0))
        if requests > 0:
            self._obs().inc(obs_names.SERVING_MEASURED_SAMPLES_TOTAL,
                            amount=requests, service=svc.label)

    def force_retire(self, int_id: int, ts: float) -> None:
        """Journal replay of a service retirement (no planning runs
        during replay; replica removal rides its own journal events)."""
        svc = self.services.get(int_id)
        if svc is None or svc.retired:
            return
        for job_id, index in list(svc.replicas.items()):
            svc.draining[job_id] = index
        svc.replicas.clear()
        svc.retired = True
        svc.retired_ts = ts
        self._retired_unreaped += 1

    # ------------------------------------------------------------------
    # Queries
    # ------------------------------------------------------------------

    def _live_services(self) -> List[ServingService]:
        return [s for s in self.services.values() if not s.retired]

    def has_live_services(self) -> bool:
        return any(not s.retired for s in self.services.values())

    def has_replicas_in_flight(self) -> bool:
        return any(s.replicas or s.draining for s in self.services.values())

    def take_retired_count(self) -> int:
        """Services retired since the last call (the simulator's
        remaining-jobs decrement)."""
        n = self._retired_unreaped
        self._retired_unreaped = 0
        return n

    def reserved_total(self) -> int:
        return sum(self.last_reserved.values())

    def summary(self) -> dict:
        services = [s.summary() for s in self.services.values()]
        offered = sum(s.requests_offered for s in self.services.values())
        ok = sum(s.requests_ok for s in self.services.values())
        return {
            "services": services,
            "requests_offered": round(offered, 2),
            "slo_attainment": round(ok / offered, 6) if offered > 0 else 1.0,
        }

    def _obs(self):
        return self._sched.obs

    def set_headroom(self, headroom: float) -> None:
        """Live-retune the autoscaler headroom (the what-if plane's
        flagship knob, whatif/knobs.py). Every service's Autoscaler
        holds a reference to this tier's shared AutoscalerConfig, so
        one assignment changes the NEXT target computation everywhere;
        committed replica levels and hysteresis counters are untouched
        (the new headroom phases in through the ordinary scale-down
        patience window rather than flapping the pools)."""
        if headroom <= 0:
            raise ValueError(f"headroom must be positive, got {headroom!r}")
        self.autoscaler_config.headroom = float(headroom)

    # ------------------------------------------------------------------
    # Round planning
    # ------------------------------------------------------------------

    def plan_round(self) -> "collections.OrderedDict[JobIdPair, Tuple[int, ...]]":
        """Serving half of the round schedule. Called at every
        round-scheduling point, before training selection; physical
        callers hold the scheduler lock."""
        sched = self._sched
        now = sched.get_current_timestamp()
        round_s = sched._time_per_iteration

        self._reap_drained()
        # Aggregate cluster-share budget: max_cluster_fraction bounds
        # what ALL services together may reserve ahead of the training
        # planner; earlier-registered services draw first.
        cluster_chips = sum(sched.workers.cluster_spec.values())
        budget = int(self.autoscaler_config.max_cluster_fraction
                     * cluster_chips)
        for svc in self._live_services():
            t_rel = now - svc.arrival_ts
            if t_rel >= svc.lifetime_s - 1e-9:
                self._retire_service(svc, now)
                continue
            budget -= self._scale_service(svc, t_rel, round_s, budget)
        self._reap_drained()

        assignments = self._assign_chips()
        self._account_round(assignments, now, round_s)

        if sched._shockwave_planner is not None:
            # Shrink the capacity row the MILP sees: the planner budgets
            # training over what serving has not reserved.
            sched._shockwave_planner.reserved_gpus = self.reserved_total()
        return assignments

    def _scale_service(self, svc: ServingService, t_rel: float,
                       round_s: float, budget: int) -> int:
        """Reconcile one service to its target; returns the chips it
        claims against the tier's aggregate budget."""
        window_end = min(t_rel + round_s, svc.lifetime_s)
        peak = svc.load.peak_rate(t_rel, window_end, samples=WINDOW_SAMPLES)
        cap = min(svc.max_replicas, max(budget, 0))
        # min(): the autoscaler's committed level may predate a budget
        # shrink (another service scaled up, chips died) — the cap wins.
        # `svc.mu` is the measurement-refined service rate (== the
        # analytic prior until replicas report); the measured p99 of
        # the last accounted round escalates past a model that missed
        # a breach (None without enough samples — always in sim).
        target = min(svc.autoscaler.target_replicas(
            peak, svc.mu, svc.slo_p99_s, cap, round_s,
            measured_p99_s=svc.measured_p99_for_scaling(
                self.autoscaler_config.measured_min_samples)), cap)
        svc.target = target
        active = len(svc.replicas)
        if target > active:
            for _ in range(target - active):
                self._spawn_replica(svc)
            self._obs().inc(obs_names.SERVING_SCALE_EVENTS_TOTAL,
                            amount=target - active, direction="up")
        elif target < active:
            # Drain the highest-index replicas first (deterministic, and
            # sticky placement keeps the longest-lived replicas warm).
            for job_id, _ in sorted(svc.replicas.items(),
                                    key=lambda kv: kv[1],
                                    reverse=True)[: active - target]:
                self._drain_replica(svc, job_id)
            self._obs().inc(obs_names.SERVING_SCALE_EVENTS_TOTAL,
                            amount=active - target, direction="down")
        return target

    def _spawn_replica(self, svc: ServingService) -> None:
        sched = self._sched
        index = svc.next_replica_index
        svc.next_replica_index += 1
        anchor = svc.job
        # The replica's measured request clock needs two values the
        # anchor command does not carry: the service lifetime (seeded
        # spikes are drawn over it — the replica must place them where
        # the analytic model does) and the service-relative spawn time
        # (a replica spawned at the diurnal peak must sample peak load,
        # not the t=0 trough). Journaled with the job, so replay
        # reconstructs the same stream.
        t_rel = max(sched.get_current_timestamp() - svc.arrival_ts, 0.0)
        replica = Job(
            job_id=None, job_type=anchor.job_type,
            command=(f"{anchor.command} --replica_of {svc.int_id} "
                     f"--replica_index {index} "
                     f"--service_lifetime_s {svc.lifetime_s:g} "
                     f"--arrival_phase_s {t_rel:g}"),
            working_directory=anchor.working_directory,
            num_steps_arg=anchor.num_steps_arg,
            # Effectively unbounded step budget: a replica retires by
            # scale-down or service end, never by finishing its steps.
            total_steps=int(1e9),
            duration=svc.lifetime_s,
            scale_factor=1, mode=anchor.mode,
            priority_weight=anchor.priority_weight, SLO=anchor.SLO,
            needs_data_dir=False)
        # add_job routes mode="serving" + --replica_of back through
        # adopt_replica (same path journal replay takes).
        sched.add_job(replica)

    def _drain_replica(self, svc: ServingService, job_id: JobIdPair) -> None:
        index = svc.replicas.pop(job_id, None)
        if index is None:
            return
        svc.draining[job_id] = index

    def _reap_drained(self) -> None:
        """Remove draining replicas whose in-flight round (if any) has
        completed — physically their lease was simply not renewed, so
        the process checkpoints out at expiry and its Done lands before
        the round rolls."""
        sched = self._sched
        for svc in self.services.values():
            for job_id in list(svc.draining):
                if not any(m in sched.acct.jobs
                           for m in job_id.singletons()):
                    svc.draining.pop(job_id, None)
                    continue
                in_flight = (
                    job_id in sched.rounds.current_assignments
                    and job_id not in sched.rounds.completed_in_round)
                if in_flight:
                    continue
                svc.draining.pop(job_id, None)
                sched._remove_job(job_id)

    def _retire_service(self, svc: ServingService, now: float) -> None:
        for job_id in list(svc.replicas):
            self._drain_replica(svc, job_id)
        svc.retired = True
        svc.retired_ts = now
        self._retired_unreaped += 1
        sched = self._sched
        sched._last_completion_time = max(sched._last_completion_time, now)
        sched._completed_jobs.add(JobIdPair(svc.int_id))
        sched._job_timelines.setdefault(svc.int_id, []).append(
            f"t={now:.1f} SERVICE_RETIRED offered="
            f"{svc.requests_offered:.1f} attainment={svc.attainment():.4f}")
        sched._emit_serving_retired(svc.int_id, now)
        self._obs().set_gauge(obs_names.SERVING_SERVICES,
                              len(self._live_services()))
        logger.info("[Serving] service %d retired after %.0fs: "
                    "attainment=%.4f peak_replicas=%d", svc.int_id,
                    now - svc.arrival_ts, svc.attainment(),
                    svc.peak_assigned)

    # ------------------------------------------------------------------
    # Chip reservation
    # ------------------------------------------------------------------

    def _assign_chips(self) -> "collections.OrderedDict[JobIdPair, Tuple[int, ...]]":
        """Reserve one chip per active replica, sticky where the
        previous chip is still alive and unclaimed. Deterministic order:
        services by id, replicas by index.

        Gray-failure awareness: chips on suspect/degraded hosts
        (`sched.suspect_worker_ids()`) are placed LAST — a latency-SLO
        replica pinned to a straggler misses its p99 every round — and
        sticky reuse of a chip that turned suspect is abandoned. In
        simulation the suspect set is always empty and placement is
        unchanged."""
        sched = self._sched
        workers = sched.workers
        suspect = sched.suspect_worker_ids()
        assignments: "collections.OrderedDict[JobIdPair, Tuple[int, ...]]" = (
            collections.OrderedDict())
        assigned: set = set()
        # Per-type strided pools, same walk as Scheduler._take_workers.
        pools = {
            wt: [[w for w in server if w not in workers.dead]
                 for server in workers.type_to_server_ids.get(wt, [])]
            for wt in sorted(workers.type_to_server_ids)}
        reserved: Dict[str, int] = {}

        def take_chip(allow_suspect: bool) -> Optional[int]:
            for wt in sorted(pools):
                for server in pools[wt]:
                    for w in list(server):
                        if w in assigned:
                            server.remove(w)
                            continue
                        if not allow_suspect and w in suspect:
                            continue  # keep for the fallback pass
                        server.remove(w)
                        reserved[wt] = reserved.get(wt, 0) + 1
                        return w
            return None

        def take_best_chip() -> Optional[int]:
            chip = take_chip(allow_suspect=False)
            if chip is None and suspect:
                # Better a suspect chip than a starved replica.
                chip = take_chip(allow_suspect=True)
            return chip

        for svc in self._live_services():
            for job_id, _index in sorted(svc.replicas.items(),
                                         key=lambda kv: kv[1]):
                if not any(m in sched.acct.jobs
                           for m in job_id.singletons()):
                    continue
                prev = sched.rounds.current_assignments.get(job_id)
                if (prev and len(prev) == 1 and prev[0] not in assigned
                        and prev[0] not in workers.dead
                        and prev[0] not in suspect):
                    chip = prev[0]
                    wt = workers.id_to_type[chip]
                    reserved[wt] = reserved.get(wt, 0) + 1
                else:
                    chip = take_best_chip()
                    if chip is None:
                        logger.warning(
                            "[Serving] no chip available for replica %s "
                            "of service %d (cluster exhausted)", job_id,
                            svc.int_id)
                        continue
                assigned.add(chip)
                assignments[job_id] = (chip,)
        self.last_reserved = reserved
        return assignments

    # ------------------------------------------------------------------
    # SLO accounting
    # ------------------------------------------------------------------

    def _account_round(self, assignments, now: float, round_s: float) -> None:
        sched = self._sched
        obs = self._obs()
        per_service: Dict[int, int] = {}
        for job_id in assignments:
            service_id = self._replica_service.get(job_id.integer_job_id())
            if service_id is not None:
                per_service[service_id] = per_service.get(service_id, 0) + 1
        for svc in self._live_services():
            n = per_service.get(svc.int_id, 0)
            svc.rounds_total += 1
            svc.peak_assigned = max(svc.peak_assigned, n)
            t_rel = now - svc.arrival_ts
            window_end = min(t_rel + round_s, svc.lifetime_s)
            width = max(window_end - t_rel, 0.0)
            step = width / WINDOW_SAMPLES if width > 0 else 0.0
            offered = ok = 0.0
            worst_p99 = 1.0 / svc.mu
            violated = False
            for i in range(WINDOW_SAMPLES if step > 0 else 0):
                t = t_rel + (i + 0.5) * step
                rate = svc.load.rate(t)
                weight = rate * step
                if weight <= 0.0:
                    continue
                p99 = p99_latency(rate, n, svc.mu)
                worst_p99 = max(worst_p99, p99)
                offered += weight
                if p99 <= svc.slo_p99_s:
                    ok += weight
                else:
                    violated = True
            svc.requests_offered += offered
            svc.requests_ok += ok
            if violated:
                svc.rounds_violated += 1
            if n == 0 and svc.target == 0:
                svc.rounds_at_zero += 1
            window = svc.measured.take_window()
            svc.last_measured_window = window
            history_row = dict(
                round=sched.rounds.num_completed_rounds, t=round(now, 3),
                target=svc.target, assigned=n, offered=round(offered, 3),
                p99_s=(None if worst_p99 == float("inf")
                       else round(worst_p99, 6)),
                ok=not violated)
            obs.set_gauge(obs_names.SERVING_REPLICAS, n, service=svc.label)
            obs.set_gauge(obs_names.SERVING_TARGET_REPLICAS, svc.target,
                          service=svc.label)
            saturated = worst_p99 == float("inf")
            obs.set_gauge(obs_names.SERVING_SATURATED, int(saturated),
                          service=svc.label)
            if saturated:
                # A saturated pool has no finite modeled p99: DROP the
                # series rather than freeze it at its last healthy
                # value (the stale-gauge bug) — the saturated gauge
                # above is the round's latency story.
                obs.remove_series(obs_names.SERVING_P99_SECONDS,
                                  service=svc.label)
            else:
                obs.set_gauge(obs_names.SERVING_P99_SECONDS, worst_p99,
                              service=svc.label)
            if window is not None:
                self._export_measured(svc, window, worst_p99, round_s,
                                      history_row, now)
            elif svc.measured.has_samples:
                # The service HAS measured before but this round saw no
                # fresh samples (replicas quiet, draining, worker
                # death): drop the window-scoped series rather than
                # freeze a possibly-breaching round forever — the same
                # stale-gauge rule as the saturated p99 above. The mu
                # gauge stays: it is cumulative state, not a window.
                for spec in (obs_names.SERVING_MEASURED_P50_SECONDS,
                             obs_names.SERVING_MEASURED_P99_SECONDS,
                             obs_names.SERVING_TOKENS_PER_S,
                             obs_names.SERVING_MEASURED_VS_ANALYTIC_P99):
                    obs.remove_series(spec, service=svc.label)
            svc.history.append(history_row)
            if len(svc.history) > HISTORY_LIMIT:
                del svc.history[: len(svc.history) - HISTORY_LIMIT]
            obs.set_gauge(obs_names.SERVING_SLO_ATTAINMENT,
                          svc.attainment(), service=svc.label)
            if offered > 0:
                obs.inc(obs_names.SERVING_REQUESTS_TOTAL, amount=ok,
                        service=svc.label, slo="ok")
                if offered - ok > 0:
                    obs.inc(obs_names.SERVING_REQUESTS_TOTAL,
                            amount=offered - ok, service=svc.label,
                            slo="violated")
        obs.set_gauge(obs_names.SERVING_RESERVED_CHIPS,
                      self.reserved_total())

    def _export_measured(self, svc: ServingService, window: dict,
                         analytic_p99: float, round_s: float,
                         history_row: dict, now: float) -> None:
        """Export one service's measured round window: gauges, the
        measured-vs-analytic calibration error, and the /history.json
        training row (collected by the physical round loop through
        `take_measured_rows`). Only ever reached when replicas shipped
        samples — never in simulation."""
        obs = self._obs()
        tokens_per_s = window["tokens"] / round_s if round_s > 0 else 0.0
        obs.set_gauge(obs_names.SERVING_MEASURED_P50_SECONDS,
                      window["p50_s"], service=svc.label)
        obs.set_gauge(obs_names.SERVING_MEASURED_P99_SECONDS,
                      window["p99_s"], service=svc.label)
        obs.set_gauge(obs_names.SERVING_TOKENS_PER_S, tokens_per_s,
                      service=svc.label)
        obs.set_gauge(obs_names.SERVING_MU_ESTIMATE, svc.mu,
                      service=svc.label)
        ratio = None
        if analytic_p99 not in (float("inf"), 0.0):
            ratio = window["p99_s"] / analytic_p99
            obs.set_gauge(obs_names.SERVING_MEASURED_VS_ANALYTIC_P99,
                          ratio, service=svc.label)
        else:
            # Saturated analytic model: no finite ratio exists — drop
            # the series instead of freezing the last finite one.
            obs.remove_series(obs_names.SERVING_MEASURED_VS_ANALYTIC_P99,
                              service=svc.label)
        history_row.update(
            measured_p50_s=round(window["p50_s"], 6),
            measured_p99_s=round(window["p99_s"], 6),
            measured_requests=window["requests"],
            tokens_per_s=round(tokens_per_s, 3),
            mu_estimate=round(svc.mu, 6))
        self._measured_rows.append({
            "service": svc.int_id, "t": round(now, 3),
            "requests": window["requests"],
            "measured_p50_s": round(window["p50_s"], 6),
            "measured_p99_s": round(window["p99_s"], 6),
            "analytic_p99_s": (None if analytic_p99 == float("inf")
                               else round(analytic_p99, 6)),
            "measured_vs_analytic_p99": (None if ratio is None
                                         else round(ratio, 4)),
            "tokens_per_s": round(tokens_per_s, 3),
            "mu_estimate": round(svc.mu, 6),
            "mu_analytic": round(svc.mu_analytic, 6),
        })
        if len(self._measured_rows) > HISTORY_LIMIT:
            # Bounded even when no history collector drains the rows
            # (physical drive without --history).
            del self._measured_rows[: len(self._measured_rows)
                                    - HISTORY_LIMIT]

    def take_measured_rows(self) -> List[dict]:
        """Drain the measured per-round rows accumulated since the last
        call — the physical round loop feeds them into the telemetry
        history (`/history.json`), the mu-estimation training set
        ROADMAP item 2 consumes. Caller holds the scheduler lock."""
        rows, self._measured_rows = self._measured_rows, []
        return rows


__all__ = ["ServingTier", "ServingService", "WINDOW_SAMPLES"]
