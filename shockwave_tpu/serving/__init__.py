"""Serving tier: latency-SLO inference jobs co-scheduled with training.

- `load` — deterministic diurnal/bursty request-rate curves.
- `latency_model` — analytic M/M/c (offered load, replicas) -> p50/p99.
- `autoscaler` — round-by-round replica targets with hysteresis,
  scale-to-zero, and a cluster-share cap.
- `tier` — the coordinator wired into the scheduler's round loop:
  replica lifecycle, chip reservation ahead of the training planner,
  and requests-weighted SLO-attainment accounting.

See README "Serving tier" and the trace-level job class in
`core/trace.py` (mode ``serving``).
"""
from .autoscaler import Autoscaler, AutoscalerConfig
from .latency_model import (p50_latency, p99_latency, replicas_for_slo)
from .load import DiurnalLoad, Spike, seeded_spikes
from .tier import ServingService, ServingTier

__all__ = [
    "Autoscaler", "AutoscalerConfig", "DiurnalLoad", "ServingService",
    "ServingTier", "Spike", "p50_latency", "p99_latency",
    "replicas_for_slo", "seeded_spikes",
]
