"""Scheduler crash-restart recovery: in-process snapshot/restore +
conservative requeue semantics, the full SIGKILL-the-scheduler loopback
(acceptance criterion), and the MILP solver exception guard."""
import json
import os
import pickle
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from shockwave_tpu.core.job import Job, JobIdPair
from shockwave_tpu.sched import journal
from shockwave_tpu.sched.physical import PhysicalScheduler
from shockwave_tpu.sched.scheduler import Scheduler, SchedulerConfig
from shockwave_tpu.solver import get_policy

TESTS_DIR = os.path.dirname(__file__)
REPO = os.path.abspath(os.path.join(TESTS_DIR, ".."))
DATA = os.path.join(REPO, "data")
RUN_PHYSICAL = os.path.join(REPO, "scripts", "drivers", "run_physical.py")
THROUGHPUTS = os.path.join(DATA, "tacc_throughputs.json")


def free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _job(total_steps=300):
    return Job(None, "ResNet-18 (batch size 32)",
               "python3 main.py --batch_size 32",
               "image_classification/cifar10", "--num_steps",
               total_steps=total_steps, duration=10000)


def _make_physical(state_dir, resume=False, port=None):
    return PhysicalScheduler(
        get_policy("max_min_fairness"), throughputs_file=THROUGHPUTS,
        config=SchedulerConfig(
            time_per_iteration=2.0, heartbeat_interval_s=0.0,
            state_dir=str(state_dir), resume=resume,
            snapshot_interval_rounds=2),
        port=port or free_port())


@pytest.mark.recovery
@pytest.mark.timeout(120)
class TestPhysicalRestoreAndRequeue:
    def test_restart_recovers_state_and_requeues_inflight(self, tmp_path):
        d = tmp_path / "state"
        a = _make_physical(d)
        try:
            # A worker host registered over the real RPC path (endpoint
            # recorded), two jobs, one with journaled progress.
            ids, _ = a._register_worker_rpc("v5e", 2, "127.0.0.1",
                                            free_port())
            j0 = a.add_job(_job(300))
            j1 = a.add_job(_job(300))
            with a._cv:
                a.rounds.current_assignments[j0] = (ids[0],)
                a._running_jobs.add(j0)
                a._dispatch_seq += 1
                a._dispatch_stamp[(j0, ids[0])] = a._dispatch_seq
            a.done_callback(j0, ids[0], [120], [1.0])
            # Round rolls; j1's round is still in flight at the "crash".
            with a._cv:
                a.rounds.completed_in_round = set()
                a.rounds.current_assignments = {j1: (ids[1],)}
                a.rounds.num_completed_rounds += 1
                a._emit("round_ended",
                        round=a.rounds.num_completed_rounds)
                a._maybe_snapshot()  # interval=2 -> not due yet; harmless
            failures_before = dict(a.acct.failures)
        finally:
            a.shutdown()

        b = _make_physical(d, resume=True)
        try:
            # Durable state came back...
            assert set(b.acct.jobs) == {j0, j1}
            assert b.acct.total_steps_run[j0] == 120
            assert b.workers.cluster_spec == {"v5e": 2}
            assert b.rounds.num_completed_rounds == 1
            assert b.run_meta == {} or isinstance(b.run_meta, dict)
            # ...the worker host was re-adopted with a fresh channel...
            assert len(b._worker_hosts) == 1
            assert set(b._worker_connections) == set(ids)
            # ...and the in-flight round was requeued conservatively:
            # no assignments, no failure charged.
            assert not b.rounds.current_assignments
            assert b.rounds.next_assignments is None
            assert not b._running_jobs
            assert b.acct.failures[j1] == failures_before[j1] == 0
            assert b.acct.failures[j0] == 0
            # The allocation thread re-plans over the recovered state
            # (it may already have consumed the update flag).
            deadline = time.time() + 5
            while time.time() < deadline:
                with b._lock:
                    if (not b._need_to_update_allocation
                            and b._allocation):
                        break
                time.sleep(0.05)
            assert b._allocation, "allocation never recomputed"
            # j0 was mid-round per the replayed journal: its abandoned
            # lease is marked in the timeline.
            tl = b._job_timelines[j0.integer_job_id()]
            assert any("RECOVERY_REQUEUE" in line for line in tl)
        finally:
            b.shutdown()

    def test_post_restart_gates_reject_orphans(self, tmp_path):
        d = tmp_path / "state"
        a = _make_physical(d)
        try:
            ids, _ = a._register_worker_rpc("v5e", 2, "127.0.0.1",
                                            free_port())
            j0 = a.add_job(_job(300))
        finally:
            a.shutdown()

        b = _make_physical(d, resume=True)
        try:
            j0 = JobIdPair(0)
            worker = b.workers.worker_ids[0]
            # A pre-crash trainer's Done has no dispatch stamp from this
            # incarnation: discarded, no steps credited.
            b.done_callback(j0, worker, [500], [1.0])
            assert b.acct.total_steps_run[j0] == 0
            assert j0 not in b._completed_jobs
            # Its lease renewal gets a zero lease (checkpoint + exit).
            out = b._update_lease_callback(j0, worker, 50, 1.0, 100, 10.0)
            assert out == (0, 0.0, 0.0, 0.0)
            # And a late InitJob from a pre-crash spawn: zero grant.
            assert b._init_job_callback(j0) == (0, 0.0, 0.0)
            # Once THIS incarnation dispatches, reports flow normally.
            with b._cv:
                b.rounds.current_assignments[j0] = (worker,)
                b._running_jobs.add(j0)
                b._dispatch_seq += 1
                b._dispatch_stamp[(j0, worker)] = b._dispatch_seq
            # ...but the requeued job being REDISPATCHED (to `worker`)
            # must not re-arm the pre-crash copy on the OTHER chip: a
            # renewal from a worker the job is not assigned to still
            # gets a zero lease, or two copies would train concurrently.
            other = next(i for i in b.workers.worker_ids if i != worker)
            assert b._update_lease_callback(
                j0, other, 50, 1.0, 100, 10.0) == (0, 0.0, 0.0, 0.0)
            b.done_callback(j0, worker, [80], [1.0])
            assert b.acct.total_steps_run[j0] == 80
            # The orphan gates are TIME-BOUNDED: past the drain window
            # they stand down, so this incarnation's own slow trainers
            # (round rolled during a long compile) get normal leases
            # again instead of a kill/requeue livelock.
            with b._cv:
                del b.rounds.current_assignments[j0]
                b._recovered_at -= 10_000.0
            assert b._init_job_callback(j0) != (0, 0.0, 0.0)
        finally:
            b.shutdown()

    def test_quarantine_survives_resume_with_exact_capacity(
            self, tmp_path):
        """Satellite (gray-failure resilience): quarantine/unquarantine
        are journaled, so a scheduler killed with a worker quarantined
        restores the quarantine AND its capacity accounting exactly on
        --resume. (Every journal append is fsync'd at emit time, so the
        durable state at shutdown() is byte-identical to a SIGKILL's —
        the subprocess SIGKILL variant of this path is the chaos
        campaign's physical mode.)"""
        d = tmp_path / "state"
        a = _make_physical(d)
        try:
            ids_a, _ = a._register_worker_rpc("v5e", 1, "127.0.0.1",
                                              free_port())
            ids_b, _ = a._register_worker_rpc("v5e", 1, "127.0.0.1",
                                              free_port())
            a.add_job(_job(300))
            key_b = next(k for k, h in a._worker_hosts.items()
                         if set(h["worker_ids"]) == set(ids_b))
            with a._cv:
                a._quarantine_worker_host(key_b)
            assert set(a.workers.quarantined) == set(ids_b)
            assert a.workers.cluster_spec == {"v5e": 1}
        finally:
            a.shutdown()

        b = _make_physical(d, resume=True)
        try:
            # Quarantine state and capacity accounting restored exactly.
            assert set(b.workers.quarantined) == set(ids_b)
            assert b.workers.cluster_spec == {"v5e": 1}
            assert set(ids_b) <= b.workers.dead
            assert set(ids_a) & b.workers.dead == set()
            # Host-level lifecycle rebuilt: release clock restarted
            # conservatively, health pinned degraded, serving avoids it.
            host_b = b._worker_hosts[key_b]
            assert "quarantined_at" in host_b
            assert set(ids_b) <= b.suspect_worker_ids()
            # Probed release restores capacity (backoff forced elapsed),
            # and is journaled too.
            with b._cv:
                host_b["quarantined_at"] -= 10_000.0
                b._maybe_release_quarantine(key_b)
            assert not b.workers.quarantined
            assert b.workers.cluster_spec == {"v5e": 2}
        finally:
            b.shutdown()

        # Third incarnation: the RELEASE also survives a restart.
        c = _make_physical(d, resume=True)
        try:
            assert not c.workers.quarantined
            assert c.workers.cluster_spec == {"v5e": 2}
        finally:
            c.shutdown()

    def test_quarantine_restores_from_compacted_snapshot(self, tmp_path):
        """Quarantine state must survive journal compaction: once the
        quarantine events are folded into a snapshot, the marker comes
        back from WorkerState.quarantined alone."""
        d = tmp_path / "state"
        a = _make_physical(d)
        try:
            a._register_worker_rpc("v5e", 1, "127.0.0.1", free_port())
            ids_b, _ = a._register_worker_rpc("v5e", 1, "127.0.0.1",
                                              free_port())
            key_b = next(k for k, h in a._worker_hosts.items()
                         if set(h["worker_ids"]) == set(ids_b))
            with a._cv:
                a._quarantine_worker_host(key_b)
                # Force a compacting snapshot AFTER the quarantine so
                # its journal events are behind the snapshot horizon.
                a.rounds.num_completed_rounds = 2
                a._emit("round_ended", round=2)
                a._maybe_snapshot()
        finally:
            a.shutdown()

        b = _make_physical(d, resume=True)
        try:
            assert set(b.workers.quarantined) == set(ids_b)
            assert b.workers.cluster_spec == {"v5e": 1}
            assert "quarantined_at" in b._worker_hosts[key_b]
            assert set(ids_b) <= b.suspect_worker_ids()
        finally:
            b.shutdown()

    def test_fresh_start_refuses_nonempty_state_dir(self, tmp_path):
        d = tmp_path / "state"
        a = _make_physical(d)
        try:
            a.add_job(_job(100))
        finally:
            a.shutdown()
        with pytest.raises(ValueError, match="resume"):
            _make_physical(d, resume=False)

    def test_resume_without_state_dir_is_an_error(self):
        with pytest.raises(ValueError, match="state_dir"):
            PhysicalScheduler(
                get_policy("max_min_fairness"),
                throughputs_file=THROUGHPUTS,
                config=SchedulerConfig(resume=True), port=free_port())

    @pytest.mark.timeout(60)
    def test_resume_with_wrong_trace_fails_fast(self, tmp_path):
        """The submission cursor is positional: resuming against a
        different trace must error, not blend two workloads."""
        line = ("ResNet-18 (batch size 32)\tpython3 main.py "
                "--batch_size 32\timage_classification/cifar10\t"
                "--num_steps\t0\t300\t1\tstatic\t1\t-1.000000\t10000\t0")
        orig = tmp_path / "orig.trace"
        orig.write_text(line + "\n")
        wrong = tmp_path / "wrong.trace"
        wrong.write_text(line + "\n")
        d = tmp_path / "state"
        a = _make_physical(d)
        try:
            a.record_run_meta(start_time=1.0, trace=str(orig),
                              policy="max_min_fairness")
        finally:
            a.shutdown()
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.run(
            [sys.executable, RUN_PHYSICAL, "--trace", str(wrong),
             "--policy", "max_min_fairness", "--throughputs", THROUGHPUTS,
             "--round_duration", "2", "--port", str(free_port()),
             "--state_dir", str(d), "--resume"],
            capture_output=True, text=True, env=env, timeout=50)
        assert proc.returncode != 0
        assert "mismatch" in (proc.stdout + proc.stderr)


def _wait_for_port(port, timeout=30):
    deadline = time.time() + timeout
    while time.time() < deadline:
        with socket.socket() as s:
            s.settimeout(0.2)
            try:
                s.connect(("127.0.0.1", port))
                return True
            except OSError:
                time.sleep(0.1)
    return False


def _spawn_stub_worker(sched_port, tmp_path, name):
    state = tmp_path / f"{name}.json"
    log = open(tmp_path / f"{name}.log", "w")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(TESTS_DIR, "fault_stub_worker.py"),
         "--sched_port", str(sched_port),
         "--worker_port", str(free_port()),
         "--num_chips", "1", "--state_file", str(state)],
        stdout=log, stderr=subprocess.STDOUT, env=env)
    return proc, state, log


def _spawn_scheduler(tmp_path, sched_port, state_dir, trace, output,
                     resume=False, name="sched"):
    log = open(tmp_path / f"{name}.log", "w")
    cmd = [sys.executable, RUN_PHYSICAL,
           "--trace", str(trace), "--policy", "max_min_fairness",
           "--throughputs", THROUGHPUTS,
           "--expected_num_workers", "1",
           "--round_duration", "2", "--port", str(sched_port),
           "--state_dir", str(state_dir), "--snapshot_interval", "2",
           "--output", str(output),
           "--heartbeat_interval", "0.2", "--worker_timeout", "0.6",
           "--probe_failures", "1", "--kill_wait", "0.5",
           "--completion_buffer", "5", "--verbose"]
    if resume:
        cmd.append("--resume")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                            env=env)
    return proc, log


@pytest.mark.recovery
@pytest.mark.faults
@pytest.mark.timeout(180)
class TestSchedulerCrashRestart:
    """Acceptance: SIGKILL the scheduler PROCESS mid-round, restart it
    with --resume against the same state dir, and every job completes
    with exact step accounting (no loss, no double count)."""

    def test_sigkill_midround_resume_completes_all_jobs(self, tmp_path):
        sched_port = free_port()
        state_dir = tmp_path / "state"
        out1, out2 = tmp_path / "m1.pkl", tmp_path / "m2.pkl"
        # Two 300-step jobs arriving at t=0; one chip at 100 steps/s and
        # 2 s rounds means ~2 rounds per job -> several rounds of work.
        trace = tmp_path / "crash.trace"
        line = ("ResNet-18 (batch size 32)\tpython3 main.py "
                "--batch_size 32\timage_classification/cifar10\t"
                "--num_steps\t0\t300\t1\tstatic\t1\t-1.000000\t10000\t0")
        trace.write_text(line + "\n" + line + "\n")

        sched1, slog1 = _spawn_scheduler(tmp_path, sched_port, state_dir,
                                         trace, out1, name="sched1")
        assert _wait_for_port(sched_port), "scheduler 1 never bound"
        worker, wstate, wlog = _spawn_stub_worker(sched_port, tmp_path, "w")
        sched2 = None
        slog2 = None
        try:
            # Wait until real progress is journaled but the trace is far
            # from drained, then SIGKILL the scheduler mid-flight.
            deadline = time.time() + 60
            while time.time() < deadline:
                if sched1.poll() is not None:
                    pytest.fail("scheduler 1 exited prematurely: "
                                + (tmp_path / "sched1.log").read_text())
                rec = journal.load_state(str(state_dir))
                types = [e["type"] for e in rec.events]
                done = sum(t == "microtask_done" for t in types)
                removed = sum(t == "job_removed" for t in types)
                if rec.snapshot is not None or done >= 1:
                    if removed < 2:
                        break
                time.sleep(0.05)
            else:
                pytest.fail("no journaled progress within 60s: "
                            + (tmp_path / "sched1.log").read_text())
            os.kill(sched1.pid, signal.SIGKILL)
            sched1.wait(timeout=10)

            sched2, slog2 = _spawn_scheduler(
                tmp_path, sched_port, state_dir, trace, out2,
                resume=True, name="sched2")
            try:
                rc = sched2.wait(timeout=90)
            except subprocess.TimeoutExpired:
                pytest.fail("resumed scheduler did not finish: "
                            + (tmp_path / "sched2.log").read_text())
            assert rc == 0, (tmp_path / "sched2.log").read_text()

            with open(out2, "rb") as f:
                metrics = pickle.load(f)
            assert metrics["all_jobs_completed"] is True
            assert len(metrics["jct_list"]) == 2
            assert metrics["makespan"] > 0
            assert metrics["avg_jct"] and metrics["avg_jct"] > 0

            # Cross-check the durable record: rebuild a scheduler from
            # the final state dir and verify exact step accounting
            # across the crash (journaled progress + post-restart runs
            # sum to each job's budget — nothing lost, nothing double-
            # counted).
            final = Scheduler(get_policy("max_min_fairness"),
                              throughputs_file=THROUGHPUTS)
            final.restore_from_durable_state(
                journal.load_state(str(state_dir)))
            assert final._completed_jobs == {JobIdPair(0), JobIdPair(1)}
            for int_id in (0, 1):
                jid = JobIdPair(int_id)
                assert final.acct.total_steps_run[jid] == 300, (
                    f"job {int_id} accounted "
                    f"{final.acct.total_steps_run[jid]} steps, not 300")
                assert final.acct.completion_times[jid] is not None
                assert final.acct.completion_times[jid] > 0
        finally:
            for proc in (sched1, sched2, worker):
                if proc is not None and proc.poll() is None:
                    proc.kill()
                    proc.wait(timeout=10)
            for log in (slog1, slog2, wlog):
                if log is not None:
                    log.close()


@pytest.mark.recovery
@pytest.mark.timeout(120)
class TestRepeatedIncarnationRecovery:
    """Satellite (control-plane HA): crash -> promote -> crash the new
    leader -> promote again, with the serving tier AND what-if plane
    active. Serving services, tuned knobs, and quarantine records must
    survive BOTH hops — recovery is idempotent across incarnations, not
    a one-shot. (In-process: every journal append is fsync'd at emit
    time, so the durable state at shutdown() is byte-identical to a
    SIGKILL's; the subprocess SIGKILL variant is the chaos campaign's
    HA mode and tests/test_ha.py's loopback failover.)"""

    def _incarnation(self, state_dir, resume, epoch):
        return PhysicalScheduler(
            get_policy("max_min_fairness"), throughputs_file=THROUGHPUTS,
            config=SchedulerConfig(
                time_per_iteration=2.0, heartbeat_interval_s=0.0,
                state_dir=str(state_dir), resume=resume,
                snapshot_interval_rounds=2,
                ha={"lease_interval_s": 0.2, "lease_ttl_s": 60.0,
                    "claimed_epoch": epoch},
                whatif={"admission": "always_admit"}),
            port=free_port())

    def _serving_job(self):
        from shockwave_tpu.core.trace import serving_command
        return Job(None, "Serving (batch size 1)",
                   serving_command(base_rps=4.0, peak_rps=8.0,
                                   period_s=600.0, tokens_per_request=64,
                                   decode_tokens_per_s=1600.0,
                                   max_replicas=4),
                   "serving", "--num_steps", total_steps=0,
                   duration=14400, mode="serving", SLO=0.5)

    def test_state_survives_two_failover_hops(self, tmp_path):
        from shockwave_tpu.sched.ha import try_claim_epoch

        d = tmp_path / "state"
        os.makedirs(d)
        assert try_claim_epoch(str(d), 1, role="leader")
        a = self._incarnation(d, resume=False, epoch=1)
        try:
            ids_a, _ = a._register_worker_rpc("v5e", 1, "127.0.0.1",
                                              free_port())
            ids_b, _ = a._register_worker_rpc("v5e", 1, "127.0.0.1",
                                              free_port())
            a.add_job(_job(300))
            service_id = a.add_job(self._serving_job())
            assert a._serving_tier is not None
            assert not a._serving_tier.services[
                service_id.integer_job_id()].retired
            # A what-if-committed knob (journaled durable config).
            a._emit_whatif_knob("quarantine_backoff_s", 45.0,
                                round=0, sweep=[])
            from shockwave_tpu.whatif.knobs import get_knob
            get_knob("quarantine_backoff_s").set(a, 45.0)
            # And a quarantined straggler.
            key_b = next(k for k, h in a._worker_hosts.items()
                         if set(h["worker_ids"]) == set(ids_b))
            with a._cv:
                a._quarantine_worker_host(key_b)
            assert set(a.workers.quarantined) == set(ids_b)
        finally:
            a.shutdown()

        # Hop 1: standby claims epoch 2 and recovers.
        assert try_claim_epoch(str(d), 2, role="standby")
        b = self._incarnation(d, resume=True, epoch=2)
        try:
            assert b._ha.epoch == 2 and b._durability.epoch == 2
            svc = b._serving_tier.services[service_id.integer_job_id()]
            assert not svc.retired
            assert b._health_cfg.quarantine_backoff_s == 45.0
            assert b._whatif_knob_values[
                "quarantine_backoff_s"] == 45.0
            assert set(b.workers.quarantined) == set(ids_b)
            assert b.workers.cluster_spec == {"v5e": 1}
            # Mutate state between the hops: release the quarantine so
            # hop 2 must ALSO replay incremental epoch-2 events, not
            # just re-read epoch-1 state.
            with b._cv:
                b._worker_hosts[key_b]["quarantined_at"] -= 10_000.0
                b._maybe_release_quarantine(key_b)
            assert not b.workers.quarantined
        finally:
            b.shutdown()

        # Hop 2: a third incarnation claims epoch 3 and recovers the
        # blended epoch-1 + epoch-2 history.
        assert try_claim_epoch(str(d), 3, role="standby")
        c = self._incarnation(d, resume=True, epoch=3)
        try:
            assert c._ha.epoch == 3
            svc = c._serving_tier.services[service_id.integer_job_id()]
            assert not svc.retired
            assert c._health_cfg.quarantine_backoff_s == 45.0
            assert not c.workers.quarantined       # release survived
            assert c.workers.cluster_spec == {"v5e": 2}
            assert JobIdPair(0) in c.acct.jobs     # training job alive
            # Journal chain is exactly-one-writer-per-epoch clean.
            rec = journal.load_state(str(d))
            assert rec.stale_orphans == []
            epochs = [e.get("epoch") for e in rec.events]
            assert all(e in (1, 2, 3) for e in epochs)
            non_decreasing = all(x <= y for x, y in
                                 zip(epochs, epochs[1:]))
            assert non_decreasing
        finally:
            c.shutdown()


@pytest.mark.recovery
class TestZeroCapacityAllocation:
    """A recovered scheduler can find its only worker endpoint dead and
    retire it, leaving zero capacity. The allocation solve must return
    empty — not feed nan coefficients into linprog and kill the
    allocation thread (which wedges the scheduler forever)."""

    def test_all_workers_retired_allocation_is_empty(self):
        s = Scheduler(get_policy("max_min_fairness"),
                      throughputs_file=THROUGHPUTS)
        ids, _ = s.register_worker("v100", 1)
        s.add_job(_job(300))
        s.deregister_workers(ids)
        assert sum(s.workers.cluster_spec.values()) == 0
        assert s._compute_allocation() == {}
        # Capacity returns -> allocation resumes normally.
        s.revive_workers(ids, "v100")
        assert s._compute_allocation() != {}


@pytest.mark.recovery
class TestSolverExceptionGuard:
    """Satellite: a solver EXCEPTION (not mere infeasibility) must fall
    through to the greedy fallback, recorded in SolveStats, instead of
    killing the round loop."""

    def _jobs(self, n=2):
        from shockwave_tpu.shockwave.metadata import JobMetadata
        profile = {
            "model": "ResNet-18", "dataset": "cifar10", "scale_factor": 1,
            "num_epochs": 4, "num_samples_per_epoch": 100,
            "util_every_epoch": [50] * 4, "mem_every_epoch": [1024] * 4,
            "duration_every_epoch": [60.0] * 4,
            "bs_every_epoch": [32] * 4,
        }
        return [JobMetadata(i, dict(profile)) for i in range(n)]

    def test_solver_raise_degrades_to_greedy(self, monkeypatch):
        from shockwave_tpu.shockwave import milp as milp_mod

        def boom(*args, **kwargs):
            raise RuntimeError("synthetic HiGHS crash")

        monkeypatch.setattr(milp_mod, "milp", boom)
        jobs = self._jobs()
        stats = []
        x = milp_mod.plan_schedule(
            jobs, round_index=0, future_nrounds=4, round_duration=60.0,
            ngpus=1, share_series=[[(0, 500.0)], [(0, 500.0)]],
            opts=milp_mod.MilpOptions(), stats_out=stats)
        # Greedy fallback schedule: boolean, right shape, capacity held.
        assert x.shape == (2, 4) and x.dtype == bool
        assert (x.sum(axis=0) <= 1).all()
        assert x.any(), "greedy fallback scheduled nothing"
        assert stats and stats[-1].path == "greedy"
        assert "synthetic HiGHS crash" in (stats[-1].error or "")

    def test_rank_exception_keeps_unranked_schedule(self, monkeypatch):
        from shockwave_tpu.shockwave import milp as milp_mod

        def boom(*args, **kwargs):
            raise ValueError("rank solver blew up")

        monkeypatch.setattr(milp_mod, "milp", boom)
        x = np.zeros((2, 3), dtype=bool)
        x[0, 0] = x[1, 1] = True
        out = milp_mod._rank_in_schedule(
            x, priorities=[2.0, 1.0], nworkers=[1, 1], ngpus=1,
            opts=milp_mod.MilpOptions())
        assert (out == x).all()

    def test_healthy_solver_unaffected(self):
        from shockwave_tpu.shockwave import milp as milp_mod
        jobs = self._jobs()
        stats = []
        x = milp_mod.plan_schedule(
            jobs, round_index=0, future_nrounds=4, round_duration=60.0,
            ngpus=1, share_series=[[(0, 500.0)], [(0, 500.0)]],
            opts=milp_mod.MilpOptions(), stats_out=stats)
        assert x.shape == (2, 4)
        assert stats and stats[-1].error is None
        assert stats[-1].path != "greedy"
