"""Job template registry: the 5 active model families x batch sizes.

Mirrors the reference's template table (reference: scheduler/job_table.py:
110-130, job_template.py) with commands pointing at this repo's JAX
workloads. A3C / CycleGAN templates exist but are excluded from the
generator table, exactly as in the reference.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List


@dataclass(frozen=True)
class JobTemplate:
    model: str              # job_type string, e.g. "ResNet-18 (batch size 32)"
    command: str            # command with %s placeholder(s) for the data dir
    working_directory: str  # run dir relative to the workloads root
    num_steps_arg: str      # CLI flag the dispatcher appends the step cap to
    needs_data_dir: bool = True
    distributed: bool = False


def resnet18(batch_size: int) -> JobTemplate:
    return JobTemplate(
        model=f"ResNet-18 (batch size {batch_size})",
        command=f"python3 main.py --data_dir=%s/cifar10 --batch_size {batch_size}",
        working_directory="image_classification/cifar10",
        num_steps_arg="--num_steps",
        distributed=True,
    )


def resnet50(batch_size: int) -> JobTemplate:
    return JobTemplate(
        model=f"ResNet-50 (batch size {batch_size})",
        command=f"python3 main.py -j 4 -a resnet50 -b {batch_size} %s/imagenet/",
        working_directory="image_classification/imagenet",
        num_steps_arg="--num_minibatches",
        distributed=True,
    )


def transformer(batch_size: int) -> JobTemplate:
    return JobTemplate(
        model=f"Transformer (batch size {batch_size})",
        command=("python3 train.py -data %s/translation/multi30k.atok.low.pt "
                 f"-batch_size {batch_size} -proj_share_weight"),
        working_directory="translation",
        num_steps_arg="-step",
        distributed=True,
    )


def lm(batch_size: int) -> JobTemplate:
    return JobTemplate(
        model=f"LM (batch size {batch_size})",
        command=f"python3 main.py --cuda --data %s/wikitext2 --batch_size {batch_size}",
        working_directory="language_modeling",
        num_steps_arg="--steps",
        distributed=True,
    )


def recommendation(batch_size: int) -> JobTemplate:
    return JobTemplate(
        model=f"Recommendation (batch size {batch_size})",
        command=f"python3 train.py --data_dir %s/ml-20m/pro_sg/ --batch_size {batch_size}",
        working_directory="recommendation",
        num_steps_arg="-n",
    )


def a3c() -> JobTemplate:
    return JobTemplate(
        model="A3C",
        command="python3 main.py --env PongDeterministic-v4 --workers 4 --amsgrad True",
        working_directory="rl",
        num_steps_arg="--max-steps",
        needs_data_dir=False,
    )


def cyclegan() -> JobTemplate:
    return JobTemplate(
        model="CycleGAN",
        command="python3 cyclegan.py --dataset_path %s/monet2photo --decay_epoch 0",
        working_directory="cyclegan",
        num_steps_arg="--n_steps",
    )


def _build_table() -> List[JobTemplate]:
    table: List[JobTemplate] = []
    for bs in [32, 64, 128, 256]:
        table.append(resnet18(bs))
    for bs in [16, 32, 64]:
        table.append(resnet50(bs))
    # Transformer capped at bs 128 (reference avoids bs 256 OOM on a
    # 16 GB V100; the profile carries the same limit).
    for bs in [16, 32, 64, 128]:
        table.append(transformer(bs))
    for bs in [5, 10, 20, 40, 80]:
        table.append(lm(bs))
    for bs in [512, 1024, 2048, 4096, 8192]:
        table.append(recommendation(bs))
    # a3c() and cyclegan() templates exist but stay out of the generator
    # table (non-dynamic, non-distributed), as in the reference.
    return table


JOB_TABLE: List[JobTemplate] = _build_table()

__all__ = ["JobTemplate", "JOB_TABLE", "resnet18", "resnet50", "transformer",
           "lm", "recommendation", "a3c", "cyclegan"]
