"""Result plotting: JCT / fairness CDFs, policy bar charts, and per-round
schedule heatmaps from metric pickles (reference: scheduler/plotting.py).

Every function takes `{label: metrics_dict}` where each metrics dict is
one driver-output pickle (simulate.py / run_physical.py / the sweep
scripts), and writes a PNG. Usable as a CLI:

    python -m shockwave_tpu.plotting --metric jct \
        --pickles shockwave=out/shockwave.pkl gavel=out/mmf.pkl \
        --output jct_cdf.png
"""
from __future__ import annotations

import argparse
import pickle
from typing import Dict, List, Optional

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402
import numpy as np  # noqa: E402


def _cdf_axes(ax, xlabel: str):
    ax.set_ylabel("CDF")
    ax.set_xlabel(xlabel)
    ax.set_ylim(0, 1)
    ax.grid(alpha=0.3)
    ax.legend()


def _plot_cdf(ax, values: List[float], label: str):
    xs = np.sort(np.asarray(values, dtype=float))
    ys = np.arange(1, len(xs) + 1) / len(xs)
    ax.plot(xs, ys, label=label, drawstyle="steps-post")


def plot_jct_cdf(results: Dict[str, dict], output: str,
                 hours: bool = True) -> str:
    """CDF of job completion times per policy (reference: plotting.py's
    JCT CDF figures)."""
    fig, ax = plt.subplots(figsize=(5, 3.5))
    for label, metrics in results.items():
        jcts = np.asarray(metrics["jct_list"], dtype=float)
        _plot_cdf(ax, jcts / 3600.0 if hours else jcts, label)
    _cdf_axes(ax, "JCT (hours)" if hours else "JCT (s)")
    fig.tight_layout()
    fig.savefig(output, dpi=150)
    plt.close(fig)
    return output


def plot_ftf_cdf(results: Dict[str, dict], output: str,
                 themis: bool = False) -> str:
    """CDF of finish-time-fairness rho per policy; rho > 1 means the job
    did worse than its fair share (reference: plotting.py rho CDFs)."""
    key = ("finish_time_fairness_themis_list" if themis
           else "finish_time_fairness_list")
    fig, ax = plt.subplots(figsize=(5, 3.5))
    for label, metrics in results.items():
        _plot_cdf(ax, metrics[key], label)
    ax.axvline(1.0, color="k", linestyle="--", linewidth=0.8)
    _cdf_axes(ax, "finish-time fairness " + r"$\rho$")
    fig.tight_layout()
    fig.savefig(output, dpi=150)
    plt.close(fig)
    return output


def plot_policy_bars(results: Dict[str, dict], output: str,
                     metric: str = "makespan", hours: bool = True) -> str:
    """Bar chart of a scalar metric (makespan / avg_jct / cluster_util)
    across policies."""
    labels = list(results)
    values = [float(results[k][metric]) for k in labels]
    if hours and metric in ("makespan", "avg_jct"):
        values = [v / 3600.0 for v in values]
        unit = " (hours)"
    else:
        unit = ""
    fig, ax = plt.subplots(figsize=(5, 3.5))
    ax.bar(labels, values)
    ax.set_ylabel(metric + unit)
    ax.grid(alpha=0.3, axis="y")
    plt.xticks(rotation=20, ha="right")
    fig.tight_layout()
    fig.savefig(output, dpi=150)
    plt.close(fig)
    return output


def plot_schedule_heatmap(metrics: dict, output: str,
                          max_rounds: Optional[int] = None) -> str:
    """Rounds x jobs occupancy map from `per_round_schedule`
    (reference: plotting.py per-round schedule heatmaps)."""
    schedule = metrics["per_round_schedule"]
    if max_rounds:
        schedule = schedule[:max_rounds]
    job_ids = sorted({int(j) for rnd in schedule for j in rnd})
    if not job_ids:
        raise ValueError("empty per_round_schedule")
    col = {j: i for i, j in enumerate(job_ids)}
    grid = np.zeros((len(schedule), len(job_ids)))
    for r, rnd in enumerate(schedule):
        for j, worker_ids in rnd.items():
            # Values are the assigned worker-id tuples; plot chip counts.
            grid[r, col[int(j)]] = (len(worker_ids)
                                    if hasattr(worker_ids, "__len__")
                                    else worker_ids)
    fig, ax = plt.subplots(figsize=(6, 4))
    im = ax.imshow(grid.T, aspect="auto", interpolation="nearest",
                   cmap="viridis", origin="lower")
    ax.set_xlabel("round")
    ax.set_ylabel("job")
    fig.colorbar(im, label="chips allocated")
    fig.tight_layout()
    fig.savefig(output, dpi=150)
    plt.close(fig)
    return output


def plot_utilization(results: Dict[str, dict], output: str) -> str:
    """Per-round cluster utilization timeline per policy."""
    fig, ax = plt.subplots(figsize=(6, 3.5))
    for label, metrics in results.items():
        util = metrics.get("utilization_list") or []
        ax.plot(range(len(util)), util, label=label, linewidth=0.9)
    ax.set_xlabel("round")
    ax.set_ylabel("cluster utilization")
    ax.set_ylim(0, 1.05)
    ax.grid(alpha=0.3)
    ax.legend()
    fig.tight_layout()
    fig.savefig(output, dpi=150)
    plt.close(fig)
    return output


def _load(pairs: List[str]) -> Dict[str, dict]:
    results = {}
    for pair in pairs:
        label, path = pair.split("=", 1)
        with open(path, "rb") as f:
            results[label] = pickle.load(f)
    return results


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--metric", required=True,
                   choices=["jct", "ftf", "ftf_themis", "bars", "heatmap",
                            "utilization"])
    p.add_argument("--pickles", nargs="+", required=True,
                   help="label=path pairs of driver metric pickles")
    p.add_argument("--bar_metric", default="makespan")
    p.add_argument("--output", required=True)
    args = p.parse_args()

    results = _load(args.pickles)
    if args.metric == "jct":
        plot_jct_cdf(results, args.output)
    elif args.metric == "ftf":
        plot_ftf_cdf(results, args.output)
    elif args.metric == "ftf_themis":
        plot_ftf_cdf(results, args.output, themis=True)
    elif args.metric == "bars":
        plot_policy_bars(results, args.output, metric=args.bar_metric)
    elif args.metric == "heatmap":
        plot_schedule_heatmap(next(iter(results.values())), args.output)
    elif args.metric == "utilization":
        plot_utilization(results, args.output)
    print(args.output)


if __name__ == "__main__":
    main()
