#!/usr/bin/env python3
"""Fleet-trace smoke drive: one deterministic loopback run with tracing
on, end to end — propagate span context scheduler -> worker, write span
shards, merge them, validate the merged trace's cross-process parent
links, and `explain` every job from the journal.

    python scripts/tests/trace_smoke.py --workdir W --explain_out E.txt

The worker is a deterministic stub (fixed simulated throughput and
execution time, like tests/fault_stub_worker.py) so the drive's journal
— and therefore the round-quantized `obs.explain` output — is a pure
function of the configuration: the CI trace-smoke job runs this twice
and byte-compares the explain outputs. Exit nonzero on any validation
failure (missing shards, disconnected chain, explain coverage < 99%).
"""
import argparse
import json
import os
import re
import socket
import sys
import threading
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__),
                                    "..", ".."))
sys.path.insert(0, REPO)

from shockwave_tpu.core.job import Job  # noqa: E402
from shockwave_tpu.obs import names as obs_names  # noqa: E402
from shockwave_tpu.obs import explain as explain_mod  # noqa: E402
from shockwave_tpu.obs.merge import parent_chain, spans_by_id  # noqa: E402
from shockwave_tpu.obs.shard import ShardSpanWriter  # noqa: E402


def free_port():
    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


class ShardStubWorker:
    """In-process stub daemon with fleet-trace support: consumes the
    propagated RunJob span context, records runjob/done-report spans
    into a worker shard, and reports deterministic progress (fixed
    simulated throughput / execution time)."""

    def __init__(self, sched_port, worker_port, trace_dir, num_chips=1,
                 throughput=100.0, execution_time=0.4):
        from shockwave_tpu.runtime.clients import (
            IteratorToSchedulerClient, WorkerToSchedulerClient)
        from shockwave_tpu.runtime.servers import serve_worker
        self.throughput = throughput
        self.execution_time = execution_time
        self.sched_port = sched_port
        self.shard = ShardSpanWriter(trace_dir, role="worker")
        self._iter_client = IteratorToSchedulerClient
        self._client = WorkerToSchedulerClient("localhost", sched_port)
        self.server = serve_worker(worker_port, {
            "RunJob": self._run_job, "KillJob": self._noop_kill,
            "Reset": self._noop_reset, "Shutdown": self._noop_reset,
        })
        self.worker_ids, self.round_duration = self._client.register_worker(
            "v5e", "127.0.0.1", worker_port, num_chips)

    def _noop_kill(self, job_id):
        pass  # the stub never hosts a killable process

    def _noop_reset(self):
        pass

    def _run_job(self, jobs, worker_id, round_id, trace=None):
        parent, send_ts = trace if trace is not None else (None, None)
        with self.shard.span(
                obs_names.SPAN_RUNJOB, parent=parent, round=round_id,
                worker=worker_id, jobs=[j["job_id"] for j in jobs],
                **({"send_ts": send_ts} if send_ts is not None
                   else {})) as ctx:
            thread = threading.Thread(
                target=self._execute, args=(jobs, worker_id, ctx),
                daemon=True)
            thread.start()

    def _execute(self, jobs, worker_id, parent):
        max_steps = 10**9
        for j in jobs:
            it = self._iter_client(j["job_id"], worker_id, "localhost",
                                   self.sched_port)
            max_steps, _, _ = it.init()
        time.sleep(self.execution_time)
        steps = [min(int(self.throughput * self.round_duration),
                     j["num_steps"], int(max_steps)) for j in jobs]
        with self.shard.span(obs_names.SPAN_DONE_REPORT, parent=parent,
                             jobs=[j["job_id"] for j in jobs]):
            self._client.notify_done(
                [j["job_id"] for j in jobs], worker_id, steps,
                [self.execution_time] * len(jobs))
        self.shard.flush()

    def stop(self):
        self.shard.flush()
        self.server.stop(grace=0)


def run_drive(workdir, num_jobs, round_duration, max_rounds):
    from shockwave_tpu.sched.physical import PhysicalScheduler
    from shockwave_tpu.sched.scheduler import SchedulerConfig
    from shockwave_tpu.solver import get_policy
    trace_dir = os.path.join(workdir, "trace")
    state_dir = os.path.join(workdir, "state")
    sched_port, worker_port = free_port(), free_port()
    sched = PhysicalScheduler(
        get_policy("max_min_fairness"),
        throughputs_file=os.path.join(REPO,
                                      "data/tacc_throughputs.json"),
        config=SchedulerConfig(
            time_per_iteration=round_duration, max_rounds=max_rounds,
            state_dir=state_dir, snapshot_interval_rounds=10_000,
            obs_trace_dir=trace_dir, history={}),
        expected_num_workers=1, port=sched_port)
    worker = ShardStubWorker(sched_port, worker_port, trace_dir)
    job_ids = []
    try:
        for i in range(num_jobs):
            job_ids.append(sched.add_job(Job(
                None, "ResNet-18 (batch size 32)",
                "python3 main.py --batch_size 32",
                "image_classification/cifar10", "--num_steps",
                total_steps=200 * (i + 2), duration=100000)))
        runner = threading.Thread(target=sched.run, daemon=True)
        runner.start()
        deadline = time.time() + 30 * round_duration
        while (time.time() < deadline
               and len(sched._completed_jobs) < num_jobs):
            time.sleep(0.2)
        if len(sched._completed_jobs) < num_jobs:
            raise SystemExit(
                f"drive incomplete: {len(sched._completed_jobs)}/"
                f"{num_jobs} jobs finished")
    finally:
        sched._done_event.set()
        worker.stop()
        sched.shutdown()
        sched._server.stop(grace=0)
    return trace_dir, state_dir, [j.integer_job_id() for j in job_ids]


def validate_trace(trace_dir):
    """The merged trace must exist, parse, and carry at least one
    worker-side runjob span whose parent chain reaches the scheduler's
    round root across the process boundary."""
    merged_path = os.path.join(trace_dir, obs_names.MERGED_TRACE_NAME)
    with open(merged_path) as f:
        merged = json.load(f)
    events = merged["traceEvents"]
    index = spans_by_id(events)
    runjobs = [e for e in events
               if e.get("name") == obs_names.SPAN_RUNJOB
               and (e.get("args") or {}).get("role") == "worker"]
    if not runjobs:
        raise SystemExit("merged trace has no worker runjob spans")
    connected = 0
    for e in runjobs:
        chain = parent_chain(index, e)
        roles = [(c.get("args") or {}).get("role") for c in chain]
        names_ = [c.get("name") for c in chain]
        if ("scheduler" in roles
                and obs_names.SPAN_ROUND in names_):
            connected += 1
    if connected == 0:
        raise SystemExit("no worker runjob span chains to a scheduler "
                         "round root — propagation is broken")
    return {"merged": merged_path, "spans": len(events),
            "runjob_spans": len(runjobs), "connected": connected}


def explain_jobs(state_dir, job_ids):
    """Stable explain output for every job, concatenated; asserts the
    >=99% coverage acceptance line per job."""
    events = explain_mod.read_all_events(state_dir)
    chunks = []
    for int_id in job_ids:
        tl = explain_mod.build_timeline(events, int_id)
        text = explain_mod.render(tl)
        m = re.search(r"total\s+\d+\s+([0-9.]+)%", text)
        if m is None or float(m.group(1)) < 99.0:
            raise SystemExit(
                f"explain coverage below 99% for job {int_id}:\n{text}")
        chunks.append(text)
    return "\n\n".join(chunks) + "\n"


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--workdir", required=True)
    p.add_argument("--explain_out", required=True,
                   help="file the byte-stable explain output is "
                        "written to (CI cmp's two runs)")
    p.add_argument("--num_jobs", type=int, default=2)
    p.add_argument("--round_duration", type=float, default=2.0)
    p.add_argument("--max_rounds", type=int, default=12)
    args = p.parse_args()

    os.makedirs(args.workdir, exist_ok=True)
    trace_dir, state_dir, job_ids = run_drive(
        args.workdir, args.num_jobs, args.round_duration,
        args.max_rounds)
    summary = validate_trace(trace_dir)
    explain_text = explain_jobs(state_dir, job_ids)
    with open(args.explain_out, "w") as f:
        f.write(explain_text)
    print(json.dumps({**summary, "jobs": job_ids,
                      "explain_out": args.explain_out}))


if __name__ == "__main__":
    main()
