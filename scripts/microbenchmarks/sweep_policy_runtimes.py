#!/usr/bin/env python3
"""Policy solve-time scaling microbenchmark.

Times `policy.get_allocation` over a grid of (num_jobs, cluster size)
with realistic throughput spreads, answering "how expensive is each
LP/MILP as the cluster grows" — the per-round scheduling overhead
(reference: scheduler/scripts/microbenchmarks/sweep_policy_runtimes.py).

Example:
    python scripts/microbenchmarks/sweep_policy_runtimes.py \
        --policies max_min_fairness finish_time_fairness isolated \
        --num_jobs 16 64 128 --cluster_sizes 16 64
"""
import argparse
import json
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.core.job import JobIdPair
from shockwave_tpu.obs import Observability
from shockwave_tpu.obs import names as obs_names
from shockwave_tpu.obs.clock import perf_clock
from shockwave_tpu.solver import get_policy

# Multi-worker-type throughput spread: jobs run fastest on the first
# type, mirroring the v100/p100/k80 spreads in the shipped oracle.
TYPE_SPEEDUPS = {"v100": 1.0, "p100": 0.55, "k80": 0.25}


def synth_state(num_jobs, cluster_size, num_worker_types, seed):
    rng = random.Random(seed)
    worker_types = list(TYPE_SPEEDUPS)[:num_worker_types]
    job_ids = [JobIdPair(i) for i in range(num_jobs)]
    throughputs, scale_factors, priorities = {}, {}, {}
    for j in job_ids:
        base = rng.uniform(0.5, 50.0)
        throughputs[j] = {wt: base * TYPE_SPEEDUPS[wt] for wt in worker_types}
        scale_factors[j] = rng.choices([1, 2, 4, 8],
                                       weights=[0.7, 0.1, 0.15, 0.05])[0]
        priorities[j] = 1.0
    per_type = max(1, cluster_size // num_worker_types)
    cluster = {wt: per_type for wt in worker_types}
    return throughputs, scale_factors, priorities, cluster


def time_policy(obs, policy_name, num_jobs, cluster_size,
                num_worker_types, trials, seed):
    """Times each solve through the obs pipeline (one span + one
    histogram observation per trial) instead of an ad-hoc clock loop,
    so the sweep's numbers come from the same instrumentation the
    scheduler itself reports."""
    # Slice the tracer buffer from here: a repeated sweep combination
    # (e.g. --num_jobs 64 64) must not fold earlier calls' spans into
    # this call's min/mean.
    events_before = len(obs.tracer.events())
    for t in range(trials):
        throughputs, sfs, prios, cluster = synth_state(
            num_jobs, cluster_size, num_worker_types, seed + t)
        policy = get_policy(policy_name, seed=seed + t)
        times_since_start = {j: 0.0 for j in sfs}
        num_steps = {j: 10000 for j in sfs}
        with obs.span(obs_names.SPAN_POLICY_SOLVE, policy=policy_name,
                      num_jobs=num_jobs, cluster_size=cluster_size,
                      trial=t), \
                obs.timed(obs_names.POLICY_SOLVE_SECONDS,
                          policy=policy_name):
            if policy_name == "proportional":
                policy.get_allocation(throughputs, cluster)
            elif policy_name in ("isolated", "isolated_plus", "gandiva",
                                 "gandiva_fair") \
                    or policy_name.startswith("fifo"):
                policy.get_allocation(throughputs, sfs, cluster)
            elif policy_name.startswith("allox"):
                policy.get_allocation(throughputs, sfs, times_since_start,
                                      num_steps, [], cluster)
            elif policy_name.startswith("min_total_duration"):
                policy.get_allocation(throughputs, sfs, num_steps, cluster)
            elif policy_name == "max_sum_throughput_perf":
                policy.get_allocation(throughputs, sfs, cluster)
            elif policy_name.startswith("max_sum_throughput"):
                policy.get_allocation(throughputs, sfs, cluster,
                                      num_steps_remaining=num_steps)
            elif policy_name.startswith("finish_time_fairness"):
                policy.get_allocation(throughputs, sfs, prios,
                                      times_since_start, num_steps, cluster)
            else:
                policy.get_allocation(throughputs, sfs, prios, cluster)
    times = [e["dur"] for e in obs.tracer.events()[events_before:]
             if e["name"] == obs_names.SPAN_POLICY_SOLVE]
    return min(times), sum(times) / len(times)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--policies", nargs="*", default=[
        "isolated", "max_min_fairness", "max_min_fairness_perf",
        "finish_time_fairness", "min_total_duration",
        "max_sum_throughput_perf", "gandiva", "fifo"])
    p.add_argument("--num_jobs", nargs="*", type=int, default=[16, 64, 128])
    p.add_argument("--cluster_sizes", nargs="*", type=int, default=[16, 64])
    p.add_argument("--num_worker_types", type=int, default=1)
    p.add_argument("--trials", type=int, default=3)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--output", default=None, help="JSON results path")
    p.add_argument("--trace_out", default=None, metavar="TRACE_JSON",
                   help="export the per-trial solve spans as "
                        "Chrome-trace JSON")
    p.add_argument("--metrics_out", default=None, metavar="PROM_TXT",
                   help="dump the solve-time histograms as Prometheus "
                        "text")
    args = p.parse_args()

    # Force-enabled local bundle on the perf clock: a benchmark must
    # measure even when the ambient SWTPU_OBS=0 disables production
    # telemetry.
    obs = Observability(clock=perf_clock, enabled=True)
    results = []
    for policy_name in args.policies:
        for n in args.num_jobs:
            for c in args.cluster_sizes:
                best, mean = time_policy(obs, policy_name, n, c,
                                         args.num_worker_types,
                                         args.trials, args.seed)
                row = {"policy": policy_name, "num_jobs": n,
                       "cluster_size": c, "best_s": round(best, 4),
                       "mean_s": round(mean, 4)}
                results.append(row)
                print(json.dumps(row), flush=True)
    if args.output:
        with open(args.output, "w") as f:
            json.dump(results, f, indent=1)
    if args.trace_out:
        obs.tracer.export_chrome_trace(args.trace_out)
    if args.metrics_out:
        with open(args.metrics_out, "w") as f:
            f.write(obs.registry.render_prometheus())


if __name__ == "__main__":
    main()
