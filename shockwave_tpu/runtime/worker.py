"""Worker daemon: registers this host's TPU chips with the scheduler and
dispatches training jobs onto them (reference: scheduler/worker.py).

Usage:
    python -m shockwave_tpu.runtime.worker \
        --worker_type v5e --sched_addr 10.0.0.2 --sched_port 50070 \
        --worker_port 50061 --run_dir workloads/ --checkpoint_dir /nfs/ckpt
"""
from __future__ import annotations

import argparse
import logging
import os
import signal
import threading
import time

import grpc

from ..obs import get_observability
from ..obs import names as obs_names
from ..obs.logconfig import LEVELS, setup_logging
from . import resilience
from .clients import WorkerToSchedulerClient
from .dispatcher import Dispatcher
from .servers import get_host_ip, serve_worker

logger = logging.getLogger("shockwave_tpu.runtime")

REGISTER_RETRY_WINDOW_S = 300.0
REGISTER_RETRY_INTERVAL_S = 5.0


def detect_num_chips() -> int:
    try:
        import jax
        return len(jax.devices())
    except Exception:  # noqa: BLE001 - no accelerator runtime available
        return 0


class WorkerDaemon:
    #: Written by RunJob handlers (gRPC pool threads), read by the obs
    #: exporter's request thread (/healthz) — guarded by the daemon's
    #: leaf lock. Surfaced by the race-detector pass.
    _LOCK_PROTECTED = frozenset({"_last_dispatch_time"})

    def __init__(self, worker_type: str, sched_addr: str, sched_port: int,
                 worker_port: int, num_chips: int, run_dirs: dict,
                 data_dir: str, checkpoint_dir: str,
                 obs_port: int = None, trace_dir: str = None):
        from ..analysis.sanitizer import maybe_wrap
        self._shutdown_event = threading.Event()
        self._lock = maybe_wrap(threading.Lock(), "WorkerDaemon._lock")
        self._obs = get_observability()
        self._obs_server = None
        if obs_port is not None:
            from ..obs.exporter import ObsHttpServer
            self._obs_server = ObsHttpServer(
                self._obs.registry, health_fn=self._obs_health,
                port=obs_port).start()
        self._worker_type = worker_type
        self._last_dispatch_time = 0.0
        # Fleet tracing (opt-in): this daemon's bounded span shard in
        # the drive's trace directory; scheduler-propagated span
        # contexts (RunJob metadata) parent this daemon's runjob/launch
        # spans, and the dispatcher forwards them into trainers.
        from . import spans
        self._trace_dir = trace_dir or spans.trace_dir_from_env()
        self._span_shard = spans.init_process_shard(self._trace_dir,
                                                    role="worker")
        self._rpc_client = WorkerToSchedulerClient(sched_addr, sched_port)

        # Control-plane HA: reject dispatches from a deposed leader
        # (stale epoch -> FAILED_PRECONDITION via the server fence) and
        # chase a promoted one (advanced epoch -> re-resolve the
        # scheduler endpoint / reset breakers before its work runs).
        self._fence = resilience.EpochFence()

        callbacks = {
            "RunJob": self._run_job,
            "KillJob": self._kill_job,
            "Reset": self._reset,
            "Shutdown": self._shutdown,
        }
        self._server = serve_worker(worker_port, callbacks,
                                    fence=self._fence,
                                    on_epoch_advance=self._on_epoch_advance)

        # Daemons race the scheduler at cluster bring-up (and the
        # scheduler may spend a minute importing before its server
        # listens), so registration retries with backoff instead of
        # dying on the first connection refusal.
        deadline = time.monotonic() + REGISTER_RETRY_WINDOW_S
        while True:
            try:
                worker_ids, round_duration = self._rpc_client.register_worker(
                    worker_type=worker_type, ip_addr=get_host_ip(),
                    port=worker_port, num_chips=num_chips)
                break
            except grpc.RpcError as e:
                # Registration now carries a per-attempt deadline, so a
                # stalled (not just absent) scheduler surfaces as
                # DEADLINE_EXCEEDED — retry both transport codes.
                if (not resilience.is_retryable(e)
                        or time.monotonic() >= deadline):
                    # Don't leave the control server listening on a
                    # half-constructed daemon (its handlers dereference
                    # a dispatcher that was never built).
                    self._server.stop(grace=0)
                    raise
                logger.info("scheduler at %s:%d unavailable; retrying",
                            sched_addr, sched_port)
                time.sleep(REGISTER_RETRY_INTERVAL_S)
        logger.info("registered %d chips as workers %s (round %.0fs)",
                    num_chips, worker_ids, round_duration)
        self._worker_ids = worker_ids
        # Done may legitimately block at the scheduler until the round
        # boundary (early finisher); its deadline must cover a round.
        self._rpc_client.stretch_done_deadline(round_duration + 60.0)

        os.makedirs(checkpoint_dir, exist_ok=True)
        self._dispatcher = Dispatcher(
            round_duration, chip_ids=list(range(num_chips)),
            worker_rpc_client=self._rpc_client, sched_addr=sched_addr,
            sched_port=sched_port, run_dirs=run_dirs, data_dir=data_dir,
            checkpoint_dir=checkpoint_dir,
            span_shard=self._span_shard, trace_dir=self._trace_dir)

    def _on_epoch_advance(self, epoch: int) -> None:
        """A new leader's first dispatch reached this daemon: point the
        report channel at it before the dispatched work needs to Done
        (the client also self-heals lazily on its next failure, but the
        eager refresh saves the first post-failover report a full
        failover-retry loop)."""
        logger.warning("leader epoch advanced to %d; re-resolving "
                       "scheduler endpoint", epoch)
        self._rpc_client.refresh_endpoint()

    def _obs_health(self) -> dict:
        with self._lock:
            last_dispatch = self._last_dispatch_time
        return {
            "worker_type": self._worker_type,
            "worker_ids": list(getattr(self, "_worker_ids", [])),
            "leader_epoch_seen": self._fence.epoch,
            "last_dispatch_age_s": round(
                time.time() - last_dispatch, 3)
            if last_dispatch else None,
        }

    def _run_job(self, jobs, worker_id, round_id, trace=None):
        # Worker-side dispatch heartbeat: a daemon that stops receiving
        # RunJobs (partitioned, or starved by the scheduler) shows up as
        # a growing age on this stamp.
        now = time.time()
        with self._lock:
            self._last_dispatch_time = now
        self._obs.inc(obs_names.WORKER_JOBS_DISPATCHED_TOTAL)
        self._obs.set_gauge(obs_names.WORKER_LAST_DISPATCH_TIMESTAMP, now)
        parent, send_ts = trace if trace is not None else (None, None)
        if self._span_shard is not None:
            # The runjob span records this host's RECEIVE stamp beside
            # the scheduler's send stamp — the RPC timestamp pair the
            # merge aligns per-host clocks from. The launch span (the
            # trainer process's lifetime) is the dispatcher's.
            with self._span_shard.span(
                    obs_names.SPAN_RUNJOB, parent=parent,
                    round=round_id, worker=worker_id,
                    jobs=[j["job_id"] for j in jobs],
                    **({"send_ts": send_ts} if send_ts is not None
                       else {})) as ctx:
                self._dispatcher.dispatch_jobs(jobs, worker_id, round_id,
                                               trace_parent=ctx)
        else:
            self._dispatcher.dispatch_jobs(jobs, worker_id, round_id)

    def _kill_job(self, job_id):
        self._dispatcher.kill_job(job_id)

    def _reset(self):
        self._dispatcher.reset()

    def _shutdown(self):
        self._dispatcher.shutdown()
        self._shutdown_event.set()

    def join(self):
        self._shutdown_event.wait()
        self._server.stop(grace=1)
        if self._span_shard is not None:
            from . import spans
            spans.flush()
        if self._obs_server is not None:
            self._obs_server.stop()


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--worker_type", "-t", default="v5e")
    p.add_argument("--sched_addr", "-i", required=True)
    p.add_argument("--sched_port", "-s", type=int, default=50070)
    p.add_argument("--worker_port", "-w", type=int, default=50061)
    p.add_argument("--num_chips", "-g", type=int, default=None,
                   help="default: autodetect via jax.devices()")
    p.add_argument("--static_run_dir", default="shockwave_tpu/workloads")
    p.add_argument("--accordion_run_dir", default="shockwave_tpu/workloads")
    p.add_argument("--gns_run_dir", default="shockwave_tpu/workloads")
    p.add_argument("--data_dir", default=None)
    p.add_argument("--checkpoint_dir", default="/tmp/swtpu_checkpoints")
    p.add_argument("--obs_port", type=int, default=None,
                   help="serve /metrics + /healthz for this daemon "
                        "(0 = ephemeral port; default disabled)")
    p.add_argument("--trace_dir", default=None,
                   help="directory this daemon (and its trainer "
                        "subprocesses) write span shards into; merge "
                        "with python -m shockwave_tpu.obs.merge "
                        "(default: $SWTPU_SPAN_SHARD_DIR, else "
                        "disabled)")
    p.add_argument("--log_level", default="info", choices=LEVELS)
    args = p.parse_args(argv)

    setup_logging(args.log_level)

    num_chips = args.num_chips if args.num_chips is not None else detect_num_chips()
    if num_chips <= 0:
        raise RuntimeError("no accelerator chips detected; pass --num_chips")

    daemon = WorkerDaemon(
        worker_type=args.worker_type, sched_addr=args.sched_addr,
        sched_port=args.sched_port, worker_port=args.worker_port,
        num_chips=num_chips,
        run_dirs={"static": args.static_run_dir,
                  "accordion": args.accordion_run_dir,
                  "gns": args.gns_run_dir,
                  # Serving replicas (workloads/serving/serve.py) live
                  # in the same tree as the static training scripts.
                  "serving": args.static_run_dir},
        data_dir=args.data_dir, checkpoint_dir=args.checkpoint_dir,
        obs_port=args.obs_port, trace_dir=args.trace_dir)
    signal.signal(signal.SIGINT, lambda s, f: daemon._shutdown())
    daemon.join()


if __name__ == "__main__":
    main()
