"""Fleet-scale simulation: vectorized sim core + Monte Carlo sweep.

Bit-identical-replay regression suite (scalar reference oracle vs the
vectorized passes in sched/simcore.py) across every policy in
reproduce/pickles plus the serving mixed trace, the GNS point-query
equivalence, deterministic fault injection, and the sweep harness's
byte-equal-artifact / resume / crash-safety contracts.
"""
import json
import os
import pickle
import subprocess
import sys

import pytest

from shockwave_tpu.core.adaptation import (_GNS_SEGMENTS, gns_bs_at,
                                           gns_bs_schedule)
from shockwave_tpu.core.oracle import read_throughputs
from shockwave_tpu.core.profiles import build_profiles
from shockwave_tpu.core.trace import parse_trace
from shockwave_tpu.sched import Scheduler, SchedulerConfig
from shockwave_tpu.solver import get_policy

REPO = os.path.join(os.path.dirname(__file__), "..")
DATA = os.path.join(REPO, "data")
TRACE = os.path.join(DATA, "canonical_120job.trace")
SERVING_TRACE = os.path.join(DATA, "serving_mixed.trace")
THROUGHPUTS = os.path.join(DATA, "tacc_throughputs.json")
SWEEP_DRIVER = os.path.join(REPO, "scripts", "drivers",
                            "sweep_scenarios.py")

#: Every policy with a canonical result pickle in reproduce/pickles/.
PICKLE_POLICIES = ("max_min_fairness", "gandiva_fair", "allox",
                   "max_sum_throughput_perf", "min_total_duration",
                   "finish_time_fairness", "shockwave")


def run_replay(policy, *, vectorized, trace=TRACE, max_jobs=None,
               max_rounds=None, config=None, seed=0):
    """One in-process replay; returns a picklable result bundle with no
    wall-clock telemetry (SolveStats wall fields are stripped)."""
    jobs, arrivals = parse_trace(trace)
    if max_jobs is not None:
        jobs, arrivals = jobs[:max_jobs], arrivals[:max_jobs]
    throughputs = read_throughputs(THROUGHPUTS)
    profiles = build_profiles(jobs, throughputs)
    shockwave_config = None
    serving_config = None
    if config is not None:
        with open(config) as f:
            shockwave_config = json.load(f)
        serving_config = shockwave_config.pop("serving", None)
    elif policy == "shockwave":
        shockwave_config = {}
    if shockwave_config is not None:
        shockwave_config["num_gpus"] = 32
        shockwave_config["time_per_iteration"] = 120.0
    sched = Scheduler(
        get_policy(policy, seed=seed), simulate=True,
        throughputs_file=THROUGHPUTS, profiles=profiles,
        config=SchedulerConfig(
            time_per_iteration=120.0, seed=seed, max_rounds=max_rounds,
            shockwave=shockwave_config, serving=serving_config,
            vectorized_sim=vectorized))
    makespan = sched.simulate({"v100": 32}, arrivals, jobs)
    solve_stats = [{k: v for k, v in s.items()
                    if k not in ("wall_s", "assembly_s")}
                   for s in sched.get_solve_stats()]
    return {
        "makespan": makespan,
        "jct": sched.get_average_jct(),
        "ftf": sched.get_finish_time_fairness(),
        "util": sched.get_cluster_utilization(),
        "rounds": sched.rounds.num_completed_rounds,
        "per_round_schedule": sched.rounds.per_round_schedule,
        "timelines": sched._job_timelines,
        "solve_stats": solve_stats,
        "serving": sched.serving_summary(),
    }


class TestScalarVectorizedParity:
    """The acceptance gate: scalar oracle == vectorized passes, to the
    pickle byte. Tier-1 runs subsampled replays across every canonical
    policy; the slow suite replays the full canonical trace."""

    @pytest.mark.parametrize("policy", PICKLE_POLICIES)
    def test_subsampled_replay_bit_identical(self, policy):
        kwargs = dict(max_jobs=25, max_rounds=40)
        if policy == "shockwave":
            kwargs["config"] = os.path.join(REPO, "configs",
                                            "tacc_32gpus.json")
        a = run_replay(policy, vectorized=False, **kwargs)
        b = run_replay(policy, vectorized=True, **kwargs)
        assert pickle.dumps(a) == pickle.dumps(b)

    def test_serving_mixed_replay_bit_identical(self):
        config = os.path.join(REPO, "configs", "serving_mixed.json")
        a = run_replay("max_min_fairness", vectorized=False,
                       trace=SERVING_TRACE, config=config, max_rounds=40)
        b = run_replay("max_min_fairness", vectorized=True,
                       trace=SERVING_TRACE, config=config, max_rounds=40)
        assert pickle.dumps(a) == pickle.dumps(b)
        assert b["serving"] is not None

    @pytest.mark.slow
    @pytest.mark.parametrize("policy", PICKLE_POLICIES)
    def test_full_canonical_replay_bit_identical(self, policy):
        kwargs = {}
        if policy == "shockwave":
            kwargs["config"] = os.path.join(REPO, "configs",
                                            "tacc_32gpus.json")
        a = run_replay(policy, vectorized=False, **kwargs)
        b = run_replay(policy, vectorized=True, **kwargs)
        assert pickle.dumps(a) == pickle.dumps(b)

    def test_canonical_values_pinned(self):
        """The vectorized max_min subsample of the canonical replay is
        deterministic run to run (same process)."""
        a = run_replay("max_min_fairness", vectorized=True, max_jobs=25,
                       max_rounds=40)
        b = run_replay("max_min_fairness", vectorized=True, max_jobs=25,
                       max_rounds=40)
        assert pickle.dumps(a) == pickle.dumps(b)


class TestGnsPointQuery:
    """gns_bs_at must agree with the full memoized schedule for every
    profiled (model, bs, scale_factor) segment table, including the
    first-segment-only final-epoch rule and the MAX_BS cap."""

    @pytest.mark.parametrize("key", sorted(_GNS_SEGMENTS))
    def test_matches_full_schedule(self, key):
        model, bs0, sf = key
        for num_epochs in (1, 5, 40, 120, 763):
            schedule = gns_bs_schedule(model, bs0, num_epochs, sf)
            for epoch in range(num_epochs):
                assert gns_bs_at(model, bs0, num_epochs, sf, epoch) == \
                    schedule[epoch], (key, num_epochs, epoch)

    def test_non_adaptive_model(self):
        assert gns_bs_at("Transformer", 32, 100, 1, 50) == 32


def make_job(total_steps=20000, scale_factor=1):
    from shockwave_tpu.core.job import Job
    return Job(None, "ResNet-18 (batch size 32)",
               "python3 main.py --batch_size 32",
               "image_classification/cifar10", "--num_steps",
               total_steps=total_steps, duration=2000,
               scale_factor=scale_factor)


class TestFaultInjection:
    """simulate(fault_events=...): deterministic chip kill/revive at
    round boundaries — the sweep's failure-scenario hook."""

    def _run(self, fault_events=None, num_jobs=4, num_workers=4):
        sched = Scheduler(
            get_policy("max_min_fairness", seed=0), simulate=True,
            throughputs_file=THROUGHPUTS,
            config=SchedulerConfig(time_per_iteration=120.0))
        jobs = [make_job() for _ in range(num_jobs)]
        makespan = sched.simulate({"v100": num_workers},
                                  [0.0] * num_jobs, jobs,
                                  fault_events=fault_events)
        return sched, makespan

    def test_kill_shrinks_capacity_and_slows_completion(self):
        _, base = self._run()
        sched, slow = self._run(fault_events=[
            {"at": 100.0, "kill": [0, 1]},
            {"at": 8000.0, "revive": [0, 1], "worker_type": "v100"}])
        assert len(sched._completed_jobs) == 4
        assert slow > base  # two of four chips lost for most of the run
        from shockwave_tpu.obs import names as obs_names
        assert sched.obs.registry.value(
            obs_names.SIM_FAULT_EVENTS_TOTAL, action="kill") == 1

    def test_revive_restores_capacity(self):
        sched, _ = self._run(fault_events=[
            {"at": 100.0, "kill": [2, 3]},
            {"at": 400.0, "revive": [2, 3], "worker_type": "v100"}])
        assert sched.workers.cluster_spec["v100"] == 4
        assert not sched.workers.dead

    def test_all_chips_down_waits_for_revive(self):
        """With every chip dead the sim must advance to the revive
        event instead of declaring deadlock."""
        sched, _ = self._run(fault_events=[
            {"at": 100.0, "kill": [0, 1, 2, 3]},
            {"at": 2000.0, "revive": [0, 1, 2, 3],
             "worker_type": "v100"}])
        assert len(sched._completed_jobs) == 4

    def test_deterministic(self):
        events = [{"at": 150.0, "kill": [1]},
                  {"at": 3000.0, "revive": [1], "worker_type": "v100"}]
        _, a = self._run(fault_events=list(events))
        _, b = self._run(fault_events=list(events))
        assert a == b


def run_sweep(out, num_scenarios=4, processes=2, extra=()):
    from conftest import cpu_subprocess_env
    cmd = [sys.executable, SWEEP_DRIVER,
           "--trace", TRACE, "--policy", "max_min_fairness",
           "--throughputs", THROUGHPUTS, "--cluster_spec", "v100:32",
           "--round_duration", "120",
           "--num_scenarios", str(num_scenarios),
           "--processes", str(processes),
           "--subsample", "0.1:0.2", "--load_scale", "0.8:1.2",
           "--arrival_jitter_s", "300", "--fault_rate", "1",
           "--out", out, *extra]
    res = subprocess.run(cmd, capture_output=True, text=True, cwd=REPO,
                         timeout=600, env=cpu_subprocess_env())
    assert res.returncode == 0, res.stderr[-2000:]
    return json.loads(res.stdout.strip().splitlines()[-1])


class TestSweepHarness:
    def test_byte_equal_artifacts_across_process_counts(self, tmp_path):
        """Same seeds -> byte-equal artifact, regardless of pool size
        or completion order."""
        a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        run_sweep(a, processes=1)
        run_sweep(b, processes=4)
        assert open(a, "rb").read() == open(b, "rb").read()

    def test_artifact_schema_and_aggregate(self, tmp_path):
        out = str(tmp_path / "sweep.json")
        summary = run_sweep(out)
        assert summary["completed"] == 4
        doc = json.load(open(out))
        assert set(doc) == {"schema", "meta", "scenarios", "aggregate"}
        assert len(doc["scenarios"]) == 4
        for record in doc["scenarios"].values():
            assert "summary" in record and "params" in record
            assert record["summary"]["makespan"] > 0
        agg = doc["aggregate"]
        assert agg["num_ok"] == 4 and agg["num_failed"] == 0
        assert {"p10", "p50", "p90", "p99", "mean"} <= set(
            agg["makespan"])

    def test_resume_skips_completed_seeds(self, tmp_path):
        out = str(tmp_path / "sweep.json")
        run_sweep(out, num_scenarios=2)
        summary = run_sweep(out, num_scenarios=4)
        assert summary["skipped_existing"] == 2
        assert summary["completed"] == 4
        # Extending a sweep yields the identical artifact a fresh
        # 4-scenario run produces (resume is content-transparent).
        fresh = str(tmp_path / "fresh.json")
        run_sweep(fresh, num_scenarios=4)
        assert open(out, "rb").read() == open(fresh, "rb").read()

    def test_meta_mismatch_refuses_resume(self, tmp_path):
        out = str(tmp_path / "sweep.json")
        run_sweep(out, num_scenarios=2)
        from conftest import cpu_subprocess_env
        res = subprocess.run(
            [sys.executable, SWEEP_DRIVER, "--trace", TRACE,
             "--policy", "max_min_fairness",
             "--throughputs", THROUGHPUTS, "--cluster_spec", "v100:32",
             "--round_duration", "120", "--num_scenarios", "2",
             "--subsample", "0.5:0.6",  # different knobs
             "--out", out],
            capture_output=True, text=True, cwd=REPO, timeout=600,
            env=cpu_subprocess_env())
        assert res.returncode != 0
        assert "different sweep parameters" in res.stderr

    def test_sweep_config_defaults(self, tmp_path):
        cfg = tmp_path / "sweep_cfg.json"
        cfg.write_text(json.dumps({
            "trace": TRACE, "policy": "max_min_fairness",
            "throughputs": THROUGHPUTS, "cluster_spec": "v100:32",
            "round_duration": 120.0, "num_scenarios": 2,
            "subsample": "0.1:0.2"}))
        out = str(tmp_path / "sweep.json")
        from conftest import cpu_subprocess_env
        res = subprocess.run(
            [sys.executable, SWEEP_DRIVER, "--sweep_config", str(cfg),
             "--out", out],
            capture_output=True, text=True, cwd=REPO, timeout=600,
            env=cpu_subprocess_env())
        assert res.returncode == 0, res.stderr[-2000:]
        assert json.load(open(out))["aggregate"]["num_ok"] == 2


class TestBenchSimRound:
    def test_smoke(self, tmp_path):
        """The microbenchmark's CI gate: identical assignments on both
        paths and the speedup floor at the largest smoke grid point."""
        from conftest import cpu_subprocess_env
        res = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "microbenchmarks",
                          "bench_sim_round.py"),
             "--smoke", "--rounds", "5", "--min_speedup", "2.0",
             "--metrics_out", str(tmp_path / "prom.txt")],
            capture_output=True, text=True, cwd=REPO, timeout=900,
            env=cpu_subprocess_env())
        assert res.returncode == 0, (res.stdout + res.stderr)[-2000:]
        rows = [json.loads(line)
                for line in res.stdout.strip().splitlines()]
        assert all(r.get("assignments_equal", r.get("bit_identical"))
                   for r in rows)
        prom = (tmp_path / "prom.txt").read_text()
        assert "swtpu_sim_round_core_seconds" in prom
