"""Lock-discipline annotations shared by the static analyzer and the
runtime sanitizer.

Convention (enforced by ``python -m shockwave_tpu.analysis``, pass
``lock-discipline``, and spot-checked at runtime by
``analysis/sanitizer.py`` when ``SWTPU_SANITIZE=1``):

- A class declares the attribute names that must only be touched while
  holding ``self._lock`` in a class-level ``_LOCK_PROTECTED`` frozenset.
- A method that touches protected state but does not take the lock
  itself is annotated ``@requires_lock``: its contract is that every
  caller already holds ``self._lock`` (or the condition variable built
  on it). The static pass treats the method body as lock-covered; the
  sanitizer verifies the contract on entry when enabled.

``requires_lock`` is free when the sanitizer is off apart from one env
lookup — no lock operations, no tracebacks — so annotating hot-path
helpers costs nothing in production.
"""
from __future__ import annotations

import functools


def _lock_owned(lock) -> bool:
    """Best-effort ownership check for RLocks and the sanitizer's
    instrumented wrapper (both expose ``_is_owned``); objects without
    it (plain Lock) are unverifiable and count as owned."""
    probe = getattr(lock, "_is_owned", None)
    if probe is None:
        return True
    try:
        return bool(probe())
    except Exception:  # noqa: BLE001 - a broken probe must not fail the call
        return True


def requires_lock(fn):
    """Mark `fn` as "caller must hold ``self._lock``".

    The marker is what the static lock-discipline pass keys on; the
    wrapper additionally reports a violation to the concurrency
    sanitizer when ``SWTPU_SANITIZE=1`` and the receiver's lock is not
    held at entry (recorded, not raised — the report surfaces at test
    teardown with the offending qualname)."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        from ..analysis import sanitizer
        if sanitizer.enabled():
            lock = getattr(self, "_lock", None)
            if lock is not None and not _lock_owned(lock):
                sanitizer.monitor().record_unowned(
                    f"{type(self).__name__}.{fn.__name__}")
        return fn(self, *args, **kwargs)

    wrapper.__swtpu_requires_lock__ = True
    return wrapper
