"""Whole-tree thread-root discovery and the static call graph.

The concurrency passes need to know *which code runs on which thread*.
This module walks the indexed tree once and answers two questions:

1. **Where do threads start?** (`discover_thread_roots`) Every
   ``threading.Thread(target=...)`` / ``threading.Timer(..., cb)``
   spawn, every ``ThreadingHTTPServer`` request-handler class (its
   ``do_*`` methods run on per-request threads), every gRPC servicer
   callback (the dict handed to ``serve_scheduler``/``serve_worker`` —
   each value runs on a server-pool thread), and every callable handed
   to a component that invokes it from its own thread (the
   ``health_fn``/``history_fn`` exporter callbacks, the HA
   ``on_fenced`` hook). A spawn whose target the resolver cannot pin to
   a function in the tree is itself a finding (pass ``thread-roots``):
   code the race detector cannot see behind is an unchecked thread.

2. **What does each thread reach?** (`CallGraph`) An AST-level
   call graph over the indexed tree: ``self.m()`` resolves through the
   class hierarchy, ``self.attr.m()`` and local-variable calls resolve
   through constructor-assignment type inference
   (``self.attr = ClassName(...)`` / ``ClassName.from_config(...)`` /
   annotations), bare names resolve to local/nested/module functions.
   Reachability from each discovered root gives the race detector its
   thread-entry -> reachable-methods map.

The resolver is deliberately modest: dynamic dispatch through unknown
callables (e.g. the return value of ``fork.thaw``) is not followed.
That keeps detached-twin rollouts — objects constructed *inside* a
thread and never shared — out of the cross-thread state, which is the
behavior a lockset analysis wants.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .core import Finding, RepoIndex, SourceFile, call_name, const_str, finding

#: Spawn call sites whose argument is a new thread's entry point.
THREAD_SPAWN_CALLS = frozenset({"threading.Thread", "Thread"})
TIMER_SPAWN_CALLS = frozenset({"threading.Timer", "Timer"})
HTTP_SERVER_CALLS = frozenset({"ThreadingHTTPServer",
                               "http.server.ThreadingHTTPServer"})
#: Server constructors taking a {rpc-name: callable} dict: every value
#: runs on a gRPC server-pool thread (concurrently with itself).
RPC_SERVE_FUNCS = frozenset({"serve_scheduler", "serve_worker"})
#: Keyword arguments that hand a callable to a component which invokes
#: it from its own thread (exporter request threads, the HA renewal
#: thread). Kept small and explicit: each entry is a real cross-thread
#: contract in this tree.
CALLBACK_ROOT_KWARGS = frozenset({"health_fn", "history_fn", "on_fenced"})

#: Roots of these kinds run CONCURRENTLY WITH THEMSELVES (thread pools:
#: one root, many threads), so a single such root is already a race
#: surface on its own.
SELF_CONCURRENT_KINDS = frozenset({"rpc-handler", "http-handler",
                                   "callback"})


# ----------------------------------------------------------------------
# Graph nodes
# ----------------------------------------------------------------------

@dataclass(frozen=True)
class FuncKey:
    """One function node: a method ((class, name)), a nested function
    ((class, 'method.<locals>.fn')), or a module-level function
    ((None, 'module.py:fn'))."""
    cls: Optional[str]
    name: str

    def __str__(self) -> str:
        return f"{self.cls}.{self.name}" if self.cls else self.name


@dataclass
class FuncInfo:
    key: FuncKey
    node: ast.AST            # FunctionDef / AsyncFunctionDef
    src: SourceFile
    #: Defining class (None for module functions); the class whose
    #: fields `self.X` refers to inside this function.
    cls: Optional[str] = None


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    src: SourceFile
    bases: List[str] = field(default_factory=list)
    methods: Dict[str, FuncInfo] = field(default_factory=dict)


@dataclass(frozen=True)
class ThreadRoot:
    """One discovered thread entry point."""
    key: FuncKey
    kind: str                # thread | timer | rpc-handler | http-handler | callback
    src_rel: str
    line: int

    @property
    def label(self) -> str:
        return f"{self.kind}:{self.key}@{self.src_rel}:{self.line}"


# ----------------------------------------------------------------------
# Small AST helpers
# ----------------------------------------------------------------------

def _base_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


def _annotation_classes(node: ast.AST, known: Set[str]) -> Set[str]:
    """Class names appearing anywhere inside an annotation expression
    (handles Optional[X], "X" string annotations, Dict[_, X])."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in known:
            out.add(sub.id)
        elif (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
              and sub.value in known):
            out.add(sub.value)
    return out


class CallGraph:
    """Classes, attribute types, and call resolution over one index.

    Built once per analyzer run (``RepoIndex.call_graph()`` memoizes)
    and shared by the thread-roots and race-detector passes.
    """

    def __init__(self, index: RepoIndex):
        self.index = index
        self.classes: Dict[str, ClassInfo] = {}
        #: Module functions: (src.rel, name) -> FuncInfo, plus nested
        #: functions keyed by their FuncKey.
        self.module_funcs: Dict[Tuple[str, str], FuncInfo] = {}
        self.funcs: Dict[FuncKey, FuncInfo] = {}
        #: (class, attr) -> possible class names of the attribute.
        self.attr_types: Dict[Tuple[str, str], Set[str]] = {}
        #: (class, attr) -> True for fields holding locks/queues/events
        #: (their own synchronization).
        self.sync_fields: Dict[Tuple[str, str], str] = {}
        #: Per-class lock aliasing: attr -> canonical lock attr (e.g.
        #: `_cv = threading.Condition(self._lock)` makes _cv ≡ _lock).
        self.lock_alias: Dict[Tuple[str, str], str] = {}
        #: (class, attr) -> the sanitizer name literal from
        #: `maybe_wrap(lock, "Class._attr")` — the lockflow passes use
        #: these so static lock identities match the runtime sanitizer's
        #: order-graph node names exactly (runtime ⊆ static containment
        #: is then a plain string-set comparison).
        self.lock_names: Dict[Tuple[str, str], str] = {}
        self._reach_memo: Dict[FuncKey, Set[FuncKey]] = {}
        self._callee_memo: Dict[FuncKey, Set[FuncKey]] = {}
        self._local_types_memo: Dict[FuncKey, Dict[str, Set[str]]] = {}
        self._nested_memo: Dict[FuncKey, Dict[str, FuncKey]] = {}
        self._local_assigns_memo: Dict[FuncKey, Dict[str, list]] = {}
        self._build()

    # -- construction --------------------------------------------------

    def _build(self) -> None:
        for src in self.index.files:
            self._collect_defs(src)
        known = set(self.classes)
        for info in self.classes.values():
            self._infer_attr_types(info, known)

    def _collect_defs(self, src: SourceFile) -> None:
        def visit(node: ast.AST, cls: Optional[ClassInfo],
                  prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    info = ClassInfo(child.name, child, src,
                                     bases=[b for b in
                                            (_base_name(x)
                                             for x in child.bases)
                                            if b])
                    # First definition wins on a tree-wide name clash
                    # (rare; fixture classes are scanned separately).
                    self.classes.setdefault(child.name, info)
                    visit(child, info, "")
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    name = prefix + child.name
                    if cls is not None:
                        key = FuncKey(cls.name, name)
                        fi = FuncInfo(key, child, src, cls=cls.name)
                        cls.methods.setdefault(name, fi)
                    else:
                        key = FuncKey(None, f"{src.rel}:{name}")
                        fi = FuncInfo(key, child, src)
                        self.module_funcs.setdefault((src.rel, child.name
                                                      if not prefix
                                                      else name), fi)
                    self.funcs.setdefault(key, fi)
                    visit(child, cls, name + ".<locals>.")
                else:
                    visit(child, cls, prefix)

        visit(src.tree, None, "")

    _SYNC_CONSTRUCTORS = {
        "threading.Lock": "lock", "threading.RLock": "lock",
        "threading.Condition": "lock", "maybe_wrap": "lock",
        "sanitizer.maybe_wrap": "lock",
        "threading.Event": "event", "threading.local": "tls",
        "queue.Queue": "queue", "queue.SimpleQueue": "queue",
        "collections.deque": "deque",
    }

    def _infer_attr_types(self, info: ClassInfo, known: Set[str]) -> None:
        for fi in info.methods.values():
            for node in ast.walk(fi.node):
                target = None
                value = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    target, value = node.target, node.value
                    if (isinstance(target, ast.Attribute)
                            and isinstance(target.value, ast.Name)
                            and target.value.id == "self"):
                        for cname in _annotation_classes(node.annotation,
                                                         known):
                            self.attr_types.setdefault(
                                (info.name, target.attr), set()).add(cname)
                if (target is None or not isinstance(target, ast.Attribute)
                        or not isinstance(target.value, ast.Name)
                        or target.value.id != "self"):
                    continue
                attr = target.attr
                if not isinstance(value, ast.Call):
                    continue
                name = call_name(value)
                kind = self._SYNC_CONSTRUCTORS.get(
                    name) or self._SYNC_CONSTRUCTORS.get(
                    name.rsplit(".", 1)[-1] if "." in name else name)
                if kind is not None:
                    self.sync_fields[(info.name, attr)] = kind
                    if name.rsplit(".", 1)[-1] == "Condition" and value.args:
                        inner = value.args[0]
                        if (isinstance(inner, ast.Attribute)
                                and isinstance(inner.value, ast.Name)
                                and inner.value.id == "self"):
                            self.lock_alias[(info.name, attr)] = inner.attr
                    if name.rsplit(".", 1)[-1] == "maybe_wrap":
                        if len(value.args) >= 2:
                            label = const_str(value.args[1])
                            if label is not None:
                                self.lock_names.setdefault(
                                    (info.name, attr), label)
                        continue  # wrapped lock: type stays "lock"
                    continue
                # Constructor / classmethod-constructor type inference.
                head = name.split(".", 1)[0]
                tail = name.rsplit(".", 1)[0] if "." in name else name
                for candidate in (name, tail, head):
                    if candidate in known:
                        self.attr_types.setdefault(
                            (info.name, attr), set()).add(candidate)
                        break

    # -- class hierarchy ----------------------------------------------

    def mro(self, cls: str) -> List[str]:
        """The class plus its indexed ancestors (linearized, cycles
        guarded)."""
        out, frontier, seen = [], [cls], set()
        while frontier:
            name = frontier.pop(0)
            if name in seen or name not in self.classes:
                continue
            seen.add(name)
            out.append(name)
            frontier.extend(self.classes[name].bases)
        return out

    def subclasses(self, cls: str) -> List[str]:
        return sorted(name for name, info in self.classes.items()
                      if cls in self.mro(name) and name != cls)

    def lookup_method(self, cls: str, method: str) -> Optional[FuncInfo]:
        for name in self.mro(cls):
            fi = self.classes[name].methods.get(method)
            if fi is not None:
                return fi
        return None

    def attr_classes(self, cls: str, attr: str) -> Set[str]:
        out: Set[str] = set()
        for name in self.mro(cls):
            out |= self.attr_types.get((name, attr), set())
        return out

    def is_sync_field(self, cls: str, attr: str) -> bool:
        return any((name, attr) in self.sync_fields
                   for name in self.mro(cls))

    def canonical_lock(self, cls: str, attr: str) -> str:
        for name in self.mro(cls):
            alias = self.lock_alias.get((name, attr))
            if alias is not None:
                return alias
        return attr

    # -- call resolution ----------------------------------------------

    def _local_types(self, fi: FuncInfo) -> Dict[str, Set[str]]:
        """var name -> possible classes, from constructor assignments
        and `var = self.attr` aliases inside one function."""
        memo = self._local_types_memo.get(fi.key)
        if memo is not None:
            return memo
        out: Dict[str, Set[str]] = {}
        known = set(self.classes)
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            var = node.targets[0].id
            value = node.value
            if isinstance(value, ast.Call):
                name = call_name(value)
                for candidate in (name,
                                  name.rsplit(".", 1)[0] if "." in name
                                  else name,
                                  name.split(".", 1)[0]):
                    if candidate in known:
                        out.setdefault(var, set()).add(candidate)
                        break
            elif (isinstance(value, ast.Name) and value.id == "self"
                    and fi.cls is not None):
                out.setdefault(var, set()).add(fi.cls)
            elif (isinstance(value, ast.Attribute)
                    and isinstance(value.value, ast.Name)
                    and value.value.id == "self" and fi.cls is not None):
                for cname in sorted(self.attr_classes(fi.cls, value.attr)):
                    out.setdefault(var, set()).add(cname)
        self._local_types_memo[fi.key] = out
        return out

    def _nested_funcs(self, fi: FuncInfo) -> Dict[str, FuncKey]:
        """Immediate nested function defs of `fi` by bare name."""
        memo = self._nested_memo.get(fi.key)
        if memo is not None:
            return memo
        out: Dict[str, FuncKey] = {}
        base = (fi.key.name if fi.cls is not None
                else fi.key.name.split(":", 1)[1])
        for child in ast.walk(fi.node):
            if (isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and child is not fi.node):
                nested_name = f"{base}.<locals>.{child.name}"
                if fi.cls is not None:
                    key = FuncKey(fi.cls, nested_name)
                else:
                    key = FuncKey(None, f"{fi.src.rel}:{nested_name}")
                if key in self.funcs:
                    out.setdefault(child.name, key)
        self._nested_memo[fi.key] = out
        return out

    def resolve_callable(self, expr: ast.AST, fi: FuncInfo,
                         local_types: Optional[Dict[str, Set[str]]] = None,
                         _depth: int = 0) -> List[FuncKey]:
        """Function nodes a callable expression may refer to (empty =
        unresolvable). Used for call edges AND thread-spawn targets."""
        if _depth > 4:
            return []
        if local_types is None:
            local_types = self._local_types(fi)
        nested = self._nested_funcs(fi)
        # Conditional callback: `fn if cond else None` resolves to the
        # union of its resolvable branches (a literal-None branch is
        # "no callback", not an opaque target).
        if isinstance(expr, ast.IfExp):
            out = []
            for branch in (expr.body, expr.orelse):
                if isinstance(branch, ast.Constant) and branch.value is None:
                    continue
                out.extend(self.resolve_callable(branch, fi, local_types,
                                                 _depth + 1))
            return out
        # self.m / self.attr.m
        if isinstance(expr, ast.Attribute):
            holder = expr.value
            method = expr.attr
            if isinstance(holder, ast.Name):
                if holder.id == "self" and fi.cls is not None:
                    target = self.lookup_method(fi.cls, method)
                    return [target.key] if target else []
                classes: Set[str] = set()
                if holder.id in self.classes:   # ClassName.m
                    classes.add(holder.id)
                classes |= local_types.get(holder.id, set())
                out = []
                for cname in sorted(classes):
                    target = self.lookup_method(cname, method)
                    if target is not None:
                        out.append(target.key)
                return out
            if (isinstance(holder, ast.Attribute)
                    and isinstance(holder.value, ast.Name)
                    and holder.value.id == "self" and fi.cls is not None):
                out = []
                for cname in sorted(self.attr_classes(fi.cls, holder.attr)):
                    target = self.lookup_method(cname, method)
                    if target is not None:
                        out.append(target.key)
                return out
            return []
        if isinstance(expr, ast.Name):
            if expr.id in nested:
                return [nested[expr.id]]
            mf = self.module_funcs.get((fi.src.rel, expr.id))
            if mf is not None:
                return [mf.key]
            # A bare name bound to a class: calling it constructs; the
            # interesting entry for reachability is __init__.
            if expr.id in self.classes:
                target = self.lookup_method(expr.id, "__init__")
                return [target.key] if target else []
            # Local callable alias: `cb = self._kill_job` (possibly on
            # several branches) then Timer(..., cb) — union over every
            # assignment the name receives in this function.
            out = []
            for value in self._local_assigns(fi).get(expr.id, ()):
                if not isinstance(value, ast.Name):
                    out.extend(self.resolve_callable(value, fi,
                                                     local_types,
                                                     _depth + 1))
            return out
        return []

    def _local_assigns(self, fi: FuncInfo) -> Dict[str, list]:
        """var name -> every value expression assigned to it in `fi`
        (one walk, memoized)."""
        memo = self._local_assigns_memo.get(fi.key)
        if memo is not None:
            return memo
        out: Dict[str, list] = {}
        for node in ast.walk(fi.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                out.setdefault(node.targets[0].id, []).append(node.value)
        self._local_assigns_memo[fi.key] = out
        return out

    def callees(self, key: FuncKey) -> Set[FuncKey]:
        memo = self._callee_memo.get(key)
        if memo is not None:
            return memo
        fi = self.funcs.get(key)
        if fi is None:
            return set()
        local_types = self._local_types(fi)
        out: Set[FuncKey] = set()
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                for target in self.resolve_callable(node.func, fi,
                                                    local_types):
                    out.add(target)
        self._callee_memo[key] = out
        return out

    def reachable(self, key: FuncKey) -> Set[FuncKey]:
        """All function nodes reachable from `key` (inclusive)."""
        if key in self._reach_memo:
            return self._reach_memo[key]
        seen: Set[FuncKey] = set()
        frontier = [key]
        while frontier:
            cur = frontier.pop()
            if cur in seen:
                continue
            seen.add(cur)
            frontier.extend(self.callees(cur))
        self._reach_memo[key] = seen
        return seen


# ----------------------------------------------------------------------
# Thread-root discovery
# ----------------------------------------------------------------------

def _spawn_target(node: ast.Call, kw: str, pos: int) -> Optional[ast.AST]:
    for k in node.keywords:
        if k.arg == kw:
            return k.value
    if len(node.args) > pos:
        return node.args[pos]
    return None


def _resolve_dict_literal(expr: ast.AST, fi: FuncInfo,
                          graph: CallGraph) -> Optional[ast.Dict]:
    if isinstance(expr, ast.Dict):
        return expr
    if isinstance(expr, ast.Name):
        for node in ast.walk(fi.node):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == expr.id
                    and isinstance(node.value, ast.Dict)):
                return node.value
    return None


def discover_thread_roots(index: RepoIndex,
                          rpc_serve_funcs: Iterable[str] = RPC_SERVE_FUNCS,
                          callback_kwargs: Iterable[str]
                          = CALLBACK_ROOT_KWARGS,
                          ) -> Tuple[List[ThreadRoot], List[Finding]]:
    """Walk the tree for thread entry points. Returns (roots, findings);
    a finding is a spawn whose target could not be resolved to a
    function in the indexed tree."""
    pass_id = "thread-roots"
    graph = index.call_graph()
    rpc_serve_funcs = frozenset(rpc_serve_funcs)
    callback_kwargs = frozenset(callback_kwargs)
    # One discovery per analyzer run: the thread-roots pass and the
    # race detector both call this with identical inputs. The memo
    # lives on the index and is cleared by reset_suppression_hits (a
    # new run must re-consult suppressions, or the audit would flag
    # the load-bearing thread-roots ignores as stale).
    memo = getattr(index, "_thread_roots_memo", None)
    if memo is None:
        memo = index._thread_roots_memo = {}
    memo_key = (rpc_serve_funcs, callback_kwargs)
    if memo_key in memo:
        return memo[memo_key]
    roots: List[ThreadRoot] = []
    findings: List[Finding] = []
    seen: Set[Tuple[FuncKey, str]] = set()

    def add_root(key: FuncKey, kind: str, src: SourceFile,
                 line: int) -> None:
        if (key, kind) in seen:
            return
        seen.add((key, kind))
        roots.append(ThreadRoot(key, kind, src.rel, line))

    def unresolved(src: SourceFile, node: ast.AST, what: str) -> None:
        f = finding(src, node, pass_id,
                    f"{what} cannot be statically resolved to a "
                    "function in the tree: the race detector cannot "
                    "see behind this thread entry (name the target "
                    "directly, or suppress with a justification)")
        if f is not None:
            findings.append(f)

    def resolve_or_flag(expr: ast.AST, fi: FuncInfo, kind: str,
                        src: SourceFile, node: ast.AST,
                        what: str) -> None:
        targets = graph.resolve_callable(expr, fi)
        if not targets:
            unresolved(src, node, what)
            return
        for key in targets:
            add_root(key, kind, src, node.lineno)

    def handle_call(node: ast.Call, fi: FuncInfo,
                    src: SourceFile) -> None:
            name = call_name(node)
            tail = name.rsplit(".", 1)[-1] if "." in name else name
            if name in THREAD_SPAWN_CALLS:
                target = _spawn_target(node, "target", 1)
                if target is None:
                    # Thread() with no target runs an overridden run();
                    # not used in this tree — flag so it can't hide.
                    unresolved(src, node, "threading.Thread with no "
                                          "resolvable target")
                else:
                    resolve_or_flag(target, fi, "thread", src, node,
                                    "threading.Thread target")
            elif name in TIMER_SPAWN_CALLS:
                target = _spawn_target(node, "function", 1)
                if target is None:
                    unresolved(src, node, "threading.Timer callback")
                else:
                    resolve_or_flag(target, fi, "timer", src, node,
                                    "threading.Timer callback")
            elif tail == "ThreadingHTTPServer":
                if len(node.args) >= 2:
                    handler = node.args[1]
                    cname = handler.id if isinstance(handler, ast.Name) \
                        else None
                    info = graph.classes.get(cname) if cname else None
                    if info is None:
                        unresolved(src, node,
                                   "ThreadingHTTPServer handler class")
                    else:
                        for mname in sorted(info.methods):
                            if mname.startswith("do_"):
                                add_root(info.methods[mname].key,
                                         "http-handler", src, node.lineno)
            elif tail in rpc_serve_funcs:
                for arg in list(node.args) + [k.value for k in
                                              node.keywords
                                              if k.arg not in
                                              callback_kwargs]:
                    d = _resolve_dict_literal(arg, fi, graph)
                    if d is None:
                        continue
                    for value in d.values:
                        resolve_or_flag(value, fi, "rpc-handler", src,
                                        node, "gRPC servicer callback")
            for k in node.keywords:
                if k.arg in callback_kwargs:
                    resolve_or_flag(k.value, fi, "callback", src, node,
                                    f"{k.arg}= callback")

    for key in sorted(graph.funcs, key=lambda k: (k.cls or "", k.name)):
        fi = graph.funcs[key]
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Call):
                handle_call(node, fi, fi.src)

    # Module-level statements spawn threads too (driver scripts,
    # `if __name__` blocks): scan top-level code with a per-module
    # pseudo-function context so local vars / module functions resolve.
    # Function/class bodies are skipped — they were handled above.
    for src in index.files:
        module_fi = FuncInfo(FuncKey(None, f"{src.rel}:<module>"),
                             src.tree, src)
        stack = list(src.tree.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(node, ast.Call):
                handle_call(node, module_fi, src)
            stack.extend(ast.iter_child_nodes(node))

    roots.sort(key=lambda r: (r.src_rel, r.line, r.kind, str(r.key)))
    memo[memo_key] = (roots, findings)
    return roots, findings


def check_thread_roots(index: RepoIndex,
                       rpc_serve_funcs: Iterable[str] = RPC_SERVE_FUNCS,
                       callback_kwargs: Iterable[str]
                       = CALLBACK_ROOT_KWARGS) -> List[Finding]:
    """Pass entry point: every thread spawn in the tree must have a
    statically resolvable entry function — an opaque target is a thread
    the race detector cannot check, which is how unchecked concurrency
    sneaks in."""
    _, findings = discover_thread_roots(index, rpc_serve_funcs,
                                        callback_kwargs)
    return findings
