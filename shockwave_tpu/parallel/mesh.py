"""Device mesh and sharding helpers.

The framework's data plane: jobs shard over a `jax.sharding.Mesh` and let
XLA insert collectives on ICI — replacing the reference's PyTorch
DDP/NCCL stack (reference: workloads/pytorch/*/main.py dist.init calls).

Axis conventions used across the workloads:
  dp — data parallel (batch sharded, params replicated; psum on grads)
  pp — pipeline parallel (layer stages; ppermute activation hops)
  tp — tensor parallel (feature-sharded matmuls)
  sp — sequence parallel (ring attention over sequence shards)
  ep — expert parallel (MoE experts sharded; all-to-all dispatch)
"""
from __future__ import annotations

import logging
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)


def make_mesh(dp: Optional[int] = None, tp: int = 1, sp: int = 1,
              pp: int = 1, ep: int = 1,
              devices: Optional[Sequence] = None,
              batch_size: Optional[int] = None) -> Mesh:
    """Build a (dp, pp, tp, sp, ep) mesh; dp defaults to the remaining
    devices. Size-1 axes cost nothing and keep PartitionSpecs valid
    everywhere, so every mesh carries all five names.

    With `batch_size`, dp is capped at the largest divisor of the global
    batch (a dp-sharded batch's leading dim must divide evenly); any
    leftover devices stay out of the mesh. Small-batch jobs on a
    many-device host (e.g. CycleGAN at batch 1) would otherwise fail
    at the first device_put.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    rest = pp * tp * sp * ep
    if dp is None:
        assert n % rest == 0, (n, pp, tp, sp, ep)
        dp = n // rest
        # Cap only in single-process mode: dropping devices from a
        # multi-host gang's mesh could leave a host with no addressable
        # devices, wedging the gang instead of failing loudly.
        if batch_size is not None and jax.process_count() == 1:
            while dp > 1 and batch_size % dp:
                dp -= 1
            if dp * rest < n:
                logger.warning(
                    "mesh: batch_size=%d caps dp at %d; %d of %d devices "
                    "left out of the mesh and will idle", batch_size, dp,
                    n - dp * rest, n)
    else:
        # An explicit shape must cover the devices exactly — a silently
        # undersized mesh would skew profiling/throughput numbers.
        assert dp * rest == n, f"mesh {dp}x{pp}x{tp}x{sp}x{ep} != {n} devices"
    arr = np.array(devices[:dp * rest]).reshape((dp, pp, tp, sp, ep))
    return Mesh(arr, axis_names=("dp", "pp", "tp", "sp", "ep"))


def data_parallel_sharding(mesh: Mesh) -> Tuple[NamedSharding, NamedSharding]:
    """(batch_sharding, replicated_sharding) for pure data parallelism."""
    return (NamedSharding(mesh, P("dp")), NamedSharding(mesh, P()))


def replicate(mesh: Mesh, tree):
    """Replicate a pytree onto every device of the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def shard_batch(mesh: Mesh, batch):
    """Shard a batch pytree along its leading axis over the dp axis."""
    sharding = NamedSharding(mesh, P("dp"))
    return jax.device_put(batch, sharding)


def local_batch_slice(global_batch_size: int, process_index: Optional[int] = None,
                      process_count: Optional[int] = None) -> slice:
    """The slice of a global batch this host is responsible for feeding."""
    if process_index is None:
        process_index = jax.process_index()
    if process_count is None:
        process_count = jax.process_count()
    assert global_batch_size % process_count == 0
    per = global_batch_size // process_count
    return slice(process_index * per, (process_index + 1) * per)


_distributed_initialized = False


def maybe_initialize_distributed(coordinator: Optional[str],
                                 num_processes: Optional[int],
                                 process_id: Optional[int]) -> None:
    """Join a multi-host JAX cluster when dispatched as part of a gang.

    MUST run before any JAX computation (model init included): jax
    refuses to initialize the distributed runtime once the XLA backend
    exists. Workload mains therefore call this through
    train_common.parse_args() as their very first JAX-touching act;
    the Trainer's own call is a no-op by then (idempotent)."""
    global _distributed_initialized
    if (coordinator and num_processes and num_processes > 1
            and not _distributed_initialized):
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id)
        _distributed_initialized = True
