"""gRPC service bindings without protoc's grpc plugin.

Service/method tables are declared once; `make_stub` builds a client-side
callable stub and `generic_handler` a server-side handler from the same
table, so the two can never drift apart.
"""
from __future__ import annotations

from typing import Callable, Dict

import grpc

from .proto import control_pb2 as pb

SERVICES: Dict[str, Dict[str, tuple]] = {
    "shockwave_tpu.WorkerToScheduler": {
        "RegisterWorker": (pb.RegisterWorkerRequest, pb.RegisterWorkerResponse),
        "Done": (pb.DoneRequest, pb.Empty),
    },
    "shockwave_tpu.SchedulerToWorker": {
        "RunJob": (pb.RunJobRequest, pb.Empty),
        "KillJob": (pb.KillJobRequest, pb.Empty),
        "Reset": (pb.Empty, pb.Empty),
        "Shutdown": (pb.Empty, pb.Empty),
    },
    "shockwave_tpu.IteratorToScheduler": {
        "InitJob": (pb.InitJobRequest, pb.InitJobResponse),
        "UpdateLease": (pb.UpdateLeaseRequest, pb.UpdateLeaseResponse),
        "UpdateResourceRequirement": (pb.UpdateResourceRequirementRequest, pb.Empty),
    },
}


class Stub:
    """Client stub exposing one attribute per RPC method."""

    def __init__(self, channel: grpc.Channel, service: str):
        for method, (req_cls, resp_cls) in SERVICES[service].items():
            callable_ = channel.unary_unary(
                f"/{service}/{method}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString,
            )
            setattr(self, method, callable_)


def generic_handler(service: str, implementations: Dict[str, Callable]):
    """Build a grpc generic handler from {method_name: fn(request, context)}."""
    method_handlers = {}
    for method, fn in implementations.items():
        req_cls, resp_cls = SERVICES[service][method]
        method_handlers[method] = grpc.unary_unary_rpc_method_handler(
            fn,
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    return grpc.method_handlers_generic_handler(service, method_handlers)
