"""Max-sum-throughput ("MST") policies.

LP maximizing total (optionally cost-normalized) throughput, with optional
per-job SLO rate constraints (reference:
scheduler/policies/max_sum_throughput.py:44-108).
"""
from __future__ import annotations

import numpy as np

from .lp import LinearProgram
from .policy import Policy


class ThroughputNormalizedByCostSumWithPerfSLOs(Policy):
    name = "ThroughputNormalizedByCostSum_PerfSLOs"

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       cluster_spec, instance_costs=None, SLOs=None,
                       num_steps_remaining=None):
        SLOs = SLOs or {}
        num_steps_remaining = num_steps_remaining or {}
        throughputs, index = self.flatten(unflattened_throughputs, cluster_spec)
        if throughputs is None:
            return None
        m, n = throughputs.shape
        job_ids, worker_types = index
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)

        costs = np.ones(n)
        if instance_costs is not None:
            costs = np.array([instance_costs[wt] for wt in worker_types])

        def build(include_slos: bool):
            lp = LinearProgram(m * n)
            for row, rhs in zip(*self.cluster_capacity_rows(m, n, sf, self._num_workers)):
                lp.add_le(row, rhs)
            for row, rhs in zip(*self.job_time_rows(m, n)):
                lp.add_le(row, rhs)
            if include_slos:
                for job_id, slo in SLOs.items():
                    i = job_ids.index(job_id)
                    row = lp.row()
                    row[i * n:(i + 1) * n] = -throughputs[i]
                    lp.add_le(row, -num_steps_remaining[job_id] / slo)
            c = -(throughputs / costs).reshape(m * n)
            return lp.minimize(c).solve()

        res = build(include_slos=bool(SLOs))
        if not res.success and SLOs:
            # SLOs unsatisfiable: drop them rather than fail the round.
            res = build(include_slos=False)
        if not res.success:
            return None
        return self.unflatten(res.x.reshape((m, n)).clip(0.0, 1.0), index)


class ThroughputSumWithPerf(Policy):
    name = "ThroughputSumWithPerf"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._policy = ThroughputNormalizedByCostSumWithPerfSLOs(solver)

    def get_allocation(self, unflattened_throughputs, scale_factors, cluster_spec):
        return self._policy.get_allocation(unflattened_throughputs,
                                           scale_factors, cluster_spec)


class ThroughputNormalizedByCostSumWithPerf(Policy):
    name = "ThroughputNormalizedByCostSum_Perf"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._policy = ThroughputNormalizedByCostSumWithPerfSLOs(solver)

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       cluster_spec, instance_costs):
        return self._policy.get_allocation(unflattened_throughputs, scale_factors,
                                           cluster_spec, instance_costs=instance_costs)
