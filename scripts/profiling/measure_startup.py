#!/usr/bin/env python3
"""Per-dispatch startup profiler: calibrate the simulator's cold-dispatch
overhead against reality.

Every physical (re)dispatch of a job pays a fixed cost the throughput
oracle cannot see: interpreter + jax import, input-pipeline setup,
checkpoint restore, first-step jit (against the persistent XLA compile
cache), and the exit-path checkpoint save. This script measures that
cost the way the dispatcher actually incurs it — by spawning the real
workload entrypoints (core/job_table.py templates, the same commands a
trace row carries) for a 1-step run and timing spawn -> exit — and
writes the per-worker-type mean into the oracle file's
``__meta__.dispatch_overhead_s`` (core/oracle.py), which activates the
simulator's calibrated cold-dispatch model (sched/scheduler.py).

For each family the first (cold-compile-cache) run is a discarded
warmup — re-dispatches in a physical run hit the warm persistent cache,
which is the regime the simulator charges — then ``--repeats`` runs are
measured, each restoring the checkpoint the previous run saved, so the
measurement includes restore + save exactly like a mid-trace redispatch.

Counterpart of the reference's fidelity-calibration step: its simulator
bakes a flat 20 s checkpoint/restore charge measured on its GPU cluster
(reference: scheduler/scheduler.py:1936-1968); here the charge is
measured per worker type on the actual deployment host.

Example (CPU loopback calibration):
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \\
      python scripts/profiling/measure_startup.py --worker_type cpu \\
      --oracle reproduce/fidelity/cpu_throughputs.json \\
      --families "ResNet-18 (batch size 32)" "LM (batch size 20)"
"""
import argparse
import datetime
import json
import os
import platform
import shutil
import shlex
import statistics
import subprocess
import sys
import tempfile
import time

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
sys.path.insert(0, REPO)

from shockwave_tpu.core.job_table import JOB_TABLE, a3c, cyclegan  # noqa: E402

WORKLOADS = os.path.join(REPO, "shockwave_tpu", "workloads")


def run_once(template, data_dir, ckpt_dir, timeout):
    """Spawn the workload exactly like the dispatcher does, for 1 step;
    return wall seconds from spawn to exit."""
    command = template.command
    if template.needs_data_dir and "%s" in command:
        command = command % (data_dir,)
    command = (f"{command} --local_rank 0 {template.num_steps_arg} 1 "
               f"--checkpoint_dir {ckpt_dir}")
    cwd = os.path.join(WORKLOADS, template.working_directory)
    t0 = time.monotonic()
    proc = subprocess.run(
        shlex.split(command), cwd=cwd, timeout=timeout,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    elapsed = time.monotonic() - t0
    if proc.returncode != 0:
        raise RuntimeError(
            f"{template.model}: exit {proc.returncode}:\n"
            f"{proc.stdout.decode(errors='replace')[-2000:]}")
    return elapsed


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--worker_type", required=True)
    p.add_argument("--oracle", required=True,
                   help="throughput-oracle JSON to write __meta__ into")
    p.add_argument("--families", nargs="+",
                   default=["ResNet-18 (batch size 32)", "LM (batch size 20)",
                            "Recommendation (batch size 512)"],
                   help="job_type strings (job_table models) to profile")
    p.add_argument("--repeats", type=int, default=2,
                   help="measured runs per family after the cache warmup")
    p.add_argument("--data_dir", default="/tmp/swtpu_data",
                   help="dataset root; absent datasets fall back synthetic")
    p.add_argument("--timeout", type=float, default=900.0)
    args = p.parse_args()

    by_model = {t.model: t for t in JOB_TABLE + [a3c(), cyclegan()]}
    per_family = {}
    for family in args.families:
        if family not in by_model:
            raise SystemExit(f"unknown job type {family!r}; "
                             f"known: {sorted(by_model)}")
        template = by_model[family]
        ckpt_dir = tempfile.mkdtemp(prefix="swtpu_startup_")
        try:
            warmup = run_once(template, args.data_dir, ckpt_dir, args.timeout)
            samples = [run_once(template, args.data_dir, ckpt_dir,
                                args.timeout)
                       for _ in range(args.repeats)]
        finally:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
        per_family[family] = {
            "cold_compile_s": round(warmup, 2),
            "samples_s": [round(s, 2) for s in samples],
            "mean_s": round(statistics.mean(samples), 2),
        }
        print(f"{family}: warmup {warmup:.1f}s, "
              f"measured {per_family[family]['samples_s']}")

    overhead = round(statistics.mean(
        f["mean_s"] for f in per_family.values()), 2)

    with open(args.oracle) as f:
        oracle = json.load(f)
    meta = oracle.setdefault("__meta__", {})
    # This script is the sole owner of the dispatch_overhead_s* keys
    # (solo spawn->exit proxy). measure_deployed.py writes its in-lease
    # shortfall — a different quantity — under lease_shortfall_s*,
    # which the simulator prefers when both are present; keeping the
    # keys disjoint means neither run can clobber the other's scalar
    # with mismatched semantics.
    meta.setdefault("dispatch_overhead_s", {})[args.worker_type] = overhead
    meta.setdefault("dispatch_overhead_detail", {})[args.worker_type] = {
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "host": platform.node(),
        "python": platform.python_version(),
        "method": "spawn->exit of 1-step runs, warm XLA cache, "
                  "ckpt restore+save included; mean over families",
        "per_family": per_family,
    }
    with open(args.oracle, "w") as f:
        json.dump(oracle, f, indent=1)
        f.write("\n")
    print(f"dispatch_overhead_s[{args.worker_type}] = {overhead} "
          f"-> {args.oracle}")


if __name__ == "__main__":
    main()
