from .job import Job, JobIdPair
from .trace import parse_trace, job_to_trace_line
from .oracle import read_throughputs, parse_job_type_tuple
from .throughput_estimator import ThroughputEstimator, als_complete
from .constants import DATASET_SIZES, MODEL_DATASET, MAX_BS, steps_per_epoch, num_epochs_for

__all__ = [
    "Job",
    "JobIdPair",
    "parse_trace",
    "job_to_trace_line",
    "read_throughputs",
    "parse_job_type_tuple",
    "ThroughputEstimator",
    "als_complete",
    "DATASET_SIZES",
    "MODEL_DATASET",
    "MAX_BS",
    "steps_per_epoch",
    "num_epochs_for",
]
