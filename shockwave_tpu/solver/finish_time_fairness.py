"""Finish-time fairness ("Themis") policy.

Minimizes the maximum rho = expected-finish-time-shared /
expected-finish-time-isolated across jobs (reference:
scheduler/policies/finish_time_fairness.py:101-126).

The reference solves a convex program with `inv_pos`; here we exploit that
for a FIXED rho the feasibility region is linear:

    rho >= (t_i + R_i / theta_i) / iso_i
    <=>  theta_i >= R_i / (rho * iso_i - t_i)      (when rho*iso_i > t_i)

where theta_i = sum_j tput_ij * x_ij, so we binary-search the smallest
feasible rho with HiGHS feasibility LPs — same pattern the reference uses
for makespan in min_total_duration.
"""
from __future__ import annotations

import numpy as np

from .lp import LinearProgram, solve_feasibility
from .policy import Policy, PolicyWithPacking
from .simple import IsolatedPolicy


class _IsolatedTimeTracker:
    """Cross-round bookkeeping of the isolated-baseline time each job has
    notionally accumulated, shared by the perf and packing variants."""

    def _init_tracker(self):
        self._isolated = IsolatedPolicy()
        self._cumulative_isolated_time = {}
        self._prev_isolated_throughputs = {}
        self._prev_steps_remaining = {}

    def _reset_tracker(self):
        self._prev_isolated_throughputs = {}
        self._prev_steps_remaining = {}

    def _isolated_time_arrays(self, job_ids, num_steps_remaining,
                              times_since_start, isolated_tputs):
        """(expected_isolated, remaining, elapsed) arrays; also folds the
        steps completed since the previous allocation into the cumulative
        isolated-time baseline."""
        nj = len(job_ids)
        expected_isolated = np.zeros(nj)
        remaining = np.zeros(nj)
        elapsed = np.zeros(nj)
        for i, job_id in enumerate(job_ids):
            self._cumulative_isolated_time.setdefault(job_id, 0.0)
            if job_id in self._prev_steps_remaining:
                steps_done = (self._prev_steps_remaining[job_id]
                              - num_steps_remaining[job_id])
                self._cumulative_isolated_time[job_id] += (
                    steps_done / self._prev_isolated_throughputs[job_id])
            remaining[i] = num_steps_remaining[job_id]
            elapsed[i] = times_since_start[job_id]
            expected_isolated[i] = (self._cumulative_isolated_time[job_id]
                                    + remaining[i] / isolated_tputs[i, 0])
        return expected_isolated, remaining, elapsed

    def _commit_tracker(self, job_ids, num_steps_remaining, isolated_tputs):
        self._prev_steps_remaining = dict(num_steps_remaining)
        self._prev_isolated_throughputs = {
            job_ids[i]: float(isolated_tputs[i, 0])
            for i in range(len(job_ids))}

    @staticmethod
    def _refine_weights(reqs):
        """Objective weights for the slack-refinement LP.  At the converged
        rho the feasibility vertex can pin every non-bottleneck job to
        exactly its rho bound, whereas the reference's interior-point solve
        (finish_time_fairness.py:101-126 via ECOS) spreads leftover
        capacity, so jobs realize rho below the max.  Re-solving at fixed
        rho* maximizing TOTAL effective throughput (equal weights over jobs
        still needing work) turns that slack into progress: on the canonical
        120-job trace it cuts the unfair fraction 0.242 -> 0.150 and avg JCT
        ~9% vs returning the raw feasibility vertex (measured;
        gradient-of-rho and 1/req weightings were also tried and lose on
        makespan or unfairness respectively)."""
        w = np.zeros(len(reqs))
        w[reqs > 1e-12] = 1.0
        return w


class FinishTimeFairnessPolicyWithPerf(Policy, _IsolatedTimeTracker):
    name = "FinishTimeFairness_Perf"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._init_tracker()

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       unflattened_priority_weights, times_since_start,
                       num_steps_remaining, cluster_spec):
        throughputs, index = self.flatten(unflattened_throughputs, cluster_spec)
        if throughputs is None:
            self._reset_tracker()
            return None
        m, n = throughputs.shape
        job_ids, worker_types = index
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)

        isolated_tputs = self._isolated.get_throughputs(
            throughputs, index, scale_factors, cluster_spec)

        # rho compares against a consistent cross-round isolated baseline.
        expected_isolated, remaining, elapsed = self._isolated_time_arrays(
            job_ids, num_steps_remaining, times_since_start, isolated_tputs)

        def build(rho: float):
            lp = LinearProgram(m * n)
            reqs = np.zeros(m)
            for i in range(m):
                denom = rho * expected_isolated[i] - elapsed[i]
                if denom <= 0:
                    return None  # cannot meet rho for job i at any allocation
                reqs[i] = remaining[i] / denom
                row = lp.row()
                row[i * n:(i + 1) * n] = -throughputs[i]
                lp.add_le(row, -reqs[i])
            for row, rhs in zip(*self.cluster_capacity_rows(m, n, sf, self._num_workers)):
                lp.add_le(row, rhs)
            for row, rhs in zip(*self.job_time_rows(m, n)):
                lp.add_le(row, rhs)
            return lp, reqs

        def feasible(rho: float):
            built = build(rho)
            return None if built is None else solve_feasibility(built[0])

        lo, hi = 1e-3, 10.0
        x = feasible(hi)
        while x is None and hi < 1e7:
            lo, hi = hi, hi * 10.0
            x = feasible(hi)
        if x is None:
            # No rho achievable (e.g. throughput 0 rows): fall back to isolated.
            result = self._isolated.get_allocation(
                unflattened_throughputs, scale_factors, cluster_spec)
        else:
            best = x
            while hi > lo * 1.01:
                mid = (lo + hi) / 2.0
                x = feasible(mid)
                if x is not None:
                    best, hi = x, mid
                else:
                    lo = mid
            built = build(hi)
            if built is not None:
                lp, reqs = built
                w = self._refine_weights(reqs)
                c = np.zeros(m * n)
                for i in range(m):
                    c[i * n:(i + 1) * n] = -w[i] * throughputs[i]
                res = lp.minimize(c).solve()
                if res.success:
                    best = res.x
            result = self.unflatten(best[:m * n].reshape((m, n)).clip(0.0, 1.0),
                                    index)

        self._commit_tracker(job_ids, num_steps_remaining, isolated_tputs)
        return result


class FinishTimeFairnessPolicyWithPacking(PolicyWithPacking, _IsolatedTimeTracker):
    """Packed Themis: minimize max rho where each single job's effective
    throughput sums over the combinations containing it (reference:
    finish_time_fairness.py:160-279). Same binary-search-on-rho reduction
    as the perf variant, with packed capacity/time constraints."""

    name = "FinishTimeFairness_Packing"

    def __init__(self, solver=None):
        super().__init__(solver)
        self._init_tracker()

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       unflattened_priority_weights, times_since_start,
                       num_steps_remaining, cluster_spec):
        tensor, index = self.flatten(unflattened_throughputs, cluster_spec,
                                     unflattened_priority_weights)
        if tensor is None or len(tensor) == 0:
            self._reset_tracker()
            return None
        job_ids, single_job_ids, worker_types, relevant = index
        m, n = tensor[0].shape
        sf = self.scale_factors_array(scale_factors, job_ids, m, n)

        singles_matrix = np.array(
            [[unflattened_throughputs[s][wt] for wt in worker_types]
             for s in single_job_ids], dtype=float)
        isolated_tputs = self._isolated.get_throughputs(
            singles_matrix, (single_job_ids, worker_types), scale_factors,
            cluster_spec)

        expected_isolated, remaining, elapsed = self._isolated_time_arrays(
            single_job_ids, num_steps_remaining, times_since_start,
            isolated_tputs)

        def build(rho: float):
            lp = LinearProgram(m * n)
            reqs = np.zeros(len(single_job_ids))
            for si, s in enumerate(single_job_ids):
                denom = rho * expected_isolated[si] - elapsed[si]
                if denom <= 0:
                    return None
                reqs[si] = remaining[si] / denom
                row = lp.row()
                for ci in relevant[s]:
                    row[ci * n:(ci + 1) * n] = -tensor[si, ci]
                lp.add_le(row, -reqs[si])
            for row, rhs in zip(*self.cluster_capacity_rows(
                    m, n, sf, self._num_workers)):
                lp.add_le(row, rhs)
            for row, rhs in zip(*self.per_job_time_rows(
                    job_ids, single_job_ids, relevant, n)):
                lp.add_le(row, rhs)
            for i in range(m):
                for j in range(n):
                    if sf[i, j] == 0:
                        lp.bounds[i * n + j] = (0, 0)
            return lp, reqs

        def feasible(rho: float):
            built = build(rho)
            return None if built is None else solve_feasibility(built[0])

        lo, hi = 1e-3, 10.0
        x = feasible(hi)
        while x is None and hi < 1e7:
            lo, hi = hi, hi * 10.0
            x = feasible(hi)
        if x is None:
            singles = {s: dict(unflattened_throughputs[s])
                       for s in single_job_ids}
            result = self._isolated.get_allocation(
                singles, scale_factors, cluster_spec)
        else:
            best = x
            while hi > lo * 1.01:
                mid = (lo + hi) / 2.0
                x = feasible(mid)
                if x is not None:
                    best, hi = x, mid
                else:
                    lo = mid
            built = build(hi)
            if built is not None:
                lp, reqs = built
                w = self._refine_weights(reqs)
                c = np.zeros(m * n)
                for si, s in enumerate(single_job_ids):
                    for ci in relevant[s]:
                        c[ci * n:(ci + 1) * n] -= w[si] * tensor[si, ci]
                res = lp.minimize(c).solve()
                if res.success:
                    best = res.x
            result = self.unflatten(
                best[:m * n].reshape((m, n)).clip(0.0, 1.0), index)

        self._commit_tracker(single_job_ids, num_steps_remaining,
                             isolated_tputs)
        return result


class FinishTimeFairnessPolicy(Policy):
    """Collapses all worker types to the reference type's throughput before
    delegating (reference: finish_time_fairness.py:37-45)."""

    name = "FinishTimeFairness"

    def __init__(self, solver=None, reference_worker_type="v100"):
        super().__init__(solver)
        self._perf = FinishTimeFairnessPolicyWithPerf(solver)
        self._reference_worker_type = reference_worker_type

    def get_allocation(self, unflattened_throughputs, scale_factors,
                       priority_weights, times_since_start,
                       num_steps_remaining, cluster_spec):
        uniform = {
            job_id: {wt: per_wt[self._reference_worker_type] for wt in per_wt}
            for job_id, per_wt in unflattened_throughputs.items()
        }
        if not uniform:
            return None
        return self._perf.get_allocation(
            uniform, scale_factors, priority_weights, times_since_start,
            num_steps_remaining, cluster_spec)
