#!/usr/bin/env python3
"""Trace-driven physical-cluster driver.

Runs the real round-based scheduler: starts the gRPC control plane, waits
for `--expected_num_workers` worker daemons to register, submits the
trace's jobs at their arrival offsets in wall-clock time, and drives
rounds until every job completes
(reference: scheduler/scripts/drivers/run_scheduler_with_trace.py).

Example (single-host loopback):
    python scripts/drivers/run_physical.py \
        --trace data/canonical_120job.trace \
        --policy max_min_fairness \
        --throughputs data/tacc_throughputs.json \
        --expected_num_workers 1 --round_duration 360 &
    python -m shockwave_tpu.runtime.worker --worker_type v100 \
        --sched_addr 127.0.0.1 --sched_port 50070 --worker_port 50061
"""
import argparse
import json
import logging
import os
import pickle
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from shockwave_tpu.core.metrics import unfair_fraction
from shockwave_tpu.core.oracle import read_throughputs
from shockwave_tpu.core.profiles import build_profiles
from shockwave_tpu.core.trace import parse_trace
from shockwave_tpu.obs.logconfig import LEVELS, setup_logging
from shockwave_tpu.sched import SchedulerConfig
from shockwave_tpu.sched.physical import PhysicalScheduler
from shockwave_tpu.solver import get_policy


def submit_jobs(sched, jobs, arrival_times, start_time, skip=0):
    """Feed the trace to the scheduler in real time.

    `skip` jobs at the head are already inside the scheduler (crash
    recovery: their journaled submissions were replayed); arrivals the
    outage overran are submitted immediately, later ones keep their
    original wall-clock offsets relative to the ORIGINAL run start.
    """
    for job, arrival in list(zip(jobs, arrival_times))[skip:]:
        delay = start_time + arrival - time.time()
        if delay > 0:
            time.sleep(delay)
        sched.add_job(job)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--trace", required=True)
    p.add_argument("--policy", default="max_min_fairness")
    p.add_argument("--throughputs", required=True)
    p.add_argument("--expected_num_workers", type=int, default=None,
                   help="block until this many chips have registered")
    p.add_argument("--round_duration", type=float, default=360.0)
    p.add_argument("--port", type=int, default=50070)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--max_rounds", type=int, default=None)
    p.add_argument("--timeout", type=float, default=None,
                   help="hard wall-clock cap in seconds")
    p.add_argument("--config", default=None,
                   help="JSON file of shockwave hyperparameters")
    p.add_argument("--output", default=None, help="metrics pickle path")
    p.add_argument("--timeline_dir", default=None)
    p.add_argument("--watchdog", type=float, default=None,
                   help="dump all thread tracebacks every N seconds")
    p.add_argument("--completion_buffer", type=float, default=None,
                   help="seconds past the round end before the "
                        "unresponsive-kill watchdog fires (default 60)")
    p.add_argument("--first_init_grace", type=float, default=300.0,
                   help="seconds a freshly dispatched job may stay silent "
                        "before it can be killed (slow relayed-TPU "
                        "backend init; 0 disables)")
    # Fault-tolerance knobs (defaults recorded in
    # configs/fault_tolerance.json; see README "Failure model & recovery").
    p.add_argument("--heartbeat_interval", type=float, default=10.0,
                   help="worker liveness monitor cadence in seconds "
                        "(0 disables the monitor)")
    p.add_argument("--worker_timeout", type=float, default=30.0,
                   help="seconds of worker silence before an active Ping "
                        "probe is sent")
    p.add_argument("--probe_failures", type=int, default=2,
                   help="consecutive failed probes before a worker is "
                        "declared dead and its jobs are requeued")
    p.add_argument("--kill_wait", type=float, default=30.0,
                   help="seconds _kill_job waits for the worker to confirm "
                        "before synthesizing a zero-step completion")
    # Gray-failure knobs (see README "Gray failures & chaos testing").
    p.add_argument("--no_worker_health", action="store_true",
                   help="disable the per-host gray-failure health "
                        "classifier and worker quarantine")
    p.add_argument("--quarantine_backoff", type=float, default=None,
                   help="seconds a quarantined host sits out before its "
                        "probed probational release (doubles per "
                        "re-quarantine; default 120)")
    p.add_argument("--health_config", default=None, metavar="JSON",
                   help="JSON file (or inline JSON object) of "
                        "runtime/resilience.HealthConfig field overrides "
                        "for the gray-failure classifier")
    # What-if plane knobs (see README "What-if control plane").
    p.add_argument("--whatif", default=None, metavar="JSON",
                   help="JSON file (or inline JSON object) of "
                        "whatif.WhatIfConfig field overrides — enables "
                        "the online what-if control plane (digital-twin "
                        "forks each round: advisory admission verdicts, "
                        "knob auto-tuning, forecasts). A 'whatif' block "
                        "in --config does the same; this flag wins")
    # Control-plane HA knobs (defaults recorded in configs/ha.json;
    # see README "Control-plane HA").
    p.add_argument("--ha", default=None, metavar="JSON",
                   help="JSON file (or inline JSON object) of "
                        "sched/ha.HAConfig field overrides — enables "
                        "the HA control plane (fenced leader epoch, "
                        "liveness lease, hot-standby failover). "
                        "Requires --state_dir")
    p.add_argument("--ha_standby", action="store_true",
                   help="run as the HOT STANDBY: tail the leader's "
                        "journal into a warm twin, promote "
                        "automatically when its lease lapses, then "
                        "continue this driver as the new leader "
                        "(implies --resume at promotion)")
    # Durability knobs (defaults recorded in configs/durability.json;
    # see README "Scheduler crash recovery").
    p.add_argument("--state_dir", "--state-dir", dest="state_dir",
                   default=None,
                   help="directory for the write-ahead journal + "
                        "snapshots; enables crash recovery")
    p.add_argument("--resume", action="store_true",
                   help="rebuild scheduler state from --state_dir "
                        "(snapshot + journal replay) instead of starting "
                        "fresh")
    p.add_argument("--snapshot_interval", "--snapshot-interval",
                   dest="snapshot_interval", type=int, default=10,
                   help="rounds between compacting snapshots (bounds "
                        "journal size; 0 disables snapshots)")
    p.add_argument("--no_pipelined_solve", action="store_true",
                   help="disable the background planner solve thread "
                        "(shockwave policy): the MILP runs inline at "
                        "mid-round under the historical half-round "
                        "budget clamp (see README 'Planner "
                        "performance')")
    # Observability knobs (see README "Observability").
    p.add_argument("--obs_port", type=int, default=None,
                   help="serve Prometheus /metrics + JSON /healthz on "
                        "this port (0 = ephemeral; default disabled)")
    p.add_argument("--obs_trace", default=None, metavar="TRACE_JSON",
                   help="export the round-pipeline span trace as "
                        "Chrome-trace JSON at shutdown (view in "
                        "Perfetto, or summarize with python -m "
                        "shockwave_tpu.obs.report)")
    p.add_argument("--trace_dir", default=None, metavar="DIR",
                   help="fleet-trace directory: propagate span context "
                        "on every dispatch, write the scheduler's span "
                        "shard here at shutdown, and merge every shard "
                        "present (point worker daemons at the same "
                        "directory via --trace_dir / "
                        "$SWTPU_SPAN_SHARD_DIR) into one Perfetto "
                        "trace; explain a job with python -m "
                        "shockwave_tpu.obs.explain")
    p.add_argument("--history", default=None, metavar="JSON",
                   help="JSON file (or inline JSON object) of "
                        "obs/history.TelemetryHistory overrides "
                        "(max_rounds, flush_interval_rounds, path). "
                        "Default: enabled with defaults when "
                        "--state_dir is set")
    p.add_argument("--no_history", action="store_true",
                   help="disable the telemetry-history ring (and its "
                        "/history.json + swtpu_alert checks)")
    p.add_argument("--log_level", default=None, choices=LEVELS,
                   help="root log level (default: warning, or info "
                        "with --verbose)")
    p.add_argument("--verbose", action="store_true")
    args = p.parse_args()
    if args.resume and not args.state_dir:
        # Silently starting fresh would resubmit the whole trace and
        # abandon the crashed run — the exact loss --resume prevents.
        p.error("--resume requires --state_dir (the directory of the "
                "crashed run's journal)")

    setup_logging(args.log_level
                  or ("info" if args.verbose else "warning"))

    jobs, arrival_times = parse_trace(args.trace)
    throughputs = read_throughputs(args.throughputs)
    profiles = build_profiles(jobs, throughputs)

    shockwave_config = None
    serving_config = None
    whatif_config = None
    if args.config:
        with open(args.config) as f:
            shockwave_config = json.load(f)
        # Serving-tier autoscaler + what-if blocks (policy-agnostic;
        # same file convention as simulate.py).
        serving_config = shockwave_config.pop("serving", None)
        whatif_config = shockwave_config.pop("whatif", None)
    if args.whatif:
        if args.whatif.strip().startswith("{"):
            whatif_config = json.loads(args.whatif)
        else:
            with open(args.whatif) as f:
                whatif_config = json.load(f)
    if shockwave_config is None and args.policy == "shockwave":
        shockwave_config = {}
    if shockwave_config is not None:
        if args.expected_num_workers:
            shockwave_config.setdefault("num_gpus", args.expected_num_workers)
        shockwave_config["time_per_iteration"] = args.round_duration

    worker_health = None
    if args.health_config:
        if args.health_config.strip().startswith("{"):
            worker_health = json.loads(args.health_config)
        else:
            with open(args.health_config) as f:
                worker_health = json.load(f)
    if args.quarantine_backoff is not None:
        worker_health = dict(worker_health or {})
        worker_health["quarantine_backoff_s"] = args.quarantine_backoff

    ha_config = None
    if args.ha:
        if args.ha.strip().startswith("{"):
            ha_config = json.loads(args.ha)
        else:
            with open(args.ha) as f:
                ha_config = json.load(f)
        if not args.state_dir:
            p.error("--ha requires --state_dir (the lease, epoch claims "
                    "and shipped journal all live there)")
    if args.ha_standby and ha_config is None:
        p.error("--ha_standby requires --ha (the standby needs the "
                "lease/epoch knobs to watch the leader)")

    history_config = None
    if not args.no_history:
        if args.history:
            if args.history.strip().startswith("{"):
                history_config = json.loads(args.history)
            else:
                with open(args.history) as f:
                    history_config = json.load(f)
        elif args.state_dir:
            history_config = {}

    policy = get_policy(args.policy, seed=args.seed)
    config = SchedulerConfig(
        time_per_iteration=args.round_duration, seed=args.seed,
        max_rounds=args.max_rounds, shockwave=shockwave_config,
        watchdog_interval=args.watchdog,
        job_completion_buffer_s=args.completion_buffer,
        first_init_grace_s=args.first_init_grace,
        heartbeat_interval_s=args.heartbeat_interval,
        worker_timeout_s=args.worker_timeout,
        worker_probe_failures=args.probe_failures,
        kill_wait_s=args.kill_wait,
        worker_health_enabled=not args.no_worker_health,
        worker_health=worker_health,
        state_dir=args.state_dir, resume=args.resume,
        snapshot_interval_rounds=args.snapshot_interval,
        pipelined_planning=not args.no_pipelined_solve,
        obs_port=args.obs_port, obs_trace_path=args.obs_trace,
        obs_trace_dir=args.trace_dir, history=history_config,
        serving=serving_config, whatif=whatif_config, ha=ha_config)

    if args.ha_standby:
        # Hot-standby phase: tail the leader's journal into a warm twin
        # until its lease lapses and this process wins the promotion
        # CAS — then fall through to the normal driver path as the new
        # leader, re-entering through the conservative --resume
        # recovery (load_state + in-flight requeue + orphan gates).
        from shockwave_tpu.obs import get_observability
        from shockwave_tpu.sched.ha import HAConfig, HotStandby
        from shockwave_tpu.sched.scheduler import Scheduler
        from shockwave_tpu.whatif.fork import twin_config

        ha_cfg = HAConfig.from_dict(ha_config)

        def _twin_factory():
            return Scheduler(get_policy(args.policy, seed=args.seed),
                             simulate=True, profiles=profiles,
                             throughputs_file=args.throughputs,
                             config=twin_config(config))

        standby = HotStandby(args.state_dir, ha_cfg,
                             twin_factory=_twin_factory)
        standby_obs = None
        if args.obs_port is not None:
            from shockwave_tpu.obs.exporter import ObsHttpServer
            standby_obs = ObsHttpServer(
                get_observability().registry, health_fn=standby.health,
                port=args.obs_port).start()
            print(f"standby obs endpoint: "
                  f"http://0.0.0.0:{standby_obs.port}/metrics and "
                  "/healthz", file=sys.stderr, flush=True)
        # Blocks through lost promotion races too (the standby resumes
        # following until it wins one); returns only with a record.
        record = standby.run_until_promoted(port=args.port)
        if standby_obs is not None:
            # The promoted scheduler re-binds its own endpoint.
            standby_obs.stop()
        print(json.dumps({
            "ha_promoted": True, "epoch": record.epoch,
            "applied_seq": record.applied_seq,
            "replication_lag_s": round(record.replication_lag_s, 4),
        }), file=sys.stderr, flush=True)
        ha_config = dict(ha_config)
        ha_config["claimed_epoch"] = record.epoch
        from dataclasses import replace as _replace
        config = _replace(config, resume=True, ha=ha_config)
        args.resume = True

    sched = PhysicalScheduler(
        policy, throughputs_file=args.throughputs, profiles=profiles,
        expected_num_workers=args.expected_num_workers, port=args.port,
        config=config)
    if sched.obs_port is not None:
        # stderr, unconditionally: with --obs_port 0 this line is the
        # ONLY place the resolved ephemeral port appears, and the
        # default warning log level would swallow an info record.
        print(f"obs endpoint: http://0.0.0.0:{sched.obs_port}/metrics "
              "and /healthz", file=sys.stderr, flush=True)

    # Crash recovery: rebase on the ORIGINAL run's start time (journaled
    # as run_meta) so arrival offsets and makespan stay on one clock,
    # and skip trace jobs whose submission was already replayed.
    already_submitted = sched.num_jobs_submitted
    start_time = sched.run_meta.get("start_time") if args.resume else None
    if start_time is None:
        start_time = time.time()
        # abspath at RECORD time: the resume-side mismatch guard must
        # compare paths independent of each process's cwd.
        sched.record_run_meta(start_time=start_time,
                              trace=os.path.abspath(args.trace),
                              policy=args.policy)
    else:
        # The submission cursor is positional: resuming against a
        # DIFFERENT trace (or policy) would silently skip the wrong
        # head of the new trace and blend two workloads' accounting.
        meta = sched.run_meta
        for field, given in (("trace", os.path.abspath(args.trace)),
                             ("policy", args.policy)):
            recorded = meta.get(field)
            if field == "trace" and recorded is not None:
                recorded = os.path.abspath(recorded)
            if recorded is not None and recorded != given:
                raise SystemExit(
                    f"--resume {field} mismatch: this state dir was "
                    f"recorded with {field}={recorded!r}, but "
                    f"{given!r} was passed; resume with the original "
                    f"{field} (or use a fresh state dir)")
        if already_submitted:
            logging.warning("resumed with %d/%d trace jobs already "
                            "submitted", already_submitted, len(jobs))
    submitter = threading.Thread(
        target=submit_jobs,
        args=(sched, jobs, arrival_times, start_time, already_submitted),
        daemon=True)
    submitter.start()

    if args.timeout is not None:
        def _deadline():
            time.sleep(args.timeout)
            logging.warning("timeout reached; shutting down")
            sched.shutdown()
            os._exit(3)
        threading.Thread(target=_deadline, daemon=True).start()

    if args.resume and sched.get_num_completed_jobs() >= len(jobs):
        # The crash happened after the last completion; run() would wait
        # forever for jobs that will never arrive.
        logging.warning("all %d jobs had completed before the restart; "
                        "reporting recovered metrics", len(jobs))
    else:
        sched.run()
    if getattr(sched, "ha_fenced", False):
        # Deposed by a promoted standby: the successor owns the run
        # (and the journal). Exit distinctly — a fenced stand-down is
        # the HA design working, not a failure, and the chaos driver
        # asserts this exact code for the SIGCONTed old leader.
        print(json.dumps({"ha_fenced": True,
                          "epoch": sched._ha.epoch if sched._ha else None}),
              file=sys.stderr, flush=True)
        sched.shutdown()
        sys.exit(7)
    # Last completion, not teardown: run() returning includes the final
    # round's drain + shutdown, which the reference's makespan (stamped
    # as soon as is_done polls true) does not contain. The physical
    # clock is wall time, so rebase against the driver's start.
    last_done = sched.get_last_completion_time()
    # A max_rounds/timeout exit can leave jobs unfinished; last-completion
    # time would then understate makespan vs a run that drained the trace.
    all_done = sched.get_num_completed_jobs() >= len(jobs)
    makespan = (last_done - start_time) if (last_done and all_done) else (
        time.time() - start_time)

    jct = sched.get_average_jct()
    ftf_static, ftf_themis = sched.get_finish_time_fairness()
    util, util_list = sched.get_cluster_utilization()
    ext_pct, ext, opp = sched.get_num_lease_extensions()

    serving_summary = sched.serving_summary()
    metrics = {
        "trace_file": args.trace,
        "policy": args.policy,
        "makespan": makespan,
        "all_jobs_completed": all_done,
        **({"serving": serving_summary} if serving_summary else {}),
        "avg_jct": jct[0] if jct else None,
        "geometric_mean_jct": jct[1] if jct else None,
        "harmonic_mean_jct": jct[2] if jct else None,
        "jct_list": jct[3] if jct else [],
        "finish_time_fairness_list": ftf_static,
        "finish_time_fairness_themis_list": ftf_themis,
        "cluster_util": util,
        "utilization_list": util_list,
        "extension_percentage": ext_pct,
        "num_lease_extensions": ext,
        "num_lease_extension_opportunities": opp,
        "per_round_schedule": sched.rounds.per_round_schedule,
        "time_per_iteration": args.round_duration,
        "throughput_timeline": sched.get_throughput_timeline(),
        "milp_solve_stats": sched.get_solve_stats(),
    }
    if sched._whatif is not None:
        # The plane's full evidence trail (sweeps, forecasts, advisory
        # admission verdicts) — what the committed loopback-tuning
        # artifact is built from.
        metrics["whatif"] = {
            "status": sched._whatif.status(),
            "decision_log": sched._whatif.decision_log,
            "knob_log": sched._whatif.knob_log,
            "forecast_log": sched._whatif.forecast_log,
            "shadow_log": sched._whatif.shadow_log,
        }
    if args.output:
        with open(args.output, "wb") as f:
            pickle.dump(metrics, f)
    if args.timeline_dir:
        sched.save_job_timelines(args.timeline_dir)

    unfair = unfair_fraction(ftf_static)
    print(json.dumps({
        "policy": args.policy,
        "makespan": round(makespan, 2),
        "avg_jct": round(metrics["avg_jct"], 2) if metrics["avg_jct"] else None,
        "unfair_fraction": round(unfair, 4),
        "cluster_util": round(util, 4),
        "lease_extension_pct": round(ext_pct, 2),
    }))
    sched.shutdown()


if __name__ == "__main__":
    main()
