#!/bin/bash
# Canonical experiment: all 7 paper policies on the 120-job trace,
# 32-chip cluster, 120 s rounds (reference: reproduce/tacc_32gpus.sh).
#
# policy -> figure legend mapping (same as the paper):
#   shockwave: Shockwave          min_total_duration: OSSP
#   finish_time_fairness: Themis  max_min_fairness: Gavel
#   allox: AlloX                  max_sum_throughput_perf: MST
#   gandiva_fair: Gandiva-Fair
#
# Shockwave's MILP dominates runtime (~minutes); the rest take seconds.
set -u
cd "$(dirname "$0")/.."
OUT=${1:-reproduce/pickles}
mkdir -p "$OUT"

for POLICY in shockwave min_total_duration finish_time_fairness \
              max_min_fairness allox max_sum_throughput_perf gandiva_fair
do
    echo "=== $POLICY ==="
    python3 scripts/drivers/simulate.py \
        --trace data/canonical_120job.trace \
        --policy "$POLICY" \
        --throughputs data/tacc_throughputs.json \
        --cluster_spec v100:32 \
        --round_duration 120 \
        --seed 0 \
        --config configs/tacc_32gpus.json \
        --output "$OUT/${POLICY}.pkl" \
        | tee "$OUT/${POLICY}.json"
done

python3 reproduce/aggregate_result.py "$OUT"
