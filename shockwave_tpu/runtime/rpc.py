"""gRPC service bindings without protoc's grpc plugin.

Service/method tables are declared once; `make_stub` builds a client-side
callable stub and `generic_handler` a server-side handler from the same
table, so the two can never drift apart.

`generic_handler` is also the single server-side chokepoint for the
fault-injection harness (`faults.py`): every handler consults the active
injector before running, so tests can drop / blackhole / delay any RPC
method deterministically.
"""
from __future__ import annotations

from typing import Callable, Dict

import grpc

from . import faults
from .proto import control_pb2 as pb

SERVICES: Dict[str, Dict[str, tuple]] = {
    "shockwave_tpu.WorkerToScheduler": {
        "RegisterWorker": (pb.RegisterWorkerRequest, pb.RegisterWorkerResponse),
        "Done": (pb.DoneRequest, pb.Empty),
    },
    "shockwave_tpu.SchedulerToWorker": {
        "RunJob": (pb.RunJobRequest, pb.Empty),
        "KillJob": (pb.KillJobRequest, pb.Empty),
        "Reset": (pb.Empty, pb.Empty),
        "Shutdown": (pb.Empty, pb.Empty),
        # Liveness probe: answered by the worker server itself, carrying
        # no payload — the scheduler's heartbeat monitor calls it with a
        # short deadline when piggybacked heartbeats go stale.
        "Ping": (pb.Empty, pb.Empty),
    },
    "shockwave_tpu.IteratorToScheduler": {
        "InitJob": (pb.InitJobRequest, pb.InitJobResponse),
        "UpdateLease": (pb.UpdateLeaseRequest, pb.UpdateLeaseResponse),
        "UpdateResourceRequirement": (pb.UpdateResourceRequirementRequest, pb.Empty),
    },
}


class Stub:
    """Client stub exposing one attribute per RPC method."""

    def __init__(self, channel: grpc.Channel, service: str):
        for method, (req_cls, resp_cls) in SERVICES[service].items():
            callable_ = channel.unary_unary(
                f"/{service}/{method}",
                request_serializer=req_cls.SerializeToString,
                response_deserializer=resp_cls.FromString,
            )
            setattr(self, method, callable_)


def _with_fault_hook(fn: Callable, full_method: str) -> Callable:
    def handler(request, context):
        injector = faults.get_injector()
        if injector.active():
            injector.fire(full_method, context)  # may sleep or abort
        return fn(request, context)
    return handler


def generic_handler(service: str, implementations: Dict[str, Callable]):
    """Build a grpc generic handler from {method_name: fn(request, context)}."""
    method_handlers = {}
    for method, fn in implementations.items():
        req_cls, resp_cls = SERVICES[service][method]
        method_handlers[method] = grpc.unary_unary_rpc_method_handler(
            _with_fault_hook(fn, f"{service}/{method}"),
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString,
        )
    return grpc.method_handlers_generic_handler(service, method_handlers)
