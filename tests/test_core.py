"""Core data model tests: JobIdPair, Job, traces, oracles, adaptation parity."""
import os

import pytest

from shockwave_tpu.core import (
    Job, JobIdPair, parse_trace, read_throughputs, num_epochs_for,
)
from shockwave_tpu.core.adaptation import accordion_bs_schedule, gns_bs_schedule
from shockwave_tpu.core.profiles import build_profiles

DATA = os.path.join(os.path.dirname(__file__), "..", "data")
TRACE = os.path.join(DATA, "canonical_120job.trace")
THROUGHPUTS = os.path.join(DATA, "tacc_throughputs.json")


class TestJobIdPair:
    def test_single(self):
        j = JobIdPair(3)
        assert not j.is_pair()
        assert j.integer_job_id() == 3
        assert j == 3
        assert j.singletons() == (j,)

    def test_pair_normalizes_order(self):
        assert JobIdPair(5, 2) == JobIdPair(2, 5)
        assert hash(JobIdPair(5, 2)) == hash(JobIdPair(2, 5))
        assert JobIdPair(2, 5).as_tuple() == (2, 5)

    def test_mixed_keys_in_dict(self):
        d = {}
        for i in range(50):
            d[JobIdPair(i)] = ("single", i)
        for i in range(20):
            for j in range(i + 1, 20):
                d[JobIdPair(i, j)] = ("pair", i, j)
        assert d[JobIdPair(7)] == ("single", 7)
        assert d[JobIdPair(12, 3)] == ("pair", 3, 12)
        assert len(d) == 50 + 190

    def test_ordering_singles_before_pairs(self):
        assert JobIdPair(9) < JobIdPair(0, 1)
        assert sorted([JobIdPair(1, 2), JobIdPair(3), JobIdPair(0)]) == [
            JobIdPair(0), JobIdPair(3), JobIdPair(1, 2)]

    def test_overlaps(self):
        assert JobIdPair(1).overlaps_with(JobIdPair(1, 7))
        assert not JobIdPair(2).overlaps_with(JobIdPair(1, 7))


class TestJob:
    def test_model_and_bs_parsing(self):
        j = Job(None, "ResNet-18 (batch size 32)", "python3 main.py --batch_size 32")
        assert j.model == "ResNet-18"
        assert j.batch_size == 32

    def test_update_bs_rewrites_last_token(self):
        j = Job(None, "ResNet-18 (batch size 32)",
                "python3 main.py --data_dir=%s/cifar10 --batch_size 32")
        j.update_bs(64)
        assert j.batch_size == 64
        assert j.command.endswith("--batch_size 64")

    def test_update_bs_translation_second_to_last(self):
        j = Job(None, "ResNet-50 (batch size 64)",
                "python3 main.py -j 4 -a resnet50 -b 64 %s/imagenet/")
        j.update_bs(128)
        assert j.command == "python3 main.py -j 4 -a resnet50 -b 128 %s/imagenet/"
        assert j.batch_size == 128


class TestTrace:
    def test_parse_canonical(self):
        jobs, arrivals = parse_trace(TRACE)
        assert len(jobs) == 120
        assert arrivals == sorted(arrivals)
        assert all(j.scale_factor >= 1 for j in jobs)
        modes = {j.mode for j in jobs}
        assert modes <= {"static", "accordion", "gns"}

    def test_oracle_lookup(self):
        tp = read_throughputs(THROUGHPUTS)
        v = tp["v100"][("ResNet-18 (batch size 16)", 1)]["null"]
        assert v == pytest.approx(57.68, abs=0.5)


class TestAdaptationParity:
    """Cross-check the data-driven schedules against the reference code."""

    CASES = [
        ("ResNet-18", bs, sf, n)
        for bs in (16, 32, 64, 128, 256)
        for sf in (1, 2, 4, 8)
        for n in (5, 12, 40, 80, 200, 400)
    ] + [
        ("ResNet-50", bs, sf, n)
        for bs in (16, 32, 64, 128) for sf in (1, 2, 4) for n in (50, 120, 250)
    ] + [
        ("LM", bs, sf, n)
        for bs in (5, 10, 20, 40, 80) for sf in (1, 2, 4) for n in (10, 35, 90)
    ] + [
        ("Recommendation", bs, 1, n)
        for bs in (512, 1024, 2048, 4096, 8192) for n in (15, 45, 100)
    ] + [("Transformer", 64, 1, 60)]

    def test_gns_matches_reference(self, reference_utils):
        for model, bs, sf, n in self.CASES:
            job_type = f"{model} (batch size {bs})"
            expected = reference_utils.get_gns_bs_pattern(job_type, bs, n, sf)
            got = gns_bs_schedule(model, bs, n, sf)
            assert got == expected, (model, bs, sf, n)

    def test_accordion_matches_reference(self, reference_utils):
        for model, bs, sf, n in self.CASES:
            job_type = f"{model} (batch size {bs})"
            expected = reference_utils.get_accordion_bs_pattern(job_type, bs, n, 0)
            got = accordion_bs_schedule(model, bs, n)
            assert got == expected, (model, bs, n)


class TestProfiles:
    def test_profiles_match_reference_generator(self, reference_utils, tmp_path):
        """Exact parity with the reference's Shockwave profile pickles."""
        import pickle as pkl
        import shutil
        trace_copy = tmp_path / "canonical.trace"
        shutil.copy(TRACE, trace_copy)
        reference_utils.generate_pickle_file(str(trace_copy), THROUGHPUTS)
        with open(tmp_path / "canonical.pickle", "rb") as f:
            expected = pkl.load(f)

        jobs, _ = parse_trace(TRACE)
        got = build_profiles(jobs, read_throughputs(THROUGHPUTS))
        assert len(got) == len(expected)
        for g, e in zip(got, expected):
            assert g["model"] == e["model"]
            assert g["num_epochs"] == e["num_epochs"]
            assert g["bs_every_epoch"] == e["bs_every_epoch"]
            assert g["mem_every_epoch"] == e["mem_every_epoch"]
            assert g["util_every_epoch"] == e["util_every_epoch"]
            assert g["duration_every_epoch"] == pytest.approx(e["duration_every_epoch"])
            assert int(g["scale_factor"]) == int(e["scale_factor"])

    def test_build_canonical_profiles(self):
        jobs, _ = parse_trace(TRACE)
        tp = read_throughputs(THROUGHPUTS)
        profiles = build_profiles(jobs, tp)
        assert len(profiles) == 120
        for job, p in zip(jobs, profiles):
            n = p["num_epochs"]
            assert n == num_epochs_for(job.model, job.batch_size, job.total_steps)
            for key in ("bs_every_epoch", "mem_every_epoch", "util_every_epoch",
                        "duration_every_epoch"):
                assert len(p[key]) == n
            assert all(d > 0 for d in p["duration_every_epoch"])
