"""Learned throughput oracle (ROADMAP item 2; PAPERS.md 2008.01040).

A deterministic, seeded regression over the telemetry history's
per-microtask observed-rate rows — ``(job_type, batch_size,
scale_factor, worker_type) -> steps/s`` — with a comm-scaling term per
worker *generation* (cf. EQuARX, 2506.17615: interconnect efficiency is
a property of the generation, not the individual profile row), so a
model trained on one generation's scale curves extrapolates another's.

Train offline from ``/history.json`` rings::

    python -m shockwave_tpu.oracle.train --history state/history.json \
        --out model.json

and serve predictions through the strict fallback chain in
`core/throughput_estimator.py` (profiled table -> learned prediction ->
conservative prior), which also feeds Done-report rates back into the
model's online residual corrections.

Pure numpy; no wall clocks, no unseeded RNG (the analyzer determinism
pass covers this package), byte-stable JSON artifacts.
"""
from .features import (FAMILY_HASH_BUCKETS, GENERATIONS, family_of,
                       generation_of)
from .model import MODEL_SCHEMA, ThroughputModel

__all__ = [
    "FAMILY_HASH_BUCKETS", "GENERATIONS", "family_of", "generation_of",
    "MODEL_SCHEMA", "ThroughputModel",
]
